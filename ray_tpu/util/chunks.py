"""Shared chunked object-plane transfer: ONE implementation of the
"host array -> owned chunk -> point-to-point fetch" path used by every
subsystem that ships tensors between processes without a gather.

Producers put each host array into THEIR OWN object store as a chunk
(the shm path serves same-host readers zero-copy; remote readers stream
it through the worker's 64MB-ranged `fetch_object_range` pulls) and pass
around only a metadata entry naming the chunk. Consumers rebuild an
``ObjectRef`` from the entry and pull the bytes point-to-point from the
owner — the conductor only ever sees metadata, never payload.

Extracted from ``weights/publisher.py`` / ``weights/subscriber.py`` so
the live weight fabric and the MPMD activation channels
(``ray_tpu.mpmd.channels``) share one implementation — including the
``ascontiguousarray`` guard (it would promote 0-d arrays to 1-d, so
0-d leaves skip it) — with one set of tests (``tests/test_mpmd.py``).

Ownership model (deliberate, matching the object plane): the returned
``ObjectRef``s ARE the chunks' lifetime. Callers must hold them until
every consumer has fetched; dropping the last ref frees the store entry.
"""
from __future__ import annotations

import pickle
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu._private.object_store import ObjectRef


def ensure_chunkable(host_arr: Any) -> np.ndarray:
    """`host_arr` as a C-contiguous ndarray ready for the store.

    NB: ``np.ascontiguousarray`` would promote a 0-d array to 1-d, so
    0-d arrays pass through as-is (they are trivially contiguous)."""
    arr = np.asarray(host_arr)
    if arr.ndim and not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    return arr


def put_chunk(worker, host_arr: Any) -> Tuple[Any, Dict[str, Any]]:
    """Put one host array into `worker`'s own store. Returns
    ``(ref, entry)`` — hold `ref` for the chunk's lifetime; `entry` is
    the metadata a consumer needs to fetch it point-to-point (plus the
    array's shape/dtype, so tree descriptors need no second
    conversion pass)."""
    arr = ensure_chunkable(host_arr)
    ref = worker.put(arr)
    entry = {"object_id": ref.id,
             "locator": list(worker.address),
             "nbytes": int(arr.nbytes),
             "shape": list(arr.shape),
             "dtype": str(arr.dtype)}
    return ref, entry


class ChunkFetcher:
    """Chunk puller with a per-instance cache: each needed chunk crosses
    the object plane at most once per fetcher, with remote-vs-local
    accounting (``chunks_local`` / ``chunks_fetched`` /
    ``fetched_bytes``). Callable with a chunk entry dict."""

    def __init__(self, worker, timeout: float = 60.0,
                 on_read: Optional[Callable[[int, bool], None]] = None):
        self._worker = worker
        self._timeout = timeout
        self._on_read = on_read
        self._cache: Dict[str, np.ndarray] = {}
        self.chunks_local = 0
        self.chunks_fetched = 0
        self.fetched_bytes = 0

    def __call__(self, entry: Dict[str, Any]) -> np.ndarray:
        oid = entry["object_id"]
        arr = self._cache.get(oid)
        if arr is not None:
            return arr
        was_local = self._worker.store.contains(oid)
        ref = ObjectRef(oid, locator=tuple(entry["locator"]),
                        owner=tuple(entry["locator"]))
        arr = np.asarray(self._worker.get(ref, timeout=self._timeout))
        nbytes = int(entry.get("nbytes", arr.nbytes))
        if was_local:
            self.chunks_local += 1
        else:
            self.chunks_fetched += 1
            self.fetched_bytes += nbytes
        if self._on_read is not None:
            self._on_read(nbytes, was_local)
        self._cache[oid] = arr
        return arr


# ---------------------------------------------------------- pytree payloads

def put_tree(worker, tree: Any) -> Tuple[List[Any], Dict[str, Any]]:
    """Chunk every leaf of a pytree into `worker`'s store. Returns
    ``(refs, descriptor)``: hold `refs` until consumers fetched; the
    descriptor (leaf entries + pickled treedef) is metadata-only and
    safe to route through the conductor."""
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    refs: List[Any] = []
    entries: List[Dict[str, Any]] = []
    total = 0
    for leaf in leaves:
        ref, entry = put_chunk(worker, leaf)
        refs.append(ref)
        entries.append(entry)
        total += entry["nbytes"]
    descriptor = {"leaves": entries,
                  "treedef": pickle.dumps(treedef, protocol=5),
                  "total_bytes": total}
    return refs, descriptor


def fetch_tree(worker, descriptor: Dict[str, Any],
               fetcher: Optional[ChunkFetcher] = None) -> Any:
    """Materialize a ``put_tree`` descriptor: pull each leaf chunk
    point-to-point from its owner and unflatten."""
    import jax

    if fetcher is None:
        fetcher = ChunkFetcher(worker)
    leaves = [fetcher(entry) for entry in descriptor["leaves"]]
    treedef = pickle.loads(descriptor["treedef"])
    return jax.tree.unflatten(treedef, leaves)


__all__ = ["ChunkFetcher", "ensure_chunkable", "fetch_tree", "put_chunk",
           "put_tree"]
