"""Out-of-band collectives between actors/processes — the TPU-native
equivalent of the reference's ray.util.collective
(python/ray/util/collective/collective.py:120-615, NCCL/Gloo backends).

Two planes, per SURVEY.md §5.8:

- **Device plane**: arrays living on the accelerator mesh reduce via XLA
  collectives *inside* jitted programs (`device_allreduce` below wraps a
  one-off `shard_map` psum for eager use; real training steps get their
  collectives inserted by the partitioner). There is no NCCL-style group
  bootstrap to manage — the mesh is the group.
- **Host plane**: small host tensors between worker processes reduce
  through the conductor KV (the reference's `NCCLUniqueIDStore` named
  actor, nccl_collective_group.py:28-50, generalized into the control
  plane): every rank posts its contribution under a per-op key, polls for
  the others, reduces locally. Ops must be called in the same order on
  every rank (same contract as NCCL). O(n^2) bytes — by design: bulk
  tensors belong on the device plane.
"""
from __future__ import annotations

import pickle
import threading
import time
from dataclasses import dataclass
from enum import Enum
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class ReduceOp(Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: lambda xs: np.sum(xs, axis=0),
    ReduceOp.PRODUCT: lambda xs: np.prod(xs, axis=0),
    ReduceOp.MIN: lambda xs: np.min(xs, axis=0),
    ReduceOp.MAX: lambda xs: np.max(xs, axis=0),
}

_NS = "collective"


def _kv():
    from ray_tpu import _require_worker

    return _require_worker().conductor


@dataclass
class _Group:
    name: str
    world_size: int
    rank: int
    op_count: int = 0


_groups: Dict[str, _Group] = {}
_lock = threading.Lock()


def init_collective_group(world_size: int, rank: int,
                          backend: str = "kv",
                          group_name: str = "default") -> None:
    """Imperative init, called by every participating process
    (reference collective.py:120)."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    if backend not in ("kv", "auto"):
        raise ValueError(f"unsupported backend {backend!r}; host-plane "
                         "groups use 'kv' (device plane needs no group)")
    with _lock:
        if group_name in _groups:
            raise RuntimeError(f"group {group_name!r} already initialized")
        _groups[group_name] = _Group(group_name, world_size, rank)
    # rendezvous: everyone checks in before the group is usable
    _post(group_name, "init", 0, rank, b"")
    _collect(group_name, "init", 0, world_size)


def create_collective_group(actors: Sequence[Any], world_size: int,
                            ranks: Sequence[int], backend: str = "kv",
                            group_name: str = "default") -> List[Any]:
    """Declarative init on a set of actor handles (reference
    collective.py:151): tells each actor to init_collective_group.
    The actor class must expose a method that calls init_collective_group,
    or we invoke the built-in hook via __ray_tpu_col_init__."""
    from ray_tpu.actor import ActorMethod

    if len(actors) != len(ranks):
        raise ValueError(f"{len(actors)} actors but {len(ranks)} ranks")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(
            f"ranks {sorted(ranks)} must cover 0..{world_size - 1} exactly")
    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(ActorMethod(actor, "__ray_tpu_col_init__").remote(
            world_size, rank, backend, group_name))
    import ray_tpu

    return ray_tpu.get(refs)


def destroy_collective_group(group_name: str = "default") -> None:
    """Collective teardown. Two closing barriers ensure every rank has
    finished all prior ops before any key deletion (deleting peers' keys
    while they are mid-collect would strand them until timeout); each
    rank then deletes only its own contributions. The final barrier's
    tiny b"" markers are deliberately leaked."""
    g = _groups.get(group_name)
    if g is None:
        return
    try:
        barrier(group_name)
        final_op = g.op_count  # the 2nd barrier's op_id
        barrier(group_name)
        for key in _kv().call("kv_keys", f"col/{group_name}/".encode(),
                              _NS, timeout=30.0):
            tail = key.rsplit(b"/", 1)[-1]
            parts = key.split(b"/")
            own = tail == str(g.rank).encode()
            is_final_barrier = (len(parts) > 2
                                and parts[2] == f"{final_op:08d}".encode())
            if own and not is_final_barrier:
                _kv().call("kv_del", key, _NS, timeout=30.0)
    finally:
        with _lock:
            _groups.pop(group_name, None)


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def get_rank(group_name: str = "default") -> int:
    return _get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _get(group_name).world_size


def _get(group_name: str) -> _Group:
    g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(f"collective group {group_name!r} not "
                           "initialized; call init_collective_group first")
    return g


def _key(group: str, op: str, op_id: int, rank: int) -> bytes:
    return f"col/{group}/{op_id:08d}/{op}/{rank}".encode()


def _post(group: str, op: str, op_id: int, rank: int, payload: bytes) -> None:
    _kv().call("kv_put", _key(group, op, op_id, rank), payload, True, _NS,
               timeout=60.0)


def _collect(group: str, op: str, op_id: int, world_size: int,
             timeout: float = 120.0) -> List[bytes]:
    """Poll the KV until all world_size contributions for this op exist."""
    kv = _kv()
    deadline = time.monotonic() + timeout
    out: List[Optional[bytes]] = [None] * world_size
    missing = set(range(world_size))
    delay = 0.001
    while missing:
        for r in list(missing):
            v = kv.call("kv_get", _key(group, op, op_id, r), _NS,
                        timeout=60.0)
            if v is not None:
                out[r] = v
                missing.discard(r)
        if not missing:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"collective {op} op_id={op_id} in group {group!r} timed "
                f"out waiting for ranks {sorted(missing)}")
        time.sleep(delay)
        delay = min(delay * 2, 0.05)
    return out  # type: ignore[return-value]


def _advance(g: _Group, op: str) -> int:
    """Bump the per-group op counter and garbage-collect this rank's key
    from op_id-2 (safe: any rank starting op k has read all keys of k-1,
    which implies every rank finished k-2)."""
    op_id = g.op_count
    g.op_count += 1
    if op_id >= 2:
        for key in _kv().call(
                "kv_keys", f"col/{g.name}/{op_id - 2:08d}/".encode(),
                _NS, timeout=30.0):
            if key.endswith(f"/{g.rank}".encode()):
                _kv().call("kv_del", key, _NS, timeout=30.0)
    return op_id


def allreduce(tensor: np.ndarray, group_name: str = "default",
              op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
    """All ranks contribute, all receive the reduction
    (reference collective.py:258). Returns the reduced array (also copies
    into `tensor` in place when it is a writable ndarray, matching the
    reference's in-place semantics)."""
    g = _get(group_name)
    op_id = _advance(g, "allreduce")
    arr = np.asarray(tensor)
    _post(g.name, "allreduce", op_id, g.rank, _dumps(arr))
    parts = [_loads(b) for b in
             _collect(g.name, "allreduce", op_id, g.world_size)]
    result = _REDUCERS[op](np.stack(parts)).astype(arr.dtype)
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable \
            and tensor.shape == result.shape:
        tensor[...] = result
    return result


def barrier(group_name: str = "default") -> None:
    """reference collective.py:298."""
    g = _get(group_name)
    op_id = _advance(g, "barrier")
    _post(g.name, "barrier", op_id, g.rank, b"")
    _collect(g.name, "barrier", op_id, g.world_size)


def broadcast(tensor: np.ndarray, src_rank: int = 0,
              group_name: str = "default") -> np.ndarray:
    """reference collective.py:373."""
    g = _get(group_name)
    op_id = _advance(g, "broadcast")
    if g.rank == src_rank:
        _post(g.name, "broadcast", op_id, src_rank, _dumps(np.asarray(tensor)))
        result = np.asarray(tensor)
    else:
        kv = _kv()
        deadline = time.monotonic() + 120.0
        while True:
            v = kv.call("kv_get", _key(g.name, "broadcast", op_id, src_rank),
                        _NS, timeout=60.0)
            if v is not None:
                result = _loads(v)
                break
            if time.monotonic() > deadline:
                raise TimeoutError("broadcast timed out")
            time.sleep(0.002)
    # completion marker so src's key can be GC'd by the op-window rule
    _post(g.name, "broadcast_ack", op_id, g.rank, b"")
    _collect(g.name, "broadcast_ack", op_id, g.world_size)
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable \
            and tensor.shape == result.shape and g.rank != src_rank:
        tensor[...] = result
    return result


def allgather(tensor: np.ndarray,
              group_name: str = "default") -> List[np.ndarray]:
    """Returns [rank0_tensor, rank1_tensor, ...] (reference
    collective.py:423)."""
    g = _get(group_name)
    op_id = _advance(g, "allgather")
    _post(g.name, "allgather", op_id, g.rank, _dumps(np.asarray(tensor)))
    return [_loads(b) for b in
            _collect(g.name, "allgather", op_id, g.world_size)]


def reducescatter(tensor: np.ndarray, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
    """Reduce across ranks, scatter equal chunks: rank r receives chunk r
    of the reduction (reference collective.py:472)."""
    g = _get(group_name)
    arr = np.asarray(tensor)
    if arr.shape[0] % g.world_size != 0:
        raise ValueError(
            f"leading dim {arr.shape[0]} not divisible by world size "
            f"{g.world_size}")
    op_id = _advance(g, "reducescatter")
    _post(g.name, "reducescatter", op_id, g.rank, _dumps(arr))
    parts = [_loads(b) for b in
             _collect(g.name, "reducescatter", op_id, g.world_size)]
    full = _REDUCERS[op](np.stack(parts)).astype(arr.dtype)
    return np.array_split(full, g.world_size, axis=0)[g.rank]


def send(tensor: np.ndarray, dst_rank: int,
         group_name: str = "default") -> None:
    """Point-to-point send (reference collective.py:531). Paired with a
    matching recv on dst; (src,dst) channels are ordered by a per-pair
    sequence number."""
    g = _get(group_name)
    seq = g.__dict__.setdefault("_p2p_send", {}).setdefault(dst_rank, 0)
    g.__dict__["_p2p_send"][dst_rank] = seq + 1
    key = f"col/{g.name}/p2p/{g.rank}->{dst_rank}/{seq:08d}".encode()
    _kv().call("kv_put", key, _dumps(np.asarray(tensor)), True, _NS,
               timeout=60.0)


def recv(tensor: np.ndarray, src_rank: int,
         group_name: str = "default") -> np.ndarray:
    """reference collective.py:594."""
    g = _get(group_name)
    seq = g.__dict__.setdefault("_p2p_recv", {}).setdefault(src_rank, 0)
    g.__dict__["_p2p_recv"][src_rank] = seq + 1
    key = f"col/{g.name}/p2p/{src_rank}->{g.rank}/{seq:08d}".encode()
    kv = _kv()
    deadline = time.monotonic() + 120.0
    while True:
        v = kv.call("kv_get", key, _NS, timeout=60.0)
        if v is not None:
            kv.call("kv_del", key, _NS, timeout=30.0)
            result = _loads(v)
            break
        if time.monotonic() > deadline:
            raise TimeoutError(f"recv from rank {src_rank} timed out")
        time.sleep(0.002)
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable \
            and tensor.shape == result.shape:
        tensor[...] = result
    return result


# ---------------------------------------------------------------------------
# Device plane: eager XLA collectives over a mesh axis.


def device_allreduce(x, mesh, axis: str = "dp", op: ReduceOp = ReduceOp.SUM):
    """Eager psum/pmax/pmin over a mesh axis via a one-off shard_map —
    for host-driven reductions of device arrays outside a training step.
    Inside jitted SPMD programs, just shard inputs and let XLA insert the
    collective (SURVEY.md §5.8)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.mesh import shard_map

    prims = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
             ReduceOp.MIN: jax.lax.pmin}
    if op not in prims:
        raise ValueError(f"device_allreduce does not support {op}")

    def body(v):
        return prims[op](v, axis)

    fn = shard_map(body, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
    sharded = jax.device_put(x, NamedSharding(mesh, P(axis)))
    return jax.jit(fn)(sharded)


def _dumps(arr: np.ndarray) -> bytes:
    return pickle.dumps(arr, protocol=pickle.HIGHEST_PROTOCOL)


def _loads(b: bytes) -> np.ndarray:
    return pickle.loads(b)
