"""Dask-on-ray_tpu scheduler shim.

Reference: python/ray/util/dask/ (ray_dask_get scheduler: every dask
graph task becomes a Ray task, dependencies become ObjectRefs). The
dask graph protocol is plain data — a dict of key → computation where
a computation is a task tuple ``(callable, *args)``, a key reference,
or a literal, with args nesting lists/tuples — so the scheduler here
implements that spec directly and works whether or not dask itself is
importable (it is not baked into TPU images; ``enable_dask_on_ray``
gates the dask-side registration on the import).

Usage with dask installed::

    import dask
    from ray_tpu.util.dask import ray_dask_get
    dask.compute(obj, scheduler=ray_dask_get)

Without dask, ``ray_dask_get(dsk, keys)`` still executes hand-built
graphs in the same format.
"""
from __future__ import annotations

from typing import Any, Dict, List

import ray_tpu

__all__ = ["ray_dask_get", "enable_dask_on_ray"]


def _is_key(x: Any, dsk: Dict) -> bool:
    """Dask keys are hashables present in the graph (typically str or
    (str, int...) tuples — a tuple KEY, unlike a TASK, has a non-callable
    head)."""
    try:
        return x in dsk
    except TypeError:
        return False


def _is_task(x: Any) -> bool:
    return isinstance(x, tuple) and len(x) > 0 and callable(x[0])


def _collect_refs(x: Any, out: List) -> None:
    if isinstance(x, ray_tpu.ObjectRef):
        out.append(x)
    elif isinstance(x, (list, tuple)):
        for v in x:
            _collect_refs(v, out)
    elif isinstance(x, dict):
        for v in x.values():
            _collect_refs(v, out)


def _substitute(x: Any, values: Dict[str, Any]) -> Any:
    if isinstance(x, ray_tpu.ObjectRef):
        return values[x.id]
    if isinstance(x, list):
        return [_substitute(v, values) for v in x]
    if isinstance(x, tuple):
        return tuple(_substitute(v, values) for v in x)
    if isinstance(x, dict):
        return {k: _substitute(v, values) for k, v in x.items()}
    return x


def _exec_dask_task(fn, args):
    """Executor-side: ObjectRefs nest anywhere in dask arg structures
    (the worker only auto-resolves top-level args) — batch ONE get over
    all of them so a 16-way fan-in pays one pipelined fetch, not 16
    sequential round-trips."""
    refs: List = []
    _collect_refs(args, refs)
    values = dict(zip((r.id for r in refs),
                      ray_tpu.get(refs))) if refs else {}
    return fn(*[_substitute(a, values) for a in args])


def ray_dask_get(dsk: Dict, keys: Any, **kwargs: Any) -> Any:
    """Execute a dask graph on the cluster; returns values matching the
    (possibly nested) ``keys`` structure — the dask scheduler contract
    (reference ray_dask_get, util/dask/scheduler.py)."""
    remote_exec = ray_tpu.remote(_exec_dask_task)
    cache: Dict[Any, Any] = {}  # key -> ObjectRef or literal

    def subst(x: Any) -> Any:
        """Swap key references for their (possibly ref) values inside an
        arg structure; leave task tuples to be evaluated inline (dask
        nests subtasks only in fused graphs — evaluate those eagerly on
        the driver side by submitting them anonymously)."""
        if _is_task(x):
            return remote_exec.remote(
                x[0], [subst(a) for a in x[1:]])
        if _is_key(x, dsk):
            return ensure(x)
        if isinstance(x, list):
            return [subst(v) for v in x]
        if isinstance(x, dict):
            return {k: subst(v) for k, v in x.items()}
        if isinstance(x, tuple):
            return tuple(subst(v) for v in x)
        return x

    # iterative DFS topological evaluation (recursion-free: dask graphs
    # can chain thousands of keys deep)
    def ensure(key: Any):
        if key in cache:
            return cache[key]
        stack = [key]
        while stack:
            k = stack[-1]
            if k in cache:
                stack.pop()
                continue
            comp = dsk[k]
            if _is_task(comp):
                deps = [d for d in _iter_keys(comp[1:], dsk)
                        if d not in cache]
                if deps:
                    stack.extend(deps)
                    continue
                cache[k] = remote_exec.remote(
                    comp[0], [subst(a) for a in comp[1:]])
            elif _is_key(comp, dsk):
                if comp not in cache:
                    stack.append(comp)
                    continue
                cache[k] = cache[comp]
            else:
                cache[k] = comp  # literal
            stack.pop()
        return cache[key]

    def submit_all(ks: Any) -> Any:
        if isinstance(ks, list):
            return [submit_all(k) for k in ks]
        return ensure(ks)

    refs_or_vals = submit_all(keys)
    refs: List = []
    _collect_refs(refs_or_vals, refs)
    values = dict(zip((r.id for r in refs),
                      ray_tpu.get(refs))) if refs else {}
    return _substitute(refs_or_vals, values)


def _iter_keys(args: Any, dsk: Dict):
    """Every graph-key reference anywhere inside an arg structure."""
    stack = [args]
    while stack:
        x = stack.pop()
        if _is_key(x, dsk):
            yield x
        elif _is_task(x):
            stack.extend(x[1:])
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
        elif isinstance(x, dict):
            stack.extend(x.values())


def enable_dask_on_ray() -> None:
    """Make ray_dask_get dask's default scheduler (reference
    enable_dask_on_ray); requires dask to be importable."""
    import dask  # gated: not baked into TPU images

    dask.config.set(scheduler=ray_dask_get)
