"""User-defined metrics — analog of the reference's
python/ray/util/metrics.py (Counter/Gauge/Histogram riding the OpenCensus →
metrics-agent → Prometheus pipeline, src/ray/stats/metric.h:103). Here every
process keeps a registry and pushes snapshots to the conductor
(report_metrics); ray_tpu.util.state.prometheus_metrics() renders the
aggregate in Prometheus text exposition format."""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

_DEFAULT_PUSH_INTERVAL_S = 2.0


def _push_interval() -> float:
    """Registry push cadence; RAY_TPU_METRICS_INTERVAL_S overrides (read
    per tick so a live process can be retuned — the envknobs memo makes
    the per-tick read a dict probe, not a re-parse)."""
    from ray_tpu.util import envknobs

    v = envknobs.get_float("RAY_TPU_METRICS_INTERVAL_S", 2.0)
    return v if v > 0 else _DEFAULT_PUSH_INTERVAL_S


class _Registry:
    def __init__(self):
        self._metrics: List["Metric"] = []
        self._lock = threading.Lock()
        self._pusher_started = False
        self._stop_event = threading.Event()

    def register(self, m: "Metric") -> None:
        with self._lock:
            self._metrics.append(m)
        self._ensure_pusher()

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [m._snapshot() for m in self._metrics]

    def _ensure_pusher(self) -> None:
        with self._lock:
            if self._pusher_started:
                return
            self._pusher_started = True
            # restartable: a fresh event per pusher generation, so a
            # cluster started after shutdown() gets a live push loop
            self._stop_event = stop = threading.Event()

        def push_loop():
            from ray_tpu._private import worker as worker_mod

            while not stop.wait(_push_interval()):
                w = worker_mod.global_worker
                if w is None:
                    continue
                try:
                    w.conductor.notify("report_metrics", w.worker_id,
                                       self.snapshot())
                except Exception:  # noqa: BLE001 — cluster shutting down
                    pass

        threading.Thread(target=push_loop, daemon=True,
                         name="metrics-push").start()

    def flush(self) -> None:
        """Push immediately (tests / pre-shutdown)."""
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is not None:
            w.conductor.notify("report_metrics", w.worker_id, self.snapshot())

    def stop(self) -> None:
        """Stop the push loop and push one final snapshot — called from
        ray_tpu.shutdown() so the last interval's increments are not
        lost (the seed's `while True` daemon just died with the
        process). register() after stop() restarts the loop."""
        with self._lock:
            self._stop_event.set()
            self._pusher_started = False
        try:
            self.flush()
        except Exception:  # noqa: BLE001 — conductor already gone
            pass


_registry = _Registry()


class Metric:
    """Base — reference util/metrics.py Metric."""

    _type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name or not name.replace("_", "a").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()
        _registry.register(self)

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        unknown = set(tags) - set(self._tag_keys)
        if unknown:
            raise ValueError(f"unknown tag keys {sorted(unknown)}")
        self._default_tags = dict(tags)
        return self

    def _tag_tuple(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        merged = dict(self._default_tags)
        if tags:
            unknown = set(tags) - set(self._tag_keys)
            if unknown:
                raise ValueError(f"unknown tag keys {sorted(unknown)}")
            merged.update(tags)
        return tuple(merged.get(k, "") for k in self._tag_keys)

    @staticmethod
    def _encode_tags(k: Tuple[str, ...]) -> str:
        # json, not ','.join: tag values may themselves contain commas
        import json
        return json.dumps(list(k))

    def _snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"name": self.name, "type": self._type,
                    "description": self.description,
                    "tag_keys": self._tag_keys,
                    "values": {self._encode_tags(k): v
                               for k, v in self._values.items()}}


class Counter(Metric):
    _type = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only go up")
        k = self._tag_tuple(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(Metric):
    _type = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        k = self._tag_tuple(tags)
        with self._lock:
            self._values[k] = float(value)


class Histogram(Metric):
    """Bucketed histogram — exposition emits _bucket/_sum/_count series."""

    _type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1.0, 10.0, 100.0])
        self._buckets: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._counts: Dict[Tuple[str, ...], int] = {}
        super().__init__(name, description, tag_keys)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        k = self._tag_tuple(tags)
        with self._lock:
            b = self._buckets.setdefault(
                k, [0] * (len(self.boundaries) + 1))
            i = 0
            while i < len(self.boundaries) and value > self.boundaries[i]:
                i += 1
            b[i] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1

    def _snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"name": self.name, "type": self._type,
                    "description": self.description,
                    "tag_keys": self._tag_keys,
                    "boundaries": self.boundaries,
                    "buckets": {self._encode_tags(k): v
                                for k, v in self._buckets.items()},
                    "sums": {self._encode_tags(k): v
                             for k, v in self._sums.items()},
                    "counts": {self._encode_tags(k): v
                               for k, v in self._counts.items()}}


def flush() -> None:
    _registry.flush()


def shutdown() -> None:
    """Stop the push loop + final flush (ray_tpu.shutdown() hook)."""
    _registry.stop()
