"""Distributed tracing: spans that follow tasks and actor calls across
processes — the role of the reference's OpenTelemetry integration
(python/ray/util/tracing/tracing_helper.py: _inject_tracing_into_function,
propagation over the task wire).

Zero-dependency by design (the TPU image does not bake opentelemetry):
spans use the W3C traceparent format for cross-process propagation, are
buffered per process, flushed to the conductor alongside task events, and
export as chrome-trace (Perfetto) or OTLP-shaped JSON. If the real
`opentelemetry` package is importable, span start/ends are mirrored into
it so users with an OTel pipeline get ray_tpu spans for free.

Usage:
    from ray_tpu.util import tracing
    tracing.enable()                 # driver: before submitting work
    with tracing.span("prepare-data", dataset="train"):
        ref = my_task.remote()       # child spans appear under this one
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_local = threading.local()
_lock = threading.Lock()
_finished: List["Span"] = []
_enabled = False


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start: float = field(default_factory=time.time)
    end: Optional[float] = None
    attrs: Dict[str, Any] = field(default_factory=dict)
    status: str = "OK"

    def traceparent(self) -> str:
        """W3C trace-context header value for cross-process hops."""
        return f"00-{self.trace_id}-{self.span_id}-01"


def _parse_traceparent(tp: str) -> Optional[Dict[str, str]]:
    parts = tp.split("-")
    if len(parts) != 4:
        return None
    return {"trace_id": parts[1], "span_id": parts[2]}


def enable() -> None:
    """Turn span recording on in THIS process and (via env inheritance)
    in workers spawned afterwards. Reference: `ray.init(_tracing_startup_hook)`."""
    global _enabled
    _enabled = True
    os.environ["RAY_TPU_TRACING"] = "1"


def is_enabled() -> bool:
    return _enabled or os.environ.get("RAY_TPU_TRACING") == "1"


def current_span() -> Optional[Span]:
    return getattr(_local, "span", None)


def current_traceparent() -> Optional[str]:
    """What the submitter injects into the task wire."""
    if not is_enabled():
        return None
    s = current_span()
    if s is not None:
        return s.traceparent()
    # no active span: start an implicit trace root so remote spans of one
    # driver share a trace
    root = getattr(_local, "implicit_root", None)
    if root is None:
        root = uuid.uuid4().hex
        _local.implicit_root = root
    return f"00-{root}-{'0' * 16}-01"


@contextlib.contextmanager
def span(name: str, traceparent: Optional[str] = None, **attrs):
    """Open a span. `traceparent` (from a task wire) parents this span
    into the submitting process's trace; otherwise the current in-process
    span is the parent."""
    if not is_enabled():
        yield None
        return
    parent = current_span()
    if traceparent:
        ctx = _parse_traceparent(traceparent)
        trace_id = ctx["trace_id"] if ctx else uuid.uuid4().hex
        parent_id = ctx["span_id"] if ctx and ctx["span_id"].strip("0") \
            else None
    elif parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    else:
        trace_id, parent_id = current_traceparent().split("-")[1], None
    s = Span(name=name, trace_id=trace_id, span_id=uuid.uuid4().hex[:16],
             parent_id=parent_id, attrs=dict(attrs))
    prev, _local.span = current_span(), s
    otel = _otel_start(s)
    try:
        yield s
    except BaseException as e:
        s.status = f"ERROR: {type(e).__name__}"
        raise
    finally:
        s.end = time.time()
        _local.span = prev
        _otel_end(otel, s)
        with _lock:
            _finished.append(s)
            if len(_finished) > 100_000:
                del _finished[:50_000]


_NULL_CM = contextlib.nullcontext()


def submit_span(name: str):
    """Span wrapping a task/actor-call submission (`submit:<name>`), or
    a shared no-op context manager when tracing is off. The single
    authority for submission-span naming and enablement — used by
    remote_function.remote() and ActorHandle._invoke so the unified
    timeline's submit -> execute chain cannot diverge between the two."""
    if not is_enabled():
        return _NULL_CM
    return span(f"submit:{name}")


# ------------------------------------------------------------------ export

def drain() -> List[Dict[str, Any]]:
    """Pop finished spans as dicts (the flusher ships these to the
    conductor with the task-event batch)."""
    with _lock:
        out, _finished[:] = list(_finished), []
    return [{"name": s.name, "trace_id": s.trace_id, "span_id": s.span_id,
             "parent_id": s.parent_id, "start": s.start, "end": s.end,
             "attrs": s.attrs, "status": s.status, "pid": os.getpid()}
            for s in out]


def to_chrome_trace(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Perfetto/chrome://tracing events, one X event per span, grouped by
    process and trace."""
    return [{
        "name": sp["name"], "cat": "span", "ph": "X",
        "ts": sp["start"] * 1e6,
        "dur": max(0.0, (sp["end"] or sp["start"]) - sp["start"]) * 1e6,
        "pid": sp.get("pid", 0), "tid": sp["trace_id"][:8],
        "args": dict(sp["attrs"], status=sp["status"],
                     span_id=sp["span_id"],
                     parent_id=sp["parent_id"] or ""),
    } for sp in spans]


def _otlp_status(status: str) -> Dict[str, Any]:
    """OTLP status object. Error spans carry the recorded detail (the
    exception type after "ERROR: ") as status.message — previously the
    export collapsed every failure to a bare code=2."""
    if status == "OK":
        return {"code": 1}
    detail = status[len("ERROR: "):] if status.startswith("ERROR: ") \
        else status
    return {"code": 2, "message": detail}


def to_otlp_json(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """OTLP/JSON-shaped export for users piping into a collector."""
    return {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name",
             "value": {"stringValue": "ray_tpu"}}]},
        "scopeSpans": [{"scope": {"name": "ray_tpu.util.tracing"},
                        "spans": [{
            "traceId": sp["trace_id"],
            "spanId": sp["span_id"],
            "parentSpanId": sp["parent_id"] or "",
            "name": sp["name"],
            "startTimeUnixNano": int(sp["start"] * 1e9),
            "endTimeUnixNano": int((sp["end"] or sp["start"]) * 1e9),
            "status": _otlp_status(sp["status"]),
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in sp["attrs"].items()],
        } for sp in spans]}],
    }]}


# ---------------------------------------------------- optional real OTel

def _otel_start(s: Span):
    try:
        from opentelemetry import trace as ot

        tracer = ot.get_tracer("ray_tpu")
        span = tracer.start_span(s.name, attributes=s.attrs)
        return span
    except Exception:  # noqa: BLE001 — otel absent or misconfigured
        return None


def _otel_end(otel_span, s: Span) -> None:
    if otel_span is None:
        return
    try:
        otel_span.end()
    except Exception:  # noqa: BLE001
        pass


# ------------------------------------------------- jax.profiler bridging

@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture a jax.profiler device trace around a block — the XLA/TPU
    half of the observability story (view in TensorBoard/Perfetto).
    SURVEY §5.1: host spans come from this module, device timelines from
    the XLA profiler; both land in Perfetto."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
