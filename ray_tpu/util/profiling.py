"""Device profiling: jax.profiler wired into the cluster runtime.

Reference surface: the dashboard/CLI profiling endpoints
(python/ray/dashboard worker profiling, `ray timeline`) — there they
attach py-spy to a worker; on TPU the interesting profile is the DEVICE
trace, so the integration is jax.profiler (XLA's profiler: HLO ops,
TPU step traces, memory viewer) captured either in-process or remotely
on any worker/actor via the worker RPC plane. Traces land in the
session dir (`{session}/profiles/<tag>`) where TensorBoard's profile
plugin (or xprof) reads them.

Driver-side:
    with ray_tpu.util.profiling.profile("step10"):   # in-process
        train_step(...)
    ray_tpu.util.profiling.profile_actor(handle, seconds=5)  # remote
Annotations: `annotate("fwd")` marks regions inside jitted host code
(jax.profiler.TraceAnnotation) so they show up on the trace timeline.
"""
from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Optional

_ACTIVE_DIR: Optional[str] = None


def _default_dir(tag: Optional[str]) -> str:
    from ray_tpu._private.worker import global_worker

    base = getattr(global_worker, "session_dir", None) or "/tmp/ray_tpu"
    tag = tag or time.strftime("%Y%m%d-%H%M%S")
    return os.path.join(base, "profiles", tag)


def start_profile(tag: Optional[str] = None,
                  log_dir: Optional[str] = None) -> str:
    """Begin a jax.profiler trace; returns the trace directory."""
    global _ACTIVE_DIR
    if _ACTIVE_DIR is not None:
        raise RuntimeError(f"profile already running into {_ACTIVE_DIR}")
    import jax

    d = log_dir or _default_dir(tag)
    os.makedirs(d, exist_ok=True)
    jax.profiler.start_trace(d)
    _ACTIVE_DIR = d
    return d


def stop_profile() -> str:
    """End the running trace; returns its directory. On a stop_trace
    failure the module guard stays set, keeping state in sync with
    XLA's (still-open) session so the stop can be retried."""
    global _ACTIVE_DIR
    if _ACTIVE_DIR is None:
        raise RuntimeError("no profile running")
    import jax

    jax.profiler.stop_trace()
    d = _ACTIVE_DIR
    _ACTIVE_DIR = None
    return d


@contextlib.contextmanager
def profile(tag: Optional[str] = None, log_dir: Optional[str] = None):
    """Context-managed device trace around a block of work."""
    d = start_profile(tag, log_dir)
    try:
        yield d
    finally:
        stop_profile()


def annotate(name: str, **kwargs):
    """Named region on the profiler timeline (TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name, **kwargs)


def save_device_memory_profile(path: Optional[str] = None) -> str:
    """Snapshot the device memory profile (pprof format) — jax's
    memory-leak hunting tool, surfaced next to the traces."""
    import jax

    if path is None:
        path = os.path.join(_default_dir(None) + "-memory", "memory.prof")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    jax.profiler.save_device_memory_profile(path)
    return path


# ------------------------------------------------------ remote profiling


def profile_actor(actor, seconds: float = 5.0,
                  tag: Optional[str] = None) -> str:
    """Capture a device trace ON the actor's worker process for
    `seconds` while it keeps serving calls; returns the trace dir path
    on that worker's host. The actor's jitted work during the window
    shows up in the trace (reference: dashboard worker profiling, but
    device-level)."""
    from ray_tpu._private.worker import global_worker

    addr = getattr(actor, "_address", None)
    if addr is None:
        raise TypeError("profile_actor expects an ActorHandle")
    tag = tag or f"actor-{time.strftime('%H%M%S')}"
    client = global_worker.clients.get(tuple(addr))
    d = client.call("start_device_profile", tag, timeout=30.0)
    try:
        time.sleep(seconds)
        return client.call("stop_device_profile", timeout=60.0) or d
    except BaseException:
        # never leave the remote worker tracing forever (unbounded trace
        # growth + every later profile rejected)
        try:
            client.notify("stop_device_profile")
        except Exception:  # noqa: BLE001 — worker may be gone
            pass
        raise


def list_profiles() -> list:
    """Profile trace dirs in this session (driver-local host)."""
    from ray_tpu._private.worker import global_worker

    base = getattr(global_worker, "session_dir", None)
    if base is None:
        return []
    root = os.path.join(base, "profiles")
    if not os.path.isdir(root):
        return []
    return sorted(os.path.join(root, d) for d in os.listdir(root))
