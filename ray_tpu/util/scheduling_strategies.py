"""Scheduling strategies for tasks and actors.

Reference surface: python/ray/util/scheduling_strategies.py
(NodeAffinitySchedulingStrategy) and
src/ray/raylet/scheduling/policy/node_affinity_scheduling_policy.cc for
the semantics: a hard affinity runs ONLY on the named node (waiting if
it is merely busy, failing if it is dead or can never fit the request);
a soft affinity prefers the node and falls back to the default policy
when it is gone or infeasible.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple, Union


@dataclass
class NodeAffinitySchedulingStrategy:
    """Pin a task/actor to `node_id` (from ``ray_tpu.nodes()`` /
    ``list_nodes``). ``soft=True`` degrades to DEFAULT placement when the
    node is dead or can never satisfy the resource request."""

    node_id: str
    soft: bool = False


WireStrategy = Union[str, Tuple[str, str, bool]]


def to_wire(strategy: Any) -> WireStrategy:
    """Normalize a user-facing strategy to its RPC-safe form: the plain
    policy strings pass through; strategy objects become tagged tuples."""
    if strategy is None:
        return "DEFAULT"
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return ("NODE_AFFINITY", str(strategy.node_id), bool(strategy.soft))
    if isinstance(strategy, str):
        if strategy not in ("DEFAULT", "SPREAD"):
            raise ValueError(f"unknown scheduling_strategy {strategy!r} "
                             "(expected 'DEFAULT', 'SPREAD', or a "
                             "NodeAffinitySchedulingStrategy)")
        return strategy
    raise TypeError(f"unsupported scheduling_strategy: {strategy!r}")
