"""Cluster state API — analog of the reference's python/ray/util/state/
(api.py: list_actors :788, list_tasks :1020, list_objects :1066,
summarize_tasks :1382; backed by the dashboard StateHead + GCS
GcsTaskManager). Here the conductor IS the state authority; workers answer
store-stats probes directly."""
from __future__ import annotations

import json
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional


def _conductor():
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        raise RuntimeError("ray_tpu.init() must be called first")
    return w


def list_nodes() -> List[Dict[str, Any]]:
    return _conductor().conductor.call("nodes", timeout=10.0)


def list_workers() -> List[Dict[str, Any]]:
    return _conductor().conductor.call("list_workers", timeout=10.0)


def list_actors(state: Optional[str] = None) -> List[Dict[str, Any]]:
    actors = _conductor().conductor.call("list_actors", timeout=10.0)
    if state is not None:
        actors = [a for a in actors if a.get("state") == state]
    return actors


def list_placement_groups() -> List[Dict[str, Any]]:
    return _conductor().conductor.call("list_placement_groups", timeout=10.0)


def list_tasks(limit: int = 10_000,
               name: Optional[str] = None) -> List[Dict[str, Any]]:
    w = _conductor()
    events = w.conductor.call("get_task_events", limit, timeout=30.0)
    with w._task_events_lock:  # include this process's unflushed batch
        events = events + list(w._task_events)
    if name is not None:
        events = [e for e in events if e.get("name") == name]
    return events


def list_objects() -> List[Dict[str, Any]]:
    """Per-process object-store stats (reference `ray memory` summary)."""
    w = _conductor()
    out = [dict(w.store.stats(), worker_id=w.worker_id, is_driver=True)]
    for rec in list_workers():
        addr = rec.get("address")
        if not addr:
            continue
        try:
            out.append(w.clients.get(tuple(addr)).call("store_stats",
                                                       timeout=5.0))
        except Exception:  # noqa: BLE001 — worker mid-restart
            pass
    return out


def slice_topology(group: Optional[str] = None) -> Dict[str, Any]:
    """Slice maps of jax.distributed gangs that ran a multi-slice
    rendezvous (parallel.distributed.initialize_jax_distributed with a
    slice id): {group_key: {"slices": {slice_id: [ranks]},
    "process_ids": {rank: process_id}, "world": n}}. Rank 0 of each
    gang publishes its map into the conductor KV; this reads it back —
    the state-API analog of `list_placement_groups` for DCN topology."""
    w = _conductor()
    suffix = "/slice_map"
    keys = w.conductor.call("kv_keys", b"", "_jax_distributed",
                            timeout=10.0)
    out: Dict[str, Any] = {}
    for key in keys:
        name = key.decode() if isinstance(key, bytes) else str(key)
        if not name.endswith(suffix):
            continue
        g = name[:-len(suffix)]
        if group is not None and g != group:
            continue
        raw = w.conductor.call("kv_get", key, "_jax_distributed",
                               timeout=10.0)
        if not raw:
            continue
        rec = json.loads(raw.decode())
        out[g] = {
            "slices": {int(s): rs
                       for s, rs in rec.get("slices", {}).items()},
            "process_ids": {int(r): p for r, p
                            in rec.get("process_ids", {}).items()},
            "world": rec.get("world"),
        }
    return out


def train_progress(run: Optional[str] = None) -> Dict[str, Any]:
    """Gang-wide training telemetry (the flight recorder's state-API
    surface): {run_id: {world, last_step, per_rank: {rank: {mean_ms,
    p50_ms, p99_ms, tokens_per_sec, mfu, ...}}, last_step_skew,
    last_step_breakdown, stragglers}}. Ranks ship per-step records with
    their metric/span batches; the conductor aggregates (see
    ray_tpu.observability.gang). `run` filters to one run id."""
    out = _conductor().conductor.call("get_train_progress", timeout=30.0)
    if run is not None:
        out = {k: v for k, v in out.items() if k == run}
    return out


def weight_versions(name: Optional[str] = None) -> Dict[str, Any]:
    """Live weight fabric registry state (ray_tpu.weights): per name the
    latest committed version and the kept manifests' summaries
    (version, step, run_id, bytes, host/leaf/chunk counts), plus any
    in-flight (pending) publishes. The CLI analog is
    `python -m ray_tpu weights list`; the dashboard serves it at
    /api/weights. `name` filters to one weight set."""
    out = _conductor().conductor.call("get_weight_versions", timeout=10.0)
    if name is not None:
        out = {"names": {k: v for k, v in out.get("names", {}).items()
                         if k == name},
               "pending": [p for p in out.get("pending", [])
                           if p.get("name") == name]}
    return out


def kv_cache_stats(engine: Optional[str] = None) -> Dict[str, Any]:
    """Paged-KV prefix-cache view (models/kvcache.py): per-engine stat
    snapshots (hits/misses/evictions, pool utilization, reused vs
    prefilled tokens) plus cluster totals with hit/token-reuse rates.
    The CLI analog is `python -m ray_tpu kvcache`; the dashboard serves
    it at /api/kvcache. `engine` filters to one engine id."""
    out = _conductor().conductor.call("get_kvcache_stats", timeout=10.0)
    if engine is not None:
        out = {"engines": {k: v for k, v in out.get("engines",
                                                    {}).items()
                           if v.get("engine_id") == engine},
               "totals": out.get("totals", {})}
    return out


def speculation_totals(engines: Dict[str, Dict[str, Any]]
                       ) -> Dict[str, Any]:
    """The ONE speculation rollup (counter sums + acceptance rate +
    tokens-per-verify) — shared by the conductor's
    get_speculation_stats and this module's engine filter so a new
    counter can never make the filtered view disagree with the
    cluster-wide one."""
    totals: Dict[str, Any] = {
        k: sum(int(e.get(k, 0)) for e in engines.values())
        for k in ("spec_proposed", "spec_accepted",
                  "spec_verify_ticks", "spec_emitted_tokens")}
    totals["acceptance_rate"] = (
        totals["spec_accepted"] / totals["spec_proposed"]
        if totals["spec_proposed"] else 0.0)
    totals["tokens_per_verify"] = (
        totals["spec_emitted_tokens"] / totals["spec_verify_ticks"]
        if totals["spec_verify_ticks"] else 0.0)
    totals["engines"] = len(engines)
    return totals


def speculation_stats(engine: Optional[str] = None) -> Dict[str, Any]:
    """Speculative-decoding view (models/engine.py): per-engine draft
    counters (proposed/accepted, verify ticks, tokens-per-verify,
    acceptance rate, the int8-KV flag) plus cluster totals. Rides the
    SAME conductor snapshots as kv_cache_stats() — one report channel,
    one set of numbers. The CLI analog is `python -m ray_tpu
    speculate`; the dashboard serves it at /api/speculation;
    spec_accept/spec_reject markers ride the merged timeline's kvcache
    lane. `engine` filters to one engine id."""
    out = _conductor().conductor.call("get_speculation_stats",
                                      timeout=10.0)
    if engine is not None:
        engines = {k: v for k, v in out.get("engines", {}).items()
                   if v.get("engine_id") == engine}
        # totals must describe the FILTERED view, or the one engine
        # shown disagrees with the summary printed beside it
        out = {"engines": engines,
               "totals": speculation_totals(engines)}
    return out


def pipeline_status(name: Optional[str] = None) -> Dict[str, Any]:
    """MPMD pipeline view (ray_tpu.mpmd): per-pipeline stage registry
    (formed flag, per-stage slice/worker identity), per-stage run stats
    (steps, bubble_fraction, channel bytes), cross-stage totals, and the
    channel-mailbox depth. The CLI analog is `python -m ray_tpu
    pipeline`; the dashboard serves it at /api/pipeline. `name` filters
    to one pipeline."""
    out = _conductor().conductor.call("get_pipeline_status", timeout=10.0)
    if name is not None:
        out = {"pipelines": {k: v for k, v
                             in out.get("pipelines", {}).items()
                             if k == name},
               "mailbox_depth": out.get("mailbox_depth")}
    return out


def online_status() -> Dict[str, Any]:
    """Online learning loop view (ray_tpu.online): per-component stat
    snapshots grouped by role — samplers (rollouts, tokens, serving/
    latest version, staleness incl. its high-water mark), the rollout
    buffer (occupancy, capacity, backpressured puts), the learner
    (steps, ingested rollouts/tokens, last published version) — plus
    cluster totals. The CLI analog is `python -m ray_tpu online`; the
    dashboard serves it at /api/online."""
    return _conductor().conductor.call("get_online_status",
                                       timeout=10.0)


def disagg_status() -> Dict[str, Any]:
    """Disaggregated-serving view (serve/disagg.py): per-component stat
    snapshots grouped by role — prefill servers (prefills, prefix
    reuse, published transfers/bytes), decode servers (transfers, KV
    bytes split shm/rpc, adoptions, free slots, prefill-program count —
    flat on a pure decode replica), routers (dispatched, shed, live and
    high-water queue depth) — plus cluster totals. The CLI analog is
    `python -m ray_tpu disagg`; the dashboard serves it at
    /api/disagg."""
    return _conductor().conductor.call("get_disagg_status",
                                       timeout=10.0)


def kvplane_status() -> Dict[str, Any]:
    """Global KV plane view (serve/kvplane.py): per-component
    snapshots — prefill arenas (tier-2 entries/bytes, spills, hits,
    re-adopted tokens), tier-3 publish/adopt counters, routers'
    directory routing outcomes (hit/fallback/miss) — plus cluster
    totals with tier-2 hit rate and directory hit rate, and the
    conductor-side prefix directory summary (entries, bytes, per-
    namespace counts, commit/reap/GC counters). The CLI analog is
    `python -m ray_tpu kvplane`; the dashboard serves it at
    /api/kvplane; spill/tier2_hit/tier3_publish/tier3_adopt/
    directory_hit markers ride the merged timeline's `kvplane`
    lane."""
    return _conductor().conductor.call("get_kvplane_status",
                                       timeout=10.0)


def lora_status() -> Dict[str, Any]:
    """Multi-tenant LoRA serving view (serve/lora.py): per-pool
    adapter-paging snapshots (slots, residents, hits/misses/evictions/
    hot-swaps, page-in bytes), per-router tenant request counters
    (dispatched/completed/shed/SLO misses with recent TTFT/latency
    windows), a per-tenant rollup, and cluster totals. The CLI analog
    is `python -m ray_tpu lora`; the dashboard serves it at
    /api/lora; page_in/evict/swap markers ride the merged timeline's
    `lora` lane."""
    return _conductor().conductor.call("get_lora_status",
                                      timeout=10.0)


def gateway_status() -> Dict[str, Any]:
    """HTTP front-door view (serve/gateway.py): per-replica request
    counters split by priority class (interactive/batch accepted/
    completed/shed/disconnects) and status code, recent TTFT windows
    per class, QoS gate admission/rejection stats, batch-slot
    preemptions — plus cluster totals. The CLI analog is `python -m
    ray_tpu gateway`; the dashboard serves it at /api/gateway; the
    accept/first_byte/preempt/rate_limit/disconnect markers ride the
    merged timeline's `gateway` lane."""
    return _conductor().conductor.call("get_gateway_status",
                                       timeout=10.0)


def requesttrace_status() -> Dict[str, Any]:
    """Per-request flight-recorder view (observability/requests.py):
    per-store retention counters (completed/kept/dropped, outcomes,
    replayed + preempted requests), the cluster-wide slowest-request
    list with per-phase breakdowns, and the p99-attribution report
    that diffs per-phase time between the p50 and p99 cohorts and
    names the phase that owns the tail. The CLI analog is `python -m
    ray_tpu requests`; the dashboard serves it at /api/requesttrace;
    kept traces render as real spans in the merged timeline's
    `requests` lane."""
    return _conductor().conductor.call("get_requesttrace_status",
                                       timeout=10.0)


def request_trace(request_id: str) -> Optional[Dict[str, Any]]:
    """One request's full kept trace by id (None when it was sampled
    out or has aged past the retention budget): outcome, attempts,
    per-phase spans tagged with their attempt number — failover and
    preemption replays read as child spans under the same id — plus
    any remote child phases actor-mode tiers pushed."""
    return _conductor().conductor.call("get_request_trace",
                                       str(request_id), timeout=10.0)


def servefault_status() -> Dict[str, Any]:
    """Serving-plane fault-tolerance view (serve/disagg.py failover +
    serve/autoscale.py self-healing): per-router failover counts by
    phase, sheds by attributed cause (capacity/deadline/failover/
    draining), corpses removed, recent failover-recovery latency;
    per-healer replica deaths, replacements, breaker trips and open
    hosts — plus cluster totals. The failover/replace/breaker_trip
    instant markers live in the merged timeline's RESILIENCE lane. The
    CLI analog is `python -m ray_tpu servefault`; the dashboard serves
    it at /api/servefault."""
    return _conductor().conductor.call("get_servefault_status",
                                       timeout=10.0)


def autoscaler_status() -> Dict[str, Any]:
    """Serving-autoscaler view (serve/autoscale.py): per-loop status
    snapshots (per-tier targets and bounds, scale-up/down decision
    counts, drain outcomes, replica-seconds — the provisioning cost the
    policy minimizes, last decision reason) plus cluster totals. The
    CLI analog is `python -m ray_tpu autoscale`; the dashboard serves
    it at /api/autoscale. (The NODE-level autoscaler —
    ray_tpu.autoscaler, which launches/terminates hosts — mirrors its
    status separately at /api/autoscaler.)"""
    return _conductor().conductor.call("get_autoscale_status",
                                       timeout=10.0)


def oracle_status() -> Dict[str, Any]:
    """Step-time oracle view (observability.roofline): the latest
    roofline prediction per layout ({device_step, ici_wait, dcn_wait}
    breakdown + predicted total), the predicted-vs-measured validation
    tail (per-phase residuals, fitted calibration), and totals. The CLI
    analog is `python -m ray_tpu oracle`; the dashboard serves it at
    /api/oracle."""
    return _conductor().conductor.call("get_oracle_status",
                                       timeout=10.0)


def resilience_status() -> Dict[str, Any]:
    """Recovery-subsystem view (ray_tpu.resilience): per-host failure
    scores with quarantine/drain flags, the excluded host list, event
    counters (preemption/restart/quarantine/grace_checkpoint/...),
    last time-to-recovery, and the most recent events. The CLI analog
    is `python -m ray_tpu resilience-status`; the dashboard serves it
    at /api/resilience."""
    return _conductor().conductor.call("get_resilience_status",
                                       timeout=10.0)


def summarize_tasks() -> Dict[str, Any]:
    """Group task events by name — reference api.py summarize_tasks :1382."""
    groups: Dict[str, Dict[str, Any]] = defaultdict(
        lambda: {"count": 0, "failed": 0, "total_s": 0.0,
                 "min_s": float("inf"), "max_s": 0.0})
    for ev in list_tasks():
        g = groups[ev["name"]]
        dur = max(0.0, ev["end"] - ev["start"])
        g["count"] += 1
        g["failed"] += 1 if ev.get("status") == "FAILED" else 0
        g["total_s"] += dur
        g["min_s"] = min(g["min_s"], dur)
        g["max_s"] = max(g["max_s"], dur)
    for g in groups.values():
        g["mean_s"] = g["total_s"] / max(1, g["count"])
        if g["min_s"] == float("inf"):
            g["min_s"] = 0.0
    return dict(groups)


def timeline(filename: Optional[str] = None,
             merged: bool = False) -> List[Dict[str, Any]]:
    """Chrome-trace export of task events — reference `ray timeline`
    (scripts.py; ProfileEvents via GcsTaskManager). Load the output in
    chrome://tracing or Perfetto.

    merged=True produces the unified flight-recorder timeline instead:
    task events + tracing spans + training step markers in one trace
    (`python -m ray_tpu timeline --merged`)."""
    if merged:
        from ray_tpu.observability.timeline import merged_timeline

        return merged_timeline(filename)
    from ray_tpu.observability.timeline import task_trace_events

    trace = task_trace_events(list_tasks())
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


# ---------------------------------------------------------------- metrics

def _prom_escape(s: str) -> str:
    return s.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def prometheus_metrics() -> str:
    """Render all pushed metric snapshots in Prometheus text exposition
    format — reference python/ray/_private/prometheus_exporter.py. Samples
    are grouped per metric family (HELP/TYPE once, then ALL of the family's
    series contiguously, across workers) as strict parsers require."""
    per_worker = _conductor().conductor.call("get_metrics", timeout=10.0)
    return _render_prometheus(per_worker)


def _render_prometheus(per_worker: Dict[str, Any]) -> str:
    """Pure renderer over the conductor's per-worker snapshots (shared
    with the dashboard, which has no global_worker)."""
    # family name -> list of (worker_id, snapshot dict)
    families: Dict[str, List[Any]] = {}
    for worker_id, snapshot in sorted(per_worker.items()):
        for m in snapshot:
            families.setdefault(m["name"], []).append((worker_id, m))

    def labels(keys, tag_json: str, worker_id: str, extra: str = "") -> str:
        vals = json.loads(tag_json) if tag_json else []
        parts = [f'{k}="{_prom_escape(v)}"' for k, v in zip(keys, vals)]
        parts.append(f'WorkerId="{worker_id[:12]}"')
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}"

    lines: List[str] = []
    for name, members in families.items():
        first = members[0][1]
        if first.get("description"):
            lines.append(f"# HELP {name} "
                         f"{_prom_escape(first['description'])}")
        mtype = first["type"] if first["type"] != "untyped" else "gauge"
        lines.append(f"# TYPE {name} {mtype}")
        for worker_id, m in members:
            keys = list(m.get("tag_keys") or ())
            if m["type"] == "histogram":
                for tag_json, buckets in m.get("buckets", {}).items():
                    acc = 0
                    for bound, n in zip(m["boundaries"], buckets):
                        acc += n
                        le = f'le="{bound}"'
                        lines.append(
                            f"{name}_bucket"
                            f"{labels(keys, tag_json, worker_id, le)}"
                            f" {acc}")
                    acc += buckets[-1]
                    inf = 'le="+Inf"'
                    lines.append(
                        f"{name}_bucket"
                        f"{labels(keys, tag_json, worker_id, inf)}"
                        f" {acc}")
                    lines.append(f"{name}_sum"
                                 f"{labels(keys, tag_json, worker_id)} "
                                 f"{m['sums'][tag_json]}")
                    lines.append(f"{name}_count"
                                 f"{labels(keys, tag_json, worker_id)} "
                                 f"{m['counts'][tag_json]}")
            else:
                for tag_json, v in m.get("values", {}).items():
                    lines.append(
                        f"{name}{labels(keys, tag_json, worker_id)} {v}")
    return "\n".join(lines) + ("\n" if lines else "")


def rpc_stats() -> Dict[str, Dict[str, float]]:
    """Control-plane dispatch latency by RPC method (count, mean/max
    queue and handler ms) — see ConductorHandler.get_rpc_stats."""
    return _conductor().conductor.call("get_rpc_stats", timeout=10.0)


def cluster_summary() -> Dict[str, Any]:
    """One-call overview — reference `ray status`."""
    w = _conductor()
    return {
        "timestamp": time.time(),
        "nodes": list_nodes(),
        "resources_total": w.conductor.call("cluster_resources",
                                            timeout=10.0),
        "resources_available": w.conductor.call("available_resources",
                                                timeout=10.0),
        "num_actors": len(list_actors()),
        "num_workers": len(list_workers()),
        "placement_groups": list_placement_groups(),
    }
