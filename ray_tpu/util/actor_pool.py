"""ActorPool — API of the reference's python/ray/util/actor_pool.py:
map/submit over a fixed set of actors with free/busy bookkeeping."""
from __future__ import annotations

from typing import Any, Callable, Iterable, List


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        if not self._idle:
            raise ValueError("ActorPool needs at least one actor")
        self._future_to_actor = {}
        self._pending_submits: List[tuple] = []

    def submit(self, fn: Callable, value: Any) -> None:
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref.id] = (ref, actor)
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def get_next(self, timeout: float = None) -> Any:
        import ray_tpu

        if not self._future_to_actor:
            raise StopIteration("no pending results")
        refs = [ref for ref, _ in self._future_to_actor.values()]
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next timed out")
        ref = ready[0]
        _, actor = self._future_to_actor.pop(ref.id)
        self._return_actor(actor)
        return ray_tpu.get(ref)

    def get_next_unordered(self, timeout: float = None) -> Any:
        return self.get_next(timeout)

    def _return_actor(self, actor) -> None:
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            ref = fn(actor, value)
            self._future_to_actor[ref.id] = (ref, actor)
        else:
            self._idle.append(actor)

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        return self.map(fn, values)

    def has_free(self) -> bool:
        return bool(self._idle)
