"""Small runtime guards shared across subsystems."""
from __future__ import annotations


def require_worker(what: str):
    """The connected global worker, or a clear error naming the
    operation that needed it. One implementation for every subsystem
    that fails without a cluster (weights, mpmd channels, ...)."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        raise RuntimeError(
            f"ray_tpu.init() must be called before {what}")
    return w


def pipeline_run_token(run_id: str) -> str:
    """One path-safe key segment for an MPMD pipeline generation ("/"
    is the channel-key separator). The ONE encoding both sides of the
    generation fence use: mpmd.channels builds keys with it and the
    conductor's pipeline_channel_put parses them against it — a
    divergence would reject every send as a wrong-generation key."""
    return (run_id or "default").replace("/", ":")


__all__ = ["pipeline_run_token", "require_worker"]
