"""Distributed FIFO queue backed by an asyncio actor.

Same surface as the reference's `ray.util.queue.Queue`
(/root/reference/python/ray/util/queue.py:21-305): bounded or unbounded,
blocking put/get with timeouts, *_nowait and *_nowait_batch variants, and
async put/get coroutines. The actor holds an asyncio.Queue, so blocked
producers/consumers park on the actor's event loop instead of pinning
executor threads — many callers can block concurrently on one queue
actor.
"""
from __future__ import annotations

import asyncio
from queue import Empty, Full  # re-exported, same as the reference
from typing import Any, List, Optional

import ray_tpu
from ray_tpu.exceptions import TaskError

__all__ = ["Queue", "Empty", "Full"]


def _queue_error(exc: BaseException) -> Optional[Exception]:
    """Map an actor-side failure back to the stdlib queue exception the
    reference raises: actor errors arrive wrapped in TaskError, and the
    nowait paths raise asyncio.QueueEmpty/QueueFull (which do NOT
    subclass queue.Empty/Full)."""
    cause = exc.cause if isinstance(exc, TaskError) else exc
    if isinstance(cause, (Full, asyncio.QueueFull)):
        return Full(*getattr(cause, "args", ()))
    if isinstance(cause, (Empty, asyncio.QueueEmpty)):
        return Empty(*getattr(cause, "args", ()))
    return None


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.queue: asyncio.Queue = asyncio.Queue(maxsize)

    def qsize(self) -> int:
        return self.queue.qsize()

    def empty(self) -> bool:
        return self.queue.empty()

    def full(self) -> bool:
        return self.queue.full()

    async def put(self, item: Any,
                  timeout: Optional[float] = None) -> None:
        if timeout is None:
            await self.queue.put(item)
            return
        try:
            await asyncio.wait_for(self.queue.put(item), timeout)
        except asyncio.TimeoutError:
            raise Full from None

    async def get(self, timeout: Optional[float] = None) -> Any:
        if timeout is None:
            return await self.queue.get()
        try:
            return await asyncio.wait_for(self.queue.get(), timeout)
        except asyncio.TimeoutError:
            raise Empty from None

    def put_nowait(self, item: Any) -> None:
        self.queue.put_nowait(item)

    def put_nowait_batch(self, items: List[Any]) -> None:
        # all-or-nothing, like the reference (queue.py:280)
        if self.maxsize > 0 and \
                self.queue.qsize() + len(items) > self.maxsize:
            raise Full(f"batch of {len(items)} does not fit in a queue "
                       f"holding {self.queue.qsize()}/{self.maxsize}")
        for item in items:
            self.queue.put_nowait(item)

    def get_nowait(self) -> Any:
        return self.queue.get_nowait()

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        if num_items > self.queue.qsize():
            raise Empty(f"{num_items} requested, "
                        f"{self.queue.qsize()} available")
        return [self.queue.get_nowait() for _ in range(num_items)]


class Queue:
    """Actor-backed FIFO shared by any number of tasks/actors.

    `maxsize <= 0` means unbounded. `actor_options` are forwarded to the
    underlying actor (e.g. placement, name, lifetime)."""

    def __init__(self, maxsize: int = 0,
                 actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        # max_concurrency bounds how many callers may block on the actor
        # at once (each blocked put/get holds one concurrency slot while
        # its coroutine parks on the actor's event loop)
        opts = {"max_concurrency": 64}
        opts.update(actor_options or {})
        self.actor = ray_tpu.remote(_QueueActor).options(**opts).remote(
            maxsize)

    def __reduce__(self):
        return (_rebuild_queue, (self.maxsize, self.actor))

    @staticmethod
    def _get(ref):
        """ray_tpu.get with actor-side queue errors mapped back to the
        stdlib queue.Empty/queue.Full the caller expects."""
        try:
            return ray_tpu.get(ref)
        except TaskError as e:
            qe = _queue_error(e)
            if qe is None:
                raise
            raise qe from None

    @staticmethod
    async def _get_async(ref):
        try:
            return await ray_tpu.get_async(ref)
        except TaskError as e:
            qe = _queue_error(e)
            if qe is None:
                raise
            raise qe from None

    def qsize(self) -> int:
        return self._get(self.actor.qsize.remote())

    def size(self) -> int:
        return self.qsize()

    def empty(self) -> bool:
        return self._get(self.actor.empty.remote())

    def full(self) -> bool:
        return self._get(self.actor.full.remote())

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            self._get(self.actor.put_nowait.remote(item))
            return
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        self._get(self.actor.put.remote(item, timeout))

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        if not block:
            return self._get(self.actor.get_nowait.remote())
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        return self._get(self.actor.get.remote(timeout))

    async def put_async(self, item: Any, block: bool = True,
                        timeout: Optional[float] = None) -> None:
        if not block:
            await self._get_async(self.actor.put_nowait.remote(item))
            return
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        await self._get_async(self.actor.put.remote(item, timeout))

    async def get_async(self, block: bool = True,
                        timeout: Optional[float] = None) -> Any:
        if not block:
            return await self._get_async(
                self.actor.get_nowait.remote())
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        return await self._get_async(self.actor.get.remote(timeout))

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        self._get(self.actor.put_nowait_batch.remote(list(items)))

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        return self._get(self.actor.get_nowait_batch.remote(num_items))

    def shutdown(self, force: bool = False) -> None:
        """Terminate the backing actor; the queue is unusable after."""
        if self.actor is not None:
            ray_tpu.kill(self.actor)
        self.actor = None


def _rebuild_queue(maxsize: int, actor) -> Queue:
    q = Queue.__new__(Queue)
    q.maxsize = maxsize
    q.actor = actor
    return q
