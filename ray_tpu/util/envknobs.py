"""Cached ``RAY_TPU_*`` environment-knob accessors.

The runtime's ~40 knobs were historically parsed ad hoc at call sites
— an environ probe plus ``int()``/``float()`` (and its try/except) on
every read, including per-tick paths like the metrics pusher. This
module is the ONE cached parse: each accessor memoizes the parsed
value keyed on the *raw* environment string, so

- a hot loop pays one dict probe + string compare per read, never a
  re-parse;
- a live process stays retunable (and monkeypatching tests keep
  working): changing the env var changes the raw string, which misses
  the memo and re-parses.

Unparseable values fall back to the call-site default instead of
raising — a typo'd knob must not take down a worker at an arbitrary
read site. Env names and semantics are unchanged from the historical
call-site parses; shardlint's env-knob registry (``ray_tpu analyze
--invariants``) recognizes ``get_*("RAY_TPU_X", default)`` calls as
cached reads and folds them into the canonical knob table.

Stdlib-only: imported by worker bootstrap paths where jax may be
absent or broken.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional, Tuple

# knob name -> (raw env string at parse time, parsed value)
_memo: Dict[str, Tuple[Optional[str], Any]] = {}
_lock = threading.Lock()

_TRUTHY = frozenset({"1", "true", "yes", "on"})
_FALSY = frozenset({"0", "false", "no", "off", ""})


def _resolve(name: str, default: Any, parse: Callable[[str], Any]) -> Any:
    raw = os.environ.get(name)
    with _lock:
        hit = _memo.get(name)
        if hit is not None and hit[0] == raw:
            return hit[1]
    if raw is None:
        val = default
    else:
        try:
            val = parse(raw)
        except (TypeError, ValueError):
            val = default
    with _lock:
        _memo[name] = (raw, val)
    return val


def get_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """The raw string (memoized like the rest for uniformity)."""
    return _resolve(name, default, str)


def get_int(name: str, default: int = 0) -> int:
    return _resolve(name, default, int)


def get_float(name: str, default: float = 0.0) -> float:
    return _resolve(name, default, float)


def _parse_bool(raw: str) -> bool:
    low = raw.strip().lower()
    if low in _TRUTHY:
        return True
    if low in _FALSY:
        return False
    raise ValueError(raw)


def get_bool(name: str, default: bool = False) -> bool:
    """1/true/yes/on and 0/false/no/off (case-insensitive); anything
    else falls back to the default. Knobs with historical exact-match
    semantics (``== "1"`` / ``!= "0"``) keep those via get_str."""
    return _resolve(name, default, _parse_bool)


def clear() -> None:
    """Drop the memo (tests that replace os.environ wholesale)."""
    with _lock:
        _memo.clear()


__all__ = ["get_str", "get_int", "get_float", "get_bool", "clear"]
