"""joblib backend over ray_tpu — analog of the reference's
python/ray/util/joblib/ (register_ray + RayBackend on the multiprocessing
Pool shim). Usage:

    from ray_tpu.util.joblib import register_ray_tpu
    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        Parallel()(delayed(f)(i) for i in range(100))
"""
from __future__ import annotations

from joblib._parallel_backends import MultiprocessingBackend
from joblib.parallel import register_parallel_backend


class RayTpuBackend(MultiprocessingBackend):
    """Reference util/joblib/ray_backend.py RayBackend — reuses joblib's
    pool-based backend with our Pool as the factory."""

    supports_timeout = True

    def effective_n_jobs(self, n_jobs):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        eff = int(ray_tpu.cluster_resources().get("CPU", 1))
        if n_jobs is None or n_jobs == -1:
            return eff
        return min(abs(n_jobs), eff) if n_jobs else 1

    def configure(self, n_jobs=1, parallel=None, prefer=None, require=None,
                  **memmapping_kwargs):
        from .multiprocessing import Pool

        n_jobs = self.effective_n_jobs(n_jobs)
        self._pool = Pool(processes=n_jobs)
        self.parallel = parallel
        return n_jobs

    def terminate(self):
        if getattr(self, "_pool", None) is not None:
            self._pool.terminate()
            self._pool = None


def register_ray_tpu() -> None:
    register_parallel_backend("ray_tpu", RayTpuBackend)
