"""Native (C++) tasks on the ray_tpu transport.

Reference analog: the C++ worker API (SURVEY §2.1 — the reference lets
you write tasks/actors in C++ against its gRPC core). ray_tpu's rebuild
keeps workers python-hosted (the control plane speaks pickled frames)
and gives native code a stable bytes-in/bytes-out C ABI instead — see
``ray_tpu/cpp/ray_tpu_task.h``. A task is any ``extern "C"`` symbol in
a shared library; the executing worker dlopens the library once
(cached per process) and calls it via ctypes, so the native code runs
in the worker with no serialization reimplementation and no build-time
coupling to the framework.

    f = cpp_function("./libmytasks.so", "sum_doubles")
    out: bytes = ray_tpu.get(f.remote(payload_bytes))

``cpp_actor`` wraps a library as an actor class whose methods are the
exported symbols — native state lives behind the ABI on the C++ side
(opaque handle returned by an init symbol).
"""
from __future__ import annotations

import ctypes
import os
from typing import Any, Dict, Optional

import ray_tpu

__all__ = ["cpp_function", "cpp_actor", "header_path"]

_LIBS: Dict[str, ctypes.CDLL] = {}


def header_path() -> str:
    """Path of ray_tpu_task.h for user build lines (-I$(dirname ...))."""
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "cpp", "ray_tpu_task.h")


def _load(lib_path: str) -> ctypes.CDLL:
    lib_path = os.path.abspath(lib_path)
    lib = _LIBS.get(lib_path)
    if lib is None:
        lib = ctypes.CDLL(lib_path)
        _LIBS[lib_path] = lib
    return lib


def _call_native(lib_path: str, symbol: str, payload: bytes) -> bytes:
    """Executor-side: dlopen (cached) + call the bytes ABI."""
    lib = _load(lib_path)
    fn = getattr(lib, symbol)
    fn.restype = ctypes.c_int64
    fn.argtypes = [ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
                   ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                   ctypes.POINTER(ctypes.c_size_t)]
    buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload) \
        if payload else (ctypes.c_uint8 * 1)()
    out_ptr = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t(0)
    rc = fn(buf, len(payload), ctypes.byref(out_ptr),
            ctypes.byref(out_len))
    if rc != 0:
        raise RuntimeError(
            f"native task {symbol} in {os.path.basename(lib_path)} "
            f"failed with code {rc}")
    try:
        return ctypes.string_at(out_ptr, out_len.value) \
            if out_ptr else b""
    finally:
        if out_ptr:
            libc = ctypes.CDLL(None)
            libc.free(out_ptr)


def cpp_function(lib_path: str, symbol: str, **remote_options: Any):
    """A remote function executing `symbol` from `lib_path` on a worker
    (bytes in, bytes out). The library path must be reachable on worker
    hosts — stage it via runtime_env working_dir for multi-host."""
    lib_path = os.path.abspath(lib_path)

    def task(payload: bytes = b"", *, _lib=lib_path, _sym=symbol) -> bytes:
        from ray_tpu.util.cpp import _call_native

        return _call_native(_lib, _sym, bytes(payload))

    task.__name__ = f"cpp:{symbol}"
    rf = ray_tpu.remote(task)
    return rf.options(**remote_options) if remote_options else rf


def cpp_actor(lib_path: str, symbols: list,
              init_symbol: Optional[str] = None, **actor_options: Any):
    """An actor class whose methods call exported symbols of `lib_path`
    with the same bytes ABI, sharing the dlopened library (and any
    native state behind it) across calls. `init_symbol`, when given, is
    invoked once at construction with the init payload."""
    lib_path = os.path.abspath(lib_path)
    syms = list(symbols)

    class _CppActor:
        def __init__(self, init_payload: bytes = b""):
            from ray_tpu.util.cpp import _call_native, _load

            _load(lib_path)
            if init_symbol:
                _call_native(lib_path, init_symbol, bytes(init_payload))

        def call(self, symbol: str, payload: bytes = b"") -> bytes:
            from ray_tpu.util.cpp import _call_native

            if symbol not in syms:
                raise AttributeError(
                    f"symbol {symbol!r} not exported by this cpp_actor "
                    f"(declared: {syms})")
            return _call_native(lib_path, symbol, bytes(payload))

    for s in syms:
        def _m(self, payload: bytes = b"", _s=s) -> bytes:
            return self.call(_s, payload)

        _m.__name__ = s
        setattr(_CppActor, s, _m)
    _CppActor.__name__ = f"CppActor_{os.path.basename(lib_path)}"
    rc = ray_tpu.remote(_CppActor)
    return rc.options(**actor_options) if actor_options else rc
