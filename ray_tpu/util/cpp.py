"""Native (C++) tasks on the ray_tpu transport.

Reference analog: the C++ worker API (SURVEY §2.1 — the reference lets
you write tasks/actors in C++ against its gRPC core). ray_tpu's rebuild
keeps workers python-hosted (the control plane speaks pickled frames)
and gives native code a stable bytes-in/bytes-out C ABI instead — see
``ray_tpu/cpp/ray_tpu_task.h``. A task is any ``extern "C"`` symbol in
a shared library; the executing worker dlopens the library once
(cached per process) and calls it via ctypes, so the native code runs
in the worker with no serialization reimplementation and no build-time
coupling to the framework.

    f = cpp_function("./libmytasks.so", "sum_doubles")
    out: bytes = ray_tpu.get(f.remote(payload_bytes))

``cpp_actor`` wraps a library as an actor class whose methods are the
exported symbols — native state lives behind the ABI on the C++ side
(opaque handle returned by an init symbol).

``cpp_function(lib, sym, api=True)`` selects the v2 ABI
(``ray_tpu/cpp/ray_tpu_api.h``): the task receives a table of runtime
entry points — put/get/submit/release — mirroring the reference C++
driver surface (cpp/include/ray/api.h ray::Put/Get/Task().Remote()), so
native code can create cluster objects and fan out subtasks.
"""
from __future__ import annotations

import ctypes
import os
from typing import Any, Dict, Optional

import ray_tpu

__all__ = ["cpp_function", "cpp_actor", "header_path"]

_LIBS: Dict[str, ctypes.CDLL] = {}


def header_path() -> str:
    """Path of ray_tpu_task.h for user build lines (-I$(dirname ...))."""
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "cpp", "ray_tpu_task.h")


def _load(lib_path: str) -> ctypes.CDLL:
    lib_path = os.path.abspath(lib_path)
    lib = _LIBS.get(lib_path)
    if lib is None:
        lib = ctypes.CDLL(lib_path)
        _LIBS[lib_path] = lib
    return lib


def _invoke_native(lib_path: str, symbol: str, payload: bytes,
                   api: Optional[Any] = None) -> bytes:
    """Executor-side: dlopen (cached) + call the bytes ABI; with `api`,
    the v2 form that passes the runtime table first."""
    lib = _load(lib_path)
    fn = getattr(lib, symbol)
    fn.restype = ctypes.c_int64
    base = [ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t)]
    fn.argtypes = ([ctypes.POINTER(_ApiStruct)] + base) \
        if api is not None else base
    buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload) \
        if payload else (ctypes.c_uint8 * 1)()
    out_ptr = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t(0)
    args = [buf, len(payload), ctypes.byref(out_ptr),
            ctypes.byref(out_len)]
    if api is not None:
        args.insert(0, ctypes.byref(api))
    rc = fn(*args)
    if rc != 0:
        raise RuntimeError(
            f"native task {symbol} in {os.path.basename(lib_path)} "
            f"failed with code {rc}")
    try:
        return ctypes.string_at(out_ptr, out_len.value) \
            if out_ptr else b""
    finally:
        if out_ptr:
            ctypes.CDLL(None).free(out_ptr)


def _call_native(lib_path: str, symbol: str, payload: bytes) -> bytes:
    return _invoke_native(lib_path, symbol, payload)


# ---------------------------------------------------------------- v2 API
# (ray_tpu_api.h: put/get/submit/release handed to native tasks —
# reference cpp/include/ray/api.h ray::Put/Get/Task().Remote())

_PUT_T = ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_void_p,
                          ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
                          ctypes.c_void_p)
_GET_T = ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_void_p,
                          ctypes.c_char_p, ctypes.c_double,
                          ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                          ctypes.POINTER(ctypes.c_size_t))
_SUBMIT_T = ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_void_p,
                             ctypes.c_char_p,
                             ctypes.POINTER(ctypes.c_uint8),
                             ctypes.c_size_t, ctypes.c_void_p)
_RELEASE_T = ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_void_p,
                              ctypes.c_char_p)
_FREE_T = ctypes.CFUNCTYPE(None, ctypes.POINTER(ctypes.c_uint8))
# id_out params are c_void_p: a c_char_p arg would reach the callback as
# an immutable bytes COPY and _write_id would scribble on that copy, not
# the caller's buffer (same convention as _PUT_T/_SUBMIT_T)
_CREATE_ACTOR_T = ctypes.CFUNCTYPE(
    ctypes.c_int64, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t, ctypes.c_void_p)
_CALL_ACTOR_T = ctypes.CFUNCTYPE(
    ctypes.c_int64, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t, ctypes.c_void_p)
_KILL_ACTOR_T = ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_void_p,
                                 ctypes.c_char_p)


class _ApiStruct(ctypes.Structure):
    _fields_ = [("ctx", ctypes.c_void_p), ("put", _PUT_T),
                ("get", _GET_T), ("submit", _SUBMIT_T),
                ("release", _RELEASE_T), ("free_buf", _FREE_T),
                # v2.1 appended fields (ABI-compatible extension)
                ("create_actor", _CREATE_ACTOR_T),
                ("call_actor", _CALL_ACTOR_T),
                ("kill_actor", _KILL_ACTOR_T)]


# id -> ObjectRef pins for objects minted through the native API (per
# worker process; released via api->release or at process exit)
_API_REFS: Dict[str, Any] = {}
_API_ACTORS: Dict[str, Any] = {}   # handle id -> ActorHandle (native API)
_API_STRUCTS: Dict[str, Any] = {}  # lib_path -> (_ApiStruct, callbacks)


def _libc():
    lib = ctypes.CDLL(None)
    lib.malloc.restype = ctypes.c_void_p
    lib.malloc.argtypes = [ctypes.c_size_t]
    lib.free.argtypes = [ctypes.c_void_p]
    return lib


def _write_id(id_out, ref_id: str) -> None:
    ctypes.memmove(id_out, ref_id.encode() + b"\0", len(ref_id) + 1)


def _make_api(lib_path: str) -> "_ApiStruct":
    """Per-library API table; closures bridge into the hosting worker.
    Exceptions never cross the C boundary — they map to error codes."""
    cached = _API_STRUCTS.get(lib_path)
    if cached is not None:
        return cached[0]
    libc = _libc()

    def _put(ctx, data, length, id_out):
        try:
            ref = ray_tpu.put(ctypes.string_at(data, length))
            _API_REFS[ref.id] = ref
            _write_id(id_out, ref.id)
            return 0
        except Exception:  # noqa: BLE001 — code, not unwinding into C
            return 5  # EIO

    def _get(ctx, object_id, timeout_s, out, out_len):
        try:
            ref = _API_REFS.get(object_id.decode())
            if ref is None:
                return 2  # ENOENT — not an id minted by this API
            # timeout semantics (documented in ray_tpu_api.h): < 0
            # blocks forever, 0 polls, > 0 bounds the wait
            timeout = None if timeout_s < 0 else timeout_s
            try:
                value = ray_tpu.get(ref, timeout=timeout)
            except ray_tpu.exceptions.GetTimeoutError:
                return 11  # EAGAIN — not ready within timeout
            if not isinstance(value, (bytes, bytearray)):
                return 22  # EINVAL — non-bytes object
            buf = libc.malloc(len(value))
            if not buf:
                return 12  # ENOMEM
            ctypes.memmove(buf, bytes(value), len(value))
            out[0] = ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8))
            out_len[0] = len(value)
            return 0
        except Exception:  # noqa: BLE001
            return 5

    def _submit(ctx, symbol, arg, arg_len, id_out):
        try:
            f = cpp_function(lib_path, symbol.decode(), api=True)
            ref = f.remote(ctypes.string_at(arg, arg_len))
            _API_REFS[ref.id] = ref
            _write_id(id_out, ref.id)
            return 0
        except Exception:  # noqa: BLE001
            return 5

    def _release(ctx, object_id):
        return 0 if _API_REFS.pop(object_id.decode(), None) \
            is not None else 2

    def _free(p):
        libc.free(ctypes.cast(p, ctypes.c_void_p))

    def _create_actor(ctx, methods, init_symbol, init_arg, init_len,
                      id_out):
        try:
            import uuid as _uuid

            syms = [m for m in methods.decode().split(",") if m]
            init = init_symbol.decode() if init_symbol else None
            cls = cpp_actor(lib_path, syms, init_symbol=init or None)
            payload = ctypes.string_at(init_arg, init_len) \
                if init_len else b""
            handle = cls.remote(payload)
            hid = _uuid.uuid4().hex
            _API_ACTORS[hid] = handle
            _write_id(id_out, hid)
            return 0
        except Exception:  # noqa: BLE001 — code, not unwinding into C
            return 5

    def _call_actor(ctx, actor_id, method, arg, arg_len, id_out):
        try:
            handle = _API_ACTORS.get(actor_id.decode())
            if handle is None:
                return 2  # ENOENT
            m = getattr(handle, method.decode(), None)
            if m is None:
                return 22  # EINVAL — undeclared method symbol
            ref = m.remote(ctypes.string_at(arg, arg_len)
                           if arg_len else b"")
            _API_REFS[ref.id] = ref
            _write_id(id_out, ref.id)
            return 0
        except Exception:  # noqa: BLE001
            return 5

    def _kill_actor(ctx, actor_id):
        try:
            handle = _API_ACTORS.pop(actor_id.decode(), None)
            if handle is None:
                return 2
            ray_tpu.kill(handle)
            return 0
        except Exception:  # noqa: BLE001
            return 5

    cbs = (_PUT_T(_put), _GET_T(_get), _SUBMIT_T(_submit),
           _RELEASE_T(_release), _FREE_T(_free),
           _CREATE_ACTOR_T(_create_actor), _CALL_ACTOR_T(_call_actor),
           _KILL_ACTOR_T(_kill_actor))
    api = _ApiStruct(None, *cbs)
    _API_STRUCTS[lib_path] = (api, cbs)  # keep callbacks alive
    return api


def _call_native_api(lib_path: str, symbol: str, payload: bytes) -> bytes:
    return _invoke_native(lib_path, symbol, payload, _make_api(lib_path))


def cpp_function(lib_path: str, symbol: str, api: bool = False,
                 **remote_options: Any):
    """A remote function executing `symbol` from `lib_path` on a worker
    (bytes in, bytes out). With api=True the symbol uses the v2 ABI
    (ray_tpu_api.h) and receives put/get/submit/release entry points.
    The library path must be reachable on worker hosts — stage it via
    runtime_env working_dir for multi-host."""
    lib_path = os.path.abspath(lib_path)

    def task(payload: bytes = b"", *, _lib=lib_path, _sym=symbol,
             _api=api) -> bytes:
        from ray_tpu.util import cpp as _cpp

        call = _cpp._call_native_api if _api else _cpp._call_native
        return call(_lib, _sym, bytes(payload))

    task.__name__ = f"cpp:{symbol}"
    rf = ray_tpu.remote(task)
    return rf.options(**remote_options) if remote_options else rf


def cpp_actor(lib_path: str, symbols: list,
              init_symbol: Optional[str] = None, **actor_options: Any):
    """An actor class whose methods call exported symbols of `lib_path`
    with the same bytes ABI, sharing the dlopened library (and any
    native state behind it) across calls. `init_symbol`, when given, is
    invoked once at construction with the init payload."""
    lib_path = os.path.abspath(lib_path)
    syms = list(symbols)

    class _CppActor:
        def __init__(self, init_payload: bytes = b""):
            from ray_tpu.util.cpp import _call_native, _load

            _load(lib_path)
            if init_symbol:
                _call_native(lib_path, init_symbol, bytes(init_payload))

        def call(self, symbol: str, payload: bytes = b"") -> bytes:
            from ray_tpu.util.cpp import _call_native

            if symbol not in syms:
                raise AttributeError(
                    f"symbol {symbol!r} not exported by this cpp_actor "
                    f"(declared: {syms})")
            return _call_native(lib_path, symbol, bytes(payload))

    for s in syms:
        def _m(self, payload: bytes = b"", _s=s) -> bytes:
            return self.call(_s, payload)

        _m.__name__ = s
        setattr(_CppActor, s, _m)
    _CppActor.__name__ = f"CppActor_{os.path.basename(lib_path)}"
    rc = ray_tpu.remote(_CppActor)
    return rc.options(**actor_options) if actor_options else rc
