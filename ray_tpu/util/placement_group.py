"""Placement groups: atomic gang reservation of resources.

API of the reference's python/ray/util/placement_group.py
(placement_group() :145, PlacementGroup handle :41) with strategies
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD. The conductor reserves all bundles
transactionally (single authority — no 2PC needed, cf. reference
gcs_placement_group_scheduler.cc). TPU semantics: a STRICT_PACK group of
chip bundles corresponds to an ICI-contiguous slice allocation
(SURVEY.md §2.3 "slice-topology-aware bundles").
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from .._private import worker as worker_mod

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]],
                 strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy

    def ready(self) -> bool:
        w = _worker()
        return bool(w.conductor.call("placement_group_ready", self.id,
                                     timeout=30.0))

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_seconds
        while time.monotonic() < deadline:
            if self.ready():
                return True
            time.sleep(0.05)
        return False

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: Optional[str] = None) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty "
                         "resource dicts")
    w = _worker()
    pg_id = w.conductor.call("create_placement_group", list(bundles),
                             strategy, name, timeout=60.0)
    return PlacementGroup(pg_id, list(bundles), strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    w = _worker()
    w.conductor.call("remove_placement_group",
                     getattr(pg, "id", pg), timeout=30.0)


def _worker():
    w = worker_mod.global_worker
    if w is None:
        raise RuntimeError("ray_tpu.init() must be called first")
    return w
