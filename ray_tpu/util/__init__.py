"""ray_tpu.util: distributed utilities layered on the core API (reference
python/ray/util/ — SURVEY.md §2.3)."""
from .actor_pool import ActorPool  # noqa: F401
from .placement_group import (  # noqa: F401
    PlacementGroup,
    placement_group,
    remove_placement_group,
)
from .scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
)
