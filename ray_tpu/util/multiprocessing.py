"""multiprocessing.Pool drop-in over ray_tpu tasks — analog of the
reference's python/ray/util/multiprocessing/ (Pool on actor pool). Work
items become tasks (shared worker processes), so a Pool costs nothing when
idle and parallelism is bounded by cluster CPUs, not pool size."""
from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional


class AsyncResult:
    """multiprocessing.pool.AsyncResult-compatible wrapper."""

    def __init__(self, refs, single: bool, callback=None,
                 error_callback=None):
        self._refs = refs
        self._single = single
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        # callbacks must be assigned before the waiter thread starts:
        # fast-resolving refs otherwise race _wait_bg reading them
        self._callback = callback
        self._error_callback = error_callback
        threading.Thread(target=self._wait_bg, daemon=True).start()

    def _wait_bg(self):
        import ray_tpu

        try:
            values = ray_tpu.get(list(self._refs))
            self._value = values[0] if self._single else values
            if self._callback is not None:
                self._callback(self._value)
        except BaseException as e:  # noqa: BLE001
            self._error = e
            if self._error_callback is not None:
                self._error_callback(e)
        finally:
            self._done.set()

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("not ready")
        return self._error is None

    def wait(self, timeout: Optional[float] = None) -> None:
        self._done.wait(timeout)

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("result not ready")
        if self._error is not None:
            raise self._error
        return self._value


class Pool:
    """``from ray_tpu.util.multiprocessing import Pool`` — the reference's
    drop-in (util/multiprocessing/pool.py). `processes` only bounds chunked
    map fan-out; scheduling is cluster-wide."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self._processes = processes or int(
            ray_tpu.cluster_resources().get("CPU", 1))
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False

    def _task(self, func):
        import ray_tpu

        init, initargs = self._initializer, self._initargs

        def call(*args, **kwargs):
            if init is not None:
                init(*initargs)
            return func(*args, **kwargs)

        return ray_tpu.remote(call)

    def _check(self):
        if self._closed:
            raise ValueError("Pool not running")

    # -- apply ---------------------------------------------------------------
    def apply(self, func, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args: tuple = (),
                    kwds: Optional[dict] = None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check()
        ref = self._task(func).remote(*args, **(kwds or {}))
        return AsyncResult([ref], single=True, callback=callback,
                           error_callback=error_callback)

    # -- map -----------------------------------------------------------------
    def _chunks(self, iterable: Iterable,
                chunksize: Optional[int]) -> List[List[Any]]:
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)], chunksize

    def map(self, func, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable: Iterable,
                  chunksize: Optional[int] = None, callback=None,
                  error_callback=None) -> AsyncResult:
        self._check()
        chunks, _ = self._chunks(iterable, chunksize)

        def run_chunk(chunk):
            return [func(x) for x in chunk]

        refs = [self._task(run_chunk).remote(c) for c in chunks]

        class _FlatResult(AsyncResult):
            def _wait_bg(inner):
                import ray_tpu

                try:
                    nested = ray_tpu.get(list(inner._refs))
                    inner._value = list(
                        itertools.chain.from_iterable(nested))
                    if inner._callback is not None:
                        inner._callback(inner._value)
                except BaseException as e:  # noqa: BLE001
                    inner._error = e
                    if inner._error_callback is not None:
                        inner._error_callback(e)
                finally:
                    inner._done.set()

        return _FlatResult(refs, single=False, callback=callback,
                           error_callback=error_callback)

    def starmap(self, func, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        return self.map(lambda args: func(*args), list(iterable), chunksize)

    def imap(self, func, iterable: Iterable,
             chunksize: Optional[int] = None):
        import ray_tpu

        chunks, _ = self._chunks(iterable, chunksize)

        def run_chunk(chunk):
            return [func(x) for x in chunk]

        refs = [self._task(run_chunk).remote(c) for c in chunks]
        for ref in refs:  # ordered, lazily fetched
            yield from ray_tpu.get(ref)

    def imap_unordered(self, func, iterable: Iterable,
                       chunksize: Optional[int] = None):
        import ray_tpu

        chunks, _ = self._chunks(iterable, chunksize)

        def run_chunk(chunk):
            return [func(x) for x in chunk]

        pending = [self._task(run_chunk).remote(c) for c in chunks]
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(done[0])

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *a) -> None:
        self.terminate()
