"""Train session: the API a `train_fn` sees while running under a trainer.

Mirrors the reference's _TrainSession
(python/ray/train/_internal/session.py — report :661, get_checkpoint :748,
get_dataset_shard :1054) with the same thread-local access pattern:
`ray_tpu.train.report(metrics, checkpoint=...)` from anywhere inside the
training function.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from .checkpoint import Checkpoint

_local = threading.local()


@dataclass
class TrainContext:
    world_size: int = 1
    rank: int = 0
    experiment_name: str = "default"
    trial_dir: str = ""
    dataset_shards: Dict[str, Any] = field(default_factory=dict)
    latest_checkpoint: Optional[Checkpoint] = None
    # rendezvous namespace for this gang (unique per fit); consumed by
    # parallel.distributed.setup_jax_distributed
    jax_dist_key: Optional[str] = None
    # multi-slice identity (ScalingConfig.num_slices > 1): which TPU
    # slice this rank's host belongs to; slice_map is filled in by
    # setup_jax_distributed after the slice rendezvous
    slice_id: Optional[int] = None
    num_slices: int = 1
    slice_map: Optional[Dict[int, Any]] = None
    # set by the trainer: called with (metrics, checkpoint)
    _report_fn: Optional[Callable[[Dict[str, Any], Optional[Checkpoint]],
                                  None]] = None
    _stop_requested: bool = False

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.rank

    def get_trial_dir(self) -> str:
        return self.trial_dir

    def get_slice_id(self) -> int:
        return 0 if self.slice_id is None else self.slice_id


def _set_session(ctx: Optional[TrainContext]) -> None:
    _local.ctx = ctx


def _get_session() -> Optional[TrainContext]:
    return getattr(_local, "ctx", None)


def get_context() -> TrainContext:
    ctx = _get_session()
    if ctx is None:
        raise RuntimeError("No train session active — call inside a "
                           "train_fn run by JaxTrainer/Tuner")
    return ctx


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Reference session.py:661. Reports metrics (and optionally a
    checkpoint) to the controlling trainer/tuner. Raises StopIteration-like
    control via the trainer if the trial was stopped (e.g. by a scheduler)."""
    ctx = get_context()
    if ctx._report_fn is not None:
        ctx._report_fn(dict(metrics), checkpoint)
    if ctx._stop_requested:
        raise StopTrial()


def get_checkpoint() -> Optional[Checkpoint]:
    """Reference session.py:748 — resume checkpoint, if any."""
    return get_context().latest_checkpoint


def get_dataset_shard(name: str = "train"):
    """Reference session.py:1054 — this worker's dataset shard."""
    return get_context().dataset_shards.get(name)


class StopTrial(Exception):
    """Raised inside train_fn when the controller stops the trial (analog
    of the reference's session-finish control flow)."""
