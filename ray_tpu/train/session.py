"""Train session: the API a `train_fn` sees while running under a trainer.

Mirrors the reference's _TrainSession
(python/ray/train/_internal/session.py — report :661, get_checkpoint :748,
get_dataset_shard :1054) with the same thread-local access pattern:
`ray_tpu.train.report(metrics, checkpoint=...)` from anywhere inside the
training function.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from .checkpoint import Checkpoint

_local = threading.local()


@dataclass
class TrainContext:
    world_size: int = 1
    rank: int = 0
    experiment_name: str = "default"
    trial_dir: str = ""
    dataset_shards: Dict[str, Any] = field(default_factory=dict)
    latest_checkpoint: Optional[Checkpoint] = None
    # rendezvous namespace for this gang (unique per fit); consumed by
    # parallel.distributed.setup_jax_distributed
    jax_dist_key: Optional[str] = None
    # multi-slice identity (ScalingConfig.num_slices > 1): which TPU
    # slice this rank's host belongs to; slice_map is filled in by
    # setup_jax_distributed after the slice rendezvous
    slice_id: Optional[int] = None
    num_slices: int = 1
    slice_map: Optional[Dict[int, Any]] = None
    # flight-recorder identity of this fit (observability.StepTimer
    # records ship to the conductor under this key)
    run_id: str = ""
    # restart generation (0 = first attempt); the trainer's retry loop
    # bumps it and the chaos harness scopes scripted faults to it
    attempt: int = 0
    # set by the trainer: called with (metrics, checkpoint)
    _report_fn: Optional[Callable[[Dict[str, Any], Optional[Checkpoint]],
                                  None]] = None
    _stop_requested: bool = False
    # per-rank step clock (observability.step_timer) the trainer creates;
    # TrainStep and report() feed it, users reach it via get_step_timer()
    _step_timer: Optional[Any] = None
    # the active preemption notice (conductor `resilience` pubsub): a
    # host this run touches announced it is going away — checkpoint now
    _preemption: Optional[Dict[str, Any]] = None
    _grace_acked: bool = False
    # resilience.chaos.ChaosMonkey for this attempt (scripted faults
    # fire at the report() step boundary); None = no chaos configured
    _chaos: Optional[Any] = None
    _report_count: int = 0

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.rank

    def get_trial_dir(self) -> str:
        return self.trial_dir

    def get_slice_id(self) -> int:
        return 0 if self.slice_id is None else self.slice_id


def _set_session(ctx: Optional[TrainContext]) -> None:
    _local.ctx = ctx


def _get_session() -> Optional[TrainContext]:
    return getattr(_local, "ctx", None)


def get_context() -> TrainContext:
    ctx = _get_session()
    if ctx is None:
        raise RuntimeError("No train session active — call inside a "
                           "train_fn run by JaxTrainer/Tuner")
    return ctx


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None, *,
           publish_weights: Any = None,
           weights_name: Optional[str] = None,
           weights_delta: bool = False,
           weights_version: Optional[int] = None) -> None:
    """Reference session.py:661. Reports metrics (and optionally a
    checkpoint) to the controlling trainer/tuner. Raises StopIteration-like
    control via the trainer if the trial was stopped (e.g. by a scheduler).

    report() is also the step boundary for the flight recorder: the
    session's StepTimer closes the current step here and its breakdown
    (data_wait/compile/device_step/checkpoint/report ms, tokens/sec, MFU)
    is merged into the reported metrics, so Result.metrics_history is
    self-describing. Time spent delivering the report itself (including
    synchronous checkpoint registration) lands in the NEXT step's
    "report"/"checkpoint" phase.

    ``publish_weights=params`` publishes this host's LOCAL shards of the
    pytree into the live weight fabric (ray_tpu.weights) as version
    `step` under ``weights_name`` (default: the experiment name) —
    serving replicas subscribed to that name hot-swap to it between
    decode ticks. Equivalent to ``weights.publish(params, step=step)``
    from inside the train_fn. Without a ``step`` metric the registry
    assigns latest+1 (single-host only — a multi-host gang must report
    a step so every host names the same version).
    ``weights_delta=True`` ships only the leaves whose content changed
    since this process's previous publish of the name (the online
    loop's per-step refresh path; full fallback when there is no usable
    base). ``weights_version`` overrides the version id (the online
    loop numbers publications consecutively so the staleness gauge
    counts PUBLICATIONS behind, decoupled from step numbering)."""
    ctx = get_context()
    metrics = dict(metrics)
    ctx._report_count += 1
    step = ctx._report_count
    explicit_step = False
    v = metrics.get("step")
    if v is not None:
        try:
            step = int(v)  # python/numpy/jax scalars alike
            explicit_step = True
        except (TypeError, ValueError):
            step = ctx._report_count
    timer = ctx._step_timer
    if timer is not None and timer.enabled:
        rec = timer.end_step()
        if rec is not None:
            for key in ("total_ms", "data_wait_ms", "bubble_wait_ms",
                        "compile_ms", "device_step_ms", "checkpoint_ms",
                        "report_ms", "other_ms", "tokens_per_sec",
                        "mfu"):
                if key in rec:
                    metrics.setdefault(
                        "step_time_ms" if key == "total_ms" else key,
                        rec[key])
    if publish_weights is not None:
        from ray_tpu import weights as _weights

        import time as _time

        t0 = _time.perf_counter()
        try:
            # version = the user's step metric when given (stable across
            # restarts); otherwise registry-assigned latest+1 — the
            # per-attempt report COUNT must not name versions, it resets
            # to 1 on every restart and would collide with (or sort
            # below) the previous attempt's publications
            _weights.publish(publish_weights,
                             name=weights_name or ctx.experiment_name,
                             step=(None if weights_version is not None
                                   else step if explicit_step else None),
                             version=weights_version,
                             run_id=ctx.run_id, delta=weights_delta)
        except ValueError as e:
            if "already committed" not in str(e):
                raise
            # a restarted attempt replaying an already-published step:
            # idempotent no-op, never a reason to kill the gang
        if timer is not None and timer.enabled:
            timer.record("report", _time.perf_counter() - t0)
    if ctx._report_fn is not None:
        if timer is not None and timer.enabled:
            import time as _time

            t0 = _time.perf_counter()
            try:
                ctx._report_fn(metrics, checkpoint)
            finally:
                timer.record(
                    "checkpoint" if checkpoint is not None else "report",
                    _time.perf_counter() - t0)
        else:
            ctx._report_fn(metrics, checkpoint)
    if checkpoint is not None and ctx._preemption is not None \
            and not ctx._grace_acked:
        # The grace flow: the preemption broadcast asked for a
        # step-fresh checkpoint NOW. An async save must actually be ON
        # DISK before we ack — expedite every in-flight writer and
        # block on this one's commit (the host may die right after the
        # grace window; a checkpoint still in the writer queue when it
        # does is no checkpoint at all).
        committed = True
        if hasattr(checkpoint, "future"):
            import time as _time

            from .async_checkpoint import expedite_all

            expedite_all()
            # bounded by the broadcast's own deadline: a wedged writer
            # must not pin the worker in report() past the grace window
            # it was trying to beat (then the gang would die mid-wait
            # with nothing committed AND nothing else attempted)
            deadline = ctx._preemption.get("deadline")
            budget = (max(1.0, float(deadline) - _time.time())
                      if deadline is not None
                      else float(ctx._preemption.get("grace_s") or 30.0))
            try:
                checkpoint.future.result(timeout=budget)
            except Exception:  # noqa: BLE001 — torn or still-writing
                committed = False  # save: don't ack; a later report
                #                    may still land one
            else:
                if ctx.world_size > 1 and ctx.trial_dir:
                    # workers mode persists async saves into
                    # {trial_dir}/pending from a commit hook — and hook
                    # failures are swallowed by design. A path still in
                    # the worker tempdir means the checkpoint dies with
                    # this host: acking it would record a grace
                    # checkpoint the restart cannot find.
                    import os as _os

                    pending_root = _os.path.abspath(_os.path.join(
                        ctx.trial_dir, "pending")) + _os.sep
                    committed = _os.path.abspath(
                        checkpoint.path).startswith(pending_root)
        if committed:
            ctx._grace_acked = True
            _report_resilience_event({
                "kind": "grace_checkpoint", "run_id": ctx.run_id,
                "rank": ctx.rank, "step": step,
                "node_id": ctx._preemption.get("node_id")})
    if ctx._chaos is not None:
        # scripted faults fire AFTER the report is delivered, so "kill
        # rank R at step S" leaves step S's metrics/checkpoint as the
        # deterministic resume point
        ctx._chaos.on_step(step)
    if ctx._stop_requested:
        raise StopTrial()


def get_step_timer():
    """The active session's flight-recorder StepTimer — use it to
    attribute data-loading or checkpoint time from inside a train_fn:

        with ray_tpu.train.get_step_timer().phase("data_wait"):
            batch = next(batches)

    Always returns a timer: outside a session (or with telemetry off) it
    is a shared disabled instance whose phase() is a no-op."""
    ctx = _get_session()
    if ctx is not None and ctx._step_timer is not None:
        return ctx._step_timer
    global _disabled_timer
    if _disabled_timer is None:
        from ray_tpu.observability.step_timer import StepTimer

        _disabled_timer = StepTimer(enabled=False)
    return _disabled_timer


_disabled_timer = None


def preemption_requested() -> Optional[Dict[str, Any]]:
    """Inside a train_fn: the active preemption notice, or None.

    When a host this run touches announces a maintenance event /
    preemption, the conductor broadcasts "checkpoint now, grace N
    seconds" and this returns the notice::

        {"node_id": ..., "grace_s": 30.0, "deadline": <unix ts>,
         "reason": "maintenance"}

    React by reporting a checkpoint promptly — the restarted run then
    resumes from a step-fresh checkpoint instead of the last periodic
    one. Outside a session this returns None."""
    ctx = _get_session()
    return ctx._preemption if ctx is not None else None


def _report_resilience_event(event: Dict[str, Any]) -> None:
    """Best-effort event to the conductor's resilience log (driver or
    worker process; silently a no-op without a cluster)."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        return
    try:
        w.conductor.notify("report_resilience_event", event)
    except Exception:  # noqa: BLE001 — telemetry only
        pass


def get_checkpoint() -> Optional[Checkpoint]:
    """Reference session.py:748 — resume checkpoint, if any."""
    return get_context().latest_checkpoint


def get_dataset_shard(name: str = "train"):
    """Reference session.py:1054 — this worker's dataset shard."""
    return get_context().dataset_shards.get(name)


class StopTrial(Exception):
    """Raised inside train_fn when the controller stops the trial (analog
    of the reference's session-finish control flow)."""
