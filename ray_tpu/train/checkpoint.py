"""Checkpointing: directory-based `Checkpoint` + top-K `CheckpointManager`.

API mirrors the reference's ray.train.Checkpoint
(python/ray/air/_internal + train/_internal/checkpoint_manager.py —
SURVEY.md §5.4): a checkpoint is a directory; managers keep top-K by a
score attribute. Pytree save/load is numpy-backed (`save_pytree` /
`load_pytree`) with a tensorstore/orbax escape hatch deliberately avoided
for the host-local path: one .npz + one pickle of treedef is faster to
restore for flagship-model sizes and has no async machinery to misuse.
Device arrays are pulled to host (jax.device_get) at save; `load_pytree`
returns numpy — callers re-shard with device_put/make_array (the mesh may
differ across restarts, the elastic story per SURVEY.md §7 "hard parts").
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np


class Checkpoint:
    """A directory of files (reference ray.train.Checkpoint)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        with open(os.path.join(d, "data.pkl"), "wb") as f:
            pickle.dump(data, f, protocol=5)
        return cls(d)

    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            return self.path
        if os.path.abspath(path) != self.path:
            shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextmanager
    def as_directory(self) -> Iterator[str]:
        yield self.path

    def __repr__(self) -> str:
        return f"Checkpoint({self.path})"


def save_pytree(tree: Any, directory: str, name: str = "state") -> None:
    """Flatten a pytree of arrays to {name}.npz + {name}.tree.pkl."""
    os.makedirs(directory, exist_ok=True)
    leaves, treedef = jax.tree.flatten(tree)
    host_leaves = [np.asarray(jax.device_get(x)) for x in leaves]
    arrays = {f"leaf_{i}": a for i, a in enumerate(host_leaves)}
    tmp = os.path.join(directory, f".{name}.npz.tmp")
    # pass an open file, not the path: np.savez silently appends ".npz"
    # to string filenames, which would break the atomic-rename dance
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, os.path.join(directory, f"{name}.npz"))
    with open(os.path.join(directory, f"{name}.tree.pkl"), "wb") as f:
        pickle.dump(treedef, f, protocol=5)


def load_pytree(directory: str, name: str = "state") -> Any:
    with open(os.path.join(directory, f"{name}.tree.pkl"), "rb") as f:
        treedef = pickle.load(f)
    data = np.load(os.path.join(directory, f"{name}.npz"), allow_pickle=False)
    leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
    return jax.tree.unflatten(treedef, leaves)


@dataclass(order=True)
class _Tracked:
    score: float
    index: int
    checkpoint: Checkpoint = field(compare=False)
    metrics: Dict[str, Any] = field(compare=False, default_factory=dict)


class CheckpointManager:
    """Keeps top-K checkpoints by metric under a root dir (reference
    train/_internal/checkpoint_manager.py driven by CheckpointConfig)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None,
                 score_order: str = "max"):
        self.root = root
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._tracked: List[_Tracked] = []
        self._index = 0
        # async checkpoints register from the writer thread (deferred to
        # commit time) while the trainer may register sync ones — lock
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)

    def reserve_index(self) -> int:
        """Claim the next checkpoint slot NOW — an async save registering
        later (at commit time, on the writer thread) keeps its report-time
        position in the recency order, so a sync checkpoint reported after
        it can never be ranked older."""
        with self._lock:
            idx = self._index
            self._index += 1
            return idx

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict[str, Any]] = None,
                 index: Optional[int] = None) -> Checkpoint:
        """Move `checkpoint` under the managed root and apply retention.
        Disk work happens OUTSIDE the lock: a multi-GB copy on the async
        writer thread must not block report() or best/latest reads."""
        metrics = dict(metrics or {})
        if index is None:
            index = self.reserve_index()
        dst = os.path.join(self.root, f"checkpoint_{index:06d}")
        if checkpoint.path != dst:
            if os.path.exists(dst):
                shutil.rmtree(dst)
            # same-filesystem rename when possible, else copy
            try:
                os.replace(checkpoint.path, dst)
            except OSError:
                shutil.copytree(checkpoint.path, dst)
                shutil.rmtree(checkpoint.path, ignore_errors=True)
            # keep the caller's handle valid after the move
            checkpoint.path = dst
        ckpt = Checkpoint(dst)
        with open(os.path.join(dst, "metrics.json"), "w") as f:
            json.dump(_json_safe(metrics), f)
        if self.score_attribute and self.score_attribute in metrics:
            score = float(metrics[self.score_attribute])
            if self.score_order == "min":
                score = -score
        else:
            score = float(index)  # fall back to recency
        doomed: List[_Tracked] = []
        with self._lock:
            self._tracked.append(_Tracked(score, index, ckpt, metrics))
            if self.num_to_keep is not None:
                while len(self._tracked) > self.num_to_keep:
                    worst = min(self._tracked)
                    self._tracked.remove(worst)
                    doomed.append(worst)
        for t in doomed:  # deletion outside the lock too
            shutil.rmtree(t.checkpoint.path, ignore_errors=True)
        return ckpt

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        with self._lock:
            if not self._tracked:
                return None
            return max(self._tracked).checkpoint

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        with self._lock:
            if not self._tracked:
                return None
            return max(self._tracked, key=lambda t: t.index).checkpoint

    def list_checkpoints(self) -> List[Tuple[Checkpoint, Dict[str, Any]]]:
        with self._lock:
            return [(t.checkpoint, t.metrics)
                    for t in sorted(self._tracked, key=lambda t: t.index)]


def _json_safe(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in d.items():
        if isinstance(v, (np.floating, np.integer)):
            out[k] = v.item()
        elif isinstance(v, (int, float, str, bool, type(None))):
            out[k] = v
        else:
            out[k] = str(v)
    return out
