"""Config dataclasses — API surface of the reference's
python/ray/air/config.py (ScalingConfig/RunConfig/CheckpointConfig/
FailureConfig) plus the TPU-native ShardingConfig the reference cannot
express (SURVEY.md §2.3: reference parallelism is DP-only; TP/PP/SP/EP
delegated to wrapped frameworks)."""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """Reference air/config.py ScalingConfig: num_workers + resources.
    Here: worker processes for host-side work; chips belong to the mesh."""

    num_workers: int = 1
    use_tpu: bool = True
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    # mode="workers": rendezvous the gang into one jax.distributed job
    # BEFORE train_fn runs (the reference does process-group setup for
    # the user — train/torch/config.py:64-117). Opt out for gangs doing
    # pure host-side work with no jax in the loop.
    setup_jax_distributed: bool = True


@dataclass
class ShardingConfig:
    """Named mesh axis sizes (new capability; -1 fills remaining devices).
    Maps 1:1 onto parallel.MeshConfig."""

    dp: int = -1
    fsdp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 1
    remat: bool = False  # jax.checkpoint the model forward

    def mesh_config(self):
        from ..parallel.mesh import MeshConfig

        return MeshConfig(dp=self.dp, fsdp=self.fsdp, pp=self.pp,
                          sp=self.sp, ep=self.ep, tp=self.tp)


@dataclass
class CheckpointConfig:
    """Reference air/config.py CheckpointConfig (keep top-K by metric)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclass
class FailureConfig:
    """Reference air/config.py FailureConfig."""

    max_failures: int = 0


@dataclass
class RunConfig:
    """Reference air/config.py RunConfig."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    verbose: int = 1

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        return os.path.join(base, self.name or "experiment")
