"""Config dataclasses — API surface of the reference's
python/ray/air/config.py (ScalingConfig/RunConfig/CheckpointConfig/
FailureConfig) plus the TPU-native ShardingConfig the reference cannot
express (SURVEY.md §2.3: reference parallelism is DP-only; TP/PP/SP/EP
delegated to wrapped frameworks)."""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """Reference air/config.py ScalingConfig: num_workers + resources.
    Here: worker processes for host-side work; chips belong to the mesh."""

    num_workers: int = 1
    use_tpu: bool = True
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    # mode="workers": rendezvous the gang into one jax.distributed job
    # BEFORE train_fn runs (the reference does process-group setup for
    # the user — train/torch/config.py:64-117). Opt out for gangs doing
    # pure host-side work with no jax in the loop.
    setup_jax_distributed: bool = True
    # mode="workers" on a multi-slice pod: how many TPU slices the gang
    # spans. Workers are assigned slice ids contiguously (the trainer's
    # gang placement packs a slice's hosts together) and the
    # jax.distributed rendezvous groups process ids slice-major so DCN
    # axes of a HybridMeshConfig land across slices.
    num_slices: int = 1
    # elastic floor (ray_tpu.resilience): when a restart finds less
    # schedulable capacity than num_workers (host quarantined / slice
    # preempted), the gang re-forms at the largest feasible size >= this
    # — multi-slice gangs shrink by whole slices and a ShardingConfig
    # whose dcn_dp equals num_slices follows. None = never shrink.
    min_workers: Optional[int] = None
    # MPMD pipeline parallelism (ray_tpu.mpmd.PipelineTrainer): how many
    # separately-compiled pipeline stages the job runs, one stage-gang
    # per slice. 1 = no MPMD pipeline (single-program SPMD; the `pp`
    # mesh axis remains the in-program GPipe alternative).
    num_stages: int = 1


def assign_worker_slices(num_workers: int, num_slices: int) -> list:
    """Contiguous balanced slice assignment for a worker gang: rank
    order == host order under STRICT_PACK, so contiguous ranks share a
    slice's hosts. Returns one slice id per rank, or all-None for
    single-slice gangs (no slice rendezvous needed). Used as the
    fallback when the TPU runtime advertises no slice identity
    (parallel.distributed.detect_slice_id)."""
    if num_slices <= 1:
        return [None] * num_workers
    if num_workers % num_slices != 0:
        raise ValueError(
            f"num_workers={num_workers} not divisible by "
            f"num_slices={num_slices}")
    return [i * num_slices // num_workers for i in range(num_workers)]


@dataclass
class ShardingConfig:
    """Named mesh axis sizes (new capability; -1 fills remaining devices).
    Maps 1:1 onto parallel.MeshConfig."""

    dp: int = -1
    fsdp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 1
    remat: bool = False  # jax.checkpoint the model forward
    # DCN (cross-slice) axis sizes for multi-slice pods; the plain axes
    # above then size the ICI mesh WITHIN one slice. All 1 = single
    # slice, lowers to a flat MeshConfig exactly as before.
    dcn_dp: int = 1
    dcn_fsdp: int = 1
    dcn_pp: int = 1

    @property
    def is_hybrid(self) -> bool:
        return any(v != 1 for v in (self.dcn_dp, self.dcn_fsdp,
                                    self.dcn_pp))

    def mesh_config(self):
        if self.is_hybrid:
            from ..parallel.multislice import HybridMeshConfig

            return HybridMeshConfig(
                dp=self.dp, fsdp=self.fsdp, pp=self.pp, sp=self.sp,
                ep=self.ep, tp=self.tp, dcn_dp=self.dcn_dp,
                dcn_fsdp=self.dcn_fsdp, dcn_pp=self.dcn_pp)
        from ..parallel.mesh import MeshConfig

        return MeshConfig(dp=self.dp, fsdp=self.fsdp, pp=self.pp,
                          sp=self.sp, ep=self.ep, tp=self.tp)

    def build_mesh(self, devices=None):
        """Lower to a jax Mesh: hybrid (slice-topology discovery + DCN
        block assembly) when any dcn_* axis is set, flat otherwise."""
        return self.mesh_config().build(devices)


@dataclass
class CheckpointConfig:
    """Reference air/config.py CheckpointConfig (keep top-K by metric)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclass
class FailureConfig:
    """Reference air/config.py FailureConfig."""

    max_failures: int = 0


@dataclass
class RunConfig:
    """Reference air/config.py RunConfig."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    verbose: int = 1

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        return os.path.join(base, self.name or "experiment")
