"""JaxTrainer: the Train-equivalent (reference TorchTrainer →
DataParallelTrainer → BackendExecutor → WorkerGroup, SURVEY.md §3.4),
redesigned for single-controller SPMD on TPU meshes.

Where the reference runs N actor processes each owning one GPU and
rendezvousing an NCCL group (train/torch/config.py:64-117), a TPU host
drives all its chips from one process and XLA owns the collectives; the
N-process shape only reappears across hosts. So:

- mode="spmd" (default): train_fn runs in-process against the global mesh
  built from ShardingConfig. Zero serialization on the step path; the
  trainer contributes session plumbing (report/checkpoint/datasets),
  retention, and failure retries from the last checkpoint.
- mode="workers": ScalingConfig.num_workers actor processes (gang-placed
  via a STRICT_PACK placement group) each run train_fn with
  rank/world_size, mirroring BackendExecutor.start_training
  (backend_executor.py:427) for host-side (CPU) data/eval work and
  multi-host topologies. Worker reports stream back to the driver through
  the actor channel; rank 0's checkpoints win (reference semantics).

TrainStep builds the jitted SPMD update: shard params by the model's
PartitionSpec tree, batch by ('dp','fsdp'), donate the state, and let XLA
insert psum/reduce-scatter — the step the reference delegates to torch DDP
(train_loop_utils.py:158 prepare_model).
"""
from __future__ import annotations

import logging
import os
import time
import traceback
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .checkpoint import Checkpoint, CheckpointManager
from .config import (CheckpointConfig, FailureConfig, RunConfig,
                     ScalingConfig, ShardingConfig)
from .session import (StopTrial, TrainContext, _report_resilience_event,
                      _set_session)

logger = logging.getLogger(__name__)


@dataclass
class Result:
    """Reference air/result.py Result."""

    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    path: str = ""
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    # the trial's hyperparameter config (reference Result.config —
    # populated by Tune, empty for plain Trainer fits)
    config: Dict[str, Any] = field(default_factory=dict)


def _subscribe_preemption(ctx: TrainContext):
    """Route the conductor's `resilience` pubsub into the session so
    `ray_tpu.train.preemption_requested()` sees the notice. Returns an
    unsubscribe token (None without a cluster)."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        return None

    def on_msg(msg, _ctx=ctx):
        if isinstance(msg, dict) and msg.get("kind") == "preemption":
            _ctx._preemption = msg
            # commit in-flight async saves promptly: the grace
            # checkpoint must land on disk inside the grace window, not
            # at gang completion (async_checkpoint grace flow)
            from .async_checkpoint import expedite_all

            expedite_all()

    w.subscribe_channel("resilience", on_msg)
    return (w, on_msg)


def _unsubscribe_preemption(token) -> None:
    if token is None:
        return
    try:
        token[0].unsubscribe_channel("resilience", token[1])
    except Exception:  # noqa: BLE001 — worker already torn down
        pass


def _persist_checkpoint(ck: Checkpoint, trial_dir: str, rank: int,
                        seq: int, attempt: int = 0) -> Checkpoint:
    """Move a reported checkpoint into `{trial_dir}/pending` NOW, on
    the worker, at report time — not when the gang run returns. A gang
    that dies mid-training (preemption, chaos kill) must leave its
    step-fresh checkpoints on shared storage for the restart to resume
    from; a checkpoint sitting in the dead worker's tempdir is lost.

    Names sort attempt-major: `seq` (the per-run report count) resets
    to 0 on every restart, so without the attempt prefix a long first
    attempt would out-sort a short second one and
    `_newest_pending_checkpoint` would resume attempt 3 from attempt
    1's stale state."""
    import shutil

    pending = os.path.join(trial_dir, "pending")
    os.makedirs(pending, exist_ok=True)
    dst = os.path.join(pending, f"{attempt:04d}-{seq:06d}-rank{rank}")
    if os.path.abspath(ck.path) == dst:
        return ck
    if os.path.exists(dst):
        shutil.rmtree(dst)
    try:
        os.replace(ck.path, dst)
    except OSError:  # cross-filesystem tempdir
        shutil.copytree(ck.path, dst)
        shutil.rmtree(ck.path, ignore_errors=True)
    ck.path = dst
    return ck


def _newest_pending_checkpoint(storage: str) -> Optional[Checkpoint]:
    """Latest worker-persisted checkpoint under `{storage}/pending`
    (names sort as {attempt:04d}-{seq:06d}-rank{r}, newest last)."""
    pending = os.path.join(storage, "pending")
    try:
        names = sorted(os.listdir(pending))
    except OSError:
        return None
    for name in reversed(names):
        path = os.path.join(pending, name)
        if os.path.isdir(path):
            return Checkpoint(path)
    return None


def _batch_tokens(batch) -> int:
    """Tokens per step from a batch pytree: the first leaf with >= 2
    dims contributes batch x seq (the LM convention throughout
    ray_tpu.models); 0 when no such leaf exists."""
    for leaf in jax.tree.leaves(batch):
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 2:
            return int(shape[0]) * int(shape[1])
    return 0


class TrainStep:
    """Jitted SPMD train step over a mesh.

    loss_fn(params, batch) -> scalar; optimizer is an optax
    GradientTransformation. param_specs is a PartitionSpec pytree matching
    params (e.g. models.gpt2_partition_specs); data axes default to
    ('dp','fsdp') batch sharding.

    flops_per_token is the analytic MFU fallback (e.g.
    observability.flops.train_flops_per_token(cfg)) used when the
    backend cannot report per-execution FLOPs through cost_analysis();
    when XLA does report them, the exact number wins.
    """

    def __init__(self, loss_fn: Callable, optimizer, mesh: Mesh,
                 param_specs: Any, data_spec: P = P(("dp", "fsdp")),
                 flops_per_token: Optional[float] = None):
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.param_specs = param_specs
        self.data_spec = data_spec
        self.flops_per_token = flops_per_token

        def step(state, batch):
            def loss_of(p):
                return loss_fn(p, batch)

            loss, grads = jax.value_and_grad(loss_of)(state["params"])
            updates, opt_state = optimizer.update(
                grads, state["opt_state"], state["params"])
            import optax

            params = optax.apply_updates(state["params"], updates)
            new_state = {"params": params, "opt_state": opt_state,
                         "step": state["step"] + 1}
            return new_state, {"loss": loss}

        self._step = step
        self._jitted = None
        # AOT-compiled executable (jit.lower().compile()): built at first
        # execution when a flight-recorder session is active, both to
        # time compilation explicitly and to read XLA's cost_analysis
        # FLOPs for MFU. Falls back to the plain jit cache on any
        # backend that rejects the AOT path.
        self._compiled = None

    def init_state(self, params: Any) -> Dict[str, Any]:
        """Shard params onto the mesh and build optimizer state with
        matching sharding (optimizer moments inherit the param layout).

        The param specs are shardlint-validated against the mesh first:
        spec errors (unknown axis, non-dividing dim, duplicate axis)
        raise HERE with the offending param named, instead of surfacing
        as an opaque XLA error minutes into compilation; HBM warnings
        (large replicated params) go through `warnings.warn`."""
        from ray_tpu.analysis import (MeshLayout, check_specs, errors,
                                      format_report)

        findings = check_specs(self.param_specs, params,
                               MeshLayout.from_mesh(self.mesh))
        if errors(findings):
            raise ValueError(
                "invalid param sharding for this mesh:\n"
                + format_report(errors(findings)))
        if findings:
            import warnings

            warnings.warn("shardlint: " + format_report(findings),
                          stacklevel=2)
        params = jax.device_put(params, self._shardings(self.param_specs))
        with self.mesh:
            opt_state = jax.jit(
                self.optimizer.init,
                in_shardings=(self._shardings(self.param_specs),))(params)
        return {"params": params, "opt_state": opt_state,
                "step": jax.device_put(np.int64(0))}

    def _shardings(self, spec_tree):
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), spec_tree,
            is_leaf=lambda x: isinstance(x, P))

    def __call__(self, state, batch):
        from .session import _get_session

        ctx = _get_session()
        timer = ctx._step_timer if ctx is not None else None
        if timer is not None and not timer.enabled:
            timer = None
        first = self._jitted is None
        if first:
            batch_sh = jax.tree.map(
                lambda _: NamedSharding(self.mesh, self.data_spec), batch)
            self._jitted = jax.jit(self._step, donate_argnums=(0,),
                                   in_shardings=(None, batch_sh),
                                   )
        sharding = NamedSharding(self.mesh, self.data_spec)

        def put(x):
            # already resident with the right sharding -> zero-copy no-op;
            # avoids a host->HBM round trip on the hot step path.
            if getattr(x, "sharding", None) == sharding:
                return x
            return jax.device_put(x, sharding)

        t0 = time.perf_counter() if timer is not None else 0.0
        batch = jax.tree.map(put, batch)
        if timer is not None:
            timer.record("data_wait", time.perf_counter() - t0)
            if first:
                t0 = time.perf_counter()
                self._instrument(timer, state, batch)
                timer.record("compile", time.perf_counter() - t0)
            t0 = time.perf_counter()
        with self.mesh:
            if self._compiled is not None:
                try:
                    out = self._compiled(state, batch)
                except (TypeError, ValueError):
                    # signature/shape mismatch the AOT executable cannot
                    # absorb — raised BEFORE execution (buffers not yet
                    # donated), so retracing via jit is safe. Runtime
                    # failures (e.g. RESOURCE_EXHAUSTED) propagate: the
                    # state may already be donated and a retry would
                    # mask the real error with "Array has been deleted".
                    self._compiled = None
                    out = self._jitted(state, batch)
            else:
                out = self._jitted(state, batch)
        if timer is not None:
            # jax dispatch is async (TPU and CPU): without a sync here
            # device_step_ms would record ~1ms of dispatch while the
            # real step time leaked into other_ms and MFU exploded.
            # The sync is the flight recorder's measurement cost — it
            # trades host/device overlap for honest per-phase numbers,
            # and the telemetry-off path stays fully asynchronous.
            jax.block_until_ready(out)
            timer.record("device_step", time.perf_counter() - t0)
        return out

    def _instrument(self, timer, state, batch) -> None:
        """First-execution flight-recorder hookup: AOT-compile the step
        (so compile time is attributed explicitly, not smeared into the
        first device step), read XLA's per-execution FLOPs, and register
        tokens-per-step + the mesh's aggregate peak FLOPs for MFU."""
        from ray_tpu.observability import flops as _flops

        try:
            with self.mesh:
                self._compiled = self._jitted.lower(state, batch).compile()
            per_device = _flops.compiled_flops(self._compiled)
            if per_device:
                # cost_analysis reports the PER-DEVICE partitioned
                # program; the MFU denominator aggregates peak over the
                # whole mesh, so scale the numerator to match (verified:
                # an 8-way sharded matmul reports 1/8th the flops)
                timer.set_flops_per_step(
                    per_device * int(self.mesh.devices.size))
        except Exception:  # noqa: BLE001 — backend without AOT support
            self._compiled = None
        try:
            timer.set_peak_flops(
                _flops.total_peak_flops(self.mesh.devices))
        except Exception:  # noqa: BLE001 — exotic device objects
            pass
        tokens = _batch_tokens(batch)
        if tokens:
            timer.set_tokens_per_step(tokens)
            if timer.flops_per_step is None and self.flops_per_token:
                # analytic 6N fallback: cost_analysis was unavailable
                timer.set_flops_per_step(self.flops_per_token * tokens)


class JaxTrainer:
    """fit() runs train_fn under a session (reference BaseTrainer.fit,
    base_trainer.py:567)."""

    def __init__(self, train_fn: Callable[[Dict[str, Any]], None], *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 sharding_config: Optional[ShardingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 mode: str = "spmd"):
        self.train_fn = train_fn
        self.train_loop_config = dict(train_loop_config or {})
        self.scaling_config = scaling_config or ScalingConfig()
        self.sharding_config = sharding_config or ShardingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = dict(datasets or {})
        self.resume_from_checkpoint = resume_from_checkpoint
        self.mode = mode

    # ------------------------------------------------------------------ fit

    def fit(self) -> Result:
        storage = self.run_config.resolved_storage_path()
        os.makedirs(storage, exist_ok=True)
        cc = self.run_config.checkpoint_config
        manager = CheckpointManager(
            os.path.join(storage, "checkpoints"),
            num_to_keep=cc.num_to_keep,
            score_attribute=cc.checkpoint_score_attribute,
            score_order=cc.checkpoint_score_order)
        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        latest = self.resume_from_checkpoint
        first_failure_ts: Optional[float] = None
        # chaos plans ride the env on the driver; workers get the spec
        # forwarded explicitly (their spawn env predates the plan)
        chaos_spec = os.environ.get("RAY_TPU_CHAOS_PLAN")
        while True:
            try:
                if self.mode == "workers" and \
                        self.scaling_config.num_workers > 1:
                    result = self._fit_workers(manager, latest, storage,
                                               attempt, chaos_spec)
                else:
                    result = self._fit_spmd(manager, latest, storage,
                                            attempt, chaos_spec)
                result.path = storage
                if attempt and first_failure_ts is not None:
                    # time-to-recovery: first failure -> successful fit
                    _report_resilience_event({
                        "kind": "recovery",
                        "name": self.run_config.name or "default",
                        "attempts": attempt,
                        "ttr_s": round(time.time() - first_failure_ts, 3)})
                return result
            except (KeyboardInterrupt, SystemExit):
                # deliberate stops are not failures: Ctrl-C must kill
                # the run, not trigger a checkpoint-restart
                raise
            except Exception as e:  # noqa: BLE001
                attempt += 1
                if first_failure_ts is None:
                    first_failure_ts = time.time()
                # elastic story = checkpoint-restart (SURVEY.md §7): the
                # newest registered checkpoint wins; a gang that died
                # mid-run leaves worker-persisted checkpoints in
                # pending/ (the preemption grace flow lands there)
                latest = (manager.latest_checkpoint
                          or _newest_pending_checkpoint(storage) or latest)
                if max_failures >= 0 and attempt > max_failures:
                    return Result(error=e, checkpoint=latest, path=storage,
                                  metrics={})
                from ray_tpu.resilience import backoff_delay

                delay = backoff_delay(attempt)
                logger.warning(
                    "train attempt %d failed with %s: %s — restarting "
                    "from %s in %.2fs", attempt, type(e).__name__, e,
                    latest.path if latest else "scratch", delay)
                _report_resilience_event({
                    "kind": "restart",
                    "name": self.run_config.name or "default",
                    "attempt": attempt,
                    "cause": f"{type(e).__name__}: {e}"[:500],
                    "backoff_s": round(delay, 3),
                    "resume_from": latest.path if latest else None})
                time.sleep(delay)
                self._maybe_elastic_reform()

    def _maybe_elastic_reform(self) -> None:
        """Before a workers-mode restart: if schedulable capacity shrank
        below the gang (dead host quarantined, slice preempted) and the
        user set ScalingConfig.min_workers, re-form smaller — shrinking
        whole slices and the dcn_dp axis with them."""
        if self.mode != "workers" or \
                self.scaling_config.min_workers is None:
            return
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            return
        try:
            avail = w.conductor.call("schedulable_resources", timeout=10.0)
        except Exception:  # noqa: BLE001 — older/mid-restart conductor
            return
        per_worker = dict(self.scaling_config.resources_per_worker
                          or {"CPU": 1.0})
        per_worker.setdefault("CPU", 1.0)
        cap = min((int(avail.get(k, 0.0) // v)
                   for k, v in per_worker.items() if v > 0), default=0)
        from ray_tpu.resilience import elastic_reform

        reformed = elastic_reform(self.scaling_config,
                                  self.sharding_config, cap)
        if reformed is None:
            return
        old_n = self.scaling_config.num_workers
        old_slices = self.scaling_config.num_slices
        self.scaling_config, self.sharding_config = reformed
        logger.warning(
            "elastic re-form: capacity shrank to %d worker slot(s); "
            "gang %d workers/%d slices -> %d workers/%d slices",
            cap, old_n, old_slices, self.scaling_config.num_workers,
            self.scaling_config.num_slices)
        _report_resilience_event({
            "kind": "elastic_reform",
            "name": self.run_config.name or "default",
            "from_workers": old_n, "to_workers":
                self.scaling_config.num_workers,
            "from_slices": old_slices,
            "to_slices": self.scaling_config.num_slices})

    # ----------------------------------------------------------- spmd mode

    def _fit_spmd(self, manager: CheckpointManager,
                  latest: Optional[Checkpoint], storage: str,
                  attempt: int = 0,
                  chaos_spec: Optional[str] = None) -> Result:
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        pending_ckpts: List[Any] = []

        def report_fn(metrics: Dict[str, Any],
                      checkpoint: Optional[Checkpoint]) -> None:
            nonlocal last_metrics
            metrics = dict(metrics)
            metrics.setdefault("_time", time.time())
            history.append(metrics)
            last_metrics = metrics
            if checkpoint is not None:
                from .async_checkpoint import AsyncCheckpoint

                if isinstance(checkpoint, AsyncCheckpoint):
                    # in-flight async save: report() must not block on
                    # the disk write — reserve the recency slot NOW and
                    # register at commit time on the writer thread
                    snap = dict(metrics)
                    idx = manager.reserve_index()
                    checkpoint.add_commit_hook(
                        lambda c: manager.register(c, snap, index=idx))
                    pending_ckpts.append(checkpoint)
                else:
                    manager.register(checkpoint, metrics)

        from ray_tpu.observability.step_timer import StepTimer

        run_id = (f"{self.run_config.name or 'default'}"
                  f"/{uuid.uuid4().hex[:8]}")
        timer = StepTimer(run_id, rank=0, world_size=1)
        from ray_tpu.resilience.chaos import monkey_from_spec

        ctx = TrainContext(
            world_size=1, rank=0,
            experiment_name=self.run_config.name or "default",
            trial_dir=storage,
            dataset_shards=self._shard_datasets(0, 1),
            latest_checkpoint=latest,
            run_id=run_id,
            attempt=attempt,
            _report_fn=report_fn,
            _step_timer=timer,
            _chaos=(monkey_from_spec(chaos_spec, rank=0, attempt=attempt)
                    if chaos_spec else None))
        cfg = dict(self.train_loop_config)
        cfg["sharding_config"] = self.sharding_config
        preempt_sub = _subscribe_preemption(ctx)
        _set_session(ctx)
        try:
            self.train_fn(cfg)
        except StopTrial:
            pass
        finally:
            _set_session(None)
            _unsubscribe_preemption(preempt_sub)
            timer.close()  # flush the tail of the step-record batch
            # drain in-flight async saves before declaring the result —
            # best/latest must reflect every reported checkpoint
            for c in pending_ckpts:
                try:
                    c.wait()
                except Exception:  # noqa: BLE001 — failed save ≠ failed fit
                    pass
        return Result(metrics=last_metrics,
                      checkpoint=manager.best_checkpoint
                      or manager.latest_checkpoint or latest,
                      metrics_history=history)

    # --------------------------------------------------------- worker mode

    def _fit_workers(self, manager: CheckpointManager,
                     latest: Optional[Checkpoint], storage: str,
                     attempt: int = 0,
                     chaos_spec: Optional[str] = None) -> Result:
        import ray_tpu

        n = self.scaling_config.num_workers
        bundles = [dict(self.scaling_config.resources_per_worker or
                        {"CPU": 1.0}) for _ in range(n)]
        from ..util.placement_group import placement_group, \
            remove_placement_group

        pg = placement_group(bundles, strategy="STRICT_PACK")
        pg.wait()

        @ray_tpu.remote
        class _TrainWorker:
            """One rank of the group (reference WorkerGroup worker,
            _internal/worker_group.py:102)."""

            def __init__(self, rank: int, world: int):
                self.rank, self.world = rank, world
                self.reports: List[Any] = []

            def run(self, fn_bytes: bytes, cfg: Dict[str, Any],
                    trial_dir: str, shards: Dict[str, Any],
                    latest_path: Optional[str],
                    dist_key: Optional[str] = None,
                    slice_id: Optional[int] = None,
                    num_slices: int = 1,
                    run_id: str = "",
                    attempt: int = 0,
                    chaos_spec: Optional[str] = None) -> List[Any]:
                from ray_tpu._private import serialization
                from ray_tpu.observability.step_timer import StepTimer
                from ray_tpu.resilience.chaos import monkey_from_spec
                from ray_tpu.train.session import (TrainContext,
                                                   _set_session, StopTrial)
                from ray_tpu.train.checkpoint import Checkpoint as Ckpt
                from ray_tpu.train.trainer import (_persist_checkpoint,
                                                   _subscribe_preemption,
                                                   _unsubscribe_preemption)

                fn = serialization.loads(fn_bytes)
                out: List[Any] = []

                def report_fn(metrics, checkpoint):
                    if checkpoint is not None:
                        # durable at REPORT (or, async, COMMIT) time: a
                        # gang killed mid-training must leave its
                        # step-fresh checkpoints behind for the restart.
                        # Async saves persist from the writer thread's
                        # commit hook — strictly before wait() returns,
                        # so the grace flow's report-side wait implies
                        # the checkpoint is already in pending/.
                        if hasattr(checkpoint, "add_commit_hook"):
                            seq = len(out)
                            checkpoint.add_commit_hook(
                                lambda c, _seq=seq: _persist_checkpoint(
                                    c, trial_dir, self.rank, _seq,
                                    attempt))
                        else:
                            checkpoint = _persist_checkpoint(
                                checkpoint, trial_dir, self.rank,
                                len(out), attempt)
                    out.append((metrics, checkpoint))

                # each rank records its own steps; the conductor
                # aggregates the gang view (straggler detection)
                timer = StepTimer(run_id, rank=self.rank,
                                  world_size=self.world)
                ctx = TrainContext(
                    world_size=self.world, rank=self.rank,
                    trial_dir=trial_dir, dataset_shards=shards,
                    latest_checkpoint=(Ckpt(latest_path)
                                       if latest_path else None),
                    jax_dist_key=dist_key,
                    slice_id=slice_id, num_slices=num_slices,
                    run_id=run_id,
                    attempt=attempt,
                    _report_fn=report_fn,
                    _step_timer=timer,
                    _chaos=(monkey_from_spec(chaos_spec, rank=self.rank,
                                             attempt=attempt)
                            if chaos_spec else None))
                preempt_sub = _subscribe_preemption(ctx)
                _set_session(ctx)
                try:
                    if dist_key is not None and self.world > 1:
                        # form the gang's global jax mesh FOR the user —
                        # the reference does process-group setup in the
                        # backend (train/torch/config.py:64-117), train_fn
                        # should see the world already assembled
                        from ray_tpu.parallel.distributed import \
                            setup_jax_distributed
                        setup_jax_distributed()
                    fn(cfg)
                except StopTrial:
                    pass
                finally:
                    _set_session(None)
                    _unsubscribe_preemption(preempt_sub)
                    timer.close()  # ship this rank's tail records
                # In-flight async saves must hit disk before run() returns
                # (the driver registers these paths and then kills this
                # worker, its writer thread with it) — and a save that
                # FAILED must come back as path=None, not as a torn
                # directory the driver would register as a checkpoint.
                resolved: List[Any] = []
                import os as _os

                pending_root = _os.path.abspath(
                    _os.path.join(trial_dir, "pending")) + _os.sep
                for metrics, ck in out:
                    path = None
                    if ck is not None:
                        ok = True
                        if hasattr(ck, "future"):
                            try:
                                ck.wait()
                            except Exception:  # noqa: BLE001 — torn
                                ok = False
                            else:
                                # commit hooks swallow their own errors
                                # (a bad hook must not fail the save):
                                # a path still in the worker tempdir
                                # means the persist-to-pending/ hook
                                # FAILED — that checkpoint dies with
                                # this worker and must not be reported
                                # as durable
                                ok = _os.path.abspath(ck.path).startswith(
                                    pending_root)
                        path = ck.path if ok else None
                    resolved.append((metrics, path))
                return resolved

        from .._private import serialization

        fn_bytes = serialization.dumps(self.train_fn)
        cfg = dict(self.train_loop_config)
        cfg["sharding_config"] = self.sharding_config
        dist_key = None
        if n > 1 and getattr(self.scaling_config,
                             "setup_jax_distributed", True):
            dist_key = f"train-gang/{uuid.uuid4().hex}"
        # multi-slice gangs: the rendezvous groups process ids
        # slice-major for hybrid DCN meshes.
        from .config import assign_worker_slices

        num_slices = max(1, getattr(self.scaling_config, "num_slices", 1))
        slice_ids = assign_worker_slices(n, num_slices)
        run_id = (f"{self.run_config.name or 'default'}"
                  f"/{uuid.uuid4().hex[:8]}")
        # lease the bundle's actual resources (not the 0-CPU actor
        # default): the gang then occupies its reserved capacity and
        # each rank's lease is charged to the host its bundle lives on
        # (failure-domain accounting under ray_tpu.resilience)
        rpw = dict(self.scaling_config.resources_per_worker
                   or {"CPU": 1.0})
        opts: Dict[str, Any] = {"placement_group": pg,
                                "num_cpus": rpw.pop("CPU", 1.0)}
        if rpw:
            opts["resources"] = rpw
        workers = [_TrainWorker.options(**opts)
                   .remote(rank=i, world=n) for i in range(n)]
        from ray_tpu.resilience import GangSupervisor

        try:
            refs = [w.run.remote(
                fn_bytes, cfg, storage, self._shard_datasets(i, n),
                latest.path if latest else None, dist_key,
                slice_ids[i], num_slices, run_id, attempt, chaos_spec)
                for i, w in enumerate(workers)]
            # gang supervision: one dead rank -> cancel the survivors
            # (their collectives can never complete) so this get fails
            # fast and the fit-level retry restarts from checkpoint
            with GangSupervisor(workers, run_id=run_id):
                all_reports = ray_tpu.get(refs)
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            remove_placement_group(pg)
        # Aggregate EVERY rank's reports per step: the lowest reporting
        # rank's metrics are the headline (rank 0 whenever it reported),
        # and "rank_metrics" is ALWAYS present with one entry per rank
        # that reported that step — a stable schema even when ranks
        # report unequal step counts. A checkpoint path from ANY rank
        # registers (first one wins).
        history, last_metrics = [], {}
        n_steps = max((len(r) for r in all_reports), default=0)
        for i in range(n_steps):
            per_rank = [(rank, r[i]) for rank, r in enumerate(all_reports)
                        if len(r) > i]
            metrics = dict(per_rank[0][1][0] or {})
            metrics["rank_metrics"] = [m for _, (m, _p) in per_rank]
            history.append(metrics)
            last_metrics = metrics
            ckpt_path = next((p for _, (_m, p) in per_rank if p), None)
            if ckpt_path:
                manager.register(Checkpoint(ckpt_path), metrics)
        return Result(metrics=last_metrics,
                      checkpoint=manager.best_checkpoint
                      or manager.latest_checkpoint or latest,
                      metrics_history=history)

    # ------------------------------------------------------------ datasets

    def _shard_datasets(self, rank: int, world: int) -> Dict[str, Any]:
        shards: Dict[str, Any] = {}
        for name, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                shards[name] = ds.streaming_split(world)[rank]
            elif world > 1 and hasattr(ds, "__getitem__"):
                shards[name] = ds[rank::world]
            else:
                shards[name] = ds
        return shards
