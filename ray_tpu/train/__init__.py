"""ray_tpu.train: distributed training on TPU meshes.

Capability surface of Ray Train (reference python/ray/train/ — SURVEY.md
§2.4, §3.4): a trainer that gang-schedules a worker group, a session API
(`report`, `get_checkpoint`, `get_dataset_shard`), checkpoint management,
and config dataclasses. TPU-native twist: the "backend" is not an NCCL
process group (train/torch/config.py:64-117) but a named-axis jax Mesh;
intra-step communication is XLA collectives, so the trainer's job reduces
to placement + rendezvous + fault tolerance + checkpoint/report plumbing —
and, single-controller SPMD on one host, running the jitted step over all
local chips directly.
"""
from .config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
    ShardingConfig,
)
from .checkpoint import Checkpoint, CheckpointManager  # noqa: F401
from .async_checkpoint import (  # noqa: F401
    AsyncCheckpoint,
    AsyncCheckpointer,
    async_save,
    restore,
)
from .session import (  # noqa: F401
    get_checkpoint,
    get_context,
    get_dataset_shard,
    get_step_timer,
    preemption_requested,
    report,
)
from .trainer import JaxTrainer, Result, TrainStep  # noqa: F401


def __getattr__(name):
    # PipelineTrainer lives in ray_tpu.mpmd (the MPMD subsystem) but is
    # part of the train surface; resolved lazily to keep
    # `import ray_tpu.train` free of the mpmd/channel machinery.
    if name == "PipelineTrainer":
        from ray_tpu.mpmd import PipelineTrainer

        return PipelineTrainer
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
