"""Async sharded checkpointing — the orbax-style save path SURVEY.md §7.5
budgets (reference persistence plumbing: python/ray/train/_internal/
storage.py + checkpoint_manager.py; the reference itself has no
device-sharded story — torch.save of host tensors — so this is designed
for jax.Array natively rather than translated).

Design:

- ``save()`` synchronously snapshots each jax.Array leaf's addressable
  shards to host memory (device→host copy of replica-0 shards only —
  the cheap, unavoidable part), then hands the writes to a background
  thread and returns an :class:`AsyncCheckpoint` immediately. Training
  step N+1 runs while checkpoint N's bytes hit disk. Snapshotting before
  returning is what makes ``donate_argnums`` safe: the training step may
  overwrite the arrays the moment save() returns.
- Each process writes only its own shards plus a per-process manifest
  and a commit marker; restore requires every process's marker, so a
  torn multi-host save is detected, never silently half-loaded.
- ``restore()`` reshards onto a possibly different mesh: with
  ``like=`` (a template pytree, e.g. a freshly initialized sharded
  state), each device materializes ONLY the slices its new shard needs,
  assembled from mmap'd shard files — a dp=2,fsdp=4 checkpoint restores
  onto dp=8 without any host holding a full copy of a large array.
"""
from __future__ import annotations

import glob
import json
import os
import pickle
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from .checkpoint import Checkpoint

# Live writers, for the preemption grace flow: a "checkpoint now, grace
# N seconds" broadcast expedites EVERY in-flight save in the process so
# the grace checkpoint commits inside the window instead of resolving at
# gang completion (ray_tpu.resilience follow-up from the elastic PR).
_live_writers: "weakref.WeakSet[AsyncCheckpointer]" = weakref.WeakSet()


def expedite_all() -> None:
    """Make every live AsyncCheckpointer in this process commit its
    queued saves as fast as possible (drops test/throttle delays). Called
    when a preemption notice arrives; idempotent."""
    for writer in list(_live_writers):
        writer.expedite()

_MANIFEST = "manifest.{proc}.json"
_COMMIT = "commit.{proc}"
_TREEDEF = "treedef.pkl"


class AsyncCheckpoint(Checkpoint):
    """A Checkpoint whose bytes may still be in flight. ``wait()`` blocks
    until the write is committed (re-raising write errors); passing one
    to ``train.report`` defers manager registration until commit, and
    ``report`` itself returns immediately."""

    def __init__(self, path: str):
        super().__init__(path)
        self.future: "Future[None]" = Future()
        self._hooks: List[Callable[["AsyncCheckpoint"], None]] = []
        self._hook_lock = threading.Lock()

    @property
    def committed(self) -> bool:
        return self.future.done()

    def wait(self) -> "AsyncCheckpoint":
        self.future.result()
        return self

    def add_commit_hook(self, fn: Callable[["AsyncCheckpoint"], None]
                        ) -> None:
        """Run ``fn(self)`` once the write is committed — on the writer
        thread, strictly before ``wait()`` returns. Runs inline if the
        checkpoint is already committed. (The future resolves under
        _hook_lock, so a hook added while done()==False is guaranteed to
        be picked up by the writer's drain loop, never lost.)"""
        with self._hook_lock:
            if not self.future.done():
                self._hooks.append(fn)
                return
        fn(self)

    def _run_hooks_and_resolve(self, error: Optional[BaseException]) -> None:
        import logging

        while True:
            with self._hook_lock:
                hooks, self._hooks = self._hooks, []
                if not hooks:
                    # resolve UNDER the lock: closes the window where a
                    # concurrent add_commit_hook appends after our swap
                    # but before done() flips
                    if error is not None:
                        self.future.set_exception(error)
                    else:
                        self.future.set_result(None)
                    return
            if error is None:
                for fn in hooks:
                    try:
                        fn(self)
                    except Exception:  # noqa: BLE001 — a bad hook ≠ bad save
                        logging.getLogger("ray_tpu.train").exception(
                            "async-checkpoint commit hook failed for %s "
                            "(checkpoint is on disk but NOT registered)",
                            self.path)


def _leaf_snapshots(leaf: Any) -> Tuple[Dict[str, Any],
                                        List[Tuple[tuple, np.ndarray]]]:
    """(meta, [(index_slices, host_array)]) for this process's share of a
    leaf. jax.Arrays contribute their replica-0 addressable shards (the
    union across processes covers the array exactly once); anything else
    is written whole by process 0."""
    if isinstance(leaf, jax.Array):
        shape, dtype = tuple(leaf.shape), np.dtype(leaf.dtype).name
        shards = []
        for s in leaf.addressable_shards:
            if s.replica_id != 0:
                continue
            # scalar arrays have an empty index tuple; the zip handles it
            idx = tuple(sl.indices(dim) for sl, dim in zip(s.index, shape))
            shards.append((idx, np.asarray(s.data)))
        return {"shape": list(shape), "dtype": dtype}, shards
    arr = np.asarray(leaf)
    meta = {"shape": list(arr.shape), "dtype": arr.dtype.name}
    if jax.process_index() != 0:
        return meta, []
    full = tuple((0, dim, 1) for dim in arr.shape)
    return meta, [(full, arr)]


class AsyncCheckpointer:
    """Background writer for sharded pytree checkpoints. One writer
    thread serializes saves in submission order (so deferred manager
    registrations happen in order too)."""

    def __init__(self):
        self._queue: List[Tuple[AsyncCheckpoint, list, Any]] = []
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._test_write_delay = 0.0  # test knob: per-save artificial I/O
        self._expedited = False
        _live_writers.add(self)

    def expedite(self) -> None:
        """Commit queued saves promptly: skip throttle/test delays (an
        in-progress delay is cut short). The preemption grace flow calls
        this so ``wait()`` on the grace checkpoint returns within the
        grace window."""
        with self._cv:
            self._expedited = True
            self._cv.notify_all()

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._write_loop,
                                            daemon=True,
                                            name="async-ckpt-writer")
            self._thread.start()

    def save(self, directory: str, tree: Any) -> AsyncCheckpoint:
        """Snapshot now, write later. Returns immediately; the returned
        checkpoint's ``wait()``/``future`` tracks the disk write."""
        leaves, treedef = jax.tree.flatten(tree)
        snaps = []
        for i, leaf in enumerate(leaves):
            meta, shards = _leaf_snapshots(leaf)
            snaps.append((i, meta, shards))
        ckpt = AsyncCheckpoint(os.path.abspath(directory))
        with self._cv:
            self._queue.append((ckpt, snaps, treedef))
            self._ensure_thread()
            self._cv.notify()
        return ckpt

    def wait_until_finished(self) -> None:
        with self._cv:
            while self._queue:
                self._cv.wait(0.05)

    def _write_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue:
                    self._cv.wait(0.2)
                ckpt, snaps, treedef = self._queue[0]
            error: Optional[BaseException] = None
            try:
                self._write_one(ckpt.path, snaps, treedef)
                if self._test_write_delay:
                    # poll-sleep so expedite() can cut a delay short
                    deadline = time.monotonic() + self._test_write_delay
                    while time.monotonic() < deadline \
                            and not self._expedited:
                        time.sleep(0.01)
            except BaseException as e:  # noqa: BLE001 — surface via future
                error = e
            ckpt._run_hooks_and_resolve(error)
            with self._cv:
                self._queue.pop(0)
                self._cv.notify_all()

    def _write_one(self, directory: str, snaps: list, treedef: Any) -> None:
        proc, nproc = jax.process_index(), jax.process_count()
        os.makedirs(directory, exist_ok=True)
        # Overwriting an existing checkpoint: invalidate OUR commit marker
        # before touching any shard bytes, and clear our stale files — a
        # crash mid-write must read as torn, never as the old checkpoint
        # silently mixed with new shards. (Each process touches only its
        # own files; restore ignores manifests >= process_count.)
        try:
            os.remove(os.path.join(directory, _COMMIT.format(proc=proc)))
        except FileNotFoundError:
            pass
        for stale in glob.glob(os.path.join(directory,
                                            f"leaf*_p{proc}_s*.npy")):
            os.remove(stale)
        manifest: Dict[str, Any] = {"process": proc, "process_count": nproc,
                                    "leaves": {}}
        for leaf_idx, meta, shards in snaps:
            entries = []
            for shard_idx, (index, host_arr) in enumerate(shards):
                fname = f"leaf{leaf_idx}_p{proc}_s{shard_idx}.npy"
                with open(os.path.join(directory, fname), "wb") as f:
                    np.save(f, host_arr)
                entries.append({"file": fname,
                                "index": [list(t) for t in index]})
            manifest["leaves"][str(leaf_idx)] = {**meta, "shards": entries}
        if proc == 0:
            with open(os.path.join(directory, _TREEDEF), "wb") as f:
                pickle.dump(treedef, f, protocol=5)
        tmp = os.path.join(directory, f".manifest.{proc}.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(directory,
                                     _MANIFEST.format(proc=proc)))
        # commit marker last: a restore that sees it knows every shard
        # and the manifest of this process are fully on disk
        with open(os.path.join(directory, _COMMIT.format(proc=proc)),
                  "w") as f:
            f.write("ok")


_default = AsyncCheckpointer()


def async_save(directory: str, tree: Any) -> AsyncCheckpoint:
    """Module-level convenience on a shared default writer."""
    return _default.save(directory, tree)


def wait_until_finished() -> None:
    _default.wait_until_finished()


def _load_manifests(directory: str) -> List[Dict[str, Any]]:
    head = os.path.join(directory, _MANIFEST.format(proc=0))
    if not os.path.exists(head):
        raise FileNotFoundError(f"no checkpoint manifests in {directory}")
    with open(head) as f:
        first = json.load(f)
    nproc = int(first["process_count"])
    # read EXACTLY processes 0..nproc-1: stale manifest.{>=nproc}.json left
    # by an earlier larger-world save must not leak old shards in
    manifests = [first]
    for p in range(1, nproc):
        path = os.path.join(directory, _MANIFEST.format(proc=p))
        if not os.path.exists(path):
            raise ValueError(
                f"checkpoint {directory} is torn: manifest of process "
                f"{p}/{nproc} is missing")
        with open(path) as f:
            manifests.append(json.load(f))
    for p in range(nproc):
        if not os.path.exists(os.path.join(directory,
                                           _COMMIT.format(proc=p))):
            raise ValueError(
                f"checkpoint {directory} is torn: process {p}/{nproc} "
                "never committed its shards")
    return manifests


class _LeafReader:
    """Assembles arbitrary slices of one saved leaf from its (possibly
    many, possibly overlapping) shards, reading only the bytes the
    requested slice touches.

    `loader(shard) -> np.ndarray` materializes one shard's payload; the
    default mmaps the checkpoint's .npy file so a reshard never loads
    untouched bytes. ray_tpu.weights reuses this exact assembly with a
    loader that fetches the shard chunk from its producer's object
    store — the reshard-on-fetch contract is one code path."""

    def __init__(self, directory: Optional[str], shape: tuple, dtype,
                 shards: List[Dict[str, Any]],
                 loader: Optional[Callable[[Dict[str, Any]],
                                           np.ndarray]] = None):
        self.directory = directory
        self.shape = shape
        self.dtype = dtype
        self.shards = shards
        self._loader = loader or self._load_mmap

    def _load_mmap(self, shard: Dict[str, Any]) -> np.ndarray:
        return np.load(os.path.join(self.directory, shard["file"]),
                       mmap_mode="r")

    def read(self, index: Tuple[slice, ...]) -> np.ndarray:
        bounds = tuple(sl.indices(dim)[:2]
                       for sl, dim in zip(index, self.shape))
        out_shape = tuple(b - a for a, b in bounds)
        out = np.empty(out_shape, dtype=self.dtype)
        # coverage mask: replicated shards overlap, and a later copy of
        # an already-filled region must not be LOADED at all — shard
        # order is the placement preference (ray_tpu.weights sorts
        # same-host chunks first, so a colocated replica wins over a
        # remote RPC pull)
        mask = np.zeros(out_shape, dtype=bool)
        want = int(np.prod(out_shape)) if out_shape else 1
        for sh in self.shards:
            sidx = [tuple(t) for t in sh["index"]]
            inter = []
            for (a, b), (sa, sb, _step) in zip(bounds, sidx):
                lo, hi = max(a, sa), min(b, sb)
                if lo >= hi:
                    inter = None
                    break
                inter.append((lo, hi, sa, a))
            if inter is None and self.shape:
                continue
            if self.shape:
                dst = tuple(slice(lo - a, hi - a)
                            for lo, hi, _, a in inter)
                if mask[dst].all():
                    continue  # fully covered: skip the load entirely
            arr = self._loader(sh)
            if not self.shape:  # scalar
                return np.array(arr, dtype=self.dtype)
            src = tuple(slice(lo - sa, hi - sa) for lo, hi, sa, _ in inter)
            out[dst] = arr[src]
            mask[dst] = True
        filled = int(mask.sum())
        if filled < want:
            raise ValueError(
                f"checkpoint shards do not cover requested slice {index} "
                f"of leaf with shape {self.shape} ({filled}/{want} elems)")
        return out


def materialize_like(readers: List[_LeafReader], treedef: Any,
                     like: Any) -> Any:
    """Rebuild a pytree from per-leaf readers with the TEMPLATE's
    shardings: each jax.Array template leaf materializes via
    ``jax.make_array_from_callback``, so every device reads ONLY the
    slice its own shard needs — source and target layouts may differ
    freely and no host ever assembles a full copy of a sharded leaf.
    A template dtype differing from the stored one casts on device.
    Shared by ``restore(like=)`` and the weight fabric's
    reshard-on-fetch (ray_tpu.weights.WeightSubscriber)."""
    like_leaves = treedef.flatten_up_to(like)
    out_leaves = []
    for r, tmpl in zip(readers, like_leaves):
        if isinstance(tmpl, jax.Array) and hasattr(tmpl, "sharding"):
            if tuple(tmpl.shape) != r.shape:
                raise ValueError(
                    f"template leaf shape {tuple(tmpl.shape)} != saved "
                    f"shape {r.shape}")
            arr = jax.make_array_from_callback(
                r.shape, tmpl.sharding, r.read)
            out_leaves.append(arr.astype(tmpl.dtype)
                              if np.dtype(tmpl.dtype).name != r.dtype.name
                              else arr)
        else:
            full = r.read(tuple(slice(0, d) for d in r.shape))
            out_leaves.append(full)
    return jax.tree.unflatten(treedef, out_leaves)


def restore(directory: str, *, like: Any = None) -> Any:
    """Load a checkpoint saved by :func:`async_save`/``save``.

    ``like=None``: every leaf comes back as a fully-assembled numpy array.
    ``like=template``: the template's structure must match the saved
    tree; leaves that are jax.Arrays are restored WITH the template's
    sharding — each new shard reads only its own slice, so the source
    and target meshes may differ freely (the dp/fsdp→dp reshard story).
    """
    manifests = _load_manifests(directory)
    with open(os.path.join(directory, _TREEDEF), "rb") as f:
        treedef = pickle.load(f)
    n_leaves = treedef.num_leaves
    readers: List[_LeafReader] = []
    for i in range(n_leaves):
        metas = [m["leaves"].get(str(i)) for m in manifests]
        meta = next(m for m in metas if m is not None)
        shards = [s for m in metas if m is not None for s in m["shards"]]
        readers.append(_LeafReader(directory, tuple(meta["shape"]),
                                   np.dtype(meta["dtype"]), shards))
    if like is None:
        leaves = [r.read(tuple(slice(0, d) for d in r.shape))
                  for r in readers]
        return jax.tree.unflatten(treedef, leaves)
    return materialize_like(readers, treedef, like)
