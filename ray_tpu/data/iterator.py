"""Batch iteration with prefetch + device transfer.

Reference: python/ray/data/_internal/iterator/ (DataIterator,
iter_batches with prefetch_batches, local shuffle buffer) and Train's
per-worker shards. TPU-native addition: `iter_jax_batches` double-buffers
host->HBM transfers (jax.device_put on the next batch while the current
one computes) and can place batches directly into a mesh sharding so the
training step never sees host data.
"""
from __future__ import annotations

import threading
from collections import deque
from queue import Queue
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from .block import Block, BlockAccessor


def _fetch_blocks(refs, prefetch: int) -> Iterator[Block]:
    """Prefetch block fetches `prefetch` ahead of consumption."""
    import ray_tpu

    refs = list(refs) if not hasattr(refs, "__next__") else refs
    window: deque = deque()
    it = iter(refs)
    done = False
    while True:
        while not done and len(window) <= prefetch:
            try:
                window.append(next(it))
            except StopIteration:
                done = True
        if not window:
            return
        yield ray_tpu.get(window.popleft())


def _rebatch(blocks: Iterator[Block], batch_size: Optional[int],
             drop_last: bool) -> Iterator[Block]:
    """Coalesce/slice blocks into exact batch_size row chunks."""
    if batch_size is None:
        yield from blocks
        return
    buf: List[Block] = []
    buffered = 0
    for b in blocks:
        if b.num_rows == 0:
            continue
        buf.append(b)
        buffered += b.num_rows
        while buffered >= batch_size:
            merged = BlockAccessor.concat(buf)
            yield BlockAccessor(merged).slice(0, batch_size)
            rest = BlockAccessor(merged).slice(batch_size, merged.num_rows)
            buf = [rest] if rest.num_rows else []
            buffered = rest.num_rows
    if buffered and not drop_last:
        yield BlockAccessor.concat(buf)


def _local_shuffle(blocks: Iterator[Block], buffer_size: int,
                   seed: Optional[int]) -> Iterator[Block]:
    """Reservoir-style local shuffle (reference
    local_shuffle_buffer_size): accumulate rows up to buffer_size, emit
    random permutations."""
    rng = np.random.default_rng(seed)
    buf: List[Block] = []
    rows = 0
    for b in blocks:
        buf.append(b)
        rows += b.num_rows
        if rows >= buffer_size:
            merged = BlockAccessor.concat(buf)
            perm = rng.permutation(merged.num_rows).tolist()
            yield BlockAccessor(merged).take_rows(perm)
            buf, rows = [], 0
    if buf:
        merged = BlockAccessor.concat(buf)
        perm = rng.permutation(merged.num_rows).tolist()
        yield BlockAccessor(merged).take_rows(perm)


def iter_batches(refs, *, batch_size: Optional[int] = 256,
                 batch_format: str = "numpy", prefetch_batches: int = 1,
                 local_shuffle_buffer_size: Optional[int] = None,
                 local_shuffle_seed: Optional[int] = None,
                 drop_last: bool = False) -> Iterator[Any]:
    blocks = _fetch_blocks(refs, prefetch_batches)
    if local_shuffle_buffer_size:
        blocks = _local_shuffle(blocks, local_shuffle_buffer_size,
                                local_shuffle_seed)
    for chunk in _rebatch(blocks, batch_size, drop_last):
        yield BlockAccessor(chunk).to_batch(batch_format)


def iter_jax_batches(refs, *, batch_size: Optional[int] = 256,
                     sharding=None, dtypes: Optional[Dict[str, Any]] = None,
                     drop_last: bool = True,
                     **kw) -> Iterator[Any]:
    """Double-buffered device feed: the next batch's device_put overlaps
    the caller's compute on the current batch (host->HBM pipelining).

    Note drop_last defaults to True here (unlike iter_batches): a ragged
    final batch would trigger an XLA recompilation of the jitted step.
    Pass drop_last=False if the tail rows matter more than compile churn.
    """
    import jax

    def put(batch: Dict[str, np.ndarray]):
        if dtypes:
            batch = {k: v.astype(dtypes[k]) if k in dtypes else v
                     for k, v in batch.items()}
        if sharding is not None:
            return {k: jax.device_put(v, sharding) for k, v in batch.items()}
        return {k: jax.device_put(v) for k, v in batch.items()}

    host_iter = iter_batches(refs, batch_size=batch_size,
                             batch_format="numpy", drop_last=drop_last, **kw)
    pending = None
    for batch in host_iter:
        nxt = put(batch)  # async dispatch; completes while caller computes
        if pending is not None:
            yield pending
        pending = nxt
    if pending is not None:
        yield pending


class DataIterator:
    """Handle given to each train worker by streaming_split (reference
    python/ray/data/iterator.py DataIterator)."""

    def __init__(self, ds):
        self._ds = ds

    def iter_batches(self, **kw) -> Iterator[Any]:
        return self._ds.iter_batches(**kw)

    def iter_torch_batches(self, **kw) -> Iterator[Any]:
        return self._ds.iter_torch_batches(**kw)

    def iter_jax_batches(self, **kw) -> Iterator[Any]:
        return self._ds.iter_jax_batches(**kw)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        return self._ds.iter_rows()

    def materialize(self):
        return self._ds.materialize()

    def count(self) -> int:
        return self._ds.count()
