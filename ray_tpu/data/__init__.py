"""ray_tpu.data — distributed datasets over ray_tpu tasks (reference
python/ray/data: lazy plans, streaming execution, Arrow blocks)."""
from .block import Block, BlockAccessor  # noqa: F401
from .compute import ActorPoolStrategy, TaskPoolStrategy  # noqa: F401
from .dataset import Dataset, GroupedData  # noqa: F401
from .datasource import (from_arrow, from_items, from_numpy,  # noqa: F401
                         from_pandas, range, range_tensor, read_binary_files,
                         read_csv, read_images, read_json, read_numpy,
                         read_avro, read_bigquery, read_databricks_tables,
                         read_mongo, read_parquet, read_sql, read_text,
                         read_tfrecords, read_webdataset)
from .iterator import DataIterator  # noqa: F401

__all__ = [
    "ActorPoolStrategy", "TaskPoolStrategy",
    "Block", "BlockAccessor", "Dataset", "GroupedData", "DataIterator",
    "range", "range_tensor", "from_items", "from_numpy", "from_pandas",
    "from_arrow", "read_parquet", "read_csv", "read_json", "read_text",
    "read_binary_files", "read_numpy", "read_images", "read_sql",
    "read_tfrecords", "read_webdataset", "read_avro", "read_mongo",
    "read_bigquery", "read_databricks_tables",
]
