"""Streaming executor: drives the fused plan over ray_tpu tasks.

Reference: python/ray/data/_internal/execution/streaming_executor.py:51 —
a pull-based operator pipeline with bounded in-flight tasks per operator
(backpressure) so datasets larger than memory stream through. Here each
pipeline stage is a Python generator over block ObjectRefs; map stages
keep at most `max_in_flight` tasks outstanding and yield refs in order;
all-to-all stages (repartition/shuffle/sort) are two-phase
split-per-input-block + merge-per-output-block shuffles, the same
task-graph shape the reference plans.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from . import plan as P
from .block import Block, BlockAccessor


def _remote(fn: Callable, num_cpus: float = 1.0):
    import ray_tpu

    return ray_tpu.remote(num_cpus=num_cpus)(fn)


# --- per-block task bodies (top-level so pickling is cheap) ---------------


def _run_read_task(task: Callable[[], Block]) -> Block:
    return task()


def _run_stage(stage: P.FusedStage, block: Block) -> Block:
    return stage(block)


class _PoolWorker:
    """One actor of an ActorPoolStrategy pool: the stage (and any
    callable-class UDF inside it) is constructed once here and reused
    for every block routed to this actor (reference
    _internal/compute.py ActorPoolStrategy semantics)."""

    def __init__(self, stage: P.FusedStage):
        self._stage = stage

    def apply(self, block: Block) -> Block:
        return self._stage(block)

    def exit(self) -> None:
        """Graceful teardown: queued after the actor's in-flight applies,
        so they finish first. The arena segment is left for the
        cluster-stop sweep — unlinking here could break refs fetched but
        not yet mapped by consumers."""
        from ray_tpu.actor import exit_actor

        exit_actor()


def _count_rows(block: Block) -> int:
    return block.num_rows


def _block_info(block: Block) -> Tuple[int, int]:
    acc = BlockAccessor(block)
    return (acc.num_rows(), int(acc.size_bytes()))


def _slice_block(block: Block, start: int, end: int) -> Block:
    return BlockAccessor(block).slice(start, end)


def _split_block(block: Block, n: int, mode: str, seed: Optional[int],
                 boundaries: Optional[List[Any]], key: Optional[str]
                 ) -> List[Block]:
    """Phase 1 of a shuffle: partition one block into n chunks. With
    num_returns=n the worker stores each chunk separately; for n==1 the
    single return must be the bare block, not a 1-list."""
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    if mode == "even":
        cuts = np.linspace(0, rows, n + 1).astype(int)
        chunks = [acc.slice(int(a), int(b))
                  for a, b in zip(cuts, cuts[1:])]
    elif mode == "random":
        rng = np.random.default_rng(seed)
        assign = rng.integers(0, n, rows)
        chunks = [acc.take_rows(np.nonzero(assign == i)[0].tolist())
                  for i in range(n)]
    elif mode == "range":
        vals = block.column(key).to_numpy(zero_copy_only=False)
        assign = np.searchsorted(np.asarray(boundaries), vals, side="right")
        chunks = [acc.take_rows(np.nonzero(assign == i)[0].tolist())
                  for i in range(n)]
    else:
        raise ValueError(mode)
    return chunks if n > 1 else chunks[0]


def _merge_blocks(sort_key: Optional[str], descending: bool,
                  shuffle_seed: Optional[int], *chunks: Block) -> Block:
    """Phase 2: concat chunk i from every input (optionally sort/shuffle).
    Chunks are passed as top-level args so they are real task dependencies
    (dispatch waits for the split phase; no worker-starving in-task get)."""
    out = BlockAccessor.concat(list(chunks))
    if sort_key is not None and out.num_rows > 0:
        out = BlockAccessor(out).sort(sort_key, descending)
    if shuffle_seed is not None and out.num_rows > 0:
        rng = np.random.default_rng(shuffle_seed)
        perm = rng.permutation(out.num_rows).tolist()
        out = BlockAccessor(out).take_rows(perm)
    return out


def _sample_block_keys(block: Block, key: str, n: int) -> np.ndarray:
    return BlockAccessor(block).sample_keys(key, n)


class _ByteBudget:
    """Per-operator in-flight byte accounting + host-store pressure —
    the reference's ResourceManager
    (data/_internal/execution/resource_manager.py:32) scoped to this
    runtime: operators charge an estimate per admitted task (the input
    block's measured bytes where known, the target block size
    otherwise) and admission stalls while the charge would exceed the
    budget or /dev/shm (the zero-copy block store) is past its
    high-water fraction. Count-based windows still apply on top."""

    def __init__(self, cap_bytes: int, shm_high_water: float):
        self.cap = cap_bytes
        self.shm_high_water = shm_high_water
        self.inflight = 0
        self._last_shm_check = 0.0
        self._shm_pressured = False

    def admit_ok(self, est: int) -> bool:
        if self.cap and self.inflight > 0 \
                and self.inflight + est > self.cap:
            return False
        return not self._host_pressure()

    def charge(self, est: int) -> None:
        self.inflight += est

    def release(self, est: int) -> None:
        self.inflight -= est

    def _host_pressure(self) -> bool:
        if self.shm_high_water <= 0:
            return False
        import time as _time

        now = _time.monotonic()
        if now - self._last_shm_check > 0.2:
            self._last_shm_check = now
            try:
                import shutil

                u = shutil.disk_usage("/dev/shm")
                self._shm_pressured = (u.used / max(u.total, 1)
                                       > self.shm_high_water)
            except OSError:
                self._shm_pressured = False
        return self._shm_pressured


class StreamingExecutor:
    """Executes a fused stage list, yielding output block refs in order."""

    def __init__(self, stages: List[Any], *, max_in_flight: int = 8,
                 default_shuffle_blocks: int = 8,
                 target_block_size: Optional[int] = None,
                 memory_budget: Optional[int] = None):
        self.stages = stages
        self.max_in_flight = max_in_flight
        self.default_shuffle_blocks = default_shuffle_blocks
        # reference DataContext.target_max_block_size (128MB default):
        # source/map outputs larger than this split into row ranges so
        # one fat file/UDF can't monopolize downstream task memory
        if target_block_size is None:
            from .block import TARGET_MAX_BLOCK_SIZE

            target_block_size = int(os.environ.get(
                "RAY_TPU_DATA_TARGET_BLOCK_SIZE",
                str(TARGET_MAX_BLOCK_SIZE)))
        self.target_block_size = target_block_size
        from ray_tpu._private.config import config as _cfg

        if memory_budget is None:
            memory_budget = int(os.environ.get(
                "RAY_TPU_DATA_MEMORY_BUDGET", str(_cfg.data_memory_budget)))
        self.memory_budget = memory_budget
        self._shm_high_water = _cfg.data_shm_high_water
        # measured bytes of upstream blocks (filled by _resized probes),
        # consumed as admission estimates by the next map stage
        self._block_bytes: Dict[str, int] = {}

    def run(self) -> Iterator[Any]:
        """Yields ObjectRefs of output blocks. Per-stage execution stats
        (blocks yielded, wall time spent producing them) accumulate in
        self.stage_stats — reference Dataset.stats()."""
        self.stage_stats: List[Dict[str, Any]] = []
        it: Optional[Iterator[Any]] = None
        for stage in self.stages:
            name = getattr(stage, "name", type(stage).__name__)
            it = self._instrumented(name, self._apply(stage, it))
        assert it is not None, "empty plan"
        return it

    def _instrumented(self, name: str, it: Iterator[Any]) -> Iterator[Any]:
        """Count blocks and time-to-yield per stage. wall_s is
        CUMULATIVE — pulls nest, so a stage's time includes everything
        upstream; per-stage self time is derived at report time as the
        difference of consecutive cumulative times (single-consumer
        chain)."""
        import time as _time

        rec = {"stage": name, "blocks": 0, "wall_s": 0.0}
        self.stage_stats.append(rec)

        def gen():
            while True:
                t0 = _time.perf_counter()
                try:
                    ref = next(it)
                except StopIteration:
                    rec["wall_s"] += _time.perf_counter() - t0
                    return
                rec["wall_s"] += _time.perf_counter() - t0
                rec["blocks"] += 1
                yield ref

        return gen()

    # --- stage drivers ----------------------------------------------------
    def _apply(self, stage, upstream: Optional[Iterator[Any]]):
        if isinstance(stage, P.FromBlocks):
            return iter(stage.refs)
        if isinstance(stage, P.Union):
            return self._run_union(stage)
        if isinstance(stage, P.Read):
            return self._resized(self._run_source(stage))
        if isinstance(stage, P.FusedStage):
            return self._resized(self._run_map(stage, upstream))
        if isinstance(stage, P.Repartition):
            return self._run_shuffle(upstream, stage.num_blocks, "even",
                                     None, None, None, None)
        if isinstance(stage, P.RandomShuffle):
            # an unseeded shuffle still needs a concrete merge-phase seed,
            # otherwise the within-partition permutation is skipped
            seed = stage.seed if stage.seed is not None else \
                int.from_bytes(os.urandom(4), "little")
            return self._run_shuffle(upstream, None, "random", stage.seed,
                                     None, None, seed)
        if isinstance(stage, P.Sort):
            return self._run_sort(upstream, stage)
        if isinstance(stage, P.Limit):
            return self._run_limit(upstream, stage.n)
        raise TypeError(f"unknown stage {stage}")

    def _run_union(self, union: P.Union) -> Iterator[Any]:
        for branch in union.branches:
            yield from execute(list(branch),
                               max_in_flight=self.max_in_flight,
                               target_block_size=self.target_block_size)

    def _run_source(self, read: P.Read) -> Iterator[Any]:
        # read tasks charge 0 bytes (output size unknown before the read
        # runs — charging the target block size would silently throttle
        # read concurrency below the count window); count window + the
        # host high-water stall still bound them
        task = _remote(_run_read_task)
        return self._windowed(iter(read.read_tasks), task.remote,
                              self.max_in_flight)

    def _run_map(self, stage: P.FusedStage,
                 upstream: Iterator[Any]) -> Iterator[Any]:
        strategy = stage.compute
        if strategy is not None:
            return self._run_actor_pool(stage, upstream, strategy)
        task = _remote(_run_stage)
        window = stage.concurrency or self.max_in_flight
        return self._windowed(
            upstream, lambda ref: task.remote(stage, ref), window,
            est=self._estimate_bytes)

    def _estimate_bytes(self, ref) -> int:
        """Admission estimate for a map task consuming `ref`: the bytes
        the resize probe measured for that block, 0 when unmeasured
        (charging a guess like the target block size over-throttles
        pipelines of small blocks; unmeasured inputs stay bounded by the
        count window and the host high-water stall)."""
        return self._block_bytes.pop(getattr(ref, "id", None), None) or 0

    def _run_actor_pool(self, stage: P.FusedStage, upstream: Iterator[Any],
                        strategy) -> Iterator[Any]:
        """Bounded autoscaling actor pool for one map stage: round-robin
        block routing (each actor's queue stays FIFO), ordered yield, pool
        growth when every actor is saturated, teardown when the stage
        drains (reference ActorPoolStrategy + _ActorPool).

        Outputs are made durable AT YIELD TIME: the pool dies at stage
        end, so before a ref leaves this generator its block is completed
        and (if its bytes live only on a pool actor) locally materialized
        — a zero-copy shm mapping on the same host. Memory stays
        O(window), refs the consumer drops free normally, and early
        abandonment can never strand a yielded ref on a dead actor."""
        import ray_tpu
        from ray_tpu._private import worker as worker_mod

        actor_cls = ray_tpu.remote(num_cpus=strategy.num_cpus)(_PoolWorker)
        per_actor = max(1, strategy.max_tasks_in_flight_per_actor)
        actors = [actor_cls.remote(stage)
                  for _ in range(strategy.min_size)]
        inflight: List[Any] = []
        rr = 0

        def durable(ref):
            ray_tpu.wait([ref], num_returns=1)
            w = worker_mod.global_worker
            # error results are stored at the owner already (contains()
            # is true for them) — the consumer's get() surfaces those.
            # A failed fetch must RAISE: once the pool exits, the data is
            # unrecoverable, so silently yielding the ref would convert
            # a loud failure here into a confusing one later. (Known
            # limitation: driver-store eviction under extreme pressure
            # can still drop the fetched copy as "refetchable".)
            if w is not None and not w.store.contains(ref.id):
                ray_tpu.get(ref, timeout=120.0)
            return ref

        try:
            for ref in upstream:
                if len(inflight) >= len(actors) * per_actor:
                    if len(actors) < strategy.resolved_max_size:
                        actors.append(actor_cls.remote(stage))
                    else:
                        yield durable(inflight.pop(0))
                inflight.append(
                    actors[rr % len(actors)].apply.remote(ref))
                rr += 1
            for out in inflight:
                yield durable(out)
        finally:
            for a in actors:
                try:
                    # graceful: queued behind in-flight applies, so none
                    # are killed mid-computation (ray_tpu.kill would be
                    # immediate SIGKILL)
                    a.exit.remote()
                except Exception:  # noqa: BLE001 — already dead
                    try:
                        ray_tpu.kill(a)
                    except Exception:  # noqa: BLE001
                        pass

    def _resized(self, upstream: Iterator[Any]) -> Iterator[Any]:
        """Split oversized output blocks into ~target_block_size row
        ranges (reference _internal/output_buffer.py BlockOutputBuffer,
        which splits inside the producing task via dynamic returns — a
        mechanism this runtime lacks, so the split runs as follow-up
        tasks). That stays cheap HERE because blocks over the shm
        threshold are zero-copy mappings on the holder's host and
        locality-aware leasing steers the probe/slice tasks to that
        node: no wire re-transfer of the fat block, just in-memory
        arrow slicing. The per-block probe get() is a tiny message."""
        if not self.target_block_size:
            yield from upstream
            return
        import ray_tpu

        info = _remote(_block_info)
        sl = _remote(_slice_block)

        def emit(ref, info_ref):
            rows, nbytes = ray_tpu.get(info_ref)
            if nbytes <= self.target_block_size or rows <= 1:
                if getattr(ref, "id", None) is not None:
                    # measured size feeds the next operator's byte-budget
                    # admission estimate (_estimate_bytes)
                    self._block_bytes[ref.id] = nbytes
                yield ref
                return
            k = min(rows, -(-nbytes // self.target_block_size))
            cuts = np.linspace(0, rows, k + 1).astype(int)
            for a, b in zip(cuts, cuts[1:]):
                if b > a:
                    piece = sl.remote(ref, int(a), int(b))
                    if getattr(piece, "id", None) is not None:
                        self._block_bytes[piece.id] = nbytes // k
                    yield piece

        # probes run concurrently across the window: the per-block
        # info round-trip overlaps upstream execution instead of
        # serializing the driver loop
        buf: List[Tuple[Any, Any]] = []
        for ref in upstream:
            buf.append((ref, info.remote(ref)))
            if len(buf) >= self.max_in_flight:
                yield from emit(*buf.pop(0))
        for pair in buf:
            yield from emit(*pair)

    def _windowed(self, items: Iterator[Any], submit, window: int,
                  est=None) -> Iterator[Any]:
        """Backpressure: keep at most `window` tasks in flight AND stay
        inside the operator byte budget (`est(item)` bytes charged per
        admitted task, released when its ref is yielded), yielding refs
        in submission order (ordered streaming, like the reference's
        bundle queues + ConcurrencyCapBackpressurePolicy and the
        ResourceManager memory budgets). Admission happens BEFORE
        `submit`, so a stalled operator launches nothing."""
        import ray_tpu

        # one budget instance per operator (the flag documents a
        # per-operator cap): concurrent stages each admit up to the full
        # budget rather than splitting one shared pool
        budget = _ByteBudget(self.memory_budget, self._shm_high_water)
        buf: List[Any] = []
        costs: List[int] = []
        for item in items:
            e = int(est(item)) if est is not None else 0
            while buf and (len(buf) >= window or not budget.admit_ok(e)):
                ray_tpu.wait([buf[0]], num_returns=1)
                yield buf.pop(0)
                budget.release(costs.pop(0))
            ref = submit(item)
            buf.append(ref)
            costs.append(e)
            budget.charge(e)
        for ref, e in zip(buf, costs):
            yield ref
            budget.release(e)

    def _materialize_refs(self, upstream: Iterator[Any]) -> List[Any]:
        return list(upstream)

    def _run_shuffle(self, upstream, num_out, mode, seed, key,
                     boundaries, merge_shuffle_seed) -> Iterator[Any]:
        import ray_tpu

        in_refs = self._materialize_refs(upstream)
        if not in_refs:
            return iter(())
        n = num_out or max(len(in_refs), 1)
        split = _remote(_split_block)
        merge = _remote(_merge_blocks)
        chunk_refs = []
        for ref in in_refs:
            rets = split.options(num_returns=n).remote(ref, n, mode, seed,
                                                       boundaries, key)
            chunk_refs.append(rets if isinstance(rets, list) else [rets])
        # chunk_refs[i][j] = chunk j of input block i
        out = []
        for j in range(n):
            seed_j = None if merge_shuffle_seed is None \
                else merge_shuffle_seed + j
            out.append(merge.remote(None, False, seed_j,
                                    *[c[j] for c in chunk_refs]))
        return iter(out)

    def _run_sort(self, upstream, stage: P.Sort) -> Iterator[Any]:
        import ray_tpu

        in_refs = self._materialize_refs(upstream)
        if not in_refs:
            return iter(())
        n = len(in_refs)
        sample = _remote(_sample_block_keys)
        sampled = [s for s in ray_tpu.get(
            [sample.remote(r, stage.key, 16) for r in in_refs]) if len(s)]
        if not sampled:
            # every block is empty: nothing to range-partition
            return iter(in_refs)
        samples = np.sort(np.concatenate(sampled))
        # n-1 ascending boundaries -> n range partitions (searchsorted
        # requires ascending; descending output comes from reversing the
        # partition order + per-partition descending merge sort)
        idx = np.linspace(0, len(samples) - 1, n + 1).astype(int)[1:-1]
        boundaries = samples[idx].tolist()
        split = _remote(_split_block)
        merge = _remote(_merge_blocks)
        chunk_refs = []
        for ref in in_refs:
            rets = split.options(num_returns=n).remote(
                ref, n, "range", None, boundaries, stage.key)
            chunk_refs.append(rets if isinstance(rets, list) else [rets])
        out = [merge.remote(stage.key, stage.descending, None,
                            *[c[j] for c in chunk_refs]) for j in range(n)]
        if stage.descending:
            out.reverse()
        return iter(out)

    def _run_limit(self, upstream, n: int) -> Iterator[Any]:
        import ray_tpu

        count = _remote(_count_rows)
        sl = _remote(_slice_block)
        remaining = n
        for ref in upstream:
            if remaining <= 0:
                break
            rows = ray_tpu.get(count.remote(ref))
            if rows <= remaining:
                yield ref
                remaining -= rows
            else:
                yield sl.remote(ref, 0, remaining)
                remaining = 0


def execute(logical_ops: List[P.LogicalOp], **kw) -> Iterator[Any]:
    return StreamingExecutor(P.fuse(logical_ops), **kw).run()
