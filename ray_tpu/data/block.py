"""Blocks: the unit of data movement in ray_tpu.data.

The reference stores blocks as Arrow tables in plasma
(python/ray/data/_internal/ — `Block = Union[pa.Table, pd.DataFrame]`);
here a block IS a pyarrow.Table in the host object store, with a
`BlockAccessor` providing the format conversions (arrow/pandas/numpy
batches, rows, slicing, sort/merge primitives) the executor and iterators
need. Tensor columns use Arrow lists with fixed shape metadata so numpy
round-trips are zero-copy where pyarrow allows.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa

Block = pa.Table
DEFAULT_BATCH_SIZE = 1024
# reference: DataContext.target_max_block_size = 128MiB
TARGET_MAX_BLOCK_SIZE = 128 * 1024 * 1024


def _np_to_arrow(col: np.ndarray) -> pa.Array:
    if col.ndim == 1:
        return pa.array(col)
    # tensor column: fixed-size lists with the shape stashed in metadata
    width = int(np.prod(col.shape[1:]))
    flat = col.reshape(len(col), width)
    arr = pa.FixedSizeListArray.from_arrays(
        pa.array(flat.ravel()), width)
    return arr


class BlockAccessor:
    """Format bridge for one block (reference
    python/ray/data/_internal/arrow_block.py ArrowBlockAccessor)."""

    def __init__(self, block: Block):
        self._table = block

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        return BlockAccessor(BlockAccessor.batch_to_block(block))

    # --- construction -----------------------------------------------------
    @staticmethod
    def batch_to_block(batch: Any) -> Block:
        """dict-of-columns / pandas / arrow / list-of-rows -> pa.Table."""
        import pandas as pd

        if isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
        if isinstance(batch, dict):
            cols, names, shapes = [], [], {}
            for name, col in batch.items():
                col = np.asarray(col)
                names.append(name)
                cols.append(_np_to_arrow(col))
                if col.ndim > 1:
                    shapes[name] = col.shape[1:]
            t = pa.table(dict(zip(names, cols)))
            if shapes:
                meta = {f"shape:{k}".encode():
                        repr(tuple(v)).encode() for k, v in shapes.items()}
                t = t.replace_schema_metadata(
                    {**(t.schema.metadata or {}), **meta})
            return t
        if isinstance(batch, list):
            if batch and isinstance(batch[0], dict):
                keys = list(batch[0].keys())
                return BlockAccessor.batch_to_block(
                    {k: np.asarray([row[k] for row in batch]) for k in keys})
            return pa.table({"item": pa.array(batch)})
        raise TypeError(f"cannot convert {type(batch)} to a block")

    @staticmethod
    def from_rows(rows: Sequence[Dict[str, Any]]) -> Block:
        return BlockAccessor.batch_to_block(list(rows))

    # --- basic props ------------------------------------------------------
    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def schema(self) -> pa.Schema:
        return self._table.schema

    def to_arrow(self) -> pa.Table:
        return self._table

    def column_names(self) -> List[str]:
        return self._table.column_names

    # --- conversions ------------------------------------------------------
    def _tensor_shape(self, name: str):
        import ast

        meta = self._table.schema.metadata or {}
        raw = meta.get(f"shape:{name}".encode())
        if not raw:
            return None
        try:
            # literal_eval only: metadata round-trips through files, so it
            # is untrusted input
            shape = ast.literal_eval(raw.decode())
        except (ValueError, SyntaxError):
            return None
        return shape if isinstance(shape, tuple) else None

    def to_numpy(self, columns: Optional[Sequence[str]] = None
                 ) -> Dict[str, np.ndarray]:
        cols = columns or self._table.column_names
        out = {}
        for name in cols:
            col = self._table.column(name)
            if pa.types.is_fixed_size_list(col.type):
                flat = col.combine_chunks().flatten().to_numpy(
                    zero_copy_only=False)
                width = col.type.list_size
                arr = flat.reshape(self._table.num_rows, width)
                shape = self._tensor_shape(name)
                if shape:
                    arr = arr.reshape((self._table.num_rows,) + shape)
            else:
                arr = col.to_numpy(zero_copy_only=False)
            out[name] = arr
        return out

    def to_pandas(self):
        return self._table.to_pandas()

    def to_batch(self, batch_format: str = "numpy"):
        if batch_format in ("numpy", "numpy_items"):
            return self.to_numpy()
        if batch_format in ("pandas", "pd"):
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self._table
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # --- row access -------------------------------------------------------
    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        np_cols = self.to_numpy()
        names = list(np_cols)
        for i in range(self.num_rows()):
            yield {n: np_cols[n][i] for n in names}

    def slice(self, start: int, end: int) -> Block:
        return self._table.slice(start, end - start)

    def take_rows(self, indices: Sequence[int]) -> Block:
        return self._table.take(pa.array(indices, type=pa.int64()))

    # --- merge/sort primitives (for repartition / sort / shuffle) --------
    @staticmethod
    def concat(blocks: Sequence[Block]) -> Block:
        blocks = list(blocks)
        nonempty = [b for b in blocks if b.num_rows > 0]
        if not nonempty:
            # preserve schema from an empty input so downstream column ops
            # still see the dataset's columns
            return blocks[0].slice(0, 0) if blocks else pa.table({})
        return pa.concat_tables(nonempty, promote_options="permissive")

    def sort(self, key: str, descending: bool = False) -> Block:
        order = "descending" if descending else "ascending"
        return self._table.sort_by([(key, order)])

    def sample_keys(self, key: str, n: int) -> np.ndarray:
        if self._table.num_rows == 0:
            return np.array([])
        vals = self._table.column(key).to_numpy(zero_copy_only=False)
        idx = np.random.default_rng(0).choice(
            len(vals), size=min(n, len(vals)), replace=False)
        return vals[idx]


def batches_of(block: Block, batch_size: Optional[int],
               batch_format: str = "numpy") -> Iterator[Any]:
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if batch_size is None or batch_size >= n:
        if n > 0:
            yield acc.to_batch(batch_format)
        return
    for start in range(0, n, batch_size):
        yield BlockAccessor(
            acc.slice(start, min(start + batch_size, n))
        ).to_batch(batch_format)
