"""Compute strategies for map operators.

Reference: python/ray/data/_internal/compute.py:65 (ActorPoolStrategy) —
`map_batches(compute=ActorPoolStrategy(...))` runs the UDF on a bounded,
autoscaling pool of dedicated actors so stateful/expensive-to-construct
UDFs (model weights, tokenizers) are built once per actor and reused
across batches, instead of once per worker that happens to pull a task.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class TaskPoolStrategy:
    """Default: stateless tasks on the shared worker pool; `size` caps
    the stage's in-flight tasks (per-operator backpressure knob)."""

    size: Optional[int] = None


@dataclass(frozen=True)
class ActorPoolStrategy:
    """Bounded pool of dedicated actors for one map stage.

    The pool starts at `min_size` and grows up to `max_size` (defaults
    to min_size) when every actor already has
    `max_tasks_in_flight_per_actor` blocks queued; it is torn down when
    the stage finishes. Construction-per-actor + reuse-across-batches is
    the contract (reference compute.py ActorPoolStrategy semantics).
    """

    min_size: int = 1
    max_size: Optional[int] = None
    max_tasks_in_flight_per_actor: int = 2
    num_cpus: float = 1.0

    def __post_init__(self):
        if self.min_size < 1:
            raise ValueError("min_size must be >= 1")
        if self.max_size is not None and self.max_size < self.min_size:
            raise ValueError("max_size must be >= min_size")

    @property
    def resolved_max_size(self) -> int:
        return self.max_size if self.max_size is not None else self.min_size
