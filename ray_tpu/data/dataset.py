"""Dataset: lazy, distributed data over ray_tpu tasks.

Reference: python/ray/data/dataset.py (Dataset, 5,142 lines) — lazy
logical plan, streaming execution, per-shard iteration for trainers.
Same capability surface here: transforms build a LogicalOp chain,
`iter_batches`/`take`/`write_*` trigger streaming execution, and
`streaming_split`/`split` produce per-worker shards for the
Train-equivalent (`get_dataset_shard`).
"""
from __future__ import annotations

import builtins
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Tuple, Union)

import numpy as np
import pyarrow as pa

from . import plan as P
from .block import Block, BlockAccessor, batches_of
from .executor import StreamingExecutor, execute


class Dataset:
    def __init__(self, ops: List[P.LogicalOp]):
        self._ops = ops
        self._materialized: Optional[List[Any]] = None  # block refs

    # --- plan builders ----------------------------------------------------
    def _chain(self, op: P.LogicalOp) -> "Dataset":
        return Dataset(self._ops + [op])

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return self._chain(P.MapRows("map", fn))

    def map_batches(self, fn: Union[Callable, type], *,
                    batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    concurrency: Optional[int] = None,
                    compute: Optional[Any] = None,
                    **_ignored) -> "Dataset":
        """fn: batch->batch, or a callable class. With
        compute=ActorPoolStrategy(...) the stage runs on a bounded pool
        of dedicated actors (stateful UDF constructed once per actor,
        reused across batches — reference _internal/compute.py:65);
        without it a callable class is constructed once per worker
        process. Plain functions may also use a pool. `concurrency` caps
        this stage's in-flight tasks."""
        if isinstance(fn, type):
            return self._chain(P.MapBatches(
                "map_batches", None, batch_size, batch_format,
                fn_constructor=fn, concurrency=concurrency,
                compute=compute))
        return self._chain(P.MapBatches("map_batches", fn, batch_size,
                                        batch_format,
                                        concurrency=concurrency,
                                        compute=compute))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        return self._chain(P.FlatMap("flat_map", fn))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        return self._chain(P.Filter("filter", fn))

    def add_column(self, col: str, fn: Callable) -> "Dataset":
        return self._chain(P.AddColumn("add_column", col, fn))

    def drop_columns(self, cols: Sequence[str]) -> "Dataset":
        return self._chain(P.DropColumns("drop_columns", tuple(cols)))

    def select_columns(self, cols: Sequence[str]) -> "Dataset":
        return self._chain(P.SelectColumns("select_columns", tuple(cols)))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self._chain(P.RenameColumns("rename_columns",
                                           tuple(mapping.items())))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._chain(P.Repartition("repartition", num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._chain(P.RandomShuffle("random_shuffle", seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._chain(P.Sort("sort", key, descending))

    def limit(self, n: int) -> "Dataset":
        return self._chain(P.Limit("limit", n))

    def union(self, *others: "Dataset") -> "Dataset":
        branches = [tuple(self._source_ops())]
        branches += [tuple(o._source_ops()) for o in others]
        return Dataset([P.Union("union", tuple(branches))])

    def _source_ops(self) -> List[P.LogicalOp]:
        """Ops to re-execute this dataset lazily — materialized refs are
        reused rather than recomputed."""
        if self._materialized is not None:
            return [P.FromBlocks("materialized", tuple(self._materialized))]
        return self._ops

    def zip(self, other: "Dataset") -> "Dataset":
        import ray_tpu

        a = self.materialize()._materialized
        b = other.materialize()._materialized

        def zip_blocks(x, y):
            xt, yt = BlockAccessor(x).to_arrow(), BlockAccessor(y).to_arrow()
            if xt.num_rows != yt.num_rows:
                raise ValueError("zip: block row counts differ; "
                                 "repartition first")
            for name in yt.column_names:
                out_name = name
                while out_name in xt.column_names:
                    out_name += "_1"  # disambiguate (reference zip suffix)
                xt = xt.append_column(out_name, yt.column(name))
            return xt

        if len(a) != len(b):
            raise ValueError("zip: datasets must have equal block counts; "
                             "repartition first")
        z = ray_tpu.remote(zip_blocks)
        return Dataset(
            [P.FromBlocks("zip", tuple(z.remote(x, y)
                                       for x, y in zip(a, b)))])

    def join(self, other: "Dataset", on: str, *, how: str = "inner",
             num_partitions: Optional[int] = None,
             suffix: str = "_r") -> "Dataset":
        """Distributed hash join on a key column — reference
        Dataset.join (python/ray/data/dataset.py joins via
        hash-partitioned shuffle). Both sides are hash-partitioned on
        `on` into `num_partitions` buckets (tasks), then each bucket
        pair is joined with pandas merge. `how`: inner/left/right/outer.
        Right-side duplicate column names get `suffix`."""
        import ray_tpu

        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(f"unsupported join how={how!r}")
        a = self.materialize()._materialized
        b = other.materialize()._materialized
        # a zero-block side still joins (empty inner/left result, pass-
        # through for outer): give it one empty key-only block so every
        # merge partition has something to concat
        if not a:
            a = [ray_tpu.put(pa.table({on: []}))]
        if not b:
            b = [ray_tpu.put(pa.table({on: []}))]
        n = num_partitions or max(len(a), len(b), 1)

        def part(block, on=on, n=n):
            import pandas as pd

            df = BlockAccessor(block).to_pandas()
            if df.empty:
                # keep the schema: a merge partition whose every chunk
                # is empty must still know this side's columns
                outs = [pa.Table.from_pandas(df, preserve_index=False)] * n
            else:
                buckets = pd.util.hash_array(
                    df[on].to_numpy(), categorize=False) % n
                outs = [pa.Table.from_pandas(df[buckets == i],
                                             preserve_index=False)
                        for i in range(n)]
            return outs if n > 1 else outs[0]

        def merge(na, *chunks):
            # on/how/suffix ride the pickled closure; chunks are real
            # task args so dispatch waits for both partition phases
            import pandas as pd

            left = [BlockAccessor(c).to_pandas() for c in chunks[:na]]
            right = [BlockAccessor(c).to_pandas() for c in chunks[na:]]
            ldf = pd.concat(left, ignore_index=True)
            rdf = pd.concat(right, ignore_index=True)
            out = ldf.merge(rdf, on=on, how=how, suffixes=("", suffix))
            return pa.Table.from_pandas(out, preserve_index=False)

        p = ray_tpu.remote(part)
        m = ray_tpu.remote(merge)
        a_chunks = [p.options(num_returns=n).remote(ref) for ref in a]
        b_chunks = [p.options(num_returns=n).remote(ref) for ref in b]
        if n == 1:
            a_chunks = [[c] for c in a_chunks]
            b_chunks = [[c] for c in b_chunks]
        out = [m.remote(len(a),
                        *[c[i] for c in a_chunks],
                        *[c[i] for c in b_chunks])
               for i in range(n)]
        return Dataset([P.FromBlocks("join", tuple(out))])

    # --- execution --------------------------------------------------------
    def _execute(self) -> Iterator[Any]:
        ex = StreamingExecutor(P.fuse(self._ops))
        self._last_executor = ex  # stats() reads stage_stats from here
        return ex.run()

    def _block_refs(self) -> Iterator[Any]:
        if self._materialized is not None:
            return iter(self._materialized)
        return self._execute()

    def _ensure_refs(self) -> List[Any]:
        """Execute once and cache — metadata ops (count/schema/...) must
        not re-run the plan on every call."""
        if self._materialized is None:
            self._materialized = list(self._execute())
        return self._materialized

    def materialize(self) -> "Dataset":
        if self._materialized is None:
            refs = list(self._block_refs())
            ds = Dataset([P.FromBlocks("materialized", tuple(refs))])
            ds._materialized = refs
            ds._last_executor = getattr(self, "_last_executor", None)
            return ds
        return self

    def _blocks(self) -> Iterator[Block]:
        import ray_tpu

        for ref in self._block_refs():
            yield ray_tpu.get(ref)

    # --- consumption ------------------------------------------------------
    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for block in self.limit(n)._blocks():
            out.extend(BlockAccessor(block).iter_rows())
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for block in self._blocks():
            out.extend(BlockAccessor(block).iter_rows())
        return out

    def take_batch(self, batch_size: int = 20,
                   batch_format: str = "numpy") -> Any:
        rows = self.take(batch_size)
        if not rows:
            schema = self.schema()
            empty = schema.empty_table() if schema is not None \
                else BlockAccessor.from_rows([])
            return BlockAccessor(empty).to_batch(batch_format)
        return BlockAccessor(
            BlockAccessor.from_rows(rows)).to_batch(batch_format)

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        import ray_tpu

        cnt = ray_tpu.remote(lambda b: b.num_rows)
        return sum(ray_tpu.get([cnt.remote(r) for r in self._ensure_refs()]))

    def num_blocks(self) -> int:
        return len(self._ensure_refs())

    def schema(self) -> Optional[pa.Schema]:
        import ray_tpu

        for ref in self._ensure_refs():
            return BlockAccessor(ray_tpu.get(ref)).schema()
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def size_bytes(self) -> int:
        import ray_tpu

        sz = ray_tpu.remote(lambda b: b.nbytes)
        return sum(ray_tpu.get([sz.remote(r) for r in self._ensure_refs()]))

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._blocks():
            yield from BlockAccessor(block).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 1,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     drop_last: bool = False) -> Iterator[Any]:
        from .iterator import iter_batches as _ib

        return _ib(self._block_refs(), batch_size=batch_size,
                   batch_format=batch_format,
                   prefetch_batches=prefetch_batches,
                   local_shuffle_buffer_size=local_shuffle_buffer_size,
                   local_shuffle_seed=local_shuffle_seed,
                   drop_last=drop_last)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           device: Optional[str] = None,
                           **kw) -> Iterator[Any]:
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kw):
            yield {k: torch.as_tensor(np.ascontiguousarray(v)).to(
                device or "cpu") for k, v in batch.items()}

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         sharding=None, **kw) -> Iterator[Any]:
        """TPU-native: double-buffered host->HBM transfer; with a
        `sharding`, batches land already laid out for the mesh."""
        from .iterator import iter_jax_batches as _ijb

        return _ijb(self._block_refs(), batch_size=batch_size,
                    sharding=sharding, **kw)

    # --- shards / splits --------------------------------------------------
    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        refs = list(self._block_refs())
        if len(refs) < n or equal:
            # repartition the already-produced refs; do not re-run the plan
            src = Dataset([P.FromBlocks("split_src", tuple(refs))])
            refs = list(src.repartition(n)._block_refs())
        groups: List[List[Any]] = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            groups[i % n].append(ref)
        return [Dataset([P.FromBlocks(f"split_{i}", tuple(g))])
                for i, g in enumerate(groups)]

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["DataIterator"]:
        """Per-worker iterators over disjoint shards (reference
        streaming_split, used by get_dataset_shard)."""
        from .iterator import DataIterator

        return [DataIterator(shard) for shard in self.split(n, equal=equal)]

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> Tuple["Dataset", "Dataset"]:
        ds = self.random_shuffle(seed=seed) if shuffle else self
        ds = ds.materialize()
        total = ds.count()
        n_test = int(total * test_size) if isinstance(test_size, float) \
            else int(test_size)
        train = ds.limit(total - n_test)
        # drop the first total-n_test rows for the test split
        test = _drop_head(ds, total - n_test)
        return train.materialize(), test.materialize()

    # --- groupby / aggregates --------------------------------------------
    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def _agg_all(self, exprs: List[Tuple[str, str]]) -> Dict[str, Any]:
        """Global aggregate via per-block partials + driver combine."""
        import ray_tpu

        def partial(block, exprs=tuple(exprs)):
            out = {}
            for col, how in exprs:
                v = block.column(col).to_numpy(zero_copy_only=False)
                if how == "sum":
                    out[(col, how)] = (v.sum(), len(v))
                elif how == "min":
                    out[(col, how)] = (v.min() if len(v) else None, len(v))
                elif how == "max":
                    out[(col, how)] = (v.max() if len(v) else None, len(v))
                elif how in ("mean", "std"):
                    out[(col, how)] = (v.sum(), (v ** 2).sum(), len(v))
                elif how == "count":
                    out[(col, how)] = (len(v),)
            return out

        t = ray_tpu.remote(partial)
        parts = ray_tpu.get([t.remote(r) for r in self._block_refs()])
        result: Dict[str, Any] = {}
        for col, how in exprs:
            vals = [p[(col, how)] for p in parts if p.get((col, how))]
            if how == "sum":
                result[f"sum({col})"] = sum(v[0] for v in vals)
            elif how == "min":
                result[f"min({col})"] = min(v[0] for v in vals
                                            if v[0] is not None)
            elif how == "max":
                result[f"max({col})"] = max(v[0] for v in vals
                                            if v[0] is not None)
            elif how == "count":
                result[f"count({col})"] = sum(v[0] for v in vals)
            elif how == "mean":
                n = sum(v[2] for v in vals)
                result[f"mean({col})"] = sum(v[0] for v in vals) / max(n, 1)
            elif how == "std":
                n = sum(v[2] for v in vals)
                s1 = sum(v[0] for v in vals)
                s2 = sum(v[1] for v in vals)
                mean = s1 / max(n, 1)
                var = s2 / max(n, 1) - mean ** 2
                result[f"std({col})"] = float(np.sqrt(max(var, 0.0)))
        return result

    def sum(self, col: str):
        return self._agg_all([(col, "sum")])[f"sum({col})"]

    def min(self, col: str):
        return self._agg_all([(col, "min")])[f"min({col})"]

    def max(self, col: str):
        return self._agg_all([(col, "max")])[f"max({col})"]

    def mean(self, col: str):
        return self._agg_all([(col, "mean")])[f"mean({col})"]

    def std(self, col: str):
        return self._agg_all([(col, "std")])[f"std({col})"]

    # --- conversion / writing --------------------------------------------
    def to_pandas(self):
        return BlockAccessor.concat(list(self._blocks())).to_pandas()

    def to_arrow_refs(self) -> List[Any]:
        return list(self._block_refs())

    def write_parquet(self, path: str) -> None:
        self._write(path, "parquet")

    def write_csv(self, path: str) -> None:
        self._write(path, "csv")

    def write_json(self, path: str) -> None:
        self._write(path, "json")

    def _write(self, path: str, fmt: str) -> None:
        import os

        import ray_tpu

        os.makedirs(path, exist_ok=True)

        def write_block(block, i, path=path, fmt=fmt):
            import pyarrow.csv as pacsv
            import pyarrow.parquet as pq

            f = os.path.join(path, f"part-{i:05d}.{fmt}")
            if fmt == "parquet":
                pq.write_table(block, f)
            elif fmt == "csv":
                pacsv.write_csv(block, f)
            elif fmt == "json":
                import json as _json

                rows = list(BlockAccessor(block).iter_rows())
                with open(f, "w") as fh:
                    for r in rows:
                        fh.write(_json.dumps(
                            {k: (v.tolist() if isinstance(v, np.ndarray)
                                 else (v.item() if isinstance(
                                     v, np.generic) else v))
                             for k, v in r.items()}) + "\n")
            return f

        w = ray_tpu.remote(write_block)
        ray_tpu.get([w.remote(ref, i)
                     for i, ref in enumerate(self._block_refs())])

    def stats(self) -> str:
        """Per-stage execution stats of the most recent run (reference
        Dataset.stats()): blocks produced and driver-side wall time per
        stage. Stages pipeline, so times OVERLAP — they are not a sum.
        Before execution, falls back to the fused plan summary."""
        ex = getattr(self, "_last_executor", None)
        if ex is None or not getattr(ex, "stage_stats", None):
            stages = P.fuse(self._ops)
            return " -> ".join(getattr(s, "name", type(s).__name__)
                               for s in stages)
        width = max(5, max(len(r["stage"]) for r in ex.stage_stats))
        # wall_s is cumulative (pulls nest through upstream iterators);
        # self_s isolates each stage as the consecutive difference
        lines = [f"{'stage':<{width}}  blocks    cum_s   self_s"]
        prev = 0.0
        for r in ex.stage_stats:
            self_s = max(0.0, r["wall_s"] - prev)
            prev = r["wall_s"]
            lines.append(f"{r['stage']:<{width}}  "
                         f"{r['blocks']:>6}  {r['wall_s']:>7.3f}  "
                         f"{self_s:>7.3f}")
        return "\n".join(lines)

    def __repr__(self):
        return f"Dataset(ops={[o.name for o in self._ops]})"


def _drop_head(ds: Dataset, n: int) -> Dataset:
    import ray_tpu

    refs = list(ds._block_refs())
    cnt = ray_tpu.remote(lambda b: b.num_rows)
    counts = ray_tpu.get([cnt.remote(r) for r in refs])
    sl = ray_tpu.remote(lambda b, s, e: BlockAccessor(b).slice(s, e))
    out, skipped = [], 0
    for ref, rows in zip(refs, counts):
        if skipped + rows <= n:
            skipped += rows
            continue
        if skipped < n:
            out.append(sl.remote(ref, n - skipped, rows))
            skipped = n
        else:
            out.append(ref)
    return Dataset([P.FromBlocks("tail", tuple(out))])


class GroupedData:
    """Hash-free groupby: range-partition on the key via sort-shuffle then
    per-partition pandas groupby (reference _internal/planner/aggregate.py
    sort-based aggregation)."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, exprs: List[Tuple[str, str]]) -> Dataset:
        import ray_tpu

        key = self._key
        sorted_ds = self._ds.sort(key)

        def agg_block(block, key=key, exprs=tuple(exprs)):
            import pandas as pd

            df = BlockAccessor(block).to_pandas()
            if df.empty:
                return pa.table({})
            agg_map: Dict[str, List[str]] = {}
            for col, how in exprs:
                agg_map.setdefault(col, []).append(how)
            g = df.groupby(key, sort=True).agg(agg_map)
            g.columns = [f"{how}({col})" for col, how in
                         ((c, h) for c, hs in agg_map.items() for h in hs)]
            g = g.reset_index()
            return pa.Table.from_pandas(g, preserve_index=False)

        t = ray_tpu.remote(agg_block)
        refs = [t.remote(r) for r in sorted_ds._block_refs()]
        return Dataset([P.FromBlocks("groupby_agg", tuple(refs))])

    def count(self) -> Dataset:
        ds = self._agg([(self._key, "count")])
        return ds.rename_columns({f"count({self._key})": "count()"})

    def sum(self, col: str) -> Dataset:
        return self._agg([(col, "sum")])

    def min(self, col: str) -> Dataset:
        return self._agg([(col, "min")])

    def max(self, col: str) -> Dataset:
        return self._agg([(col, "max")])

    def mean(self, col: str) -> Dataset:
        return self._agg([(col, "mean")])

    def map_groups(self, fn: Callable) -> Dataset:
        import ray_tpu

        key = self._key
        sorted_ds = self._ds.sort(key)

        def apply_groups(block, key=key, fn=fn):
            import pandas as pd

            df = BlockAccessor(block).to_pandas()
            if df.empty:
                return pa.table({})
            outs = [BlockAccessor.batch_to_block(fn(g))
                    for _, g in df.groupby(key, sort=True)]
            return BlockAccessor.concat(outs)

        t = ray_tpu.remote(apply_groups)
        return Dataset([P.FromBlocks(
            "map_groups",
            tuple(t.remote(r) for r in sorted_ds._block_refs()))])
