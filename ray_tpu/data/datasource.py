"""Read APIs — reference python/ray/data/read_api.py + datasource/
(parquet/csv/json/text/binary/images/numpy readers as parallel read
tasks). Each file (or range chunk) becomes one zero-arg read task; the
streaming executor schedules them as ray_tpu tasks with backpressure.
"""
from __future__ import annotations

import builtins as _builtins
import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np
import pyarrow as pa

from . import plan as P
from .block import Block, BlockAccessor
from .dataset import Dataset

DEFAULT_PARALLELISM = 8


def _expand_paths(paths: Union[str, Sequence[str]],
                  suffixes: Optional[Sequence[str]] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, names in os.walk(p):
                files += [os.path.join(root, n) for n in sorted(names)]
        elif any(ch in p for ch in "*?["):
            files += sorted(_glob.glob(p))
        else:
            files.append(p)
    if suffixes:
        files = [f for f in files
                 if any(f.endswith(s) for s in suffixes)]
    if not files:
        raise FileNotFoundError(f"no input files for {paths}")
    return files


def _make_read(name: str, tasks: List[Callable[[], Block]]) -> Dataset:
    return Dataset([P.Read(name, tuple(tasks))])


# --- in-memory sources ----------------------------------------------------


def range(n: int, *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:  # noqa: A001
    cuts = np.linspace(0, n, min(parallelism, max(n, 1)) + 1).astype(int)

    def make(a: int, b: int):
        return lambda: pa.table({"id": np.arange(a, b, dtype=np.int64)})

    return _make_read("range",
                      [make(int(a), int(b)) for a, b in zip(cuts, cuts[1:])])


def range_tensor(n: int, *, shape=(1,),
                 parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    cuts = np.linspace(0, n, min(parallelism, max(n, 1)) + 1).astype(int)

    def make(a: int, b: int):
        def read():
            base = np.arange(a, b, dtype=np.int64).reshape((-1,) + (1,) *
                                                           len(shape))
            data = np.broadcast_to(base, (b - a,) + tuple(shape)).copy()
            return BlockAccessor.batch_to_block({"data": data})

        return read

    return _make_read("range_tensor",
                      [make(int(a), int(b)) for a, b in zip(cuts, cuts[1:])])


def from_items(items: Sequence[Any], *,
               parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    items = list(items)
    chunks = np.array_split(np.arange(len(items)),
                            min(parallelism, max(len(items), 1)))

    def make(idx):
        part = [items[i] for i in idx]
        return lambda: BlockAccessor.batch_to_block(part)

    return _make_read("from_items", [make(c) for c in chunks if len(c)])


def from_numpy(arr: Union[np.ndarray, Dict[str, np.ndarray]], *,
               parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    if isinstance(arr, np.ndarray):
        arr = {"data": arr}
    n = len(next(iter(arr.values())))
    cuts = np.linspace(0, n, min(parallelism, max(n, 1)) + 1).astype(int)

    def make(a: int, b: int):
        part = {k: v[a:b] for k, v in arr.items()}
        return lambda: BlockAccessor.batch_to_block(part)

    return _make_read("from_numpy",
                      [make(int(a), int(b)) for a, b in zip(cuts, cuts[1:])])


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return _make_read(
        "from_pandas",
        [(lambda d=df: pa.Table.from_pandas(d, preserve_index=False))
         for df in dfs])


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _make_read("from_arrow", [(lambda t=t: t) for t in tables])


# --- file sources ---------------------------------------------------------


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 **_kw) -> Dataset:
    files = _expand_paths(paths, (".parquet",))

    def make(f):
        def read():
            import pyarrow.parquet as pq

            return pq.read_table(f, columns=columns)

        return read

    return _make_read("read_parquet", [make(f) for f in files])


def read_csv(paths, **_kw) -> Dataset:
    files = _expand_paths(paths, (".csv",))

    def make(f):
        def read():
            import pyarrow.csv as pacsv

            return pacsv.read_csv(f)

        return read

    return _make_read("read_csv", [make(f) for f in files])


def read_json(paths, **_kw) -> Dataset:
    files = _expand_paths(paths, (".json", ".jsonl"))

    def make(f):
        def read():
            import pyarrow.json as pajson

            return pajson.read_json(f)

        return read

    return _make_read("read_json", [make(f) for f in files])


def read_text(paths, **_kw) -> Dataset:
    files = _expand_paths(paths)

    def make(f):
        def read():
            with open(f, "r") as fh:
                lines = [ln.rstrip("\n") for ln in fh]
            return pa.table({"text": lines})

        return read

    return _make_read("read_text", [make(f) for f in files])


def read_binary_files(paths, *, include_paths: bool = False,
                      **_kw) -> Dataset:
    files = _expand_paths(paths)

    def make(f):
        def read():
            with open(f, "rb") as fh:
                data = fh.read()
            cols: Dict[str, Any] = {"bytes": [data]}
            if include_paths:
                cols["path"] = [f]
            return pa.table(cols)

        return read

    return _make_read("read_binary_files", [make(f) for f in files])


def read_numpy(paths, **_kw) -> Dataset:
    files = _expand_paths(paths, (".npy",))

    def make(f):
        def read():
            return BlockAccessor.batch_to_block({"data": np.load(f)})

        return read

    return _make_read("read_numpy", [make(f) for f in files])


def read_images(paths, *, size: Optional[tuple] = None,
                mode: str = "RGB", include_paths: bool = False,
                **_kw) -> Dataset:
    files = _expand_paths(
        paths, (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp"))

    def make(f):
        def read():
            from PIL import Image

            img = Image.open(f).convert(mode)
            if size is not None:
                img = img.resize(size)
            cols: Dict[str, Any] = {"image": np.asarray(img)[None]}
            if include_paths:
                return BlockAccessor.batch_to_block(
                    {**cols, "path": np.asarray([f])})
            return BlockAccessor.batch_to_block(cols)

        return read

    return _make_read("read_images", [make(f) for f in files])


def read_sql(sql: str, connection_factory: Callable[[], Any], *,
             parallelism: int = DEFAULT_PARALLELISM,
             shard_keys: Optional[List[str]] = None,
             shard_hash_fn: str = "ABS",
             **_kw) -> Dataset:
    """DB-API 2.0 query as a dataset — reference
    python/ray/data/read_api.py read_sql (:2047). Without `shard_keys`
    the query runs as one read task (the reference's default); with
    them, rows are hash-sharded across `parallelism` tasks by appending
    a `MOD(hash, parallelism) = i` predicate, mirroring the reference's
    sharded read path."""
    def make(where: Optional[str]):
        def read():
            conn = connection_factory()
            try:
                cur = conn.cursor()
                q = sql
                if where:
                    q = f"SELECT * FROM ({sql}) __rt WHERE {where}"
                cur.execute(q)
                cols = [d[0] for d in cur.description]
                rows = cur.fetchall()
                return pa.table({c: [r[i] for r in rows]
                                 for i, c in enumerate(cols)})
            finally:
                conn.close()

        return read

    if not shard_keys:
        return _make_read("read_sql", [make(None)])

    concat = " || ".join(f"CAST({k} AS TEXT)" for k in shard_keys)
    if shard_hash_fn == "ABS":
        hash_expr = (f"{shard_hash_fn}(LENGTH({concat}) + "
                     f"UNICODE(SUBSTR({concat}, 1, 1)))")
    else:
        hash_expr = f"{shard_hash_fn}({concat})"
    # COALESCE: a NULL shard key makes the whole hash NULL, which would
    # match NO shard's predicate and silently drop the row — route NULLs
    # to shard 0 instead
    tasks = [make(f"COALESCE({hash_expr} % {parallelism}, 0) = {i}")
             for i in _builtins.range(parallelism)]  # `range` is shadowed
    return _make_read("read_sql", tasks)


def _tfrecord_records(path: str):
    """TFRecord framing: per record, {length: uint64 LE, length_crc:
    uint32, data: bytes, data_crc: uint32}. CRCs are not verified (the
    reference delegates to TF's reader; this is a dependency-free
    parser for the same format)."""
    import struct as _struct

    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = _struct.unpack("<Q", header[:8])
            data = f.read(length)
            f.read(4)  # data crc
            if len(data) < length:
                return
            yield data


def read_tfrecords(paths, *, raw: bool = False, **_kw) -> Dataset:
    """TFRecord files of tf.train.Example protos — reference
    read_api.py read_tfrecords (:1676). `raw=True` yields the record
    bytes without proto decoding; otherwise each Example's features
    become columns (bytes_list/float_list/int64_list; single-element
    lists are unwrapped, like the reference's fast-read path)."""
    files = _expand_paths(paths, (".tfrecords", ".tfrecord"))

    def make(f):
        def read():
            records = list(_tfrecord_records(f))
            if raw:
                return pa.table({"bytes": records})
            rows = [_parse_tf_example(r) for r in records]
            cols: Dict[str, List[Any]] = {}
            for r in rows:
                for k in r:
                    cols.setdefault(k, [])
            for r in rows:
                for k, acc in cols.items():
                    acc.append(r.get(k))
            return pa.table(cols)

        return read

    return _make_read("read_tfrecords", [make(f) for f in files])


def _read_varint(buf: bytes, pos: int):
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _parse_tf_example(data: bytes) -> Dict[str, Any]:
    """Minimal tf.train.Example proto decode (Example > Features >
    map<string, Feature>; Feature is oneof bytes_list/float_list/
    int64_list). Hand-rolled wire-format walk — no tensorflow/protobuf
    dependency."""
    import struct as _struct

    def parse_feature(buf):
        # Feature { BytesList=1, FloatList=2, Int64List=3 }
        pos = 0
        while pos < len(buf):
            tag, pos = _read_varint(buf, pos)
            field, wire = tag >> 3, tag & 7
            ln, pos = _read_varint(buf, pos)
            payload = buf[pos:pos + ln]
            pos += ln
            inner, ipos, vals = payload, 0, []
            if field == 1:          # BytesList: repeated bytes value=1
                while ipos < len(inner):
                    t, ipos = _read_varint(inner, ipos)
                    vl, ipos = _read_varint(inner, ipos)
                    vals.append(inner[ipos:ipos + vl])
                    ipos += vl
            elif field == 2:        # FloatList: packed float value=1
                while ipos < len(inner):
                    t, ipos = _read_varint(inner, ipos)
                    vl, ipos = _read_varint(inner, ipos)
                    vals.extend(_struct.unpack(f"<{vl // 4}f",
                                               inner[ipos:ipos + vl]))
                    ipos += vl
            elif field == 3:        # Int64List: packed varint value=1
                while ipos < len(inner):
                    t, ipos = _read_varint(inner, ipos)
                    vl, ipos = _read_varint(inner, ipos)
                    end = ipos + vl
                    while ipos < end:
                        v, ipos = _read_varint(inner, ipos)
                        vals.append(v)
            return vals
        return []

    out: Dict[str, Any] = {}
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        ln, pos = _read_varint(data, pos)
        features = data[pos:pos + ln]  # Example.features (field 1)
        pos += ln
        fpos = 0
        while fpos < len(features):
            ftag, fpos = _read_varint(features, fpos)
            fln, fpos = _read_varint(features, fpos)
            entry = features[fpos:fpos + fln]  # map entry
            fpos += fln
            epos, name, fval = 0, None, []
            while epos < len(entry):
                etag, epos = _read_varint(entry, epos)
                eln, epos = _read_varint(entry, epos)
                payload = entry[epos:epos + eln]
                epos += eln
                if etag >> 3 == 1:
                    name = payload.decode()
                else:
                    fval = parse_feature(payload)
            if name is not None:
                out[name] = fval[0] if len(fval) == 1 else fval
    return out


def read_webdataset(paths, *, parallelism: int = DEFAULT_PARALLELISM,
                    **_kw) -> Dataset:
    """WebDataset tar shards — reference read_api.py read_webdataset
    (:1840): each tar member group sharing a basename becomes one row,
    with one column per extension (bytes; .txt/.cls decoded, .json
    parsed)."""
    import json as _json
    import tarfile

    files = _expand_paths(paths, (".tar",))

    def make(f):
        def read():
            rows: Dict[str, Dict[str, Any]] = {}
            with tarfile.open(f) as tar:
                for m in tar.getmembers():
                    if not m.isfile():
                        continue
                    base, _, ext = m.name.partition(".")
                    data = tar.extractfile(m).read()
                    if ext in ("txt", "cls"):
                        val: Any = data.decode()
                    elif ext == "json":
                        val = _json.loads(data)
                    else:
                        val = data
                    rows.setdefault(base, {"__key__": base})[ext] = val
            ordered = [rows[k] for k in sorted(rows)]
            cols: Dict[str, List[Any]] = {}
            for r in ordered:
                for k in r:
                    cols.setdefault(k, [])
            for r in ordered:
                for k, acc in cols.items():
                    acc.append(r.get(k))
            return pa.table(cols)

        return read

    return _make_read("read_webdataset", [make(f) for f in files])


# --- Avro OCF (pure-python container parser, no avro dependency) -----------

def _avro_read_long(buf: bytes, pos: int):
    """Avro zig-zag varint."""
    n = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (n >> 1) ^ -(n & 1), pos


def _avro_decode(schema, buf: bytes, pos: int):
    """Decode one datum for a (parsed-JSON) Avro schema. Supports the
    core types real files use: primitives, records, enums, arrays, maps,
    unions, fixed, bytes/string."""
    import struct as _struct

    if isinstance(schema, list):  # union: long index, then that branch
        idx, pos = _avro_read_long(buf, pos)
        return _avro_decode(schema[idx], buf, pos)
    t = schema["type"] if isinstance(schema, dict) else schema
    if isinstance(t, (dict, list)):
        return _avro_decode(t, buf, pos)
    if t == "null":
        return None, pos
    if t == "boolean":
        return buf[pos] != 0, pos + 1
    if t in ("int", "long"):
        return _avro_read_long(buf, pos)
    if t == "float":
        return _struct.unpack("<f", buf[pos:pos + 4])[0], pos + 4
    if t == "double":
        return _struct.unpack("<d", buf[pos:pos + 8])[0], pos + 8
    if t in ("bytes", "string"):
        ln, pos = _avro_read_long(buf, pos)
        raw = buf[pos:pos + ln]
        return (raw.decode() if t == "string" else raw), pos + ln
    if t == "fixed":
        ln = schema["size"]
        return buf[pos:pos + ln], pos + ln
    if t == "enum":
        idx, pos = _avro_read_long(buf, pos)
        return schema["symbols"][idx], pos
    if t == "record":
        out = {}
        for f in schema["fields"]:
            out[f["name"]], pos = _avro_decode(f["type"], buf, pos)
        return out, pos
    if t == "array":
        items = []
        while True:
            cnt, pos = _avro_read_long(buf, pos)
            if cnt == 0:
                return items, pos
            if cnt < 0:  # block with byte size prefix
                cnt = -cnt
                _, pos = _avro_read_long(buf, pos)
            for _ in _builtins.range(cnt):
                item, pos = _avro_decode(schema["items"], buf, pos)
                items.append(item)
    if t == "map":
        out = {}
        while True:
            cnt, pos = _avro_read_long(buf, pos)
            if cnt == 0:
                return out, pos
            if cnt < 0:
                cnt = -cnt
                _, pos = _avro_read_long(buf, pos)
            for _ in _builtins.range(cnt):
                key, pos = _avro_decode("string", buf, pos)
                out[key], pos = _avro_decode(schema["values"], buf, pos)
    raise ValueError(f"unsupported avro type {t!r}")


def read_avro(paths, **_kw) -> Dataset:
    """Avro object-container files — reference read_api.py read_avro
    (:1475; pyarrow there, a dependency-free OCF parser here: header
    metadata map with embedded JSON schema, deflate/null codecs,
    sync-marker-delimited blocks)."""
    import json as _json
    import zlib

    files = _expand_paths(paths, (".avro",))

    def make(f):
        def read():
            data = open(f, "rb").read()
            if data[:4] != b"Obj\x01":
                raise ValueError(f"{f}: not an Avro object container file")
            pos, meta = 4, {}
            while True:
                cnt, pos = _avro_read_long(data, pos)
                if cnt == 0:
                    break
                if cnt < 0:
                    cnt = -cnt
                    _, pos = _avro_read_long(data, pos)
                for _ in _builtins.range(cnt):
                    key, pos = _avro_decode("string", data, pos)
                    val, pos = _avro_decode("bytes", data, pos)
                    meta[key] = val
            schema = _json.loads(meta["avro.schema"])
            codec = meta.get("avro.codec", b"null")
            codec = codec.decode() if isinstance(codec, bytes) else codec
            sync = data[pos:pos + 16]
            pos += 16
            rows = []
            while pos < len(data):
                cnt, pos = _avro_read_long(data, pos)
                nbytes, pos = _avro_read_long(data, pos)
                block = data[pos:pos + nbytes]
                pos += nbytes
                if codec == "deflate":
                    block = zlib.decompress(block, -15)
                elif codec != "null":
                    raise ValueError(f"unsupported avro codec {codec!r}")
                bpos = 0
                for _ in _builtins.range(cnt):
                    datum, bpos = _avro_decode(schema, block, bpos)
                    rows.append(datum)
                if data[pos:pos + 16] != sync:
                    raise ValueError(f"{f}: bad sync marker")
                pos += 16
            cols: Dict[str, List[Any]] = {}
            for r in rows:
                for k in r:
                    cols.setdefault(k, [])
            for r in rows:
                for k, acc in cols.items():
                    acc.append(r.get(k))
            return pa.table(cols)

        return read

    return _make_read("read_avro", [make(f) for f in files])


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline: Optional[List[Dict]] = None,
               parallelism: int = DEFAULT_PARALLELISM,
               client_factory: Optional[Callable[[], Any]] = None,
               **_kw) -> Dataset:
    """MongoDB collection — reference read_api.py read_mongo (:429).
    Documents are split across `parallelism` read tasks by _id-hash
    bucketing (each task runs the user's aggregation `pipeline` plus a
    bucket-filter stage). `client_factory` injects the client
    (pymongo.MongoClient by default — an optional dependency)."""
    def default_factory():
        try:
            import pymongo
        except ImportError as e:
            raise ImportError(
                "read_mongo requires the optional 'pymongo' package, or "
                "pass client_factory=") from e
        return pymongo.MongoClient(uri)

    factory = client_factory or default_factory

    def make(shard: Optional[int]):
        def read():
            client = factory()
            try:
                coll = client[database][collection]
                stages = list(pipeline or [])
                if shard is not None:
                    stages.append({"$match": {"$expr": {"$eq": [
                        {"$mod": [{"$toHashedIndexKey": "$_id"},
                                  parallelism]}, shard]}}})
                docs = list(coll.aggregate(stages))
            finally:
                client.close()
            cols: Dict[str, List[Any]] = {}
            for r in docs:
                r = dict(r)
                r["_id"] = str(r.get("_id"))
                for k in r:
                    cols.setdefault(k, [])
            for r in docs:
                r = dict(r)
                r["_id"] = str(r.get("_id"))
                for k, acc in cols.items():
                    acc.append(r.get(k))
            return pa.table(cols)

        return read

    if parallelism <= 1:
        return _make_read("read_mongo", [make(None)])
    return _make_read("read_mongo",
                      [make(i) for i in _builtins.range(parallelism)])


def read_bigquery(project_id: str, dataset: Optional[str] = None,
                  query: Optional[str] = None, *,
                  parallelism: int = DEFAULT_PARALLELISM,
                  http: Optional[Callable] = None,
                  token_fn: Optional[Callable[[], str]] = None,
                  **_kw) -> Dataset:
    """BigQuery table or query — reference read_api.py read_bigquery
    (:529; the BigQuery Storage read API there, the REST v2
    jobs.query/tabledata.list surface here, with an injectable `http`
    transport like the autoscaler's cloud providers)."""
    if (dataset is None) == (query is None):
        raise ValueError("pass exactly one of dataset='ds.table' or query=")

    def default_http():
        from ray_tpu.autoscaler.gcp import _default_http, _metadata_token

        return _default_http(token_fn or _metadata_token)

    transport = http or default_http()
    base = f"https://bigquery.googleapis.com/bigquery/v2/projects/{project_id}"

    def _rows_to_table(schema_fields, rows):
        names = [f["name"] for f in schema_fields]
        types = {f["name"]: f["type"] for f in schema_fields}

        def conv(name, v):
            if v is None:
                return None
            t = types[name]
            if t in ("INTEGER", "INT64"):
                return int(v)
            if t in ("FLOAT", "FLOAT64", "NUMERIC"):
                return float(v)
            if t in ("BOOLEAN", "BOOL"):
                return v in (True, "true", "TRUE")
            return v

        cols = {n: [] for n in names}
        for r in rows:
            for n, cell in zip(names, r.get("f", [])):
                cols[n].append(conv(n, cell.get("v")))
        return pa.table(cols)

    if query is not None:
        def read_query():
            resp = transport("POST", f"{base}/queries",
                             {"query": query, "useLegacySql": False})
            return _rows_to_table(resp["schema"]["fields"],
                                  resp.get("rows", []))

        return _make_read("read_bigquery", [read_query])

    ds_id, _, table = dataset.partition(".")
    if not table:
        raise ValueError("dataset must be 'dataset.table'")
    meta = transport("GET", f"{base}/datasets/{ds_id}/tables/{table}")
    total = int(meta.get("numRows", 0) or 0)
    schema_fields = meta["schema"]["fields"]

    if total <= 0:
        # Views and tables with a streaming buffer report no numRows, so
        # startIndex range splitting would fetch <=1 row. Fall back to a
        # single task that follows pageToken to exhaustion.
        def read_paged():
            rows: list = []
            token = None
            while True:
                url = (f"{base}/datasets/{ds_id}/tables/{table}/data"
                       f"?maxResults=10000")
                if token:
                    url += f"&pageToken={token}"
                resp = transport("GET", url)
                rows.extend(resp.get("rows", []))
                token = resp.get("pageToken")
                if not token:
                    return _rows_to_table(schema_fields, rows)

        return _make_read("read_bigquery", [read_paged])

    n = max(1, min(parallelism, total))
    step = -(-max(total, 1) // n)

    def make(start: int, count: int):
        def read():
            resp = transport(
                "GET", f"{base}/datasets/{ds_id}/tables/{table}/data"
                       f"?startIndex={start}&maxResults={count}")
            return _rows_to_table(schema_fields, resp.get("rows", []))

        return read

    return _make_read("read_bigquery",
                      [make(i * step, step) for i in _builtins.range(n)])


def read_databricks_tables(*, warehouse_id: str,
                           table: Optional[str] = None,
                           query: Optional[str] = None,
                           catalog: Optional[str] = None,
                           schema: Optional[str] = None,
                           http: Optional[Callable] = None,
                           host: Optional[str] = None,
                           token: Optional[str] = None,
                           poll_s: float = 1.0,
                           timeout_s: float = 600.0,
                           **_kw) -> Dataset:
    """Databricks SQL warehouse table/query — reference read_api.py
    read_databricks_tables (:2146; the SQL Statement Execution REST API
    in both). Credentials come from DATABRICKS_HOST/DATABRICKS_TOKEN
    (reference convention) unless `host`/`token`/`http` are injected.
    Each external-link chunk of the finished statement becomes one read
    task."""
    import json as _json
    import time as _time
    import urllib.request as _url

    if (table is None) == (query is None):
        raise ValueError("pass exactly one of table= or query=")
    host = host or os.environ.get("DATABRICKS_HOST", "")
    token = token or os.environ.get("DATABRICKS_TOKEN", "")
    if http is None and (not host or not token):
        raise ValueError("set DATABRICKS_HOST/DATABRICKS_TOKEN or pass "
                         "host=/token= (or an http= transport)")

    def default_http(method, url, body=None):
        data = _json.dumps(body).encode() if body is not None else None
        req = _url.Request(
            f"https://{host}{url}" if url.startswith("/") else url,
            data=data, method=method,
            headers={"Authorization": f"Bearer {token}",
                     "Content-Type": "application/json"})
        with _url.urlopen(req, timeout=60) as r:
            payload = r.read()
            return _json.loads(payload) if payload else {}

    transport = http or default_http
    sql = query or f"SELECT * FROM {table}"
    body = {"warehouse_id": warehouse_id, "statement": sql,
            "wait_timeout": "10s", "disposition": "EXTERNAL_LINKS",
            "format": "JSON_ARRAY"}
    if catalog:
        body["catalog"] = catalog
    if schema:
        body["schema"] = schema
    resp = transport("POST", "/api/2.0/sql/statements/", body)
    sid = resp["statement_id"]
    deadline = _time.monotonic() + timeout_s
    while resp["status"]["state"] in ("PENDING", "RUNNING"):
        if _time.monotonic() > deadline:
            raise TimeoutError(f"statement {sid} still "
                               f"{resp['status']['state']} after "
                               f"{timeout_s:.0f}s")
        _time.sleep(poll_s)
        resp = transport("GET", f"/api/2.0/sql/statements/{sid}")
    if resp["status"]["state"] != "SUCCEEDED":
        raise RuntimeError(
            f"statement {sid} {resp['status']['state']}: "
            f"{resp['status'].get('error', {}).get('message', '')}")
    cols = [c["name"] for c in
            resp["manifest"]["schema"]["columns"]]
    chunks = resp["result"].get("external_links", [])

    def make(link):
        def read():
            rows = transport("GET", link["external_link"])
            return pa.table({c: [r[i] for r in rows]
                             for i, c in enumerate(cols)})

        return read

    if not chunks:  # inline empty result
        return _make_read("read_databricks_tables",
                          [lambda: pa.table({c: [] for c in cols})])
    return _make_read("read_databricks_tables",
                      [make(ln) for ln in chunks])
