"""Read APIs — reference python/ray/data/read_api.py + datasource/
(parquet/csv/json/text/binary/images/numpy readers as parallel read
tasks). Each file (or range chunk) becomes one zero-arg read task; the
streaming executor schedules them as ray_tpu tasks with backpressure.
"""
from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np
import pyarrow as pa

from . import plan as P
from .block import Block, BlockAccessor
from .dataset import Dataset

DEFAULT_PARALLELISM = 8


def _expand_paths(paths: Union[str, Sequence[str]],
                  suffixes: Optional[Sequence[str]] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, names in os.walk(p):
                files += [os.path.join(root, n) for n in sorted(names)]
        elif any(ch in p for ch in "*?["):
            files += sorted(_glob.glob(p))
        else:
            files.append(p)
    if suffixes:
        files = [f for f in files
                 if any(f.endswith(s) for s in suffixes)]
    if not files:
        raise FileNotFoundError(f"no input files for {paths}")
    return files


def _make_read(name: str, tasks: List[Callable[[], Block]]) -> Dataset:
    return Dataset([P.Read(name, tuple(tasks))])


# --- in-memory sources ----------------------------------------------------


def range(n: int, *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:  # noqa: A001
    cuts = np.linspace(0, n, min(parallelism, max(n, 1)) + 1).astype(int)

    def make(a: int, b: int):
        return lambda: pa.table({"id": np.arange(a, b, dtype=np.int64)})

    return _make_read("range",
                      [make(int(a), int(b)) for a, b in zip(cuts, cuts[1:])])


def range_tensor(n: int, *, shape=(1,),
                 parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    cuts = np.linspace(0, n, min(parallelism, max(n, 1)) + 1).astype(int)

    def make(a: int, b: int):
        def read():
            base = np.arange(a, b, dtype=np.int64).reshape((-1,) + (1,) *
                                                           len(shape))
            data = np.broadcast_to(base, (b - a,) + tuple(shape)).copy()
            return BlockAccessor.batch_to_block({"data": data})

        return read

    return _make_read("range_tensor",
                      [make(int(a), int(b)) for a, b in zip(cuts, cuts[1:])])


def from_items(items: Sequence[Any], *,
               parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    items = list(items)
    chunks = np.array_split(np.arange(len(items)),
                            min(parallelism, max(len(items), 1)))

    def make(idx):
        part = [items[i] for i in idx]
        return lambda: BlockAccessor.batch_to_block(part)

    return _make_read("from_items", [make(c) for c in chunks if len(c)])


def from_numpy(arr: Union[np.ndarray, Dict[str, np.ndarray]], *,
               parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    if isinstance(arr, np.ndarray):
        arr = {"data": arr}
    n = len(next(iter(arr.values())))
    cuts = np.linspace(0, n, min(parallelism, max(n, 1)) + 1).astype(int)

    def make(a: int, b: int):
        part = {k: v[a:b] for k, v in arr.items()}
        return lambda: BlockAccessor.batch_to_block(part)

    return _make_read("from_numpy",
                      [make(int(a), int(b)) for a, b in zip(cuts, cuts[1:])])


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return _make_read(
        "from_pandas",
        [(lambda d=df: pa.Table.from_pandas(d, preserve_index=False))
         for df in dfs])


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _make_read("from_arrow", [(lambda t=t: t) for t in tables])


# --- file sources ---------------------------------------------------------


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 **_kw) -> Dataset:
    files = _expand_paths(paths, (".parquet",))

    def make(f):
        def read():
            import pyarrow.parquet as pq

            return pq.read_table(f, columns=columns)

        return read

    return _make_read("read_parquet", [make(f) for f in files])


def read_csv(paths, **_kw) -> Dataset:
    files = _expand_paths(paths, (".csv",))

    def make(f):
        def read():
            import pyarrow.csv as pacsv

            return pacsv.read_csv(f)

        return read

    return _make_read("read_csv", [make(f) for f in files])


def read_json(paths, **_kw) -> Dataset:
    files = _expand_paths(paths, (".json", ".jsonl"))

    def make(f):
        def read():
            import pyarrow.json as pajson

            return pajson.read_json(f)

        return read

    return _make_read("read_json", [make(f) for f in files])


def read_text(paths, **_kw) -> Dataset:
    files = _expand_paths(paths)

    def make(f):
        def read():
            with open(f, "r") as fh:
                lines = [ln.rstrip("\n") for ln in fh]
            return pa.table({"text": lines})

        return read

    return _make_read("read_text", [make(f) for f in files])


def read_binary_files(paths, *, include_paths: bool = False,
                      **_kw) -> Dataset:
    files = _expand_paths(paths)

    def make(f):
        def read():
            with open(f, "rb") as fh:
                data = fh.read()
            cols: Dict[str, Any] = {"bytes": [data]}
            if include_paths:
                cols["path"] = [f]
            return pa.table(cols)

        return read

    return _make_read("read_binary_files", [make(f) for f in files])


def read_numpy(paths, **_kw) -> Dataset:
    files = _expand_paths(paths, (".npy",))

    def make(f):
        def read():
            return BlockAccessor.batch_to_block({"data": np.load(f)})

        return read

    return _make_read("read_numpy", [make(f) for f in files])


def read_images(paths, *, size: Optional[tuple] = None,
                mode: str = "RGB", include_paths: bool = False,
                **_kw) -> Dataset:
    files = _expand_paths(
        paths, (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp"))

    def make(f):
        def read():
            from PIL import Image

            img = Image.open(f).convert(mode)
            if size is not None:
                img = img.resize(size)
            cols: Dict[str, Any] = {"image": np.asarray(img)[None]}
            if include_paths:
                return BlockAccessor.batch_to_block(
                    {**cols, "path": np.asarray([f])})
            return BlockAccessor.batch_to_block(cols)

        return read

    return _make_read("read_images", [make(f) for f in files])
