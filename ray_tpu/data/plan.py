"""Logical plan for ray_tpu.data — lazy operator list with fusion.

Reference model: python/ray/data/_internal/logical/ builds a DAG of
LogicalOperators, an optimizer fuses compatible Map* chains, and the
physical layer turns each into task submissions
(_internal/planner/plan_udf_map_op.py). Here the plan is a linear chain
(sources with union/zip handled at the Dataset level), and `fuse()`
produces FusedStage objects: one Python callable per stage applied
block-by-block in a single task (the same one-task-per-block,
fused-transform model the reference's physical optimizer achieves).
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Tuple

from .block import Block, BlockAccessor

# ---------------------------------------------------------------------------
# Logical ops


@dataclass(frozen=True)
class LogicalOp:
    name: str


@dataclass(frozen=True)
class Read(LogicalOp):
    """Source: read_fn() returns a list of zero-arg block-producing tasks."""

    read_tasks: Tuple[Callable[[], List[Block]], ...] = ()


@dataclass(frozen=True)
class FromBlocks(LogicalOp):
    refs: Tuple[Any, ...] = ()  # ObjectRefs of materialized blocks


@dataclass(frozen=True)
class Union(LogicalOp):
    """Lazy union: each branch is a full logical-op chain, executed (and
    chained) only when the plan runs."""

    branches: Tuple[Tuple[LogicalOp, ...], ...] = ()


@dataclass(frozen=True)
class MapBatches(LogicalOp):
    fn: Callable = None
    batch_size: Optional[int] = None
    batch_format: str = "numpy"
    fn_constructor: Optional[Callable] = None  # actor-mode callable class
    concurrency: Optional[int] = None
    compute: Optional[Any] = None  # compute.ActorPoolStrategy | TaskPool


@dataclass(frozen=True)
class MapRows(LogicalOp):
    fn: Callable = None


@dataclass(frozen=True)
class FlatMap(LogicalOp):
    fn: Callable = None


@dataclass(frozen=True)
class Filter(LogicalOp):
    fn: Callable = None


@dataclass(frozen=True)
class AddColumn(LogicalOp):
    col: str = ""
    fn: Callable = None


@dataclass(frozen=True)
class DropColumns(LogicalOp):
    cols: Tuple[str, ...] = ()


@dataclass(frozen=True)
class SelectColumns(LogicalOp):
    cols: Tuple[str, ...] = ()


@dataclass(frozen=True)
class RenameColumns(LogicalOp):
    mapping: Tuple[Tuple[str, str], ...] = ()


# all-to-all barriers
@dataclass(frozen=True)
class Repartition(LogicalOp):
    num_blocks: int = 0


@dataclass(frozen=True)
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None


@dataclass(frozen=True)
class Sort(LogicalOp):
    key: str = ""
    descending: bool = False


@dataclass(frozen=True)
class Limit(LogicalOp):
    n: int = 0


# ---------------------------------------------------------------------------
# Fusion: chain of per-block ops -> single callable


ROW_LEVEL = (MapRows, FlatMap, Filter, AddColumn, DropColumns,
             SelectColumns, RenameColumns)


@dataclass
class FusedStage:
    """One task per block: block -> block, applying a fused op chain."""

    ops: List[LogicalOp] = field(default_factory=list)

    @property
    def name(self) -> str:
        return "+".join(o.name for o in self.ops) or "identity"

    @property
    def concurrency(self) -> Optional[int]:
        for o in self.ops:
            if isinstance(o, MapBatches) and o.concurrency:
                return o.concurrency
            if isinstance(o, MapBatches) and o.compute is not None \
                    and getattr(o.compute, "size", None):
                return o.compute.size
        return None

    @property
    def compute(self) -> Optional[Any]:
        """The ActorPoolStrategy when this stage is a standalone
        actor-pool map (fusion keeps such stages unfused)."""
        from .compute import ActorPoolStrategy

        for o in self.ops:
            if isinstance(o, MapBatches) and \
                    isinstance(o.compute, ActorPoolStrategy):
                return o.compute
        return None

    def __call__(self, block: Block) -> Block:
        for op in self.ops:
            block = _apply_op(op, block)
        return block


def _apply_op(op: LogicalOp, block: Block) -> Block:
    import numpy as np
    import pyarrow as pa

    acc = BlockAccessor(block)
    if isinstance(op, MapBatches):
        fn = op.fn
        if op.fn_constructor is not None:
            fn = _actor_callable_cache(op.fn_constructor)
        out = []
        from .block import batches_of

        for batch in batches_of(block, op.batch_size, op.batch_format):
            res = fn(batch)
            out.append(BlockAccessor.batch_to_block(res))
        return BlockAccessor.concat(out)
    if isinstance(op, MapRows):
        return BlockAccessor.from_rows([op.fn(r) for r in acc.iter_rows()])
    if isinstance(op, FlatMap):
        rows: List[Dict[str, Any]] = []
        for r in acc.iter_rows():
            rows.extend(op.fn(r))
        return BlockAccessor.from_rows(rows)
    if isinstance(op, Filter):
        keep = [i for i, r in enumerate(acc.iter_rows()) if op.fn(r)]
        return acc.take_rows(keep)
    if isinstance(op, AddColumn):
        col = op.fn(acc.to_batch("pandas"))
        t = acc.to_arrow()
        if op.col in t.column_names:
            t = t.drop_columns([op.col])
        return t.append_column(op.col, pa.array(np.asarray(col)))
    if isinstance(op, DropColumns):
        return acc.to_arrow().drop_columns(list(op.cols))
    if isinstance(op, SelectColumns):
        return acc.to_arrow().select(list(op.cols))
    if isinstance(op, RenameColumns):
        t = acc.to_arrow()
        mapping = dict(op.mapping)
        return t.rename_columns(
            [mapping.get(c, c) for c in t.column_names])
    raise TypeError(f"not a per-block op: {op}")


_ACTOR_CALLABLES: Dict[Any, Any] = {}


def _actor_callable_cache(ctor: Callable) -> Any:
    """Callable-class UDFs are constructed once per worker process and
    reused across blocks (the reference's actor-pool compute strategy,
    python/ray/data/_internal/compute.py ActorPoolStrategy). Keyed by
    qualified name — each task unpickles a distinct class object, so
    id() would never hit."""
    key = (getattr(ctor, "__module__", ""),
           getattr(ctor, "__qualname__", repr(ctor)))
    inst = _ACTOR_CALLABLES.get(key)
    if inst is None:
        inst = ctor()
        _ACTOR_CALLABLES[key] = inst
    return inst


def fuse(ops: List[LogicalOp]) -> List[Any]:
    """[LogicalOp] -> [source | FusedStage | barrier op] pipeline.

    An actor-pool MapBatches never fuses with neighbours: its stage maps
    1:1 onto a dedicated actor pool (reference: actor-pool operators are
    their own physical operator)."""
    from .compute import ActorPoolStrategy

    stages: List[Any] = []
    current: Optional[FusedStage] = None
    for op in ops:
        if isinstance(op, (Read, FromBlocks, Union, Repartition,
                           RandomShuffle, Sort, Limit)):
            if current is not None and current.ops:
                stages.append(current)
            current = None
            stages.append(op)
        elif isinstance(op, MapBatches) and \
                isinstance(op.compute, ActorPoolStrategy):
            if current is not None and current.ops:
                stages.append(current)
            current = None
            stages.append(FusedStage(ops=[op]))
        else:
            if current is None:
                current = FusedStage()
            current.ops.append(op)
    if current is not None and current.ops:
        stages.append(current)
    return stages
