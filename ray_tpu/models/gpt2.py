"""GPT-2 in pure functional JAX, designed for the MXU and named-axis meshes.

The reference's GPT-2 benchmarks wrap HuggingFace torch models in DDP
(/root/reference/release/air_tests/air_benchmarks/ — workload defs only);
here the model itself is framework code: a pytree of arrays + jit-able
forward, with a PartitionSpec tree (`gpt2_partition_specs`) giving the
megatron-style TP layout (attention and MLP split on the `tp` axis, 2D
[fsdp, tp] sharding for the big matmuls) so the same function runs dp-only,
fsdp, tp, or combinations by changing only the mesh.

TPU-first choices: bf16 params/activations by default with fp32 layernorm
stats (ops.layers), flash attention (ops.attention — Pallas on TPU), weight
tying for the LM head, static shapes throughout, no python control flow in
the jitted path.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.attention import flash_attention
from ..ops.layers import layer_norm

Params = Dict[str, Any]


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    dtype: Any = jnp.bfloat16
    # pad vocab up so the embedding matmul tiles cleanly on the MXU / tp axis
    vocab_pad_multiple: int = 128

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def padded_vocab(self) -> int:
        m = self.vocab_pad_multiple
        return (self.vocab_size + m - 1) // m * m

    @staticmethod
    def small() -> "GPT2Config":  # 125M — the benchmark flagship
        return GPT2Config()

    @staticmethod
    def medium() -> "GPT2Config":
        return GPT2Config(num_layers=24, num_heads=16, d_model=1024)

    @staticmethod
    def tiny() -> "GPT2Config":  # test/dry-run size
        return GPT2Config(vocab_size=512, max_seq_len=128, num_layers=2,
                          num_heads=4, d_model=128)


def gpt2_init(config: GPT2Config, key: jax.Array) -> Params:
    """Initialize parameters (GPT-2 scheme: N(0, 0.02), residual projections
    scaled by 1/sqrt(2*n_layers))."""
    c = config
    k_iter = iter(jax.random.split(key, 4 + 12 * c.num_layers))

    def norm(k, *shape, scale=0.02):
        return (jax.random.normal(k, shape, dtype=jnp.float32)
                * scale).astype(c.dtype)

    resid_scale = 0.02 / np.sqrt(2 * c.num_layers)
    params: Params = {
        "wte": norm(next(k_iter), c.padded_vocab, c.d_model),
        "wpe": norm(next(k_iter), c.max_seq_len, c.d_model, scale=0.01),
        "ln_f": {"scale": jnp.ones(c.d_model, c.dtype),
                 "bias": jnp.zeros(c.d_model, c.dtype)},
        "blocks": [],
    }
    for _ in range(c.num_layers):
        params["blocks"].append({
            "ln_1": {"scale": jnp.ones(c.d_model, c.dtype),
                     "bias": jnp.zeros(c.d_model, c.dtype)},
            "attn": {
                "qkv": norm(next(k_iter), c.d_model, 3 * c.d_model),
                "qkv_b": jnp.zeros(3 * c.d_model, c.dtype),
                "proj": norm(next(k_iter), c.d_model, c.d_model,
                             scale=resid_scale),
                "proj_b": jnp.zeros(c.d_model, c.dtype),
            },
            "ln_2": {"scale": jnp.ones(c.d_model, c.dtype),
                     "bias": jnp.zeros(c.d_model, c.dtype)},
            "mlp": {
                "fc": norm(next(k_iter), c.d_model, 4 * c.d_model),
                "fc_b": jnp.zeros(4 * c.d_model, c.dtype),
                "proj": norm(next(k_iter), 4 * c.d_model, c.d_model,
                             scale=resid_scale),
                "proj_b": jnp.zeros(c.d_model, c.dtype),
            },
        })
    return params


def _attn_proj_res(x: jax.Array, a: jax.Array, p: Params,
                   config: GPT2Config) -> jax.Array:
    """Attention output projection + residual (shared by the training,
    prefix-cache, and per-slot decode blocks)."""
    a = jnp.dot(a, p["attn"]["proj"],
                preferred_element_type=jnp.float32).astype(config.dtype)
    return x + a + p["attn"]["proj_b"]


def _mlp_res(x: jax.Array, p: Params, config: GPT2Config) -> jax.Array:
    h = layer_norm(x, p["ln_2"]["scale"], p["ln_2"]["bias"])
    h = jnp.dot(h, p["mlp"]["fc"],
                preferred_element_type=jnp.float32).astype(config.dtype)
    # tanh-approximate gelu: GPT-2's historical activation, and cheaper
    # on the VPU than the erf form
    h = jax.nn.gelu(h + p["mlp"]["fc_b"], approximate=True)
    h = jnp.dot(h, p["mlp"]["proj"],
                preferred_element_type=jnp.float32).astype(config.dtype)
    return x + h + p["mlp"]["proj_b"]


def _block(x: jax.Array, p: Params, config: GPT2Config) -> jax.Array:
    c = config
    b, t, _ = x.shape
    h = layer_norm(x, p["ln_1"]["scale"], p["ln_1"]["bias"])
    qkv = jnp.dot(h, p["attn"]["qkv"],
                  preferred_element_type=jnp.float32).astype(c.dtype)
    qkv = qkv + p["attn"]["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, t, c.num_heads, c.head_dim)
    k = k.reshape(b, t, c.num_heads, c.head_dim)
    v = v.reshape(b, t, c.num_heads, c.head_dim)
    a = flash_attention(q, k, v, True).reshape(b, t, c.d_model)
    return _mlp_res(_attn_proj_res(x, a, p, c), p, c)


def _constrain(x: jax.Array, spec: Optional[P]) -> jax.Array:
    if spec is None:
        return x
    return jax.lax.with_sharding_constraint(x, spec)


def gpt2_hidden(params: Params, tokens: jax.Array, config: GPT2Config,
                remat: bool = False,
                act_spec: Optional[P] = None) -> jax.Array:
    """tokens [B, T] int32 -> final hidden states [B, T, d_model].

    remat=True checkpoints each transformer block (per-layer remat — the
    backward recomputes one layer at a time, peak activation memory is one
    layer's worth). act_spec, if given, pins the residual-stream sharding
    after every block so XLA never falls back to involuntary full
    rematerialization when tp/fsdp axes are active (requires an enclosing
    mesh context, e.g. TrainStep's)."""
    c = config
    t = tokens.shape[1]
    x = params["wte"][tokens] + params["wpe"][:t]
    x = _constrain(x, act_spec)
    block_fn = _block
    if remat:
        block_fn = jax.checkpoint(_block, static_argnums=(2,))
    for p in params["blocks"]:
        x = _constrain(block_fn(x, p, c), act_spec)
    return layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])


def gpt2_forward(params: Params, tokens: jax.Array,
                 config: GPT2Config) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, padded_vocab] (fp32)."""
    x = gpt2_hidden(params, tokens, config)
    # tied LM head
    return jnp.dot(x, params["wte"].T, preferred_element_type=jnp.float32)


# ------------------------------------------------------- KV-cache decode


def gpt2_init_kv_cache(config: GPT2Config, batch_size: int,
                       max_len: int = 0, dtype: Any = None) -> list:
    """Per-layer K/V buffers [B, S, heads, head_dim] (same layout as
    models/llama.py init_kv_cache — learned positions instead of rope)."""
    c = config
    s = max_len or c.max_seq_len
    dt = dtype or c.dtype
    return [{"k": jnp.zeros((batch_size, s, c.num_heads, c.head_dim), dt),
             "v": jnp.zeros((batch_size, s, c.num_heads, c.head_dim), dt)}
            for _ in range(c.num_layers)]


def _block_cached(x: jax.Array, p: Params, config: GPT2Config,
                  cache: Params, pos: jax.Array):
    """Cache-path block: tokens at [pos, pos+t) attend the full written
    prefix — the GPT-2 analog of llama_block_cached."""
    c = config
    b, t, _ = x.shape
    h = layer_norm(x, p["ln_1"]["scale"], p["ln_1"]["bias"])
    qkv = jnp.dot(h, p["attn"]["qkv"],
                  preferred_element_type=jnp.float32).astype(c.dtype)
    qkv = qkv + p["attn"]["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, t, c.num_heads, c.head_dim)
    k = k.reshape(b, t, c.num_heads, c.head_dim)
    v = v.reshape(b, t, c.num_heads, c.head_dim)
    ck = jax.lax.dynamic_update_slice(
        cache["k"], k.astype(cache["k"].dtype), (0, pos, 0, 0))
    cv = jax.lax.dynamic_update_slice(
        cache["v"], v.astype(cache["v"].dtype), (0, pos, 0, 0))
    s = ck.shape[1]
    scores = jnp.einsum("bthd,bshd->bhts", q, ck,
                        preferred_element_type=jnp.float32)
    scores = scores / (c.head_dim ** 0.5)
    positions = pos + jnp.arange(t)[None, :]
    col = jnp.arange(s)[None, None, None, :]
    visible = col <= positions[:, None, :, None]
    scores = jnp.where(visible, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    a = jnp.einsum("bhts,bshd->bthd", probs, cv).reshape(b, t, c.d_model)
    return _mlp_res(_attn_proj_res(x, a, p, c), p, c), {"k": ck, "v": cv}


def _block_decode(x: jax.Array, p: Params, config: GPT2Config,
                  cache: Params, pos_vec: jax.Array,
                  lora: Optional[Dict[str, Any]] = None):
    """Ragged-batch decode with PER-SLOT positions (continuous
    batching) — the GPT-2 analog of llama_block_decode. x [B, t, D];
    pos_vec [B] is each slot's BASE position (t == 1: the classic
    one-token tick; t == k+1: the speculative verify pass — see
    llama_block_decode for the masking contract the oracle rests on).

    `lora` (optional, serve/lora.py mixed-tenant decode): this layer's
    per-slot adapter selection for the fused qkv projection —
    ``{"qkv": (a [B,D,r], b [B,r,3D]), "scale": [B]}`` — added to the
    base matmul; null-adapter slots add an exact-zero delta."""
    c = config
    b, t = x.shape[0], x.shape[1]
    h = layer_norm(x, p["ln_1"]["scale"], p["ln_1"]["bias"])
    qkv = jnp.dot(h, p["attn"]["qkv"],
                  preferred_element_type=jnp.float32).astype(c.dtype)
    if lora is not None:
        from ..ops.layers import lora_delta

        qkv = qkv + lora_delta(h, *lora["qkv"], lora["scale"])
    qkv = qkv + p["attn"]["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, t, c.num_heads, c.head_dim)
    k = k.reshape(b, t, c.num_heads, c.head_dim)
    v = v.reshape(b, t, c.num_heads, c.head_dim)
    rows = jnp.arange(b)
    positions = pos_vec[:, None] + jnp.arange(t)[None, :]   # [B, t]
    ck = cache["k"].at[rows[:, None], positions].set(
        k.astype(cache["k"].dtype))
    cv = cache["v"].at[rows[:, None], positions].set(
        v.astype(cache["v"].dtype))
    s = ck.shape[1]
    scores = jnp.einsum("bthd,bshd->bhts", q, ck,
                        preferred_element_type=jnp.float32)
    scores = scores / (c.head_dim ** 0.5)
    col = jnp.arange(s)[None, None, None, :]
    visible = col <= positions[:, None, :, None]
    scores = jnp.where(visible, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    a = jnp.einsum("bhts,bshd->bthd", probs, cv).reshape(b, t, c.d_model)
    return _mlp_res(_attn_proj_res(x, a, p, c), p, c), {"k": ck, "v": cv}


def gpt2_decode(params: Params, tokens: jax.Array, config: GPT2Config,
                cache: list, pos_vec: jax.Array,
                lora: Optional[Dict[str, Any]] = None):
    """One decode step for a ragged batch: tokens [B] at per-slot
    positions pos_vec [B] ([B, q] is the speculative verify form —
    logits come back [B, q, padded_vocab]; see llama_decode). `lora`
    (optional): adapter-pool stacks + per-slot indices ``{"idx": [B],
    "scale": [P], "qkv": (a [P,L,D,r], b [P,L,r,3D])}`` — see
    llama_decode for the contract."""
    c = config
    ragged = tokens.ndim == 1
    if ragged:
        x = params["wte"][tokens[:, None]] \
            + params["wpe"][pos_vec][:, None]
    else:
        positions = pos_vec[:, None] + jnp.arange(
            tokens.shape[1])[None, :]
        x = params["wte"][tokens] + params["wpe"][positions]
    sel = None
    if lora is not None:
        idx = lora["idx"]
        sel = (lora["qkv"][0][idx], lora["qkv"][1][idx])
        scale = lora["scale"][idx]
    new_cache = []
    for li, (p, blk) in enumerate(zip(params["blocks"], cache)):
        lora_l = None if sel is None else {
            "qkv": (sel[0][:, li], sel[1][:, li]), "scale": scale}
        x, nc = _block_decode(x, p, c, blk, pos_vec, lora_l)
        new_cache.append(nc)
    x = layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    if ragged:
        x = x[:, 0]
    return jnp.dot(x, params["wte"].T,
                   preferred_element_type=jnp.float32), new_cache


def gpt2_forward_cached(params: Params, tokens: jax.Array,
                        config: GPT2Config, cache: list, pos: jax.Array):
    """Append tokens [B, T] at scalar position `pos`; returns (logits
    [B, T, padded_vocab] fp32, new_cache). pos=0 + whole prompt =
    prefill; T=1 afterwards = decode."""
    c = config
    t = tokens.shape[1]
    wpe = jax.lax.dynamic_slice(params["wpe"], (pos, 0),
                                (t, c.d_model))
    x = params["wte"][tokens] + wpe
    new_cache = []
    for p, blk in zip(params["blocks"], cache):
        x, nc = _block_cached(x, p, c, blk, pos)
        new_cache.append(nc)
    x = layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    return jnp.dot(x, params["wte"].T,
                   preferred_element_type=jnp.float32), new_cache


def _ce_sum(x: jax.Array, targets: jax.Array, wte: jax.Array,
            vocab_size: int) -> jax.Array:
    """Sum of next-token cross-entropy. x [..., d], targets [...]."""
    logits = jnp.dot(x, wte.T, preferred_element_type=jnp.float32)
    if wte.shape[0] != vocab_size:  # mask the vocab padding
        col = jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, logits.ndim - 1)
        logits = jnp.where(col < vocab_size, logits, -1e30)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.sum(ll)


def gpt2_loss(params: Params, tokens: jax.Array, targets: jax.Array,
              config: GPT2Config, remat: bool = False,
              loss_chunk_rows: int = 2048,
              act_spec: Optional[P] = None) -> jax.Array:
    """Mean next-token cross-entropy, computed in sequence chunks so the
    [B, T, padded_vocab] fp32 logits never materialize whole (at GPT-2
    vocab one full-batch logits tensor is gigabytes; chunking caps it near
    loss_chunk_rows * padded_vocab, recomputed per chunk in the backward).
    Chunking splits the sequence axis, so dp/fsdp batch sharding is
    untouched and each chunk stays a full-width MXU matmul.
    """
    c = config
    x = gpt2_hidden(params, tokens, config, remat=remat, act_spec=act_spec)
    b, t = targets.shape

    from ..ops.fused_ce import fused_ce_supported, linear_cross_entropy
    if fused_ce_supported(b * t, c.d_model, c.padded_vocab):
        # fused kernel: logits never materialize (ops/fused_ce.py)
        losses = linear_cross_entropy(
            x.reshape(b * t, c.d_model), params["wte"],
            targets.reshape(b * t), c.vocab_size)
        return jnp.sum(losses) / (b * t)

    n_chunks = min(t, max(1, (b * t) // loss_chunk_rows))
    while t % n_chunks != 0:
        n_chunks -= 1

    def chunk_fn(args):
        xi, ti = args
        return _ce_sum(xi, ti, params["wte"], c.vocab_size)

    if n_chunks == 1:
        total = chunk_fn((x, targets))
    else:
        xc = x.reshape(b, n_chunks, t // n_chunks,
                       c.d_model).swapaxes(0, 1)
        tc = targets.reshape(b, n_chunks, t // n_chunks).swapaxes(0, 1)
        total = jnp.sum(jax.lax.map(jax.checkpoint(chunk_fn), (xc, tc)))
    return total / (b * t)


def gpt2_partition_specs(config: GPT2Config) -> Params:
    """PartitionSpec tree for the params: megatron TP layout with fsdp on
    the other matmul dimension. With tp=1/fsdp=1 every spec collapses to
    replicated, so one tree serves all mesh shapes."""
    block = {
        "ln_1": {"scale": P(), "bias": P()},
        "attn": {
            "qkv": P("fsdp", "tp"),     # column-parallel
            "qkv_b": P("tp"),
            "proj": P("tp", "fsdp"),    # row-parallel
            "proj_b": P(),
        },
        "ln_2": {"scale": P(), "bias": P()},
        "mlp": {
            "fc": P("fsdp", "tp"),      # column-parallel
            "fc_b": P("tp"),
            "proj": P("tp", "fsdp"),    # row-parallel
            "proj_b": P(),
        },
    }
    return {
        # vocab sharded over BOTH model axes, d_model replicated: a 2D-
        # sharded wte ([tp, fsdp]) forces XLA into "involuntary full
        # rematerialization" reconciling the embedding-gather and LM-head
        # grad shardings (replicate-then-reshard on every step); single-dim
        # vocab sharding keeps the memory scaling and compiles clean, and
        # logits come out vocab-sharded — Megatron-style vocab-parallel CE
        "wte": P(("tp", "fsdp"), None),
        "wpe": P(None, "fsdp"),
        "ln_f": {"scale": P(), "bias": P()},
        "blocks": [block for _ in range(config.num_layers)],
    }
