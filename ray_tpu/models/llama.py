"""Llama-family decoder in pure functional JAX — RMSNorm, RoPE, SwiGLU,
grouped-query attention — with a megatron-style PartitionSpec tree.

The reference tree carries no model code (its Train/RLlib wrap torch
models, SURVEY.md §2.4); this is native framework capability following the
same idioms as models/gpt2.py: pytree params + jit-able forward, bf16
params/activations with fp32 norm stats, flash attention (Pallas on TPU),
static shapes, one spec tree serving dp/fsdp/tp by changing only the mesh.

GQA + tp note: num_kv_heads must divide by the tp degree in use (as in
every tp Llama deployment); kv heads are repeated to query heads right
before attention, which XLA lowers to a broadcast (no HBM copy)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import flash_attention
from ..ops.layers import rms_norm
from ..ops.rope import apply_rope, rope_table

Params = Dict[str, Any]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 2048
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: int = 4
    d_model: int = 768
    d_ff: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    vocab_pad_multiple: int = 128

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def padded_vocab(self) -> int:
        m = self.vocab_pad_multiple
        return (self.vocab_size + m - 1) // m * m

    @staticmethod
    def tiny() -> "LlamaConfig":  # tests / dry runs
        return LlamaConfig(vocab_size=512, max_seq_len=128, num_layers=2,
                           num_heads=4, num_kv_heads=2, d_model=128,
                           d_ff=256)

    @staticmethod
    def small() -> "LlamaConfig":  # ~125M-class
        return LlamaConfig()

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig(vocab_size=32000, max_seq_len=4096,
                           num_layers=32, num_heads=32, num_kv_heads=32,
                           d_model=4096, d_ff=11008)

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(vocab_size=128256, max_seq_len=8192,
                           num_layers=32, num_heads=32, num_kv_heads=8,
                           d_model=4096, d_ff=14336, rope_theta=500000.0)


def llama_init(config: LlamaConfig, key: jax.Array) -> Params:
    c = config
    if c.num_heads % c.num_kv_heads:
        raise ValueError("num_heads must be a multiple of num_kv_heads")
    k_iter = iter(jax.random.split(key, 2 + 7 * c.num_layers))

    def norm(k, *shape, scale=0.02):
        return (jax.random.normal(k, shape, dtype=jnp.float32)
                * scale).astype(c.dtype)

    kv_dim = c.num_kv_heads * c.head_dim
    params: Params = {
        "tok_emb": norm(next(k_iter), c.padded_vocab, c.d_model),
        "norm_f": {"scale": jnp.ones(c.d_model, c.dtype)},
        "lm_head": norm(next(k_iter), c.d_model, c.padded_vocab),
        "blocks": [],
    }
    for _ in range(c.num_layers):
        params["blocks"].append({
            "attn_norm": {"scale": jnp.ones(c.d_model, c.dtype)},
            "attn": {
                "wq": norm(next(k_iter), c.d_model, c.d_model),
                "wk": norm(next(k_iter), c.d_model, kv_dim),
                "wv": norm(next(k_iter), c.d_model, kv_dim),
                "wo": norm(next(k_iter), c.d_model, c.d_model),
            },
            "ffn_norm": {"scale": jnp.ones(c.d_model, c.dtype)},
            "mlp": {
                "w_gate": norm(next(k_iter), c.d_model, c.d_ff),
                "w_up": norm(next(k_iter), c.d_model, c.d_ff),
                "w_down": norm(next(k_iter), c.d_ff, c.d_model),
            },
        })
    return params


def _mm(x: jax.Array, w: jax.Array) -> jax.Array:
    return jnp.dot(x, w, preferred_element_type=jnp.float32).astype(x.dtype)


def _qkv(h: jax.Array, p: Params, c: LlamaConfig):
    b, t, _ = h.shape
    q = _mm(h, p["attn"]["wq"]).reshape(b, t, c.num_heads, c.head_dim)
    k = _mm(h, p["attn"]["wk"]).reshape(b, t, c.num_kv_heads, c.head_dim)
    v = _mm(h, p["attn"]["wv"]).reshape(b, t, c.num_kv_heads, c.head_dim)
    return q, k, v


def _repeat_kv(k: jax.Array, v: jax.Array, c: LlamaConfig):
    if c.num_kv_heads != c.num_heads:  # GQA: broadcast kv to query heads
        rep = c.num_heads // c.num_kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def _mlp_res(x: jax.Array, p: Params) -> jax.Array:
    h = rms_norm(x, p["ffn_norm"]["scale"])
    gate = jax.nn.silu(_mm(h, p["mlp"]["w_gate"]).astype(jnp.float32))
    up = _mm(h, p["mlp"]["w_up"]).astype(jnp.float32)
    return x + _mm((gate * up).astype(x.dtype), p["mlp"]["w_down"])


def llama_block(x: jax.Array, p: Params, cos: jax.Array, sin: jax.Array,
                config: LlamaConfig) -> jax.Array:
    c = config
    b, t, _ = x.shape
    h = rms_norm(x, p["attn_norm"]["scale"])
    q, k, v = _qkv(h, p, c)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    k, v = _repeat_kv(k, v, c)
    a = flash_attention(q, k, v, True).reshape(b, t, c.d_model)
    x = x + _mm(a, p["attn"]["wo"])
    return _mlp_res(x, p)


def llama_block_cached(x: jax.Array, p: Params, cos: jax.Array,
                       sin: jax.Array, config: LlamaConfig,
                       cache: Params, pos: jax.Array):
    """KV-cache path (prefill AND decode — tokens land at position `pos`
    and attend over everything written so far). Static shapes: the
    cache is the full [B, S, n_kv, hd] window and masking does the
    truncation, the standard fixed-shape TPU decode layout.
    Returns (x, new_cache_for_this_block)."""
    c = config
    b, t, _ = x.shape
    h = rms_norm(x, p["attn_norm"]["scale"])
    q, k, v = _qkv(h, p, c)
    positions = jnp.broadcast_to(pos + jnp.arange(t)[None, :], (b, t))
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    ck = jax.lax.dynamic_update_slice(
        cache["k"], k.astype(cache["k"].dtype), (0, pos, 0, 0))
    cv = jax.lax.dynamic_update_slice(
        cache["v"], v.astype(cache["v"].dtype), (0, pos, 0, 0))
    kk, vv = _repeat_kv(ck, cv, c)
    s = kk.shape[1]
    # decode t is tiny (1 for autoregressive steps): plain masked
    # attention over the cache window — flash brings nothing at t=1
    scores = jnp.einsum("bthd,bshd->bhts", q, kk,
                        preferred_element_type=jnp.float32)
    scores = scores / (c.head_dim ** 0.5)
    col = jnp.arange(s)[None, None, None, :]
    visible = col <= positions[:, None, :, None]
    scores = jnp.where(visible, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    a = jnp.einsum("bhts,bshd->bthd", probs, vv).reshape(b, t, c.d_model)
    x = x + _mm(a, p["attn"]["wo"])
    return _mlp_res(x, p), {"k": ck, "v": cv}


def llama_forward(params: Params, tokens: jax.Array,
                  config: LlamaConfig) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, padded_vocab] fp32."""
    c = config
    cos, sin = rope_table(c.head_dim, c.max_seq_len, c.rope_theta)
    x = params["tok_emb"][tokens]
    for p in params["blocks"]:
        x = llama_block(x, p, cos, sin, c)
    x = rms_norm(x, params["norm_f"]["scale"])
    return jnp.dot(x, params["lm_head"],
                   preferred_element_type=jnp.float32)


def llama_block_decode(x: jax.Array, p: Params, cos: jax.Array,
                       sin: jax.Array, config: LlamaConfig,
                       cache: Params, pos_vec: jax.Array,
                       lora: Optional[Dict[str, Any]] = None):
    """Ragged-batch decode with PER-SLOT positions (continuous
    batching: every batch slot is a different sequence at its own
    depth). x [B, t, D]; pos_vec [B] int32 is each slot's BASE
    position — slot b's token j lands at pos_vec[b] + j. t == 1 is the
    classic one-token tick; t == k+1 is the speculative VERIFY pass
    (models/engine.py), which scores a slot's k drafted tokens in one
    forward. Each new K/V row is scattered at its own position and
    attention is masked per (slot, query position), so query j sees
    exactly the rows a sequential j-step decode would — the
    bit-identity the speculation oracle rests on. Rows past a query's
    position stay invisible, which is also why rejected draft rows
    need no rollback: they are overwritten before any later query can
    see them.

    `lora` (optional, serve/lora.py mixed-tenant decode): this layer's
    per-slot adapter selections — ``{"wq": (a [B,D,r], b [B,r,D]),
    "wv": (a, b), "scale": [B]}`` — added to the base projections as
    ``base @ x + scatter-gathered (B·A) @ x``. Slots on the null
    adapter (all-zero A/B, scale 0) add an exact-zero delta, keeping
    the base-only math bit-identical to the lora=None path."""
    c = config
    b, t = x.shape[0], x.shape[1]
    h = rms_norm(x, p["attn_norm"]["scale"])
    if lora is None:
        q, k, v = _qkv(h, p, c)
    else:
        from ..ops.layers import lora_delta

        q = _mm(h, p["attn"]["wq"]) + lora_delta(
            h, *lora["wq"], lora["scale"])
        k = _mm(h, p["attn"]["wk"])
        v = _mm(h, p["attn"]["wv"]) + lora_delta(
            h, *lora["wv"], lora["scale"])
        q = q.reshape(b, t, c.num_heads, c.head_dim)
        k = k.reshape(b, t, c.num_kv_heads, c.head_dim)
        v = v.reshape(b, t, c.num_kv_heads, c.head_dim)
    positions = pos_vec[:, None] + jnp.arange(t)[None, :]   # [B, t]
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    rows = jnp.arange(b)
    ck = cache["k"].at[rows[:, None], positions].set(
        k.astype(cache["k"].dtype))
    cv = cache["v"].at[rows[:, None], positions].set(
        v.astype(cache["v"].dtype))
    kk, vv = _repeat_kv(ck, cv, c)
    s = kk.shape[1]
    scores = jnp.einsum("bthd,bshd->bhts", q, kk,
                        preferred_element_type=jnp.float32)
    scores = scores / (c.head_dim ** 0.5)
    col = jnp.arange(s)[None, None, None, :]
    visible = col <= positions[:, None, :, None]
    scores = jnp.where(visible, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    a = jnp.einsum("bhts,bshd->bthd", probs, vv).reshape(b, t, c.d_model)
    x = x + _mm(a, p["attn"]["wo"])
    return _mlp_res(x, p), {"k": ck, "v": cv}


def llama_decode(params: Params, tokens: jax.Array, config: LlamaConfig,
                 cache: list, pos_vec: jax.Array,
                 lora: Optional[Dict[str, Any]] = None):
    """One decode step for a ragged batch: tokens [B] at per-slot
    positions pos_vec [B]. Returns (logits [B, padded_vocab] fp32,
    new_cache). tokens [B, q] is the speculative VERIFY form: slot b's
    q tokens land at positions pos_vec[b]..pos_vec[b]+q-1 and the
    logits come back [B, q, padded_vocab] — position j's row is what a
    sequential decode would have produced after feeding tokens[:, :j+1]
    (models/engine.py accepts the longest agreeing draft prefix off
    it).

    `lora` (optional): the adapter-pool stacks + per-slot indices —
    ``{"idx": [B] int32, "scale": [P] f32, "wq": (a [P,L,D,r],
    b [P,L,r,D]), "wv": (...)}`` (serve/lora.py layout). Each slot's
    adapter is gathered out of the pool once, then every layer adds its
    per-slot low-rank delta to the wq/wv projections."""
    c = config
    cos, sin = rope_table(c.head_dim, c.max_seq_len, c.rope_theta)
    ragged = tokens.ndim == 1
    x = params["tok_emb"][tokens[:, None] if ragged else tokens]
    sel = None
    if lora is not None:
        idx = lora["idx"]
        sel = {t: (lora[t][0][idx], lora[t][1][idx])
               for t in ("wq", "wv")}
        scale = lora["scale"][idx]
    new_cache = []
    for li, (p, blk_cache) in enumerate(zip(params["blocks"], cache)):
        lora_l = None if sel is None else {
            "wq": (sel["wq"][0][:, li], sel["wq"][1][:, li]),
            "wv": (sel["wv"][0][:, li], sel["wv"][1][:, li]),
            "scale": scale}
        x, nc = llama_block_decode(x, p, cos, sin, c, blk_cache,
                                   pos_vec, lora_l)
        new_cache.append(nc)
    x = rms_norm(x, params["norm_f"]["scale"])
    if ragged:
        x = x[:, 0]
    return jnp.dot(x, params["lm_head"],
                   preferred_element_type=jnp.float32), new_cache


def init_kv_cache(config: LlamaConfig, batch_size: int,
                  max_len: int = 0, dtype: Any = None) -> list:
    """Per-layer K/V buffers [B, S, n_kv_heads, head_dim]."""
    c = config
    s = max_len or c.max_seq_len
    dt = dtype or c.dtype
    return [{"k": jnp.zeros((batch_size, s, c.num_kv_heads, c.head_dim),
                            dt),
             "v": jnp.zeros((batch_size, s, c.num_kv_heads, c.head_dim),
                            dt)}
            for _ in range(c.num_layers)]


def llama_forward_cached(params: Params, tokens: jax.Array,
                         config: LlamaConfig, cache: list,
                         pos: jax.Array):
    """Append `tokens` [B, T] at position `pos` (scalar int32); returns
    (logits [B, T, padded_vocab] fp32, new_cache). pos=0 with the whole
    prompt is prefill; T=1 afterwards is autoregressive decode."""
    c = config
    cos, sin = rope_table(c.head_dim, c.max_seq_len, c.rope_theta)
    x = params["tok_emb"][tokens]
    new_cache = []
    for p, blk_cache in zip(params["blocks"], cache):
        x, nc = llama_block_cached(x, p, cos, sin, c, blk_cache, pos)
        new_cache.append(nc)
    x = rms_norm(x, params["norm_f"]["scale"])
    return jnp.dot(x, params["lm_head"],
                   preferred_element_type=jnp.float32), new_cache


def llama_loss(params: Params, tokens: jax.Array, targets: jax.Array,
               config: LlamaConfig, remat: bool = False) -> jax.Array:
    fwd = llama_forward
    if remat:
        fwd = jax.checkpoint(llama_forward, static_argnums=(2,))
    logits = fwd(params, tokens, config)
    if config.padded_vocab != config.vocab_size:
        neg = jnp.full((config.padded_vocab - config.vocab_size,), -1e30,
                       dtype=logits.dtype)
        logits = logits.at[..., config.vocab_size:].set(neg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def llama_partition_specs(config: LlamaConfig) -> Params:
    """Megatron layout: q/k/v and gate/up column-parallel on tp, wo/down
    row-parallel, embeddings 2D-sharded. Collapses to replicated when the
    mesh has tp=fsdp=1."""
    block = {
        "attn_norm": {"scale": P()},
        "attn": {"wq": P("fsdp", "tp"), "wk": P("fsdp", "tp"),
                 "wv": P("fsdp", "tp"), "wo": P("tp", "fsdp")},
        "ffn_norm": {"scale": P()},
        "mlp": {"w_gate": P("fsdp", "tp"), "w_up": P("fsdp", "tp"),
                "w_down": P("tp", "fsdp")},
    }
    return {
        "tok_emb": P("tp", "fsdp"),
        "norm_f": {"scale": P()},
        "lm_head": P("fsdp", "tp"),
        "blocks": [block for _ in range(config.num_layers)],
    }
