"""Continuous-batching generation engine — the LLM serving throughput
story (BASELINE "Llama JAX replica, batched inference"; the reference
serves torch models and leaves batching to the replica, Serve's @batch
being request-level — this is TOKEN-level continuous batching in the
vLLM sense, rebuilt TPU-first).

Design: one fixed-shape decode loop over `max_batch` slots. Every tick
runs ONE jitted ragged-batch step (the model family's per-slot decode —
llama_decode / gpt2_decode — with per-slot positions and masking,
static shapes throughout, so XLA compiles exactly one program no
matter how requests interleave). New requests prefill into a
free slot (one jitted prefill per distinct (cached-prefix, suffix)
length pair — exact lengths, so cache rows beyond a slot's own depth
are never attended) and JOIN the running batch between ticks; finished
sequences (EOS or their token budget) free their slot between ticks.
Slots the engine isn't using decode garbage that nothing reads — the
cost of static shapes, paid once, instead of a recompile per batch
composition.

Prefill rides the paged KV prefix cache (models/kvcache.py): admission
looks up the longest cached block-aligned prefix of the prompt, gathers
those blocks from the pool, and prefills ONLY the suffix; the filled
prompt region is then SPLICED into the slot's rows of the decode slab —
an O(prompt_len) in-place update, not the O(max_batch x max_len)
full-cache copy the old `_adopt_slot` paid per admission. Admissions
between ticks are capped at ``RAY_TPU_MAX_PREFILLS_PER_TICK`` (default
1) so a burst of arrivals cannot head-of-line-block every in-flight
decode for the whole drain.

Per-request token queues make it the natural producer for Serve's
streaming path; `ContinuousBatchingEngine` is thread-safe for
concurrent submit/iterate from replica request threads. The streamed
iterator exposes ``cache_outcome`` (hit|partial|miss) so the replica's
TTFT histogram can label prefix-cache wins.
"""
from __future__ import annotations

import functools
import itertools
import os
import queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .generate import _model_fns
from .kvcache import PagedKVCache

_DONE = object()
_ENGINE_SEQ = itertools.count()


@functools.partial(jax.jit, static_argnums=(2,))
def _prefill_paged(params, suffix, config, prefix_k, prefix_v):
    """Prefill a single sequence's SUFFIX on top of a cached prefix
    ([L, c, H, hd]; c=0 is the full-prefill program). The window is the
    full max_seq_len slab — the same reduction shapes as generate()'s
    prefill, so cached and uncached paths stay bit-identical — and the
    returned cache is the stacked [L, S, H, hd] single-sequence fill.
    One compile per distinct (cached, suffix) length pair."""
    fwd = _model_fns(config)[0]
    c = prefix_k.shape[1]
    layers = prefix_k.shape[0]
    base_k = jnp.zeros((layers, config.max_seq_len) + prefix_k.shape[2:],
                       prefix_k.dtype)
    base_v = jnp.zeros_like(base_k)
    if c:
        base_k = base_k.at[:, :c].set(prefix_k)
        base_v = base_v.at[:, :c].set(prefix_v)
    cache = [{"k": base_k[layer][None], "v": base_v[layer][None]}
             for layer in range(layers)]
    logits, cache = fwd(params, suffix, config, cache, c)
    ck = jnp.stack([blk["k"][0] for blk in cache])
    cv = jnp.stack([blk["v"][0] for blk in cache])
    return logits[:, -1], ck, cv


@functools.partial(jax.jit, static_argnums=(4, 5),
                   donate_argnums=(0,))
def _splice_slot(cache, ck, cv, slot, config, plen):
    """Write a prefilled sequence's [0, plen) rows into batch slot
    `slot` of the decode slab — with the slab donated this lowers to an
    in-place O(plen) row update per layer, never a full-cache copy."""
    del config
    out = []
    for layer, blk in enumerate(cache):
        out.append({
            "k": jax.lax.dynamic_update_slice(
                blk["k"], ck[layer, :plen][None], (slot, 0, 0, 0)),
            "v": jax.lax.dynamic_update_slice(
                blk["v"], cv[layer, :plen][None], (slot, 0, 0, 0)),
        })
    return out


@functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(2,))
def _tick(params, config, cache, tokens, pos_vec):
    logits, cache = _model_fns(config)[2](params, tokens, config, cache,
                                          pos_vec)
    live = logits[:, :config.vocab_size].astype(jnp.float32)
    nxt = jnp.argmax(live, axis=-1).astype(jnp.int32)
    # per-slot logprob of the chosen (greedy = max-logit) token — the
    # rollout score stream (ray_tpu.online samplers record it per token)
    lp = jnp.max(live, axis=-1) - jax.nn.logsumexp(live, axis=-1)
    return cache, nxt, lp


class _Request:
    def __init__(self, rid: int, prompt: np.ndarray, max_new: int,
                 eos_token: Optional[int]):
        self.rid = rid
        self.prompt = prompt
        self.max_new = max_new
        self.eos_token = eos_token
        self.out: "queue.Queue" = queue.Queue()
        self.produced = 0
        self.slot: Optional[int] = None
        self.cache_outcome: Optional[str] = None  # hit|partial|miss
        self.reused_tokens = 0
        self.block_table: List[int] = []
        # per-token logprob of each emitted token (same order as the
        # token stream) — the rollout score channel
        self.scores: List[float] = []


class TokenStream:
    """Iterator over one request's tokens with the prefix-cache outcome
    attached (``cache_outcome``: hit|partial|miss, None until the
    request is admitted — always set before the first token arrives).
    Serve's streaming replica reads it to label the TTFT histogram."""

    def __init__(self, req: _Request, timeout_s: float):
        self._req = req
        self._timeout_s = timeout_s

    def __iter__(self) -> "TokenStream":
        return self

    def __next__(self) -> int:
        tok = self._req.out.get(timeout=self._timeout_s)
        if tok is _DONE:
            raise StopIteration
        return int(tok)

    @property
    def cache_outcome(self) -> Optional[str]:
        return self._req.cache_outcome

    @property
    def reused_tokens(self) -> int:
        return self._req.reused_tokens

    @property
    def scores(self) -> List[float]:
        """Per-token logprobs of the tokens emitted SO FAR (aligned
        with the token stream; complete once iteration finishes)."""
        return list(self._req.scores)


class ContinuousBatchingEngine:
    """Greedy continuous-batching decode over `max_batch` slots."""

    def __init__(self, params: Any, config: Any, *,
                 max_batch: int = 8, idle_sleep_s: float = 0.002,
                 params_version: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 kv_block_size: Optional[int] = None,
                 kv_pool_blocks: Optional[int] = None,
                 max_prefills_per_tick: Optional[int] = None):
        # config: any family _model_fns knows (LlamaConfig, GPT2Config)
        self.params = params
        self.config = config
        self.max_batch = max_batch
        self.idle_sleep_s = idle_sleep_s
        self.engine_id = f"cb-{os.getpid()}-{next(_ENGINE_SEQ)}"
        # live-weight hot swap (ray_tpu.weights): a queued (params,
        # version) is applied by the decode loop BETWEEN ticks — the
        # params pytree is a plain jit argument, so swapping it never
        # invalidates compiled programs or in-flight slots' KV caches
        self.params_version = params_version
        self._pending_swap: Optional[tuple] = None
        self.swap_count = 0
        self._cache = _model_fns(config)[1](config, max_batch)
        # paged KV prefix cache (models/kvcache.py); RAY_TPU_KV_* env
        # knobs supply defaults, constructor args win
        if prefix_cache is None:
            prefix_cache = os.environ.get("RAY_TPU_KV_CACHE", "1") != "0"
        if max_prefills_per_tick is None:
            max_prefills_per_tick = int(os.environ.get(
                "RAY_TPU_MAX_PREFILLS_PER_TICK", "1"))
        self.max_prefills_per_tick = max(1, int(max_prefills_per_tick))
        block_size = int(kv_block_size
                         or os.environ.get("RAY_TPU_KV_BLOCK_SIZE", "16"))
        pool_blocks = int(kv_pool_blocks
                          or int(os.environ.get("RAY_TPU_KV_POOL_BLOCKS",
                                                "0"))
                          or max_batch * (-(-config.max_seq_len
                                            // block_size)))
        self.kv_cache: Optional[PagedKVCache] = (
            PagedKVCache(config, block_size=block_size,
                         num_blocks=pool_blocks)
            if prefix_cache else None)
        shape = self._cache[0]["k"].shape  # [maxB, S, H, hd]
        self._empty_prefix = jnp.zeros(
            (len(self._cache), 0) + shape[2:], self._cache[0]["k"].dtype)
        # admission accounting (kv_stats / acceptance surface)
        self.prefill_calls = 0
        self.prefilled_tokens = 0
        self.spliced_tokens = 0
        self.admitted = 0
        self.max_admitted_per_tick = 0
        self._last_stats_push = 0.0
        self._tokens = np.zeros(max_batch, np.int32)
        self._pos = np.zeros(max_batch, np.int32)
        self._slot_req: List[Optional[_Request]] = [None] * max_batch
        self._free = list(range(max_batch))
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._lock = threading.Lock()
        self._next_rid = 0
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cb-engine")
        self._thread.start()

    # ------------------------------------------------------------- API
    def submit(self, prompt_tokens, max_new_tokens: int,
               eos_token: Optional[int] = None) -> "_Request":
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        if prompt.shape[1] + max_new_tokens > self.config.max_seq_len:
            raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        req = _Request(rid, prompt, max_new_tokens, eos_token)
        self._pending.put(req)
        return req

    def stream(self, prompt_tokens, max_new_tokens: int,
               eos_token: Optional[int] = None,
               timeout_s: float = 120.0) -> Iterator[int]:
        """Submit and yield tokens as the shared loop produces them.
        Returns a TokenStream whose ``cache_outcome`` labels the
        admission's prefix-cache result."""
        req = self.submit(prompt_tokens, max_new_tokens, eos_token)
        return TokenStream(req, timeout_s)

    def generate(self, prompt_tokens, max_new_tokens: int,
                 eos_token: Optional[int] = None,
                 timeout_s: float = 120.0) -> List[int]:
        return list(self.stream(prompt_tokens, max_new_tokens, eos_token,
                                timeout_s))

    def update_params(self, params: Any,
                      version: Optional[int] = None) -> threading.Event:
        """Queue a live weight swap; the decode loop applies it between
        ticks (never mid-tick), so in-flight requests keep their KV
        caches and keep decoding — under the new weights from the next
        tick on — with no restart and no drop. Returns an Event set once
        the swap has been applied. Two swaps queued between the same two
        ticks coalesce: the newer wins, both events fire."""
        ev = threading.Event()
        with self._lock:
            prev = self._pending_swap
            self._pending_swap = (params, version,
                                  (prev[2] + [ev]) if prev else [ev])
        if self._stopped.is_set() and not self._thread.is_alive():
            # decode loop confirmed exited (not merely stop-requested —
            # the loop may still be inside its final tick): apply
            # synchronously so a caller's wait() never strands on a
            # stopped engine, without ever swapping mid-tick
            self._apply_pending_swap()
        return ev

    def _apply_pending_swap(self) -> None:
        """Decode-loop only, between ticks."""
        with self._lock:
            pending, self._pending_swap = self._pending_swap, None
        if pending is None:
            return
        params, version, events = pending
        self.params = params
        self.params_version = version
        self.swap_count += 1
        # every cached block's KV was computed under the old weights:
        # drop the prefix index so no post-swap admission matches it
        # (in-flight slots decode off their own slab copy, unaffected)
        if self.kv_cache is not None:
            self.kv_cache.invalidate()
            self.publish_kv_telemetry(force=True)
        for ev in events:
            ev.set()

    def stop(self) -> None:
        self._stopped.set()
        self._thread.join(timeout=10.0)
        self._apply_pending_swap()  # fire waiters a dead loop would strand
        self.publish_kv_telemetry(force=True)

    @property
    def active_slots(self) -> int:
        with self._lock:
            return self.max_batch - len(self._free)

    # ------------------------------------------------------- telemetry
    def kv_stats(self) -> Dict[str, Any]:
        """Prefix-cache + admission counters — the snapshot pushed to
        the conductor for util.state.kv_cache_stats(), the CLI, and the
        dashboard (all surfaces report THIS dict's numbers)."""
        s: Dict[str, Any] = (self.kv_cache.stats() if self.kv_cache
                             else {"enabled": False})
        try:
            programs = _prefill_paged._cache_size()
        except Exception:  # noqa: BLE001 — older jax without _cache_size
            programs = -1
        s.update(
            engine_id=self.engine_id,
            max_batch=self.max_batch,
            max_prefills_per_tick=self.max_prefills_per_tick,
            admitted=self.admitted,
            max_admitted_per_tick=self.max_admitted_per_tick,
            prefill_calls=self.prefill_calls,
            prefill_programs=programs,
            spliced_tokens=self.spliced_tokens,
        )
        if self.kv_cache is None:
            # uncached engines still account their prefill work
            s.setdefault("prefilled_tokens", self.prefilled_tokens)
            s.setdefault("reused_tokens", 0)
        return s

    def publish_kv_telemetry(self, force: bool = False) -> None:
        """Best-effort push of kv_stats + pending timeline events to the
        conductor (no-op without a live cluster); throttled unless
        forced."""
        now = time.monotonic()
        if not force and now - self._last_stats_push < 0.5:
            return
        self._last_stats_push = now
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            if self.kv_cache is not None:
                self.kv_cache.drain_events()  # keep the buffer bounded
            return
        try:
            w.conductor.notify("report_kvcache_stats", w.worker_id,
                               self.engine_id, self.kv_stats())
            if self.kv_cache is not None:
                for ev in self.kv_cache.drain_events():
                    ev.setdefault("engine", self.engine_id)
                    w.conductor.notify("report_kvcache_event", ev)
        except Exception:  # noqa: BLE001 — cluster shutting down
            pass

    # ------------------------------------------------------------ loop
    def _admit(self) -> None:
        admitted = 0
        while self._free and admitted < self.max_prefills_per_tick:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                break
            self._admit_one(req)
            admitted += 1
        if admitted:
            self.max_admitted_per_tick = max(self.max_admitted_per_tick,
                                             admitted)
            self.publish_kv_telemetry()

    def _admit_one(self, req: _Request) -> None:
        with self._lock:
            slot = self._free.pop()
        plen = req.prompt.shape[1]
        prompt_np = req.prompt[0]
        match = None
        if self.kv_cache is not None:
            match = self.kv_cache.lookup(prompt_np, max_tokens=plen - 1)
            req.cache_outcome = match.outcome
            req.reused_tokens = match.tokens
            prefix_k, prefix_v = self.kv_cache.gather(match)
        else:
            prefix_k = prefix_v = self._empty_prefix
        cached = int(prefix_k.shape[1])
        suffix = req.prompt[:, cached:]
        last_logits, ck, cv = _prefill_paged(self.params, suffix,
                                             self.config, prefix_k,
                                             prefix_v)
        self.prefill_calls += 1
        self.prefilled_tokens += suffix.shape[1]
        if self.kv_cache is not None:
            self.kv_cache.note_prefilled(suffix.shape[1])
            req.block_table = self.kv_cache.commit(prompt_np, ck, cv,
                                                   match)
            if match.tokens:
                self.kv_cache.record_event({
                    "kind": "prefix_hit", "outcome": match.outcome,
                    "reused_tokens": match.tokens,
                    "prompt_tokens": plen, "rid": req.rid})
        self._cache = _splice_slot(self._cache, ck, cv, np.int32(slot),
                                   self.config, plen)
        self.spliced_tokens += plen
        self.admitted += 1
        live = np.asarray(last_logits[0, :self.config.vocab_size],
                          np.float32)
        first = int(np.argmax(live))
        m = float(live[first])
        score = -float(np.log(np.exp(live - m).sum()))  # m - logsumexp
        req.slot = slot
        self._slot_req[slot] = req
        self._tokens[slot] = first
        self._pos[slot] = plen
        self._emit(req, first, score)

    def _emit(self, req: _Request, tok: int, score: float = 0.0) -> None:
        req.scores.append(score)
        req.out.put(tok)
        req.produced += 1
        if (req.eos_token is not None and tok == req.eos_token) \
                or req.produced >= req.max_new:
            req.out.put(_DONE)
            slot = req.slot
            self._slot_req[slot] = None
            if self.kv_cache is not None and req.block_table:
                self.kv_cache.release(req.block_table)
                req.block_table = []
            with self._lock:
                self._free.append(slot)

    def _loop(self) -> None:
        while not self._stopped.is_set():
            self._apply_pending_swap()
            self._admit()
            if all(r is None for r in self._slot_req):
                self._stopped.wait(self.idle_sleep_s)
                continue
            cache, nxt, lp = _tick(self.params, self.config, self._cache,
                                   jnp.asarray(self._tokens),
                                   jnp.asarray(self._pos))
            self._cache = cache
            nxt_np = np.asarray(nxt)
            lp_np = np.asarray(lp)
            for slot, req in enumerate(self._slot_req):
                if req is None:
                    continue
                self._pos[slot] += 1
                tok = int(nxt_np[slot])
                self._tokens[slot] = tok
                self._emit(req, tok, float(lp_np[slot]))
