"""Continuous-batching generation engine — the LLM serving throughput
story (BASELINE "Llama JAX replica, batched inference"; the reference
serves torch models and leaves batching to the replica, Serve's @batch
being request-level — this is TOKEN-level continuous batching in the
vLLM sense, rebuilt TPU-first).

Design: one fixed-shape decode loop over `max_batch` slots. Every tick
runs ONE jitted ragged-batch step (the model family's per-slot decode —
llama_decode / gpt2_decode — with per-slot positions and masking,
static shapes throughout, so XLA compiles exactly one program no
matter how requests interleave). New requests prefill into a
free slot (one jitted prefill per distinct (cached-prefix, suffix)
length pair — exact lengths, so cache rows beyond a slot's own depth
are never attended) and JOIN the running batch between ticks; finished
sequences (EOS or their token budget) free their slot between ticks.
Slots the engine isn't using decode garbage that nothing reads — the
cost of static shapes, paid once, instead of a recompile per batch
composition.

Prefill rides the paged KV prefix cache (models/kvcache.py): admission
looks up the longest cached block-aligned prefix of the prompt, gathers
those blocks from the pool, and prefills ONLY the suffix; the filled
prompt region is then SPLICED into the slot's rows of the decode slab —
an O(prompt_len) in-place update, not the O(max_batch x max_len)
full-cache copy the old `_adopt_slot` paid per admission. Admissions
between ticks are capped at ``RAY_TPU_MAX_PREFILLS_PER_TICK`` (default
1) so a burst of arrivals cannot head-of-line-block every in-flight
decode for the whole drain.

Disaggregated serving (serve/disagg.py) splits the two phases across
replicas: a decode replica's engine never prefills at all — it ADOPTS a
prompt's already-computed KV rows via ``adopt_prefill()``, which splices
them into a free slot between ticks through the same `_splice_slot`
program (O(prompt_len), never a full-cache copy) and emits the
prefill-produced first token. Adoption is its own admission phase with
its own per-tick cap (``RAY_TPU_MAX_ADOPTIONS_PER_TICK``, default 4 —
splices are cheap relative to prefills) and its own counters
(``adopted`` / ``max_adoptions_admitted_per_tick`` vs
``prefill_admitted`` / ``max_prefills_admitted_per_tick``), so the
kvcache CLI/dashboard numbers stay truthful in both modes.

Speculative decoding (``speculate_k`` / ``RAY_TPU_SPECULATE_K``): the
tick loop above is one-token-per-step per slot — serving throughput
pinned to sequential forward passes even though the verify side is
embarrassingly batchable. With speculation on, a PROMPT-LOOKUP
proposer drafts up to k tokens per slot between ticks (no draft model,
no extra compile): first from the paged prefix index's exact token
chains (``PagedKVCache.propose`` — drafting from cache is nearly
free), then from the most recent match of the slot's own trailing
n-gram in its context. The engine then verifies all k in ONE batched
forward — ``_tick`` is shape-polymorphic from seqlen-1 to seqlen-(k+1)
per slot (the model families' ``*_decode`` take tokens [B] or
[B, k+1] with per-slot base positions) — and accepts the longest
prefix of the draft that agrees with the greedy argmax chain. Greedy
bit-identity to the unspeculated engine is the correctness oracle: an
accepted token IS the token sequential decode would have produced, and
a rejected draft's KV rows need no copy-back — per-position masking
keeps them invisible until the real decode overwrites them, and the
only pooled state drafting touches is read-only (proposals pin
nothing; the request's block refcounts alone govern pool reclamation,
so rejection rolls back by refcount, never by copy).
Surfaces: ``util.state.speculation_stats()``, ``ray_tpu speculate``,
``/api/speculation``, lazy Prometheus
(``ray_tpu_spec_proposed_total`` / ``_accepted_total`` /
``ray_tpu_spec_acceptance_rate``), and spec_accept / spec_reject
instant markers in the merged timeline's kvcache lane.

Per-request token queues make it the natural producer for Serve's
streaming path; `ContinuousBatchingEngine` is thread-safe for
concurrent submit/iterate from replica request threads. The streamed
iterator exposes ``cache_outcome`` (hit|partial|miss) so the replica's
TTFT histogram can label prefix-cache wins.
"""
from __future__ import annotations

import functools
import itertools
import os
import queue
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .generate import _model_fns, merge_lora_params
from .kvcache import PagedKVCache, resolve_pool_config

_DONE = object()
_ENGINE_SEQ = itertools.count()
_SPEC_EVENTS_KEPT = 512


def default_speculate_k() -> int:
    """The ``RAY_TPU_SPECULATE_K`` env default (0 = speculation off)
    every engine owner resolves through."""
    from ray_tpu.util import envknobs

    return max(0, envknobs.get_int("RAY_TPU_SPECULATE_K", 0))


# ----------------------------------------------------- prometheus (lazy)
# Created on first speculating engine, never at import (the kvcache /
# lora pattern — rebound ONCE to a complete dict).

_spec_metrics: Optional[Dict[str, Any]] = None
_spec_metrics_lock = threading.Lock()


def spec_metrics() -> Dict[str, Any]:
    global _spec_metrics
    m = _spec_metrics
    if m is not None:
        return m
    with _spec_metrics_lock:
        if _spec_metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _spec_metrics = dict(
                proposed=Counter(
                    "ray_tpu_spec_proposed_total",
                    "draft tokens proposed to the verify pass"),
                accepted=Counter(
                    "ray_tpu_spec_accepted_total",
                    "draft tokens accepted (greedy-agreeing prefix)"),
                acceptance_rate=Gauge(
                    "ray_tpu_spec_acceptance_rate",
                    "lifetime accepted/proposed draft-token ratio per "
                    "engine (counters are process-global; the gauge is "
                    "engine-tagged so co-resident engines can't "
                    "last-writer-wins each other)",
                    tag_keys=("engine",)))
    return _spec_metrics


@functools.partial(jax.jit, static_argnums=(2,))
def _prefill_paged(params, suffix, config, prefix_k, prefix_v):
    """Prefill a single sequence's SUFFIX on top of a cached prefix
    ([L, c, H, hd]; c=0 is the full-prefill program). The window is the
    full max_seq_len slab — the same reduction shapes as generate()'s
    prefill, so cached and uncached paths stay bit-identical — and the
    returned cache is the stacked [L, S, H, hd] single-sequence fill.
    One compile per distinct (cached, suffix) length pair."""
    fwd = _model_fns(config)[0]
    c = prefix_k.shape[1]
    layers = prefix_k.shape[0]
    base_k = jnp.zeros((layers, config.max_seq_len) + prefix_k.shape[2:],
                       prefix_k.dtype)
    base_v = jnp.zeros_like(base_k)
    if c:
        base_k = base_k.at[:, :c].set(prefix_k)
        base_v = base_v.at[:, :c].set(prefix_v)
    cache = [{"k": base_k[layer][None], "v": base_v[layer][None]}
             for layer in range(layers)]
    logits, cache = fwd(params, suffix, config, cache, c)
    ck = jnp.stack([blk["k"][0] for blk in cache])
    cv = jnp.stack([blk["v"][0] for blk in cache])
    return logits[:, -1], ck, cv


@functools.partial(jax.jit, static_argnums=(2,))
def _prefill_paged_lora(params, suffix, config, prefix_k, prefix_v,
                        lora):
    """`_prefill_paged` under ONE tenant's LoRA adapter: the low-rank
    deltas are merged into the target leaves INSIDE the jit (prefill is
    per-request single-tenant, so the merged weights never persist —
    only the decode tick pays the scatter-gathered per-slot form). One
    compile per distinct (cached, suffix, rank) shape triple."""
    params = merge_lora_params(params, config, lora)
    fwd = _model_fns(config)[0]
    c = prefix_k.shape[1]
    layers = prefix_k.shape[0]
    base_k = jnp.zeros((layers, config.max_seq_len) + prefix_k.shape[2:],
                       prefix_k.dtype)
    base_v = jnp.zeros_like(base_k)
    if c:
        base_k = base_k.at[:, :c].set(prefix_k)
        base_v = base_v.at[:, :c].set(prefix_v)
    cache = [{"k": base_k[layer][None], "v": base_v[layer][None]}
             for layer in range(layers)]
    logits, cache = fwd(params, suffix, config, cache, c)
    ck = jnp.stack([blk["k"][0] for blk in cache])
    cv = jnp.stack([blk["v"][0] for blk in cache])
    return logits[:, -1], ck, cv


def _prefill_with_cache(params, config, kv_cache, prompt, empty_prefix,
                        event_extra=None, adapter=None, namespace=None):
    """The prefill-behind-the-prefix-cache sequence shared by the
    colocated engine's `_admit_one` and the disagg `PrefillServer`:
    lookup → gather → `_prefill_paged` on the suffix → commit +
    prefix_hit event → greedy first token + its logprob score. ONE
    implementation keeps the two paths bit-identical (the disagg
    equivalence tests depend on it). Returns `(ck, cv, block_table,
    first, score, outcome, reused, suffix_len)`; the caller owns the
    returned pins (empty list when no cache).

    `adapter`/`namespace` (multi-tenant LoRA, serve/lora.py): prefill
    under one tenant's adapter slice, with the prefix cache keyed by
    (namespace, prompt) so one tenant's KV can never match
    another's."""
    plen = prompt.shape[1]
    prompt_np = prompt[0]
    outcome, reused = "miss", 0
    if kv_cache is not None:
        match = kv_cache.lookup(prompt_np, max_tokens=plen - 1,
                                namespace=namespace)
        outcome, reused = match.outcome, match.tokens
        prefix_k, prefix_v = kv_cache.gather(match)
    else:
        match = None
        prefix_k = prefix_v = empty_prefix
    cached = int(prefix_k.shape[1])
    suffix = prompt[:, cached:]
    if adapter is not None:
        last_logits, ck, cv = _prefill_paged_lora(
            params, suffix, config, prefix_k, prefix_v, adapter)
    else:
        last_logits, ck, cv = _prefill_paged(params, suffix, config,
                                             prefix_k, prefix_v)
    table: List[Any] = []
    if kv_cache is not None:
        kv_cache.note_prefilled(suffix.shape[1])
        table = kv_cache.commit(prompt_np, ck, cv, match,
                                namespace=namespace)
        if match.tokens:
            event = {"kind": "prefix_hit", "outcome": outcome,
                     "reused_tokens": reused, "prompt_tokens": plen}
            if event_extra:
                event.update(event_extra)
            kv_cache.record_event(event)
    live = np.asarray(last_logits[0, :config.vocab_size], np.float32)
    first = int(np.argmax(live))
    m = float(live[first])
    score = -float(np.log(np.exp(live - m).sum()))  # m - logsumexp
    return (ck, cv, table, first, score, outcome, int(reused),
            int(suffix.shape[1]))


@functools.partial(jax.jit, static_argnums=(4, 5),
                   donate_argnums=(0,))
def _splice_slot(cache, ck, cv, slot, config, plen):
    """Write a prefilled sequence's [0, plen) rows into batch slot
    `slot` of the decode slab — with the slab donated this lowers to an
    in-place O(plen) row update per layer, never a full-cache copy."""
    del config
    out = []
    for layer, blk in enumerate(cache):
        out.append({
            "k": jax.lax.dynamic_update_slice(
                blk["k"], ck[layer, :plen][None], (slot, 0, 0, 0)),
            "v": jax.lax.dynamic_update_slice(
                blk["v"], cv[layer, :plen][None], (slot, 0, 0, 0)),
        })
    return out


@functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(2,))
def _tick(params, config, cache, tokens, pos_vec):
    """One decode step — shape-polymorphic over the token axis:
    tokens [B] is the classic one-token tick; tokens [B, k+1] is the
    speculative VERIFY pass (column 0 each slot's last token, columns
    1..k its drafted continuation — slots with a shorter/no draft pad
    by repeating column 0; padded rows are never accepted and their
    KV rows stay masked until overwritten). jit specializes per shape,
    and the verify's row j is bit-identical to j sequential one-token
    ticks — the accept rule's whole contract, shared math by
    construction because this IS the same function."""
    logits, cache = _model_fns(config)[2](params, tokens, config, cache,
                                          pos_vec)
    live = logits[..., :config.vocab_size].astype(jnp.float32)
    nxt = jnp.argmax(live, axis=-1).astype(jnp.int32)
    # per-slot logprob of the chosen (greedy = max-logit) token — the
    # rollout score stream (ray_tpu.online samplers record it per token)
    lp = jnp.max(live, axis=-1) - jax.nn.logsumexp(live, axis=-1)
    return cache, nxt, lp


@functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(2,))
def _tick_lora(params, config, cache, tokens, pos_vec, lora):
    """The mixed-tenant decode tick: one jitted ragged-batch step with
    PER-SLOT adapter indices (`lora["idx"]`) gathering each slot's
    low-rank deltas out of the resident adapter-pool stacks —
    ``base @ x + scatter-gathered (B·A) @ x`` at the LoRA-target leaves
    (serve/lora.py). Slots on the null adapter (index 0: zero A/B,
    scale 0) compute a bit-identical base-only step, so mixed batches
    never perturb base traffic. Chosen over `_tick` only when a live
    slot actually holds an adapter; pool shapes are static, so this is
    ONE extra compiled program per engine. Shape-polymorphic like
    `_tick`: tokens [B, k+1] is the speculative verify pass, with the
    adapter deltas applied at every position."""
    logits, cache = _model_fns(config)[2](params, tokens, config, cache,
                                          pos_vec, lora)
    live = logits[..., :config.vocab_size].astype(jnp.float32)
    nxt = jnp.argmax(live, axis=-1).astype(jnp.int32)
    lp = jnp.max(live, axis=-1) - jax.nn.logsumexp(live, axis=-1)
    return cache, nxt, lp


class _Adoption:
    """A pending slot adoption: a prompt's prefilled KV rows computed
    elsewhere (a prefill replica) plus the first token its last-position
    logits produced. The decode loop splices it between ticks."""

    __slots__ = ("req", "plen", "ck", "cv", "first_token", "score")

    def __init__(self, req: "_Request", plen: int, ck, cv,
                 first_token: int, score: float):
        self.req = req
        self.plen = int(plen)
        self.ck = ck
        self.cv = cv
        self.first_token = int(first_token)
        self.score = float(score)


class _Request:
    def __init__(self, rid: int, prompt: np.ndarray, max_new: int,
                 eos_token: Optional[int]):
        self.rid = rid
        self.prompt = prompt
        self.max_new = max_new
        self.eos_token = eos_token
        self.out: "queue.Queue" = queue.Queue()
        self.produced = 0
        # per-request speculation tally (engine-wide counters can't
        # attribute accepts to one request) — the flight recorder's
        # decode_steady span reads these off the final chunked pull
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.slot: Optional[int] = None
        self.cache_outcome: Optional[str] = None  # hit|partial|miss
        self.reused_tokens = 0
        self.block_table: List[int] = []
        # per-token logprob of each emitted token (same order as the
        # token stream) — the rollout score channel
        self.scores: List[float] = []
        # the KNOWN token context (prompt + emitted) the speculative
        # proposer drafts from — empty for adoptions whose transfer
        # didn't carry the prompt (drafting then waits for history).
        # ctx_has_prompt marks that ctx[:plen] really IS the prompt:
        # the output-memory key is (adapter, prompt), and a promptless
        # adoption's first plen EMITTED tokens must neither store under
        # nor match such a key (it would evict genuine hot-prompt
        # chains from the capped LRU)
        self.ctx: List[int] = []
        self.ctx_has_prompt = False
        # incremental n-gram index over ctx for the self-lookup draft
        # fallback: {n-gram tuple: latest start position of an
        # occurrence ending BEFORE the current tail}. Amortized O(1)
        # per emitted token — a per-tick backward rescan would be
        # O(len(ctx)^2) over a long generation. `ng_indexed` = ctx
        # positions whose ending n-grams are already in.
        self.ngram_last: Dict[tuple, int] = {}
        self.ng_indexed = 0
        # multi-tenant LoRA (serve/lora.py): the tenant tag and its
        # pinned adapter-pool slot (0 = the null/base adapter)
        self.adapter_id: Optional[str] = None
        self.lora_slot = 0
        # cancel_slot() lifecycle: cancelled requests free their slot
        # (and pins) at the next tick boundary instead of decoding to
        # completion; finished guards double-release. cancel_reason
        # attributes the cancel (deadline | disconnect | preempt |
        # failover | idle_reap) in the engine's accounting.
        self.cancelled = False
        self.cancel_reason: Optional[str] = None
        self.finished = False


class TokenStream:
    """Iterator over one request's tokens with the prefix-cache outcome
    attached (``cache_outcome``: hit|partial|miss, None until the
    request is admitted — always set before the first token arrives).
    Serve's streaming replica reads it to label the TTFT histogram."""

    def __init__(self, req: _Request, timeout_s: float):
        self._req = req
        self._timeout_s = timeout_s

    def __iter__(self) -> "TokenStream":
        return self

    def __next__(self) -> int:
        tok = self._req.out.get(timeout=self._timeout_s)
        if tok is _DONE:
            raise StopIteration
        return int(tok)

    @property
    def cache_outcome(self) -> Optional[str]:
        return self._req.cache_outcome

    @property
    def reused_tokens(self) -> int:
        return self._req.reused_tokens

    @property
    def scores(self) -> List[float]:
        """Per-token logprobs of the tokens emitted SO FAR (aligned
        with the token stream; complete once iteration finishes)."""
        return list(self._req.scores)


class ContinuousBatchingEngine:
    """Greedy continuous-batching decode over `max_batch` slots."""

    def __init__(self, params: Any, config: Any, *,
                 max_batch: int = 8, idle_sleep_s: float = 0.002,
                 params_version: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 kv_block_size: Optional[int] = None,
                 kv_pool_blocks: Optional[int] = None,
                 max_prefills_per_tick: Optional[int] = None,
                 max_adoptions_per_tick: Optional[int] = None,
                 lora_pool: Optional[Any] = None,
                 speculate_k: Optional[int] = None,
                 draft_source: Optional[Callable[[List[int], int],
                                                 List[int]]] = None,
                 kv_int8: Optional[bool] = None):
        # config: any family _model_fns knows (LlamaConfig, GPT2Config)
        self.params = params
        self.config = config
        self.max_batch = max_batch
        self.idle_sleep_s = idle_sleep_s
        self.engine_id = f"cb-{os.getpid()}-{next(_ENGINE_SEQ)}"
        # live-weight hot swap (ray_tpu.weights): a queued (params,
        # version) is applied by the decode loop BETWEEN ticks — the
        # params pytree is a plain jit argument, so swapping it never
        # invalidates compiled programs or in-flight slots' KV caches
        self.params_version = params_version
        self._pending_swap: Optional[tuple] = None
        self.swap_count = 0
        self._cache = _model_fns(config)[1](config, max_batch)
        # paged KV prefix cache (models/kvcache.py); RAY_TPU_KV_* env
        # knobs supply defaults, constructor args win
        from ray_tpu.util import envknobs

        if prefix_cache is None:
            prefix_cache = envknobs.get_str(
                "RAY_TPU_KV_CACHE", "1") != "0"
        if max_prefills_per_tick is None:
            max_prefills_per_tick = envknobs.get_int(
                "RAY_TPU_MAX_PREFILLS_PER_TICK", 1)
        self.max_prefills_per_tick = max(1, int(max_prefills_per_tick))
        # adoptions (disaggregated decode) are capped per-phase: a
        # splice is O(prompt) and never compiles a prefill program, so
        # its default budget is looser than the prefill cap
        if max_adoptions_per_tick is None:
            max_adoptions_per_tick = envknobs.get_int(
                "RAY_TPU_MAX_ADOPTIONS_PER_TICK", 4)
        self.max_adoptions_per_tick = max(1, int(max_adoptions_per_tick))
        if kv_int8 is None:
            from .kvcache import kv_int8_default

            kv_int8 = kv_int8_default()
        self.kv_int8 = bool(kv_int8)
        block_size, pool_blocks = resolve_pool_config(
            config, kv_block_size, kv_pool_blocks, slots=max_batch,
            int8=self.kv_int8)
        self.kv_cache: Optional[PagedKVCache] = (
            PagedKVCache(config, block_size=block_size,
                         num_blocks=pool_blocks, int8=self.kv_int8)
            if prefix_cache else None)
        # speculative decoding (module docstring): k drafted tokens per
        # slot verified in one widened tick; 0 = the classic loop.
        # `draft_source(ctx, k) -> tokens` overrides the prompt-lookup
        # proposer (tests script full/partial/zero acceptance with it).
        if speculate_k is None:
            speculate_k = default_speculate_k()
        self.speculate_k = max(0, int(speculate_k))
        self.draft_source = draft_source
        # cross-request output memory: greedy decode under fixed
        # weights is a FUNCTION of (adapter, prompt), so a finished
        # request's token chain is a near-perfect draft for the next
        # request with the same prompt — the Zipf-hot-prompt case the
        # serving replay is made of. Wrong-by-staleness entries cost
        # acceptance, never correctness (the verify pass is the only
        # accept authority); a weight swap clears it anyway.
        self._output_memory: "OrderedDict[tuple, List[int]]" = \
            OrderedDict()
        self._output_memory_cap = 128
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_verify_ticks = 0
        self.spec_emitted = 0       # tokens emitted by DRAFTING slots
        self._spec_events: List[Dict[str, Any]] = []
        if self.speculate_k:
            spec_metrics()  # lazy registration before the first tick
        shape = self._cache[0]["k"].shape  # [maxB, S, H, hd]
        self._empty_prefix = jnp.zeros(
            (len(self._cache), 0) + shape[2:], self._cache[0]["k"].dtype)
        # admission accounting (kv_stats / acceptance surface) — split
        # per phase: prefill admissions vs adoptions of KV prefilled on
        # another replica (serve/disagg.py)
        self.prefill_calls = 0
        self.prefilled_tokens = 0
        self.spliced_tokens = 0
        self.admitted = 0            # total slots admitted (both phases)
        self.prefill_admitted = 0
        self.adopted = 0
        self.cancelled = 0           # slots freed early by cancel_slot()
        # the same count split by the caller-supplied cancel reason
        # (deadline | disconnect | preempt | failover | idle_reap |
        # unspecified) — a QoS preemption must never read as a shed
        self.cancelled_by_reason: Dict[str, int] = {}
        self.max_prefills_admitted_per_tick = 0
        self.max_adoptions_admitted_per_tick = 0
        self._last_stats_push = 0.0
        self._tokens = np.zeros(max_batch, np.int32)
        self._pos = np.zeros(max_batch, np.int32)
        self._slot_req: List[Optional[_Request]] = [None] * max_batch
        self._free = list(range(max_batch))
        # multi-tenant LoRA (serve/lora.py AdapterPool, duck-typed so
        # models/ never imports serve/): per-slot adapter-pool indices
        # (0 = null/base adapter). Adapter acquisition — including a
        # cold page-in — happens on the SUBMITTING thread, never here,
        # so paging one tenant's adapter can't stall another's ticks.
        self.lora_pool = lora_pool
        self._slot_adapter = np.zeros(max_batch, np.int32)
        if lora_pool is not None and self.kv_cache is not None:
            # prefix-cache namespaces are (tenant, adapter-version)
            # stamped, so a hot-swap can never serve old-version KV —
            # this listener only EAGERLY reclaims the superseded
            # version's blocks (they would otherwise LRU out)
            lora_pool.add_swap_listener(
                lambda tenant, old, _p=lora_pool:
                self.kv_cache.invalidate(
                    namespace=_p.cache_namespace(tenant, old)))
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._pending_adopt: "queue.Queue[_Adoption]" = queue.Queue()
        self._cancels = 0  # cancelled-but-unfreed request count
        self._lock = threading.Lock()
        self._next_rid = 0
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cb-engine")
        self._thread.start()

    # ------------------------------------------------------------- API
    def submit(self, prompt_tokens, max_new_tokens: int,
               eos_token: Optional[int] = None,
               adapter_id: Optional[str] = None) -> "_Request":
        """`adapter_id` (multi-tenant LoRA): decode this request under
        that tenant's adapter. The pool pin — and a cold adapter's
        page-in — happens HERE on the caller's thread, so paging never
        blocks the decode loop; the pin is released when the slot
        frees (finish or cancel)."""
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        if prompt.shape[1] + max_new_tokens > self.config.max_seq_len:
            raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
        lora_slot = 0
        if adapter_id is not None:
            if self.lora_pool is None:
                raise ValueError(
                    f"request for adapter {adapter_id!r} but this "
                    f"engine has no lora_pool (serve/lora.AdapterPool)")
            lora_slot = self.lora_pool.acquire(adapter_id)
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        req = _Request(rid, prompt, max_new_tokens, eos_token)
        req.ctx = [int(t) for t in prompt[0]]
        req.ctx_has_prompt = True
        req.adapter_id = adapter_id
        req.lora_slot = lora_slot
        self._pending.put(req)
        return req

    def stream(self, prompt_tokens, max_new_tokens: int,
               eos_token: Optional[int] = None,
               timeout_s: float = 120.0,
               adapter_id: Optional[str] = None) -> Iterator[int]:
        """Submit and yield tokens as the shared loop produces them.
        Returns a TokenStream whose ``cache_outcome`` labels the
        admission's prefix-cache result."""
        req = self.submit(prompt_tokens, max_new_tokens, eos_token,
                          adapter_id=adapter_id)
        return TokenStream(req, timeout_s)

    def generate(self, prompt_tokens, max_new_tokens: int,
                 eos_token: Optional[int] = None,
                 timeout_s: float = 120.0,
                 adapter_id: Optional[str] = None) -> List[int]:
        return list(self.stream(prompt_tokens, max_new_tokens, eos_token,
                                timeout_s, adapter_id=adapter_id))

    def adopt_prefill(self, prompt_len: int, first_token: int, ck, cv,
                      max_new_tokens: int,
                      eos_token: Optional[int] = None, *,
                      score: float = 0.0,
                      cache_outcome: Optional[str] = None,
                      reused_tokens: int = 0,
                      adapter_id: Optional[str] = None,
                      prompt_tokens: Optional[List[int]] = None,
                      timeout_s: float = 120.0) -> TokenStream:
        """Adopt a prompt whose prefill ran ELSEWHERE (a disaggregated
        prefill replica): ``ck/cv [L, prompt_len, H, hd]`` are the
        prompt's KV rows and `first_token` the token its last-position
        logits produced. The decode loop splices the rows into a free
        slot between ticks (`_splice_slot`, O(prompt_len) — never a
        full-cache copy) and this engine NEVER runs a prefill program
        for the request, so a decode replica's `_prefill_paged` compile
        cache stays flat. Returns the request's TokenStream, whose
        first yielded token is `first_token`. `prompt_tokens`
        (optional) hands the speculative proposer the prompt's actual
        tokens — the transfer record carries them under disaggregation
        so decode-side drafting sees the same context the colocated
        engine would; without them drafting starts from the emitted
        history alone (correctness unaffected)."""
        plen = int(prompt_len)
        if plen < 1:
            raise ValueError("prompt_len must be >= 1")
        if plen + max_new_tokens > self.config.max_seq_len:
            # the first token is already produced, so the exact bound
            # would allow one more token than submit() — but the two
            # admission paths must reject IDENTICALLY or the disagg
            # tier and the colocated fallback diverge at the
            # sequence-length boundary
            raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
        ref = self._cache[0]["k"]
        # validate the FULL layout on the caller's thread: a mismatch
        # surfacing inside _splice_slot would kill the decode loop
        # thread and wedge every request on this engine. Dtype is part
        # of the layout — asarray below would otherwise silently cast
        # a float32 prefill tier into a bfloat16 decode pool and break
        # bit-identity with no error anywhere.
        want = (len(self._cache), plen) + tuple(ref.shape[2:])
        got_k = jnp.asarray(ck)
        got_v = jnp.asarray(cv)
        if (tuple(got_k.shape) != want or tuple(got_v.shape) != want
                or got_k.dtype != ref.dtype or got_v.dtype != ref.dtype):
            raise ValueError(
                f"adopted KV layout k={tuple(got_k.shape)}:{got_k.dtype} "
                f"v={tuple(got_v.shape)}:{got_v.dtype} does not match "
                f"this engine's cache layout {want}:{ref.dtype} — the "
                f"prefill and decode tiers must run the same model "
                f"config")
        ck, cv = got_k, got_v
        lora_slot = 0
        if adapter_id is not None:
            if self.lora_pool is None:
                raise ValueError(
                    f"adoption for adapter {adapter_id!r} but this "
                    f"engine has no lora_pool (the prefill and decode "
                    f"tiers must both be LoRA-enabled)")
            # caller's thread, like submit(): a cold page-in here never
            # stalls the decode loop
            lora_slot = self.lora_pool.acquire(adapter_id)
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        req = _Request(rid, np.zeros((1, plen), np.int32),
                       max_new_tokens, eos_token)
        if prompt_tokens is not None:
            req.ctx = [int(t) for t in prompt_tokens]
            req.ctx_has_prompt = True
        req.cache_outcome = cache_outcome
        req.reused_tokens = int(reused_tokens)
        req.adapter_id = adapter_id
        req.lora_slot = lora_slot
        self._pending_adopt.put(_Adoption(req, plen, ck, cv,
                                          first_token, score))
        return TokenStream(req, timeout_s)

    def update_params(self, params: Any,
                      version: Optional[int] = None) -> threading.Event:
        """Queue a live weight swap; the decode loop applies it between
        ticks (never mid-tick), so in-flight requests keep their KV
        caches and keep decoding — under the new weights from the next
        tick on — with no restart and no drop. Returns an Event set once
        the swap has been applied. Two swaps queued between the same two
        ticks coalesce: the newer wins, both events fire."""
        ev = threading.Event()
        with self._lock:
            prev = self._pending_swap
            self._pending_swap = (params, version,
                                  (prev[2] + [ev]) if prev else [ev])
        if self._stopped.is_set() and not self._thread.is_alive():
            # decode loop confirmed exited (not merely stop-requested —
            # the loop may still be inside its final tick): apply
            # synchronously so a caller's wait() never strands on a
            # stopped engine, without ever swapping mid-tick
            self._apply_pending_swap()
        return ev

    def _apply_pending_swap(self) -> None:
        """Decode-loop only, between ticks."""
        with self._lock:
            pending, self._pending_swap = self._pending_swap, None
        if pending is None:
            return
        params, version, events = pending
        self.params = params
        self.params_version = version
        self.swap_count += 1
        # every cached block's KV was computed under the old weights:
        # drop the prefix index so no post-swap admission matches it
        # (in-flight slots decode off their own slab copy, unaffected).
        # The speculative output memory is stale the same way — keeping
        # it would only burn verify width on rejected drafts.
        self._output_memory.clear()
        if self.kv_cache is not None:
            self.kv_cache.invalidate()
            self.publish_kv_telemetry(force=True)
        for ev in events:
            ev.set()

    def cancel_slot(self, stream_or_req: Any,
                    reason: Optional[str] = None) -> bool:
        """Cancel a live request (its TokenStream or the _Request
        itself): the decode loop frees its slot — and releases its KV
        pins and LoRA adapter pin — at the NEXT TICK BOUNDARY instead
        of decoding the abandoned request to completion (the PR-12
        deadline path used to waste every remaining tick on it). The
        freed slot is immediately re-admittable. Returns False when the
        request already finished (or was already cancelled); the
        stream's consumer sees a normal end-of-stream. `reason`
        attributes the cancel in ``cancelled_by_reason`` (the QoS
        preemption path tags ``preempt`` so its cancels never read as
        sheds)."""
        req = getattr(stream_or_req, "_req", stream_or_req)
        with self._lock:
            if req.finished or req.cancelled:
                return False
            req.cancelled = True
            req.cancel_reason = reason
            self._cancels += 1
        return True

    def _apply_cancels(self) -> None:
        """Decode-loop only, between ticks: free cancelled ACTIVE slots
        (queued cancelled requests are dropped at admission instead)."""
        with self._lock:
            if self._cancels == 0:
                return
        for req in list(self._slot_req):
            if req is not None and req.cancelled and not req.finished:
                self._count_cancel(req)
                self._finish(req)
        self.publish_kv_telemetry()

    def _count_cancel(self, req: "_Request") -> None:
        key = req.cancel_reason or "unspecified"
        self.cancelled += 1
        self.cancelled_by_reason[key] = \
            self.cancelled_by_reason.get(key, 0) + 1

    def stop(self) -> None:
        self._stopped.set()
        self._thread.join(timeout=10.0)
        self._apply_pending_swap()  # fire waiters a dead loop would strand
        self.publish_kv_telemetry(force=True)

    @property
    def active_slots(self) -> int:
        with self._lock:
            return self.max_batch - len(self._free)

    @property
    def free_slots(self) -> int:
        """Open decode slots right now (the disagg router's decode-pick
        signal; pending-but-unadmitted requests do not subtract)."""
        with self._lock:
            return len(self._free)

    # ------------------------------------------------------- telemetry
    def kv_stats(self) -> Dict[str, Any]:
        """Prefix-cache + admission counters — the snapshot pushed to
        the conductor for util.state.kv_cache_stats(), the CLI, and the
        dashboard (all surfaces report THIS dict's numbers)."""
        s: Dict[str, Any] = (self.kv_cache.stats() if self.kv_cache
                             else {"enabled": False})
        try:
            programs = _prefill_paged._cache_size()
        except Exception:  # noqa: BLE001 — older jax without _cache_size
            programs = -1
        s.update(
            engine_id=self.engine_id,
            max_batch=self.max_batch,
            max_prefills_per_tick=self.max_prefills_per_tick,
            max_adoptions_per_tick=self.max_adoptions_per_tick,
            admitted=self.admitted,
            prefill_admitted=self.prefill_admitted,
            adopted=self.adopted,
            max_prefills_admitted_per_tick=(
                self.max_prefills_admitted_per_tick),
            max_adoptions_admitted_per_tick=(
                self.max_adoptions_admitted_per_tick),
            prefill_calls=self.prefill_calls,
            prefill_programs=programs,
            spliced_tokens=self.spliced_tokens,
            cancelled=self.cancelled,
            cancelled_by_reason=dict(self.cancelled_by_reason),
            lora=self.lora_pool is not None,
        )
        s.update(self.speculation_stats())
        if self.kv_cache is None:
            # uncached engines still account their prefill work
            s.setdefault("prefilled_tokens", self.prefilled_tokens)
            s.setdefault("reused_tokens", 0)
        return s

    def speculation_stats(self) -> Dict[str, Any]:
        """The speculative-decoding snapshot every surface reports —
        embedded in kv_stats() so one conductor push feeds
        util.state.speculation_stats(), `ray_tpu speculate`,
        /api/speculation, and Prometheus with the same numbers."""
        proposed = self.spec_proposed
        ticks = self.spec_verify_ticks
        return {
            "speculate_k": self.speculate_k,
            "spec_proposed": proposed,
            "spec_accepted": self.spec_accepted,
            "spec_verify_ticks": ticks,
            "spec_emitted_tokens": self.spec_emitted,
            "acceptance_rate": (self.spec_accepted / proposed
                                if proposed else 0.0),
            "tokens_per_verify": (self.spec_emitted / ticks
                                  if ticks else 0.0),
            "kv_int8": self.kv_int8,
        }

    def publish_kv_telemetry(self, force: bool = False) -> None:
        """Best-effort push of kv_stats + pending timeline events to the
        conductor (no-op without a live cluster); throttled unless
        forced."""
        now = time.monotonic()
        if not force and now - self._last_stats_push < 0.5:
            return
        self._last_stats_push = now
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            if self.kv_cache is not None:
                self.kv_cache.drain_events()  # keep the buffer bounded
            self._drain_spec_events()
            return
        try:
            w.conductor.notify("report_kvcache_stats", w.worker_id,
                               self.engine_id, self.kv_stats())
            if self.kv_cache is not None:
                for ev in self.kv_cache.drain_events():
                    ev.setdefault("engine", self.engine_id)
                    w.conductor.notify("report_kvcache_event", ev)
            # spec_accept/spec_reject markers ride the kvcache timeline
            # lane — the engine buffers them itself because a decode
            # replica (prefix cache disabled) has no kv_cache to carry
            # events through
            for ev in self._drain_spec_events():
                w.conductor.notify("report_kvcache_event", ev)
        except Exception:  # noqa: BLE001 — cluster shutting down
            pass

    # ------------------------------------------------------- admission
    def _admit(self) -> None:
        # adoptions first (disaggregated decode: splices, no prefill
        # program), then prefill admissions — each against its own
        # per-phase cap so the counters stay truthful in both modes
        adopted = 0
        while self._free and adopted < self.max_adoptions_per_tick:
            try:
                adoption = self._pending_adopt.get_nowait()
            except queue.Empty:
                break
            if self._adopt_one(adoption):
                adopted += 1
        admitted = 0
        while self._free and admitted < self.max_prefills_per_tick:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                break
            if self._admit_one(req):
                admitted += 1
        if adopted:
            self.max_adoptions_admitted_per_tick = max(
                self.max_adoptions_admitted_per_tick, adopted)
        if admitted:
            self.max_prefills_admitted_per_tick = max(
                self.max_prefills_admitted_per_tick, admitted)
        if adopted or admitted:
            self.publish_kv_telemetry()

    def _adopt_one(self, adoption: _Adoption) -> bool:
        req = adoption.req
        if req.cancelled:
            # cancelled before admission: never occupies a slot
            self._count_cancel(req)
            self._finish(req)
            return False
        with self._lock:
            slot = self._free.pop()
        plen = adoption.plen
        self._cache = _splice_slot(self._cache, adoption.ck, adoption.cv,
                                   np.int32(slot), self.config, plen)
        self.spliced_tokens += plen
        self.admitted += 1
        self.adopted += 1
        req.slot = slot
        self._slot_req[slot] = req
        self._slot_adapter[slot] = req.lora_slot
        self._tokens[slot] = adoption.first_token
        self._pos[slot] = plen
        self._emit(req, adoption.first_token, adoption.score)
        return True

    def _admit_one(self, req: _Request) -> bool:
        if req.cancelled:
            # cancelled before admission: never occupies a slot
            self._count_cancel(req)
            self._finish(req)
            return False
        with self._lock:
            slot = self._free.pop()
        plen = req.prompt.shape[1]
        adapter = None
        namespace = None
        if self.lora_pool is not None and req.adapter_id is not None:
            # slice + version read atomically: the (tenant, version)-
            # stamped namespace must describe exactly the adapter this
            # prefill computes under, even if the row hot-swaps
            # mid-compute
            adapter, aver = self.lora_pool.adapter_slice(
                req.lora_slot, with_version=True)
            namespace = self.lora_pool.cache_namespace(req.adapter_id,
                                                       aver)
        ck, cv, table, first, score, outcome, reused, suffix_len = \
            _prefill_with_cache(self.params, self.config, self.kv_cache,
                                req.prompt, self._empty_prefix,
                                event_extra={"rid": req.rid},
                                adapter=adapter,
                                namespace=namespace)
        if self.kv_cache is not None:
            req.cache_outcome = outcome
            req.reused_tokens = reused
            req.block_table = table
        self.prefill_calls += 1
        self.prefilled_tokens += suffix_len
        self._cache = _splice_slot(self._cache, ck, cv, np.int32(slot),
                                   self.config, plen)
        self.spliced_tokens += plen
        self.admitted += 1
        self.prefill_admitted += 1
        req.slot = slot
        self._slot_req[slot] = req
        self._slot_adapter[slot] = req.lora_slot
        self._tokens[slot] = first
        self._pos[slot] = plen
        self._emit(req, first, score)
        return True

    def _finish(self, req: _Request) -> None:
        """Decode-loop only: end a request's stream and free its slot,
        KV pins, and LoRA adapter pin (normal completion, admission-
        time cancel drop, and the tick-boundary cancel all share this
        one path so nothing is ever released twice)."""
        if self.speculate_k and not req.cancelled \
                and req.ctx_has_prompt:
            plen = req.prompt.shape[1]
            if len(req.ctx) > plen:
                # remember (adapter, prompt) -> full greedy chain for
                # the cross-request proposer (decode-loop-only state)
                key = (req.adapter_id, tuple(req.ctx[:plen]))
                self._output_memory[key] = list(req.ctx)
                self._output_memory.move_to_end(key)
                while len(self._output_memory) > self._output_memory_cap:
                    self._output_memory.popitem(last=False)
        req.out.put(_DONE)
        slot = req.slot
        if slot is not None:
            self._slot_req[slot] = None
            self._slot_adapter[slot] = 0
        if self.kv_cache is not None and req.block_table:
            self.kv_cache.release(req.block_table)
            req.block_table = []
        if self.lora_pool is not None and req.adapter_id is not None:
            self.lora_pool.release(req.adapter_id)
        with self._lock:
            req.finished = True
            if slot is not None:
                self._free.append(slot)
            if req.cancelled:
                self._cancels -= 1

    def _emit(self, req: _Request, tok: int, score: float = 0.0) -> None:
        req.ctx.append(int(tok))
        req.scores.append(score)
        req.out.put(tok)
        req.produced += 1
        if (req.eos_token is not None and tok == req.eos_token) \
                or req.produced >= req.max_new:
            self._finish(req)

    # ------------------------------------------------------- speculation

    def _propose(self, req: _Request, k: int) -> List[int]:
        """Draft up to `k` tokens continuing `req.ctx` — the prompt-
        lookup proposer: exact chains from the paged prefix index
        first (nearly free; strongest when many requests share
        prompts), then the most recent earlier occurrence of the
        context's own trailing n-gram (decode loops repeat themselves).
        Drafts pin nothing and may be arbitrarily wrong — the verify
        pass is the only accept authority."""
        if self.draft_source is not None:
            return [int(t) for t in self.draft_source(req.ctx, k)][:k]
        ctx = req.ctx
        plen = req.prompt.shape[1]
        if req.ctx_has_prompt and len(ctx) >= plen:
            # cross-request memory first: a finished request with the
            # SAME (adapter, prompt) decoded this exact greedy chain —
            # acceptance is ~total unless the weights moved
            mem = self._output_memory.get(
                (req.adapter_id, tuple(ctx[:plen])))
            if mem is not None and len(mem) > len(ctx) \
                    and mem[:len(ctx)] == ctx:
                return mem[len(ctx):len(ctx) + k]
        if self.kv_cache is not None and req.adapter_id is None:
            # tenant requests draft from history only: their chains
            # live under a (tenant, version) namespace this loop does
            # not re-derive per tick
            draft = self.kv_cache.propose(ctx, k)
            if draft:
                return draft
        # self n-gram lookup over the incremental index: fold in the
        # n-grams ending at positions < L-1 (the tail's own occurrence
        # must stay OUT of the index so a hit is always an EARLIER one)
        ng = req.ngram_last
        ll = len(ctx)
        for end in range(req.ng_indexed, ll - 1):
            for n in (2, 3):
                if end + 1 >= n:
                    start = end + 1 - n
                    ng[tuple(ctx[start:end + 1])] = start
        req.ng_indexed = max(req.ng_indexed, ll - 1)
        for n in (3, 2):
            if ll <= n:
                continue
            start = ng.get(tuple(ctx[-n:]))
            if start is not None:
                return ctx[start + n:start + n + k]
        return []

    def _collect_drafts(self) -> Dict[int, List[int]]:
        drafts: Dict[int, List[int]] = {}
        for slot, req in enumerate(self._slot_req):
            if req is None or req.cancelled:
                continue
            # never draft past the request's budget: tokens beyond it
            # would be verified and thrown away
            budget = req.max_new - req.produced - 1
            if budget <= 0:
                continue
            d = self._propose(req, min(self.speculate_k, budget))
            if d:
                drafts[slot] = d
        return drafts

    def _spec_event(self, ev: Dict[str, Any]) -> None:
        ev.setdefault("ts", time.time())
        ev.setdefault("engine", self.engine_id)
        with self._lock:
            self._spec_events.append(ev)
            if len(self._spec_events) > _SPEC_EVENTS_KEPT:
                del self._spec_events[:len(self._spec_events)
                                      - _SPEC_EVENTS_KEPT]

    def _drain_spec_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._spec_events = self._spec_events, []
        return out

    def _spec_tick(self, drafts: Dict[int, List[int]],
                   lora_live: bool) -> None:
        """One widened verify tick: feed [last_token, draft...] per
        slot, emit the greedy chain's longest agreement. Slots without
        a draft pad by repeating their last token — their column-0
        output is bit-identical to the plain tick's, so mixed batches
        cost one program and zero correctness."""
        k = self.speculate_k
        toks = np.repeat(self._tokens[:, None], k + 1, axis=1)
        for slot, d in drafts.items():
            toks[slot, 1:1 + len(d)] = d
        tok_dev = jnp.asarray(toks)
        pos_dev = jnp.asarray(self._pos)
        if lora_live:
            cache, nxt, lp = self.lora_pool.dispatch_tick(
                lambda la: _tick_lora(
                    self.params, self.config, self._cache, tok_dev,
                    pos_dev, la),
                self._slot_adapter)
        else:
            cache, nxt, lp = _tick(
                self.params, self.config, self._cache, tok_dev, pos_dev)
        self._cache = cache
        nxt_np = np.asarray(nxt)
        lp_np = np.asarray(lp)
        self.spec_verify_ticks += 1
        m = spec_metrics()
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            proposal = drafts.get(slot, ())
            tok = int(nxt_np[slot, 0])
            self._pos[slot] += 1
            self._tokens[slot] = tok
            self._emit(req, tok, float(lp_np[slot, 0]))
            accepted = 0
            for i, d in enumerate(proposal):
                # accept d_i only while it equals the greedy chain's
                # last token AND the request is still live (eos /
                # budget finish must stop the stream exactly where the
                # sequential engine would)
                if req.finished or int(d) != tok:
                    break
                tok = int(nxt_np[slot, i + 1])
                self._pos[slot] += 1
                self._tokens[slot] = tok
                self._emit(req, tok, float(lp_np[slot, i + 1]))
                accepted += 1
            if proposal:
                # spec_emitted counts DRAFTING slots only, so
                # tokens-per-verify measures the speculation gain (an
                # undrafted slot's base token would make the metric
                # scale with batch width, not acceptance)
                self.spec_emitted += 1 + accepted
                self.spec_proposed += len(proposal)
                self.spec_accepted += accepted
                req.spec_proposed += len(proposal)
                req.spec_accepted += accepted
                m["proposed"].inc(len(proposal))
                if accepted:
                    m["accepted"].inc(accepted)
                self._spec_event({
                    "kind": "spec_accept" if accepted else "spec_reject",
                    "rid": req.rid, "slot": slot,
                    "proposed": len(proposal), "accepted": accepted})
        if self.spec_proposed:
            m["acceptance_rate"].set(
                self.spec_accepted / self.spec_proposed,
                tags={"engine": self.engine_id})

    # ------------------------------------------------------------ loop

    def _loop(self) -> None:
        while not self._stopped.is_set():
            self._apply_pending_swap()
            self._apply_cancels()
            self._admit()
            if all(r is None for r in self._slot_req):
                self._stopped.wait(self.idle_sleep_s)
                continue
            lora_live = (self.lora_pool is not None
                         and bool(self._slot_adapter.any()))
            drafts = (self._collect_drafts() if self.speculate_k
                      else {})
            if drafts:
                self._spec_tick(drafts, lora_live)
                continue
            if lora_live:
                cache, nxt, lp = self.lora_pool.dispatch_tick(
                    lambda la: _tick_lora(
                        self.params, self.config, self._cache,
                        jnp.asarray(self._tokens),
                        jnp.asarray(self._pos), la),
                    self._slot_adapter)
            else:
                cache, nxt, lp = _tick(
                    self.params, self.config, self._cache,
                    jnp.asarray(self._tokens), jnp.asarray(self._pos))
            self._cache = cache
            nxt_np = np.asarray(nxt)
            lp_np = np.asarray(lp)
            for slot, req in enumerate(self._slot_req):
                if req is None:
                    continue
                self._pos[slot] += 1
                tok = int(nxt_np[slot])
                self._tokens[slot] = tok
                self._emit(req, tok, float(lp_np[slot]))
