"""Continuous-batching generation engine — the LLM serving throughput
story (BASELINE "Llama JAX replica, batched inference"; the reference
serves torch models and leaves batching to the replica, Serve's @batch
being request-level — this is TOKEN-level continuous batching in the
vLLM sense, rebuilt TPU-first).

Design: one fixed-shape decode loop over `max_batch` slots. Every tick
runs ONE jitted ragged-batch step (the model family's per-slot decode —
llama_decode / gpt2_decode — with per-slot positions and masking,
static shapes throughout, so XLA compiles exactly one program no
matter how requests interleave). New requests prefill into a
free slot (one jitted prefill per distinct prompt length — exact
lengths, so cache rows beyond a slot's own depth are never attended)
and JOIN the running batch between ticks; finished sequences (EOS or
their token budget) free their slot between ticks. Slots the engine
isn't using decode garbage that nothing reads — the cost of static
shapes, paid once, instead of a recompile per batch composition.

Per-request token queues make it the natural producer for Serve's
streaming path; `ContinuousBatchingEngine` is thread-safe for
concurrent submit/iterate from replica request threads.
"""
from __future__ import annotations

import functools
import queue
import threading
from typing import Any, Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .generate import _model_fns
_DONE = object()


@functools.partial(jax.jit, static_argnums=(2,))
def _prefill_one(params, prompt, config, cache1):
    """Prefill a single sequence into its own B=1 cache; returns the
    last-position logits and the filled cache. One compile per distinct
    prompt length (exact lengths: a padded prefill would leave pad
    entries inside the attended window)."""
    fwd = _model_fns(config)[0]
    logits, cache1 = fwd(params, prompt, config, cache1, 0)
    return logits[:, -1], cache1


@functools.partial(jax.jit, static_argnums=(3,), donate_argnums=(0,))
def _adopt_slot(cache, cache1, slot, config):
    """Copy a prefilled single-sequence cache into batch slot `slot`."""
    del config
    out = []
    for blk, one in zip(cache, cache1):
        out.append({
            "k": blk["k"].at[slot].set(one["k"][0]),
            "v": blk["v"].at[slot].set(one["v"][0]),
        })
    return out


@functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(2,))
def _tick(params, config, cache, tokens, pos_vec):
    logits, cache = _model_fns(config)[2](params, tokens, config, cache,
                                          pos_vec)
    nxt = jnp.argmax(logits[:, :config.vocab_size], axis=-1).astype(
        jnp.int32)
    return cache, nxt


class _Request:
    def __init__(self, rid: int, prompt: np.ndarray, max_new: int,
                 eos_token: Optional[int]):
        self.rid = rid
        self.prompt = prompt
        self.max_new = max_new
        self.eos_token = eos_token
        self.out: "queue.Queue" = queue.Queue()
        self.produced = 0
        self.slot: Optional[int] = None


class ContinuousBatchingEngine:
    """Greedy continuous-batching decode over `max_batch` slots."""

    def __init__(self, params: Any, config: Any, *,
                 max_batch: int = 8, idle_sleep_s: float = 0.002,
                 params_version: Optional[int] = None):
        # config: any family _model_fns knows (LlamaConfig, GPT2Config)
        self.params = params
        self.config = config
        self.max_batch = max_batch
        self.idle_sleep_s = idle_sleep_s
        # live-weight hot swap (ray_tpu.weights): a queued (params,
        # version) is applied by the decode loop BETWEEN ticks — the
        # params pytree is a plain jit argument, so swapping it never
        # invalidates compiled programs or in-flight slots' KV caches
        self.params_version = params_version
        self._pending_swap: Optional[tuple] = None
        self.swap_count = 0
        self._cache = _model_fns(config)[1](config, max_batch)
        self._tokens = np.zeros(max_batch, np.int32)
        self._pos = np.zeros(max_batch, np.int32)
        self._slot_req: List[Optional[_Request]] = [None] * max_batch
        self._free = list(range(max_batch))
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._lock = threading.Lock()
        self._next_rid = 0
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cb-engine")
        self._thread.start()

    # ------------------------------------------------------------- API
    def submit(self, prompt_tokens, max_new_tokens: int,
               eos_token: Optional[int] = None) -> "_Request":
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        if prompt.shape[1] + max_new_tokens > self.config.max_seq_len:
            raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        req = _Request(rid, prompt, max_new_tokens, eos_token)
        self._pending.put(req)
        return req

    def stream(self, prompt_tokens, max_new_tokens: int,
               eos_token: Optional[int] = None,
               timeout_s: float = 120.0) -> Iterator[int]:
        """Submit and yield tokens as the shared loop produces them."""
        req = self.submit(prompt_tokens, max_new_tokens, eos_token)
        while True:
            tok = req.out.get(timeout=timeout_s)
            if tok is _DONE:
                return
            yield int(tok)

    def generate(self, prompt_tokens, max_new_tokens: int,
                 eos_token: Optional[int] = None,
                 timeout_s: float = 120.0) -> List[int]:
        return list(self.stream(prompt_tokens, max_new_tokens, eos_token,
                                timeout_s))

    def update_params(self, params: Any,
                      version: Optional[int] = None) -> threading.Event:
        """Queue a live weight swap; the decode loop applies it between
        ticks (never mid-tick), so in-flight requests keep their KV
        caches and keep decoding — under the new weights from the next
        tick on — with no restart and no drop. Returns an Event set once
        the swap has been applied. Two swaps queued between the same two
        ticks coalesce: the newer wins, both events fire."""
        ev = threading.Event()
        with self._lock:
            prev = self._pending_swap
            self._pending_swap = (params, version,
                                  (prev[2] + [ev]) if prev else [ev])
        if self._stopped.is_set() and not self._thread.is_alive():
            # decode loop confirmed exited (not merely stop-requested —
            # the loop may still be inside its final tick): apply
            # synchronously so a caller's wait() never strands on a
            # stopped engine, without ever swapping mid-tick
            self._apply_pending_swap()
        return ev

    def _apply_pending_swap(self) -> None:
        """Decode-loop only, between ticks."""
        with self._lock:
            pending, self._pending_swap = self._pending_swap, None
        if pending is None:
            return
        params, version, events = pending
        self.params = params
        self.params_version = version
        self.swap_count += 1
        for ev in events:
            ev.set()

    def stop(self) -> None:
        self._stopped.set()
        self._thread.join(timeout=10.0)
        self._apply_pending_swap()  # fire waiters a dead loop would strand

    @property
    def active_slots(self) -> int:
        with self._lock:
            return self.max_batch - len(self._free)

    # ------------------------------------------------------------ loop
    def _admit(self) -> None:
        while self._free:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                return
            with self._lock:
                slot = self._free.pop()
            cache1 = _model_fns(self.config)[1](self.config, 1)
            last_logits, cache1 = _prefill_one(self.params, req.prompt,
                                               self.config, cache1)
            self._cache = _adopt_slot(self._cache, cache1, slot,
                                      self.config)
            first = int(np.argmax(
                np.asarray(last_logits[0, :self.config.vocab_size])))
            req.slot = slot
            self._slot_req[slot] = req
            self._tokens[slot] = first
            self._pos[slot] = req.prompt.shape[1]
            self._emit(req, first)

    def _emit(self, req: _Request, tok: int) -> None:
        req.out.put(tok)
        req.produced += 1
        if (req.eos_token is not None and tok == req.eos_token) \
                or req.produced >= req.max_new:
            req.out.put(_DONE)
            slot = req.slot
            self._slot_req[slot] = None
            with self._lock:
                self._free.append(slot)

    def _loop(self) -> None:
        while not self._stopped.is_set():
            self._apply_pending_swap()
            self._admit()
            if all(r is None for r in self._slot_req):
                self._stopped.wait(self.idle_sleep_s)
                continue
            cache, nxt = _tick(self.params, self.config, self._cache,
                               jnp.asarray(self._tokens),
                               jnp.asarray(self._pos))
            self._cache = cache
            nxt_np = np.asarray(nxt)
            for slot, req in enumerate(self._slot_req):
                if req is None:
                    continue
                self._pos[slot] += 1
                tok = int(nxt_np[slot])
                self._tokens[slot] = tok
                self._emit(req, tok)
