"""Mixture-of-experts decoder (Mixtral-style): Llama blocks with the dense
SwiGLU MLP replaced by top-k routed experts (ops.moe.moe_ffn — GShard
dispatch/combine einsums, expert-parallel all_to_all under shard_map) plus
the Switch load-balancing auxiliary loss.

Partition layout: experts shard on the `ep` mesh axis (first dim of
w_in/w_out), with fsdp/tp inside each expert — the EP design the reference
cannot express natively (SURVEY.md §2.3 row 'Parallelism strategies')."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.layers import rms_norm
from ..ops.moe import load_balancing_loss, moe_ffn
from ..ops.rope import rope_table
from .llama import LlamaConfig, _mm

Params = Dict[str, Any]


@dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    max_seq_len: int = 2048
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: int = 4
    d_model: int = 768
    d_ff: int = 2048
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coeff: float = 0.01
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    vocab_pad_multiple: int = 128

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def padded_vocab(self) -> int:
        m = self.vocab_pad_multiple
        return (self.vocab_size + m - 1) // m * m

    def as_llama(self) -> LlamaConfig:
        """Attention-side view of this config (reuses llama_block)."""
        return LlamaConfig(
            vocab_size=self.vocab_size, max_seq_len=self.max_seq_len,
            num_layers=self.num_layers, num_heads=self.num_heads,
            num_kv_heads=self.num_kv_heads, d_model=self.d_model,
            d_ff=self.d_ff, rope_theta=self.rope_theta, dtype=self.dtype,
            vocab_pad_multiple=self.vocab_pad_multiple)

    @staticmethod
    def tiny() -> "MoEConfig":
        return MoEConfig(vocab_size=512, max_seq_len=128, num_layers=2,
                         num_heads=4, num_kv_heads=2, d_model=128,
                         d_ff=256, num_experts=4, top_k=2)

    @staticmethod
    def mixtral_8x7b() -> "MoEConfig":
        return MoEConfig(vocab_size=32000, max_seq_len=4096, num_layers=32,
                         num_heads=32, num_kv_heads=8, d_model=4096,
                         d_ff=14336, num_experts=8, top_k=2,
                         rope_theta=1e6)


def moe_init(config: MoEConfig, key: jax.Array) -> Params:
    c = config
    if c.num_heads % c.num_kv_heads:
        raise ValueError("num_heads must be a multiple of num_kv_heads")
    k_iter = iter(jax.random.split(key, 2 + 8 * c.num_layers))

    def norm(k, *shape, scale=0.02):
        return (jax.random.normal(k, shape, dtype=jnp.float32)
                * scale).astype(c.dtype)

    kv_dim = c.num_kv_heads * c.head_dim
    params: Params = {
        "tok_emb": norm(next(k_iter), c.padded_vocab, c.d_model),
        "norm_f": {"scale": jnp.ones(c.d_model, c.dtype)},
        "lm_head": norm(next(k_iter), c.d_model, c.padded_vocab),
        "blocks": [],
    }
    for _ in range(c.num_layers):
        params["blocks"].append({
            "attn_norm": {"scale": jnp.ones(c.d_model, c.dtype)},
            "attn": {
                "wq": norm(next(k_iter), c.d_model, c.d_model),
                "wk": norm(next(k_iter), c.d_model, kv_dim),
                "wv": norm(next(k_iter), c.d_model, kv_dim),
                "wo": norm(next(k_iter), c.d_model, c.d_model),
            },
            "ffn_norm": {"scale": jnp.ones(c.d_model, c.dtype)},
            "moe": {
                "gate_w": norm(next(k_iter), c.d_model, c.num_experts),
                "w_in": norm(next(k_iter), c.num_experts, c.d_model,
                             c.d_ff),
                "w_out": norm(next(k_iter), c.num_experts, c.d_ff,
                              c.d_model),
            },
        })
    return params


def _moe_block(x: jax.Array, p: Params, cos, sin,
               config: MoEConfig) -> Tuple[jax.Array, jax.Array]:
    """Returns (x, router_logits [T_total, E]) for the aux loss."""
    from ..ops.attention import flash_attention
    from ..ops.rope import apply_rope

    c = config
    b, t, _ = x.shape
    h = rms_norm(x, p["attn_norm"]["scale"])
    q = _mm(h, p["attn"]["wq"]).reshape(b, t, c.num_heads, c.head_dim)
    k = _mm(h, p["attn"]["wk"]).reshape(b, t, c.num_kv_heads, c.head_dim)
    v = _mm(h, p["attn"]["wv"]).reshape(b, t, c.num_kv_heads, c.head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if c.num_kv_heads != c.num_heads:
        rep = c.num_heads // c.num_kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    a = flash_attention(q, k, v, True).reshape(b, t, c.d_model)
    x = x + _mm(a, p["attn"]["wo"])

    h = rms_norm(x, p["ffn_norm"]["scale"])
    y, logits = moe_ffn(
        h, p["moe"]["gate_w"], p["moe"]["w_in"], p["moe"]["w_out"],
        top_k=c.top_k, capacity_factor=c.capacity_factor,
        activation=jax.nn.silu, return_router_logits=True)
    return x + y, logits


def moe_forward(params: Params, tokens: jax.Array, config: MoEConfig,
                return_router_logits: bool = False):
    c = config
    cos, sin = rope_table(c.head_dim, c.max_seq_len, c.rope_theta)
    x = params["tok_emb"][tokens]
    all_logits = []
    for p in params["blocks"]:
        x, logits = _moe_block(x, p, cos, sin, c)
        all_logits.append(logits)
    x = rms_norm(x, params["norm_f"]["scale"])
    out = jnp.dot(x, params["lm_head"], preferred_element_type=jnp.float32)
    if return_router_logits:
        return out, all_logits
    return out


def moe_loss(params: Params, tokens: jax.Array, targets: jax.Array,
             config: MoEConfig, remat: bool = False) -> jax.Array:
    """Cross-entropy + Switch load-balancing aux loss."""
    def body(params, tokens):
        return moe_forward(params, tokens, config,
                           return_router_logits=True)

    fwd = jax.checkpoint(body) if remat else body
    logits, router_logits = fwd(params, tokens)
    if config.padded_vocab != config.vocab_size:
        neg = jnp.full((config.padded_vocab - config.vocab_size,), -1e30,
                       dtype=logits.dtype)
        logits = logits.at[..., config.vocab_size:].set(neg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    ce = -jnp.mean(ll)
    aux = sum(load_balancing_loss(lg, config.top_k)
              for lg in router_logits) / len(router_logits)
    return ce + config.aux_loss_coeff * aux


def moe_partition_specs(config: MoEConfig) -> Params:
    """Experts on `ep`, megatron tp/fsdp inside each expert."""
    block = {
        "attn_norm": {"scale": P()},
        "attn": {"wq": P("fsdp", "tp"), "wk": P("fsdp", "tp"),
                 "wv": P("fsdp", "tp"), "wo": P("tp", "fsdp")},
        "ffn_norm": {"scale": P()},
        "moe": {"gate_w": P(),
                "w_in": P("ep", "fsdp", "tp"),
                "w_out": P("ep", "tp", "fsdp")},
    }
    return {
        "tok_emb": P("tp", "fsdp"),
        "norm_f": {"scale": P()},
        "lm_head": P("fsdp", "tp"),
        "blocks": [block for _ in range(config.num_layers)],
    }
