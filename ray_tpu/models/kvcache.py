"""Paged KV cache with hash-chained prefix reuse — the serving-envelope
lever the ROADMAP names first: a fleet of requests sharing a long system
prompt must not re-prefill it per request (vLLM's PagedAttention prefix
cache, rebuilt for this engine's fixed-slab TPU decode design).

Layout: one device pool per engine, K and V each
``[layers, num_blocks, block_size, kv_heads, head_dim]`` in the model's
cache dtype. Blocks are the unit of sharing:

- **hash-chained index** — block ``i`` of a prompt is keyed by
  ``H(chain_digest(blocks < i), tokens_i)``, so a lookup walks the
  longest cached block-aligned prefix without comparing whole prompts
  (token tuples are still verified on match — a digest collision must
  never serve wrong KV). A partial tail block (prompt ends mid-block)
  is indexed separately under its parent digest + exact token tuple.
- **refcounts** — every admitted request pins the blocks backing its
  matched prefix for its lifetime; pinned blocks are never evicted or
  mutated. Refcount-0 blocks STAY cached (that is the cache) and are
  only reclaimed by LRU eviction under pool pressure, leaves first so a
  chain interior never orphans reachable descendants.
- **copy-on-write** — extending a cached partial block (request B's
  prompt continues where request A's ended mid-block) copies the shared
  block into a fresh one and writes the new tokens into the copy; the
  original stays indexed for future short matches.
- **graceful exhaustion** — when the pool has no free or evictable
  block, commit simply stops caching that prompt's remaining blocks;
  prefill correctness never depends on pool capacity.

Correctness invariant (asserted in tier-1 on CPU): engine outputs with
the cache enabled are bit-identical to the uncached engine. It holds
because cached prefix KV is byte-for-byte what a full prefill would
recompute (same absolute RoPE positions, same window length, and masked
softmax contributes exact zeros for unwritten rows), and because a
weight swap invalidates the whole index — stale-generation KV is never
matched again (in-flight slots keep decoding off their own slab copy).

**int8 blocks** (``RAY_TPU_KV_INT8=1`` or the ``int8=`` ctor arg): the
pool stores K/V as int8 with per-block-CHANNEL fp32 scales (amax over
the block's token rows, one scale per (layer, head, head_dim) channel
— the channel-wise shape that keeps RoPE'd K's per-dim dynamic range).
Quantize-on-commit and dequantize-on-gather are donated jits, O(block)
in place like every other pool mutation, so the HALVED bytes per block
buy a doubled default pool (``resolve_pool_config`` sizes 2x blocks
when int8 is on and the pool wasn't pinned explicitly) — bigger decode
batches and higher prefix-cache residency for the same HBM. Everything
OUTSIDE the pool stays bit-exact: gather hands back fp KV in the cache
dtype and the suffix prefill / splice / decode path is unchanged; the
quantization error itself is bounded by the rtol equivalence test in
tests/test_speculate.py.

**Tiered KV plane** (``serve/kvplane.py``): the pool is tier 1 of a
three-tier hierarchy. ``attach_arena()`` hooks a host-RAM arena into
the eviction path — a block evicted under pool pressure spills its
int8+per-block-channel-scales wire form (``_write_block_q``'s layout)
to the arena instead of dying, and a later ``lookup()`` whose chain
walk breaks consults the arena and re-adopts the block through the
normal insert path (int8 pools round-trip bit-exactly; fp pools
re-enter within the int8 tolerance contract).
``export_prefix()``/``import_prefix()`` move whole block-aligned
prefixes in the same wire format for tier 3 (chunk-fabric objects any
replica can adopt); ``prefix_digests()`` exposes the chain digests the
cluster-wide prefix directory is keyed by.

**Drafting from cache** (``propose()``): the index's hash chains store
EXACT token tuples, so the longest chain extending a request's current
context IS a free speculative draft — no draft model, no extra
compile. The engine's prompt-lookup proposer (models/engine.py) reads
it; proposals are never pinned (a wrong draft is rejected by the
verify pass, so correctness never depends on what propose returns).

Surfaces (the full treatment every subsystem gets):
``util.state.kv_cache_stats()``, ``ray_tpu kvcache``, dashboard
``/api/kvcache``, lazy-init Prometheus counters/gauges (no pusher on
import; the pool-utilization gauge reads the int8-doubled block count
when int8 is on), and prefix-hit / evict instant markers in the merged
timeline. Knobs: ``RAY_TPU_KV_CACHE`` (enable, default 1),
``RAY_TPU_KV_BLOCK_SIZE`` (default 16), ``RAY_TPU_KV_POOL_BLOCKS``
(default: one decode slab's worth, ``max_batch * ceil(S/block)``;
doubled under int8), ``RAY_TPU_KV_INT8`` (default 0).
"""
from __future__ import annotations

import functools
import hashlib
import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_ROOT_DIGEST = b"ray_tpu-kv-root"
_EVENTS_KEPT = 512


def kv_int8_default() -> bool:
    """The ``RAY_TPU_KV_INT8`` env default every pool owner (the
    colocated engine, the disagg prefill tier) resolves through."""
    from ray_tpu.util import envknobs

    return envknobs.get_str("RAY_TPU_KV_INT8", "0") == "1"


def resolve_pool_config(config: Any,
                        block_size: Optional[int] = None,
                        pool_blocks: Optional[int] = None, *,
                        slots: int = 4,
                        int8: bool = False) -> Tuple[int, int]:
    """Resolve ``(block_size, pool_blocks)`` from explicit args, the
    ``RAY_TPU_KV_BLOCK_SIZE`` / ``RAY_TPU_KV_POOL_BLOCKS`` env knobs, or
    the ``slots * ceil(max_seq_len / block_size)`` sizing default — the
    ONE implementation every pool owner (the colocated engine, the
    disaggregated prefill tier) defaults through. Under ``int8`` a
    DEFAULTED pool doubles its block count — int8 blocks cost half the
    bytes, so the same HBM budget holds twice the prefixes (an explicit
    block count, arg or env, is always honored as-is)."""
    from ray_tpu.util import envknobs

    bs = int(block_size
             or envknobs.get_int("RAY_TPU_KV_BLOCK_SIZE", 16))
    pb = int(pool_blocks
             or envknobs.get_int("RAY_TPU_KV_POOL_BLOCKS", 0))
    if not pb:
        pb = slots * (-(-config.max_seq_len // bs))
        if int8:
            pb *= 2
    return bs, pb


def _chain(digest: bytes, tokens: Tuple[int, ...]) -> bytes:
    h = hashlib.blake2b(digest, digest_size=16)
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.digest()


def _ns_root(namespace: Optional[str]) -> bytes:
    """The hash-chain root for a cache namespace. A multi-tenant LoRA
    deployment keys prefixes by (tenant, prompt) — a tenant's KV is
    computed under ITS adapter, so another tenant matching it would be
    served wrong values silently. Deriving a per-namespace root makes
    every digest downstream tenant-scoped; namespace=None keeps the
    historical root, so single-tenant deployments are bit-identical."""
    if namespace is None:
        return _ROOT_DIGEST
    h = hashlib.blake2b(_ROOT_DIGEST, digest_size=16)
    h.update(str(namespace).encode())
    return h.digest()


def prefix_digests(tokens, block_size: int,
                   namespace: Optional[str] = None,
                   max_blocks: int = 32) -> List[str]:
    """Chain digests at every full-block boundary of `tokens`, LONGEST
    FIRST — the keys the cluster-wide prefix directory (conductor
    ``kvplane_lookup``) matches against. Hex, because the digests cross
    the RPC plane as JSON-safe metadata. Namespace-scoped exactly like
    the index itself, so one tenant's directory entries can never match
    another's prompt."""
    tokens = np.asarray(tokens).reshape(-1)
    digest = _ns_root(namespace)
    out: List[str] = []
    n_full = min(len(tokens) // block_size, max_blocks)
    for i in range(n_full):
        blk = tuple(int(t) for t in
                    tokens[i * block_size:(i + 1) * block_size])
        digest = _chain(digest, blk)
        out.append(digest.hex())
    out.reverse()
    return out


# --------------------------------------------------------- device ops
# All pool mutation is jitted with the pool donated, so XLA updates the
# arrays in place: a block write touches O(block) bytes, never O(pool).

@functools.partial(jax.jit, donate_argnums=(0, 1))
def _write_block(pool_k, pool_v, bid, blk_k, blk_v):
    """pool [L,N,bs,H,hd] <- blk [L,bs,H,hd] at block row `bid`."""
    return (jax.lax.dynamic_update_slice(
                pool_k, blk_k[:, None], (0, bid, 0, 0, 0)),
            jax.lax.dynamic_update_slice(
                pool_v, blk_v[:, None], (0, bid, 0, 0, 0)))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _cow_extend_block(pool_k, pool_v, dst, src, blk_k, blk_v, filled_old):
    """Copy-on-write: rows ``< filled_old`` come from the SHARED block
    `src` (the copy), rows ``>= filled_old`` from the freshly prefilled
    `blk` (the write); the merge lands in `dst`."""
    sizes = (pool_k.shape[0], 1) + pool_k.shape[2:]
    old_k = jax.lax.dynamic_slice(pool_k, (0, src, 0, 0, 0), sizes)[:, 0]
    old_v = jax.lax.dynamic_slice(pool_v, (0, src, 0, 0, 0), sizes)[:, 0]
    row = jnp.arange(pool_k.shape[2])[None, :, None, None]
    merged_k = jnp.where(row < filled_old, old_k, blk_k)
    merged_v = jnp.where(row < filled_old, old_v, blk_v)
    return (jax.lax.dynamic_update_slice(
                pool_k, merged_k[:, None], (0, dst, 0, 0, 0)),
            jax.lax.dynamic_update_slice(
                pool_v, merged_v[:, None], (0, dst, 0, 0, 0)))


@functools.partial(jax.jit, static_argnums=(3,))
def _gather_prefix(pool_k, pool_v, bids, ntok):
    """Assemble a matched prefix: block rows `bids` concatenated along
    the token axis, truncated to the matched token count (the tail
    block may be partial)."""
    k = jnp.take(pool_k, bids, axis=1)      # [L, n, bs, H, hd]
    v = jnp.take(pool_v, bids, axis=1)
    ll, n, bs = k.shape[0], k.shape[1], k.shape[2]
    k = k.reshape((ll, n * bs) + k.shape[3:])[:, :ntok]
    v = v.reshape((ll, n * bs) + v.shape[3:])[:, :ntok]
    return k, v


# int8 pool twins: per-block-CHANNEL symmetric quantization — one fp32
# scale per (layer, head, head_dim) channel, amax'd over the block's
# token rows. Same donation discipline as the fp ops: a commit touches
# O(block) bytes of the int8 pool + scale pool, never O(pool).

def _quantize(blk):
    """[L, bs, H, hd] float -> (int8 same shape, f32 scale
    [L, 1, H, hd]). amax==0 channels take scale 1 so 0/0 never NaNs
    (their rows quantize to exact 0 either way)."""
    f = blk.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(f / scale), -127, 127).astype(jnp.int8)
    return q, scale


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _write_block_q(pool_k, pool_v, sk, sv, bid, blk_k, blk_v):
    """Quantize-on-commit: pool [L,N,bs,H,hd] int8 + scales
    [L,N,1,H,hd] f32 <- blk [L,bs,H,hd] at block row `bid`."""
    qk, sck = _quantize(blk_k)
    qv, scv = _quantize(blk_v)
    at = (0, bid, 0, 0, 0)
    return (jax.lax.dynamic_update_slice(pool_k, qk[:, None], at),
            jax.lax.dynamic_update_slice(pool_v, qv[:, None], at),
            jax.lax.dynamic_update_slice(sk, sck[:, None], at),
            jax.lax.dynamic_update_slice(sv, scv[:, None], at))


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _cow_extend_block_q(pool_k, pool_v, sk, sv, dst, src, blk_k, blk_v,
                        filled_old):
    """int8 copy-on-write: dequantize the SHARED block's rows
    ``< filled_old``, merge with the freshly prefilled rows, requantize
    the merged block (its own channel scales) into `dst`."""
    sizes = (pool_k.shape[0], 1) + pool_k.shape[2:]
    ssizes = (sk.shape[0], 1) + sk.shape[2:]
    row = jnp.arange(pool_k.shape[2])[None, :, None, None]

    def _old(pool, scales):
        q = jax.lax.dynamic_slice(pool, (0, src, 0, 0, 0), sizes)[:, 0]
        s = jax.lax.dynamic_slice(scales, (0, src, 0, 0, 0),
                                  ssizes)[:, 0]
        return q.astype(jnp.float32) * s

    merged_k = jnp.where(row < filled_old, _old(pool_k, sk),
                         blk_k.astype(jnp.float32))
    merged_v = jnp.where(row < filled_old, _old(pool_v, sv),
                         blk_v.astype(jnp.float32))
    qk, sck = _quantize(merged_k)
    qv, scv = _quantize(merged_v)
    at = (0, dst, 0, 0, 0)
    return (jax.lax.dynamic_update_slice(pool_k, qk[:, None], at),
            jax.lax.dynamic_update_slice(pool_v, qv[:, None], at),
            jax.lax.dynamic_update_slice(sk, sck[:, None], at),
            jax.lax.dynamic_update_slice(sv, scv[:, None], at))


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _write_block_qraw(pool_k, pool_v, sk, sv, bid, qk, qv, sck, scv):
    """Adopt an already-quantized wire-format block (tier-2/3 re-entry)
    into the int8 pool VERBATIM — no requantize, so a spill/readopt
    round trip is bit-exact for int8 pools."""
    at = (0, bid, 0, 0, 0)
    return (jax.lax.dynamic_update_slice(pool_k, qk[:, None], at),
            jax.lax.dynamic_update_slice(pool_v, qv[:, None], at),
            jax.lax.dynamic_update_slice(sk, sck[:, None], at),
            jax.lax.dynamic_update_slice(sv, scv[:, None], at))


@functools.partial(jax.jit, static_argnums=(5, 6))
def _gather_prefix_q(pool_k, pool_v, sk, sv, bids, ntok, dtype):
    """Dequant-on-gather: assemble a matched prefix out of the int8
    pool back into the cache dtype — downstream (suffix prefill,
    splice, decode) sees ordinary fp KV, so everything outside the
    quantized pool stays bit-exact plumbing."""
    def _deq(pool, scales):
        q = jnp.take(pool, bids, axis=1)       # [L, n, bs, H, hd]
        s = jnp.take(scales, bids, axis=1)     # [L, n, 1, H, hd]
        x = (q.astype(jnp.float32) * s).astype(dtype)
        ll, n, bs = x.shape[0], x.shape[1], x.shape[2]
        return x.reshape((ll, n * bs) + x.shape[3:])[:, :ntok]

    return _deq(pool_k, sk), _deq(pool_v, sv)


@functools.partial(jax.jit, static_argnums=(3,))
def _extract_block(ck, cv, start, block_size):
    """One block's rows ``[start, start+block_size)`` out of a filled
    single-sequence cache ``[L, S, H, hd]`` (start traced: one compiled
    program serves every block offset)."""
    sizes = (ck.shape[0], block_size) + ck.shape[2:]
    return (jax.lax.dynamic_slice(ck, (0, start, 0, 0), sizes),
            jax.lax.dynamic_slice(cv, (0, start, 0, 0), sizes))


# ----------------------------------------------------- prometheus (lazy)
# Created on first pool construction, never at import: importing
# ray_tpu.models must not spawn a metrics pusher (weights/metrics.py
# pattern — rebound ONCE to a complete dict).

_metrics: Optional[Dict[str, Any]] = None
_metrics_lock = threading.Lock()


def kvcache_metrics() -> Dict[str, Any]:
    global _metrics
    m = _metrics
    if m is not None:
        return m
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _metrics = dict(
                lookups=Counter(
                    "ray_tpu_kvcache_lookups_total",
                    "prefix-cache lookups at admission",
                    tag_keys=("outcome",)),
                reused_tokens=Counter(
                    "ray_tpu_kvcache_reused_tokens_total",
                    "prompt tokens served from cached KV blocks "
                    "(prefill skipped)"),
                prefilled_tokens=Counter(
                    "ray_tpu_kvcache_prefilled_tokens_total",
                    "prompt tokens actually prefilled (suffix after the "
                    "cached prefix)"),
                evictions=Counter(
                    "ray_tpu_kvcache_evictions_total",
                    "refcount-0 blocks LRU-evicted under pool pressure"),
                cow_copies=Counter(
                    "ray_tpu_kvcache_cow_copies_total",
                    "copy-on-write block copies (shared partial block "
                    "extended)"),
                utilization=Gauge(
                    "ray_tpu_kvcache_pool_utilization",
                    "fraction of pool blocks holding cached or pinned "
                    "KV"))
    return _metrics


class PrefixMatch:
    """Result of a lookup: the pinned block table backing the longest
    cached prefix, and how many prompt tokens it covers."""

    __slots__ = ("bids", "tokens", "full_blocks", "partial_bid",
                 "partial_len", "outcome")

    def __init__(self, bids: List[int], tokens: int, full_blocks: int,
                 partial_bid: Optional[int], partial_len: int,
                 outcome: str):
        self.bids = bids
        self.tokens = tokens
        self.full_blocks = full_blocks
        self.partial_bid = partial_bid
        self.partial_len = partial_len
        self.outcome = outcome


class _Block:
    __slots__ = ("bid", "tokens", "filled", "ref", "last_used",
                 "children", "index_key", "parent_bid", "parent_digest",
                 "ns")

    def __init__(self, bid: int):
        self.bid = bid
        self.tokens: Tuple[int, ...] = ()
        self.filled = 0
        self.ref = 0
        self.last_used = 0
        self.children = 0
        # ("full", digest) | ("partial", parent_digest, tokens) | None
        # (None = orphaned by invalidate(): unreachable, freed on the
        # last release)
        self.index_key: Optional[tuple] = None
        self.parent_bid: Optional[int] = None
        # the chain digest this block EXTENDS — the forward-walk key
        # the draft proposer follows (propose()); partial blocks reuse
        # their index key's parent digest
        self.parent_digest: Optional[bytes] = None
        # cache namespace (LoRA tenant) the block was committed under —
        # invalidate(namespace=) scopes an adapter hot-swap's flush to
        # exactly this tenant's blocks
        self.ns: Optional[str] = None


class PagedKVCache:
    """Block-pool KV allocator + prefix index for one engine.

    Thread-safe; in practice only the engine's decode thread mutates it
    while stats/snapshot readers come from anywhere."""

    def __init__(self, config: Any, *, block_size: int, num_blocks: int,
                 int8: Optional[bool] = None):
        from .generate import _model_fns

        if block_size < 1 or num_blocks < 1:
            raise ValueError("block_size and num_blocks must be >= 1")
        probe = _model_fns(config)[1](config, 1, max_len=block_size)
        _, _, heads, head_dim = probe[0]["k"].shape
        self.layers = len(probe)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.dtype = probe[0]["k"].dtype
        self.int8 = kv_int8_default() if int8 is None else bool(int8)
        shape = (self.layers, self.num_blocks, self.block_size, heads,
                 head_dim)
        pool_dtype = jnp.int8 if self.int8 else self.dtype
        self._pool_k = jnp.zeros(shape, pool_dtype)
        self._pool_v = jnp.zeros(shape, pool_dtype)
        if self.int8:
            sshape = (self.layers, self.num_blocks, 1, heads, head_dim)
            self._scale_k = jnp.zeros(sshape, jnp.float32)
            self._scale_v = jnp.zeros(sshape, jnp.float32)
        self._empty_k = jnp.zeros((self.layers, 0, heads, head_dim),
                                  self.dtype)
        self._lock = threading.Lock()
        self._blocks: Dict[int, _Block] = {}
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._full_index: Dict[bytes, int] = {}
        self._partial_index: Dict[bytes,
                                  Dict[Tuple[int, ...], int]] = {}
        # forward-walk index for the draft proposer: chain digest ->
        # {tokens: bid} of the FULL blocks extending it (partial tails
        # are already forward-indexed by _partial_index)
        self._children: Dict[bytes, Dict[Tuple[int, ...], int]] = {}
        self._tick = itertools.count(1)
        # tier-2 host arena (serve/kvplane.HostArena) — None keeps the
        # historical single-tier behavior bit-identically
        self._arena: Optional[Any] = None
        self._events: List[Dict[str, Any]] = []
        self._stats: Dict[str, int] = {
            k: 0 for k in ("lookups", "hits", "partial_hits", "misses",
                           "reused_tokens", "prefilled_tokens",
                           "inserted_blocks", "evictions", "cow_copies",
                           "invalidations")}
        kvcache_metrics()  # lazy registration, before the first event

    # ------------------------------------------------------------ lookup

    def lookup(self, tokens: np.ndarray, max_tokens: int,
               namespace: Optional[str] = None) -> PrefixMatch:
        """Longest cached block-aligned (+ partial tail) prefix of
        `tokens`, capped at `max_tokens` so the caller always has a
        suffix left to prefill (the last prompt position's logits feed
        the first sampled token). Matched blocks are PINNED — pair every
        lookup with a release() of the returned/committed table.
        `namespace` scopes the match (LoRA tenant: KV computed under
        one tenant's adapter can never serve another — pass the SAME
        namespace to the paired commit())."""
        tokens = np.asarray(tokens).reshape(-1)
        bs = self.block_size
        with self._lock:
            digest = _ns_root(namespace)
            bids: List[int] = []
            matched = 0
            now0 = next(self._tick)
            while matched + bs <= max_tokens:
                blk = tuple(int(t) for t in tokens[matched:matched + bs])
                nxt = _chain(digest, blk)
                bid = self._full_index.get(nxt)
                if bid is None or self._blocks[bid].tokens != blk:
                    # tier-2: a block evicted under pool pressure may
                    # still live in the host arena — re-adopt it through
                    # the normal insert path and keep walking
                    bid = None
                    if self._arena is not None:
                        payload = self._arena.take_full(nxt, blk)
                        if payload is not None:
                            parent = bids[-1] if bids else None
                            bid = self._adopt_payload_locked(
                                payload, parent, now0)
                            if bid is None:
                                self._arena.give_back(payload)
                    if bid is None:
                        break
                # pin AS WE WALK: an arena adoption further down the
                # chain may have to evict, and an unpinned match would
                # be a legal victim
                b = self._blocks[bid]
                b.ref += 1
                b.last_used = now0
                bids.append(bid)
                digest = nxt
                matched += bs
            full_blocks = len(bids)
            partial_bid: Optional[int] = None
            partial_len = 0
            for ptoks, bid in self._partial_index.get(digest, {}).items():
                k = len(ptoks)
                if (k > partial_len and matched + k <= max_tokens
                        and tuple(int(t) for t in
                                  tokens[matched:matched + k]) == ptoks):
                    partial_bid, partial_len = bid, k
            if partial_bid is None and self._arena is not None:
                payload = self._arena.take_partial(
                    digest, tokens[matched:], max_tokens - matched)
                if payload is not None:
                    parent = bids[-1] if bids else None
                    bid = self._adopt_payload_locked(payload, parent,
                                                     now0)
                    if bid is None:
                        self._arena.give_back(payload)
                    else:
                        partial_bid = bid
                        partial_len = len(payload["tokens"])
            if partial_bid is not None:
                b = self._blocks[partial_bid]
                b.ref += 1
                b.last_used = now0
                bids.append(partial_bid)
                matched += partial_len
            plen = len(tokens)
            if matched and plen - matched <= bs:
                outcome = "hit"
                self._stats["hits"] += 1
            elif matched:
                outcome = "partial"
                self._stats["partial_hits"] += 1
            else:
                outcome = "miss"
                self._stats["misses"] += 1
            self._stats["lookups"] += 1
            self._stats["reused_tokens"] += matched
        m = kvcache_metrics()
        m["lookups"].inc(tags={"outcome": outcome})
        if matched:
            m["reused_tokens"].inc(matched)
        return PrefixMatch(bids, matched, full_blocks, partial_bid,
                           partial_len, outcome)

    def gather(self, match: PrefixMatch):
        """Device prefix ``([L, tokens, H, hd] k, same v)`` for a match
        (empty arrays for a miss — the uncached-prefill program shape)."""
        if match.tokens == 0:
            return self._empty_k, self._empty_k
        bids = jnp.asarray(match.bids, jnp.int32)
        with self._lock:
            # dispatch under the lock: commit()'s pool writes are jitted
            # with the pool DONATED, so a gather dispatched between a
            # concurrent commit's donation and its pool-reference swap
            # would read a deleted Array (concurrent callers exist — the
            # disaggregated prefill tier runs prefills in parallel).
            # Same-device stream order makes the dispatch itself the
            # only critical section; the compute overlaps freely.
            if self.int8:
                return _gather_prefix_q(self._pool_k, self._pool_v,
                                        self._scale_k, self._scale_v,
                                        bids, match.tokens, self.dtype)
            return _gather_prefix(self._pool_k, self._pool_v, bids,
                                  match.tokens)

    # ------------------------------------------------ tiered KV plane

    def attach_arena(self, arena: Optional[Any]) -> None:
        """Hook a tier-2 host arena (serve/kvplane.HostArena) into the
        pool: evictions spill their wire-format payload to
        ``arena.accept()`` instead of dying, and a broken lookup chain
        walk consults ``arena.take_full()/take_partial()`` before
        giving up. ``attach_arena(None)`` detaches (single-tier
        behavior, bit-identical to pre-kvplane)."""
        with self._lock:
            self._arena = arena

    def _payload_locked(self, b: _Block) -> Dict[str, Any]:
        """One block's tier-2/3 wire-format payload: int8 K/V + f32
        per-block-channel scales (``_write_block_q``'s layout) plus the
        index identity needed to re-adopt it. int8 pools hand out their
        bytes verbatim (lossless round trip); fp pools quantize on
        spill, re-entering within the int8 tolerance contract."""
        bid = b.bid
        if self.int8:
            qk = np.asarray(self._pool_k[:, bid])
            qv = np.asarray(self._pool_v[:, bid])
            sk = np.asarray(self._scale_k[:, bid])
            sv = np.asarray(self._scale_v[:, bid])
        else:
            qk_j, sk_j = _quantize(self._pool_k[:, bid])
            qv_j, sv_j = _quantize(self._pool_v[:, bid])
            qk, sk = np.asarray(qk_j), np.asarray(sk_j)
            qv, sv = np.asarray(qv_j), np.asarray(sv_j)
        return {"index_key": b.index_key, "tokens": b.tokens,
                "filled": b.filled, "ns": b.ns,
                "parent_digest": b.parent_digest,
                "qk": qk, "qv": qv, "sk": sk, "sv": sv}

    def _adopt_payload_locked(self, payload: Dict[str, Any],
                              parent: Optional[int],
                              now: int) -> Optional[int]:
        """Re-adopt a wire-format payload into the pool through the
        normal insert path. Returns the new bid, or None when no block
        could be allocated (the caller gives the payload back to its
        tier). The adopted block starts UNPINNED — lookup/import pin
        explicitly."""
        key = payload.get("index_key")
        if key is None:
            return None
        bid = self._alloc_locked()
        if bid is None:
            return None
        if self.int8:
            (self._pool_k, self._pool_v, self._scale_k,
             self._scale_v) = _write_block_qraw(
                self._pool_k, self._pool_v, self._scale_k,
                self._scale_v, np.int32(bid), payload["qk"],
                payload["qv"], payload["sk"], payload["sv"])
        else:
            bk = (jnp.asarray(payload["qk"], jnp.float32)
                  * jnp.asarray(payload["sk"])).astype(self.dtype)
            bv = (jnp.asarray(payload["qv"], jnp.float32)
                  * jnp.asarray(payload["sv"])).astype(self.dtype)
            self._pool_k, self._pool_v = _write_block(
                self._pool_k, self._pool_v, np.int32(bid), bk, bv)
        self._insert_locked(bid, key, payload["tokens"],
                            payload["filled"], parent, now,
                            payload.get("ns"),
                            payload.get("parent_digest"))
        self._blocks[bid].ref = 0
        return bid

    def export_prefix(self, tokens, namespace: Optional[str] = None,
                      max_blocks: int = 32
                      ) -> Optional[Tuple[Dict[str, Any], int, str]]:
        """Pack the longest cached full-block chain prefix of `tokens`
        in the tier-3 wire format (stacked int8 blocks + scales + the
        exact token prefix). Returns ``(packed, n_tokens, digest_hex)``
        — digest_hex is the chain digest the prefix directory keys the
        published object by — or None when nothing is cached. Nothing
        is pinned: tier 3 holds a COPY, eviction of the source blocks
        is irrelevant."""
        tokens = np.asarray(tokens).reshape(-1)
        bs = self.block_size
        payloads: List[Dict[str, Any]] = []
        with self._lock:
            digest = _ns_root(namespace)
            matched = 0
            while (matched + bs <= len(tokens)
                   and len(payloads) < max_blocks):
                blk = tuple(int(t) for t in tokens[matched:matched + bs])
                nxt = _chain(digest, blk)
                bid = self._full_index.get(nxt)
                if bid is None or self._blocks[bid].tokens != blk:
                    break
                payloads.append(self._payload_locked(self._blocks[bid]))
                digest = nxt
                matched += bs
        if not payloads:
            return None
        packed = {"qk": np.stack([p["qk"] for p in payloads]),
                  "qv": np.stack([p["qv"] for p in payloads]),
                  "sk": np.stack([p["sk"] for p in payloads]),
                  "sv": np.stack([p["sv"] for p in payloads]),
                  "tokens": np.asarray(tokens[:matched], np.int64)}
        return packed, matched, digest.hex()

    def import_prefix(self, tokens, packed: Dict[str, Any],
                      namespace: Optional[str] = None) -> int:
        """Adopt a tier-3 packed prefix (``export_prefix``'s format,
        fetched over the chunk fabric) into this pool's index. Blocks
        already cached are skipped; the rest enter through the normal
        insert path, unpinned. Returns the number of blocks adopted.
        The packed token prefix is verified against `tokens` — a
        digest-directory collision must never seed wrong KV."""
        tokens = np.asarray(tokens).reshape(-1)
        bs = self.block_size
        ptoks = np.asarray(packed["tokens"]).reshape(-1)
        if len(ptoks) > len(tokens) \
                or not np.array_equal(tokens[:len(ptoks)], ptoks):
            return 0
        nb = int(packed["qk"].shape[0])
        adopted_bids: List[int] = []
        with self._lock:
            digest = _ns_root(namespace)
            now = next(self._tick)
            parent: Optional[int] = None
            for i in range(nb):
                if (i + 1) * bs > len(ptoks):
                    break
                blk = tuple(int(t) for t in
                            tokens[i * bs:(i + 1) * bs])
                nxt = _chain(digest, blk)
                bid = self._full_index.get(nxt)
                if bid is not None and self._blocks[bid].tokens == blk:
                    parent, digest = bid, nxt
                    continue
                payload = {"index_key": ("full", nxt), "tokens": blk,
                           "filled": bs, "ns": namespace,
                           "parent_digest": digest,
                           "qk": packed["qk"][i], "qv": packed["qv"][i],
                           "sk": packed["sk"][i], "sv": packed["sv"][i]}
                bid = self._adopt_payload_locked(payload, parent, now)
                if bid is None:
                    break
                # pin for the loop's duration: a later adoption's alloc
                # must not evict an earlier adopted leaf
                self._blocks[bid].ref = 1
                adopted_bids.append(bid)
                parent, digest = bid, nxt
            for bid in adopted_bids:
                self._blocks[bid].ref = 0
            util = 1.0 - len(self._free) / self.num_blocks
        kvcache_metrics()["utilization"].set(util)
        return len(adopted_bids)

    def force_evict(self, n: int) -> int:
        """Evict up to `n` unpinned leaf blocks (LRU order) regardless
        of pool pressure — the ``evict_storm`` chaos op. With an arena
        attached every victim spills to tier 2, so a storm sheds
        capacity, never correctness."""
        evicted = 0
        with self._lock:
            for _ in range(int(n)):
                victim: Optional[_Block] = None
                for b in self._blocks.values():
                    if b.ref == 0 and b.children == 0 \
                            and b.index_key is not None:
                        if victim is None \
                                or b.last_used < victim.last_used:
                            victim = b
                if victim is None:
                    break
                self._evict_locked(victim)
                self._free.append(victim.bid)
                evicted += 1
            util = 1.0 - len(self._free) / self.num_blocks
        kvcache_metrics()["utilization"].set(util)
        return evicted

    # ----------------------------------------------------------- propose

    def propose(self, tokens, k: int,
                namespace: Optional[str] = None) -> List[int]:
        """Draft up to `k` tokens CONTINUING `tokens` off the prefix
        index's exact token chains (prompt-lookup speculative decoding,
        models/engine.py): walk the chain matching the context's
        block-aligned prefix, then follow the full-block children (and
        finally any partial tail) whose tokens extend the context's
        remainder. Returns [] when no cached chain extends the context.
        Nothing is pinned — a wrong draft is simply rejected by the
        verify pass, so correctness never depends on this answer."""
        tokens = np.asarray(tokens).reshape(-1)
        bs = self.block_size
        n = len(tokens)
        out: List[int] = []
        with self._lock:
            digest = _ns_root(namespace)
            matched = 0
            while matched + bs <= n:
                blk = tuple(int(t) for t in tokens[matched:matched + bs])
                nxt = _chain(digest, blk)
                bid = self._full_index.get(nxt)
                if bid is None or self._blocks[bid].tokens != blk:
                    break
                digest = nxt
                matched += bs
            rem = tuple(int(t) for t in tokens[matched:])
            if len(rem) >= bs:
                return []  # context diverged from every cached chain
            while len(out) < k:
                kids = self._children.get(digest, {})
                step = None
                for toks, bid in kids.items():
                    if toks[:len(rem)] == rem and len(toks) > len(rem):
                        step = (toks, bid)
                        break
                if step is None:
                    break
                toks, bid = step
                out.extend(toks[len(rem):])
                key = self._blocks[bid].index_key
                if key is None or key[0] != "full":
                    break
                digest, rem = key[1], ()
            if len(out) < k:
                # the longest partial tail extending what's left
                best: Tuple[int, ...] = ()
                for toks in self._partial_index.get(digest, {}):
                    if toks[:len(rem)] == rem and len(toks) > len(rem) \
                            and len(toks) > len(best):
                        best = toks
                if best:
                    out.extend(best[len(rem):])
        return out[:k]

    # ------------------------------------------------------------ commit

    def _write_locked(self, bid: int, bk, bv) -> None:
        """One block write under the lock — the int8 pool quantizes on
        commit (donated, O(block) in place either way)."""
        if self.int8:
            (self._pool_k, self._pool_v, self._scale_k,
             self._scale_v) = _write_block_q(
                self._pool_k, self._pool_v, self._scale_k,
                self._scale_v, np.int32(bid), bk, bv)
        else:
            self._pool_k, self._pool_v = _write_block(
                self._pool_k, self._pool_v, np.int32(bid), bk, bv)

    def _cow_locked(self, dst: int, src: int, bk, bv,
                    filled_old: int) -> None:
        """Copy-on-write merge under the lock (int8: dequant the shared
        rows, merge, requantize the widened block)."""
        if self.int8:
            (self._pool_k, self._pool_v, self._scale_k,
             self._scale_v) = _cow_extend_block_q(
                self._pool_k, self._pool_v, self._scale_k,
                self._scale_v, np.int32(dst), np.int32(src), bk, bv,
                np.int32(filled_old))
        else:
            self._pool_k, self._pool_v = _cow_extend_block(
                self._pool_k, self._pool_v, np.int32(dst),
                np.int32(src), bk, bv, np.int32(filled_old))
        self._stats["cow_copies"] += 1
        kvcache_metrics()["cow_copies"].inc()

    def note_prefilled(self, n_tokens: int) -> None:
        with self._lock:
            self._stats["prefilled_tokens"] += int(n_tokens)
        kvcache_metrics()["prefilled_tokens"].inc(int(n_tokens))

    def commit(self, tokens: np.ndarray, ck, cv,
               match: PrefixMatch,
               namespace: Optional[str] = None) -> List[int]:
        """Insert the prompt's uncached blocks from its freshly filled
        single-sequence cache ``ck/cv [L, S, H, hd]`` and return the
        request's pinned block table (matched + inserted). Stops quietly
        when the pool is exhausted — caching is best-effort, the slot's
        own slab copy is already correct. `namespace` must match the
        paired lookup()'s."""
        tokens = np.asarray(tokens).reshape(-1)
        bs = self.block_size
        plen = len(tokens)
        n_full, tail = divmod(plen, bs)
        with self._lock:
            table = list(match.bids)
            digest = _ns_root(namespace)
            now = next(self._tick)
            parent: Optional[int] = None
            exhausted = False
            for i in range(n_full):
                blk = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
                nxt = _chain(digest, blk)
                if i < match.full_blocks:
                    parent, digest = match.bids[i], nxt
                    continue
                existing = self._full_index.get(nxt)
                if (existing is not None
                        and self._blocks[existing].tokens == blk):
                    b = self._blocks[existing]
                    b.ref += 1
                    b.last_used = now
                    table.append(existing)
                    parent, digest = existing, nxt
                    continue
                bid = self._alloc_locked()
                if bid is None:
                    exhausted = True
                    break
                bk, bv = _extract_block(ck, cv, np.int32(i * bs), bs)
                if (i == match.full_blocks
                        and match.partial_bid is not None):
                    # the matched SHARED partial block sits at this
                    # position and this prompt widens it to a full
                    # block: copy-on-write (the original stays indexed
                    # for future shorter matches)
                    self._cow_locked(bid, match.partial_bid, bk, bv,
                                     match.partial_len)
                else:
                    self._write_locked(bid, bk, bv)
                self._insert_locked(bid, ("full", nxt), blk, bs, parent,
                                    now, namespace, digest)
                table.append(bid)
                parent, digest = bid, nxt
            if tail and not exhausted:
                self._commit_tail_locked(tokens, ck, cv, match, digest,
                                         parent, n_full, tail, table,
                                         now, namespace)
            util = 1.0 - len(self._free) / self.num_blocks
        kvcache_metrics()["utilization"].set(util)
        return table

    def _commit_tail_locked(self, tokens, ck, cv, match, digest, parent,
                            n_full, tail, table, now,
                            namespace: Optional[str] = None) -> None:
        bs = self.block_size
        if (n_full + 1) * bs > ck.shape[1]:
            # the tail block's nominal extent crosses the cache window
            # (block_size not dividing max_seq_len, prompt near max):
            # dynamic_slice would clamp the start and cache shifted
            # rows — skip caching this tail, correctness first
            return
        tail_toks = tuple(int(t) for t in tokens[n_full * bs:])
        # the matched partial is the TAIL's predecessor only when it sat
        # at the final block position (otherwise it was widened to a
        # full block by the loop above)
        tail_partial = (match.partial_bid
                        if match.full_blocks == n_full else None)
        if tail_partial is not None and match.partial_len == tail:
            return  # the matched partial already covers the whole tail
        by_tok = self._partial_index.get(digest, {})
        existing = by_tok.get(tail_toks)
        if existing is not None:
            b = self._blocks[existing]
            b.ref += 1
            b.last_used = now
            table.append(existing)
            return
        bid = self._alloc_locked()
        if bid is None:
            return
        bk, bv = _extract_block(ck, cv, np.int32(n_full * bs), bs)
        if tail_partial is not None:
            # extending a SHARED cached block: copy-on-write — the old
            # entry stays indexed for future shorter matches
            self._cow_locked(bid, tail_partial, bk, bv,
                             match.partial_len)
        else:
            self._write_locked(bid, bk, bv)
        self._insert_locked(bid, ("partial", digest, tail_toks),
                            tail_toks, tail, parent, now, namespace,
                            digest)
        table.append(bid)

    def _insert_locked(self, bid: int, index_key: tuple,
                       blk_tokens: Tuple[int, ...], filled: int,
                       parent: Optional[int], now: int,
                       ns: Optional[str] = None,
                       parent_digest: Optional[bytes] = None) -> None:
        b = _Block(bid)
        b.tokens = blk_tokens
        b.filled = filled
        b.ref = 1  # the committing request's pin
        b.last_used = now
        b.index_key = index_key
        b.parent_bid = parent
        b.parent_digest = parent_digest
        b.ns = ns
        self._blocks[bid] = b
        if index_key[0] == "full":
            self._full_index[index_key[1]] = bid
            if parent_digest is not None:
                self._children.setdefault(parent_digest,
                                          {})[blk_tokens] = bid
        else:
            self._partial_index.setdefault(index_key[1],
                                           {})[index_key[2]] = bid
        if parent is not None and parent in self._blocks:
            self._blocks[parent].children += 1
        self._stats["inserted_blocks"] += 1

    # -------------------------------------------------- alloc / evict

    def _alloc_locked(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        victim: Optional[_Block] = None
        for b in self._blocks.values():
            # evictable: unpinned leaf (children of refcount-0 interiors
            # are themselves refcount-0, so leaves always drain first)
            if b.ref == 0 and b.children == 0 and b.index_key is not None:
                if victim is None or b.last_used < victim.last_used:
                    victim = b
        if victim is None:
            return None
        self._evict_locked(victim)
        return victim.bid

    def _evict_locked(self, b: _Block) -> None:
        # tier-2 spill BEFORE the index drop: the payload needs the
        # block's index identity, and accept() only ever touches host
        # memory (arena dict insert), so holding the lock is safe
        if self._arena is not None and b.index_key is not None:
            try:
                self._arena.accept(self._payload_locked(b))
            except Exception:  # noqa: BLE001 — spill is best-effort
                pass
        self._drop_index_locked(b)
        if b.parent_bid is not None and b.parent_bid in self._blocks:
            self._blocks[b.parent_bid].children -= 1
        del self._blocks[b.bid]
        self._stats["evictions"] += 1
        kvcache_metrics()["evictions"].inc()
        self._event_locked({"kind": "evict", "bid": b.bid,
                            "block_tokens": b.filled})

    def _drop_index_locked(self, b: _Block) -> None:
        key = b.index_key
        if key is None:
            return
        if key[0] == "full":
            self._full_index.pop(key[1], None)
            if b.parent_digest is not None:
                kids = self._children.get(b.parent_digest)
                if kids is not None:
                    kids.pop(b.tokens, None)
                    if not kids:
                        del self._children[b.parent_digest]
        else:
            by_tok = self._partial_index.get(key[1])
            if by_tok is not None:
                by_tok.pop(key[2], None)
                if not by_tok:
                    del self._partial_index[key[1]]
        b.index_key = None

    # ---------------------------------------------------- release / gc

    def release(self, table: List[int]) -> None:
        """Drop a finished request's pins. Refcount-0 blocks remain
        cached (LRU-evictable); orphans (invalidated while pinned) are
        freed outright."""
        with self._lock:
            for bid in table:
                b = self._blocks.get(bid)
                if b is None:
                    continue
                b.ref = max(0, b.ref - 1)
                if b.ref == 0 and b.index_key is None:
                    if b.parent_bid is not None \
                            and b.parent_bid in self._blocks:
                        self._blocks[b.parent_bid].children -= 1
                    del self._blocks[b.bid]
                    self._free.append(b.bid)
            util = 1.0 - len(self._free) / self.num_blocks
        kvcache_metrics()["utilization"].set(util)

    def invalidate(self, namespace: Optional[str] = ...) -> None:
        """Weight swap: every cached block's KV was computed under the
        OLD params — drop the whole index so no future lookup matches
        it. In-flight slots keep their pinned (now orphaned) blocks for
        refcount accounting only; they decode off their own slab.

        ``invalidate(namespace=tenant)`` scopes the flush to ONE cache
        namespace (a LoRA adapter hot-swap stales exactly that tenant's
        KV — every other tenant's blocks, and the base namespace, stay
        cached). A namespaced chain hangs off its own root digest, so
        the dropped blocks' parents are always in the same namespace
        and no surviving chain loses a reachable interior."""
        scoped = namespace is not ...
        with self._lock:
            for b in list(self._blocks.values()):
                if scoped and b.ns != namespace:
                    continue
                self._drop_index_locked(b)
                if b.ref == 0:
                    if scoped and b.parent_bid is not None \
                            and b.parent_bid in self._blocks:
                        self._blocks[b.parent_bid].children -= 1
                    del self._blocks[b.bid]
                    self._free.append(b.bid)
            if not scoped:
                for b in self._blocks.values():
                    b.children = 0
            self._stats["invalidations"] += 1
            ev: Dict[str, Any] = {"kind": "invalidate"}
            if scoped:
                ev["namespace"] = namespace
            self._event_locked(ev)
            util = 1.0 - len(self._free) / self.num_blocks
        kvcache_metrics()["utilization"].set(util)

    # -------------------------------------------------- stats / events

    def _event_locked(self, ev: Dict[str, Any]) -> None:
        ev.setdefault("ts", time.time())
        self._events.append(ev)
        if len(self._events) > _EVENTS_KEPT:
            del self._events[:len(self._events) - _EVENTS_KEPT]

    def record_event(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            self._event_locked(dict(ev))

    def drain_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._events = self._events, []
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            s: Dict[str, Any] = dict(self._stats)
            cached = sum(1 for b in self._blocks.values()
                         if b.index_key is not None)
            pinned = sum(1 for b in self._blocks.values() if b.ref > 0)
            s.update(
                enabled=True,
                block_size=self.block_size,
                num_blocks=self.num_blocks,
                free_blocks=len(self._free),
                cached_blocks=cached,
                pinned_blocks=pinned,
                pool_utilization=1.0 - len(self._free) / self.num_blocks,
                int8=self.int8,
                # bytes-per-block capacity factor vs the fp pool — the
                # "effective pool doubled" evidence every surface (and
                # the bench record) reports
                capacity_factor=2 if self.int8 else 1,
                pool_bytes=int(self._pool_k.nbytes + self._pool_v.nbytes
                               + ((self._scale_k.nbytes
                                   + self._scale_v.nbytes)
                                  if self.int8 else 0)),
            )
        looked = s["lookups"]
        s["hit_rate"] = ((s["hits"] + s["partial_hits"]) / looked
                         if looked else 0.0)
        seen = s["reused_tokens"] + s["prefilled_tokens"]
        s["token_reuse_rate"] = s["reused_tokens"] / seen if seen else 0.0
        return s
