"""ray_tpu.models: flagship model families, TPU-first.

The reference ships no models of its own (Ray wraps user torch modules);
the rebuild's north-star workloads (BASELINE.md) need a flagship LM, so
GPT-2 lives here as a pure-functional JAX implementation with first-class
sharding rules for every mesh axis the parallel layer exposes.
"""
from .gpt2 import (  # noqa: F401
    GPT2Config,
    gpt2_forward,
    gpt2_init,
    gpt2_loss,
    gpt2_partition_specs,
)
from .engine import ContinuousBatchingEngine, TokenStream  # noqa: F401
from .generate import generate, stream_generate  # noqa: F401
from .kvcache import PagedKVCache  # noqa: F401
from .llama import (  # noqa: F401
    LlamaConfig,
    init_kv_cache,
    llama_forward,
    llama_forward_cached,
    llama_init,
    llama_loss,
    llama_partition_specs,
)
from .moe_transformer import (  # noqa: F401
    MoEConfig,
    moe_forward,
    moe_init,
    moe_loss,
    moe_partition_specs,
)
