"""Autoregressive generation over KV caches — the inference half of the
serving story (BASELINE config "Llama JAX replica, batched inference";
the reference serves torch models, generation itself lives outside its
tree, so this is native framework capability like models/llama.py).

TPU-first shape discipline: prefill is ONE jitted call over the padded
prompt, the decode loop is ONE jitted lax.scan over steps with the
cache donated — no per-token dispatch, no dynamic shapes. For token
streaming (Serve), `stream_generate` trades the scan for a jitted
single-step called from Python so each token can be yielded as it
lands.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp

from .llama import LlamaConfig, init_kv_cache, llama_forward_cached


def _model_fns(config):
    """(forward_cached, init_cache, ragged_decode) for the config's
    model family — generation and the continuous-batching engine are
    model-agnostic over this cache protocol."""
    if isinstance(config, LlamaConfig):
        from .llama import llama_decode

        return llama_forward_cached, init_kv_cache, llama_decode
    from .gpt2 import (GPT2Config, gpt2_decode, gpt2_forward_cached,
                       gpt2_init_kv_cache)

    if isinstance(config, GPT2Config):
        return gpt2_forward_cached, gpt2_init_kv_cache, gpt2_decode
    raise TypeError(f"no generation support for {type(config).__name__}")


def lora_targets(config):
    """The LoRA-target leaves of a model family as
    ``((leaf_name, in_dim, out_dim), ...)`` — each names an entry of
    every block's ``["attn"]`` sub-tree. This table is the ONE place
    the serving stack (serve/lora.py AdapterPool, the engine's
    mixed-tenant decode, the per-tenant online trainer) learns which
    projections an adapter applies to, so the pool layout, the decode
    gather, and the prefill merge can never disagree."""
    if isinstance(config, LlamaConfig):
        kv_dim = config.num_kv_heads * config.head_dim
        return (("wq", config.d_model, config.d_model),
                ("wv", config.d_model, kv_dim))
    from .gpt2 import GPT2Config

    if isinstance(config, GPT2Config):
        return (("qkv", config.d_model, 3 * config.d_model),)
    raise TypeError(f"no LoRA support for {type(config).__name__}")


def merge_lora_params(params, config, lora):
    """Base params with ONE adapter's low-rank deltas folded into the
    target leaves: ``W + scale * (A_l @ B_l)`` per block. `lora` is the
    single-adapter slice ``{"scale": f32 scalar, "targets": {name:
    {"a": [L, in, r], "b": [L, r, out]}}}`` (serve/lora.py
    ``adapter_slice``). Called INSIDE the jitted prefill, so the merged
    leaves never persist — prefill is per-request single-tenant, only
    the decode tick needs the scatter-gathered per-slot form."""
    lora_targets(config)  # validates the family
    blocks = []
    for li, p in enumerate(params["blocks"]):
        attn = dict(p["attn"])
        for name, ab in lora["targets"].items():
            w = attn[name]
            delta = jnp.dot(ab["a"][li], ab["b"][li],
                            preferred_element_type=jnp.float32)
            attn[name] = w + (delta * lora["scale"]).astype(w.dtype)
        p2 = dict(p)
        p2["attn"] = attn
        blocks.append(p2)
    out = dict(params)
    out["blocks"] = blocks
    return out


def _sample_fn(vocab_size: int, temperature: float, top_k: int):
    def sample(key: jax.Array, logits: jax.Array) -> jax.Array:
        # padded vocab rows must never be sampled
        logits = logits[..., :vocab_size]
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        if top_k > 0 and top_k < vocab_size:
            kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
            logits = jnp.where(logits < kth, -1e30, logits)
        return jax.random.categorical(key, logits, axis=-1).astype(
            jnp.int32)

    return sample


@functools.partial(jax.jit, static_argnums=(2,))
def _prefill(params, prompt, config, cache):
    fwd = _model_fns(config)[0]
    logits, cache = fwd(params, prompt, config, cache, 0)
    return logits[:, -1], cache


def _decode_many(params, config, cache, first_token, start_pos, steps,
                 key, temperature, top_k):
    sample = _sample_fn(config.vocab_size, temperature, top_k)

    fwd = _model_fns(config)[0]

    def step(carry, _):
        cache, tok, pos, key = carry
        logits, cache = fwd(params, tok[:, None], config, cache, pos)
        key, sub = jax.random.split(key)
        nxt = sample(sub, logits[:, -1])
        return (cache, nxt, pos + 1, key), nxt

    (_, _, _, _), toks = jax.lax.scan(
        step, (cache, first_token, start_pos, key), None, length=steps)
    return jnp.moveaxis(toks, 0, 1)  # [B, steps]


_decode_many_jit = jax.jit(
    _decode_many, static_argnums=(1, 5, 7, 8), donate_argnums=(2,))


def generate(params: Any, config: LlamaConfig, prompt: jax.Array, *,
             max_new_tokens: int, temperature: float = 0.0,
             top_k: int = 0, key: Optional[jax.Array] = None,
             eos_token: Optional[int] = None) -> jax.Array:
    """Batched generation: prompt [B, T0] int32 -> [B, max_new_tokens]
    int32. Greedy at temperature 0, else top-k/temperature sampling.
    With eos_token, tokens after a sequence's first EOS are replaced by
    EOS (compute still runs the full static length — TPU shapes)."""
    b, t0 = prompt.shape
    if t0 + max_new_tokens > config.max_seq_len:
        raise ValueError(
            f"prompt ({t0}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({config.max_seq_len})")
    key = key if key is not None else jax.random.PRNGKey(0)
    cache = _model_fns(config)[1](config, b)
    last_logits, cache = _prefill(params, prompt, config, cache)
    key, k0 = jax.random.split(key)
    first = _sample_fn(config.vocab_size, temperature, top_k)(
        k0, last_logits)
    if max_new_tokens == 1:
        toks = first[:, None]
    else:
        rest = _decode_many_jit(params, config, cache, first,
                                jnp.int32(t0), max_new_tokens - 1, key,
                                temperature, top_k)
        toks = jnp.concatenate([first[:, None], rest], axis=1)
    if eos_token is not None:
        hit = jnp.cumsum(
            (toks == eos_token).astype(jnp.int32), axis=1) > 0
        done_before = jnp.concatenate(
            [jnp.zeros((b, 1), bool), hit[:, :-1]], axis=1)
        toks = jnp.where(done_before, eos_token, toks)
    return toks


def stream_generate(params: Any, config: LlamaConfig, prompt: jax.Array,
                    *, max_new_tokens: int, temperature: float = 0.0,
                    top_k: int = 0, key: Optional[jax.Array] = None,
                    eos_token: Optional[int] = None
                    ) -> Iterator[jax.Array]:
    """Yield one [B] int32 token batch per decode step — the producer
    Serve's streaming path consumes for token-by-token LLM responses.
    Uses a jitted single step per token (streaming is latency-bound at
    the consumer; per-step dispatch is irrelevant next to the yield)."""
    b, t0 = prompt.shape
    if t0 + max_new_tokens > config.max_seq_len:
        raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
    key = key if key is not None else jax.random.PRNGKey(0)
    sample = _sample_fn(config.vocab_size, temperature, top_k)
    cache = _model_fns(config)[1](config, b)
    last_logits, cache = _prefill(params, prompt, config, cache)
    key, sub = jax.random.split(key)
    tok = sample(sub, last_logits)
    pos = t0
    done = jnp.zeros((b,), bool)
    for _ in range(max_new_tokens):
        out = tok
        if eos_token is not None:
            out = jnp.where(done, eos_token, tok)
            done = done | (tok == eos_token)
        yield out
        if eos_token is not None and bool(done.all()):
            return
        cache, tok, key = _stream_step(params, cache, config, tok,
                                       jnp.int32(pos), temperature,
                                       top_k, key)
        pos += 1


@functools.partial(jax.jit, static_argnums=(2, 5, 6),
                   donate_argnums=(1,))
def _stream_step(params, cache, config, tok, pos, temperature, top_k,
                 key):
    # module-level so the compiled step is shared across every
    # stream_generate call with the same (config, sampling) — a serving
    # replica must not recompile per request
    fwd = _model_fns(config)[0]
    logits, cache = fwd(params, tok[:, None], config, cache, pos)
    key, sub = jax.random.split(key)
    nxt = _sample_fn(config.vocab_size, temperature, top_k)(
        sub, logits[:, -1])
    return cache, nxt, key
