"""Layout-level analysis: run the shard + collective checks against a
whole mesh layout, including the repo's built-in dryrun layouts.

`analyze_layout` is the general entry point the ISSUE describes: a
`MeshConfig`/`HybridMeshConfig`, a PartitionSpec tree + abstract params
(from `jax.eval_shape`), and optionally a function + abstract inputs to
trace for collectives — all deviceless, so a v4 pod layout lints on a
laptop. `analyze_builtin_layouts` applies it to every layout the driver's
`dryrun_multichip` exercises (dcn_dp x tp, dcn_pp x fsdp, dp x pp,
dp x sp, dp x ep); the dryrun path refuses to run a layout that does not
come back clean.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..parallel.mesh import MeshConfig
from ..parallel.multislice import HybridMeshConfig
from .collectives import (CollectiveUse, abstract_mesh, check_collectives,
                          estimate_training_dcn_traffic, scan_collectives)
from .findings import Finding, INFO
from .shardcheck import (DEFAULT_REPLICATED_THRESHOLD, MeshLayout,
                         _nbytes, check_specs)


@dataclass
class LayoutTrace:
    """One dryrun layout's oracle inputs: the deviceless mesh layout,
    the traced collectives, and rough analytic work terms. The roofline
    model (observability.roofline) prices these; the findings-based
    analyzers below reuse the same traces so both surfaces describe one
    program."""

    layout: MeshLayout
    uses: List[CollectiveUse] = field(default_factory=list)
    flops_per_step: float = 0.0
    tokens_per_step: int = 0


def analyze_layout(config: MeshConfig, n_devices: int,
                   num_slices: int = 1, *,
                   param_specs: Any = None,
                   abstract_params: Any = None,
                   data_specs: Any = None,
                   abstract_batch: Any = None,
                   fn: Optional[Callable] = None,
                   abstract_args: Sequence[Any] = (),
                   replicated_threshold: int =
                   DEFAULT_REPLICATED_THRESHOLD,
                   name: str = "") -> List[Finding]:
    """Lint one layout: spec validation + HBM replication check for the
    params, spec validation for the batch, collective/DCN-cost scan for
    `fn(*abstract_args)`. Any piece may be omitted."""
    layout = MeshLayout.from_config(config, n_devices, num_slices,
                                    name=name)
    findings: List[Finding] = []
    if param_specs is not None and abstract_params is not None:
        findings += check_specs(param_specs, abstract_params, layout,
                                replicated_threshold,
                                where=f"{layout.name}/params")
        dcn_bytes = estimate_training_dcn_traffic(layout, abstract_params)
        if dcn_bytes > 0:
            findings.append(Finding(
                "collective-over-dcn", INFO, f"{layout.name}/grad-sync",
                f"est. gradient allreduce over DCN: "
                f"{dcn_bytes / 2 ** 20:.2f} MiB per step"))
    if data_specs is not None and abstract_batch is not None:
        findings += check_specs(data_specs, abstract_batch, layout,
                                replicated_threshold,
                                where=f"{layout.name}/batch")
    if fn is not None:
        findings += check_collectives(
            layout, scan_collectives(fn, *abstract_args),
            where=f"{layout.name}/collectives")
    return findings


# ------------------------------------------------------- builtin layouts


def _abstract_gpt2(cfg) -> Any:
    """Abstract GPT-2 param tree — eval_shape never materializes it."""
    import jax
    import jax.numpy as jnp

    from ..models.gpt2 import gpt2_init

    return jax.eval_shape(
        functools.partial(gpt2_init, cfg),
        jax.ShapeDtypeStruct((2,), jnp.uint32))


def _sds(shape, dtype=None):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(tuple(shape), dtype or jnp.float32)


def analyze_dcn_dp_tp(n_devices: int = 8,
                      replicated_threshold: int =
                      DEFAULT_REPLICATED_THRESHOLD) -> List[Finding]:
    """The dryrun's dcn_dp x tp hybrid GPT-2 training layout: data
    parallelism across 2 slices over DCN, tensor parallelism on ICI."""
    import jax.numpy as jnp

    from jax.sharding import PartitionSpec as P

    from ..models.gpt2 import GPT2Config, gpt2_partition_specs

    cfg = GPT2Config.tiny()
    config = HybridMeshConfig(dp=-1, tp=2, dcn_dp=2)
    dp_total = n_devices // 2
    batch = {"tokens": _sds((2 * dp_total, 32), jnp.int32),
             "targets": _sds((2 * dp_total, 32), jnp.int32)}
    data_spec = P(("dp", "fsdp"))
    return analyze_layout(
        config, n_devices, num_slices=2,
        param_specs=gpt2_partition_specs(cfg),
        abstract_params=_abstract_gpt2(cfg),
        data_specs={k: data_spec for k in batch}, abstract_batch=batch,
        replicated_threshold=replicated_threshold, name="dcn_dp_tp")


def _trace_dcn_dp_tp(n_devices: int = 8) -> LayoutTrace:
    """Oracle inputs for the hybrid GPT-2 training layout. The data-
    parallel gradient sync IS a psum of the full param pytree over the
    data axes (the same model `estimate_training_dcn_traffic` prices),
    so it appears here as one explicit CollectiveUse."""
    import jax

    from ..models.gpt2 import GPT2Config
    from ..observability.flops import train_flops_per_token

    cfg = GPT2Config.tiny()
    seq = 32
    layout = MeshLayout.from_config(HybridMeshConfig(dp=-1, tp=2,
                                                     dcn_dp=2),
                                    n_devices, num_slices=2,
                                    name="dcn_dp_tp")
    param_bytes = sum(_nbytes(leaf) for leaf in
                      jax.tree_util.tree_leaves(_abstract_gpt2(cfg)))
    tokens = 2 * (n_devices // 2) * seq
    return LayoutTrace(
        layout=layout,
        uses=[CollectiveUse("psum", ("dp", "fsdp"), param_bytes)],
        flops_per_step=train_flops_per_token(cfg, seq) * tokens,
        tokens_per_step=tokens)


def _trace_pipeline(config: MeshConfig, n_devices: int,
                    num_slices: int, pp: int, data_parallel: int,
                    name: str) -> LayoutTrace:
    """Trace the toy GPipe pipeline (ppermute ring + final-stage psum
    over 'pp') over an abstract mesh. Empty uses when this jax has no
    AbstractMesh."""
    import jax.numpy as jnp

    from ..parallel.pipeline import make_pipeline_fn

    m = 4 * pp
    layout = MeshLayout.from_config(config, n_devices, num_slices,
                                    name=name)
    mesh = abstract_mesh(layout)
    d, batch = 16, data_parallel * m
    # toy tanh-matmul "model": ~6 flops per param per row (fwd+bwd)
    flops = 6.0 * (pp * d * d + pp * d) * batch
    if mesh is None:  # jax without AbstractMesh: nothing to trace
        return LayoutTrace(layout=layout, flops_per_step=flops,
                           tokens_per_step=batch)
    pipe = make_pipeline_fn(
        lambda p, h: jnp.tanh(h @ p[0] + p[1]), mesh, num_microbatches=m)
    params = (_sds((pp, d, d)), _sds((pp, d)))
    uses = scan_collectives(pipe, params, _sds((batch, d)))
    return LayoutTrace(layout=layout, uses=uses, flops_per_step=flops,
                       tokens_per_step=batch)


def _pipeline_findings(config: MeshConfig, n_devices: int,
                       num_slices: int, pp: int, data_parallel: int,
                       name: str) -> List[Finding]:
    """Lint the traced GPipe pipeline's collectives plus the schedule's
    analytic bubble estimate (rule pipeline-bubble). The microbatch
    count follows the M = 4*S sizing rule, so the builtin layouts' own
    estimates stay at INFO."""
    from .pipelines import check_pipeline_schedule

    findings = check_pipeline_schedule(pp, 4 * pp, "gpipe",
                                       where=f"{name}/schedule")
    trace = _trace_pipeline(config, n_devices, num_slices, pp,
                            data_parallel, name)
    if not trace.uses:  # jax without AbstractMesh: nothing was traced
        return findings + [Finding(
            "collective-over-dcn", INFO, f"{name}/collectives",
            "collective scan skipped: this jax has no AbstractMesh")]
    return findings + check_collectives(trace.layout, trace.uses,
                                        where=f"{name}/collectives")


def analyze_dcn_pp_fsdp(n_devices: int = 8, **_) -> List[Finding]:
    """The dryrun's dcn_pp x fsdp hybrid: one pipeline stage per slice
    (activations cross DCN — by design), fsdp inside each slice."""
    fsdp = n_devices // 2
    return _pipeline_findings(
        HybridMeshConfig(fsdp=fsdp, dcn_pp=2), n_devices, num_slices=2,
        pp=2, data_parallel=fsdp, name="dcn_pp_fsdp")


def analyze_dp_pp(n_devices: int = 8, **_) -> List[Finding]:
    """The dryrun's flat dp x pp GPipe layout (single slice)."""
    pp = 4
    dp = max(1, n_devices // pp)
    return _pipeline_findings(MeshConfig(dp=dp, pp=pp), n_devices,
                              num_slices=1, pp=pp, data_parallel=dp,
                              name="dp_pp")


def _trace_dp_sp(n_devices: int = 8) -> LayoutTrace:
    """The dryrun's dp x sp ring-attention trace (ppermute over 'sp')."""
    from jax.sharding import PartitionSpec as P

    from ..ops.ring_attention import ring_attention
    from ..parallel.mesh import shard_map

    sp = 4
    dp = max(1, n_devices // sp)
    layout = MeshLayout.from_config(MeshConfig(dp=dp, sp=sp), n_devices,
                                    name="dp_sp")
    batch, seq, heads, hd = 2 * dp, 32, 4, 8
    # causal attention score+value matmuls, fwd only: 2·B·T²·H·hd
    flops = 2.0 * batch * seq * seq * heads * hd
    mesh = abstract_mesh(layout)
    if mesh is None:
        return LayoutTrace(layout=layout, flops_per_step=flops,
                           tokens_per_step=batch * seq)
    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=True),
        mesh=mesh, in_specs=(P("dp", "sp"),) * 3,
        out_specs=P("dp", "sp"), check_vma=False)
    qkv = _sds((batch, seq, heads, hd))
    uses = scan_collectives(ring, qkv, qkv, qkv)
    return LayoutTrace(layout=layout, uses=uses, flops_per_step=flops,
                       tokens_per_step=batch * seq)


def analyze_dp_sp(n_devices: int = 8, **_) -> List[Finding]:
    """The dryrun's dp x sp ring-attention layout (ppermute over 'sp')."""
    trace = _trace_dp_sp(n_devices)
    if not trace.uses:
        return []
    return check_collectives(trace.layout, trace.uses,
                             where="dp_sp/collectives")


def _trace_dp_ep(n_devices: int = 8) -> LayoutTrace:
    """The dryrun's dp x ep MoE trace (all_to_all over 'ep')."""
    from jax.sharding import PartitionSpec as P

    from ..ops import moe_ffn
    from ..parallel.mesh import shard_map

    ep = 4
    dp = max(1, n_devices // ep)
    layout = MeshLayout.from_config(MeshConfig(dp=dp, ep=ep), n_devices,
                                    name="dp_ep")
    t_local, d, f, e, k = 8, 16, 32, 8, 2
    tokens = dp * ep * t_local
    # top_k experts x 3 matmuls (gate/up/down) x 2·d·f, fwd only
    flops = 6.0 * d * f * k * tokens
    mesh = abstract_mesh(layout)
    if mesh is None:
        return LayoutTrace(layout=layout, flops_per_step=flops,
                           tokens_per_step=tokens)
    fn = shard_map(
        functools.partial(moe_ffn, top_k=k, capacity_factor=float(e),
                          axis_name="ep"),
        mesh=mesh, in_specs=(P(("dp", "ep")), P(), P("ep"), P("ep")),
        out_specs=P(("dp", "ep")), check_vma=False)
    uses = scan_collectives(fn, _sds((tokens, d)),
                            _sds((d, e)), _sds((e, d, f)),
                            _sds((e, f, d)))
    return LayoutTrace(layout=layout, uses=uses, flops_per_step=flops,
                       tokens_per_step=tokens)


def analyze_dp_ep(n_devices: int = 8, **_) -> List[Finding]:
    """The dryrun's dp x ep MoE layout (all_to_all over 'ep')."""
    trace = _trace_dp_ep(n_devices)
    if not trace.uses:
        return []
    return check_collectives(trace.layout, trace.uses,
                             where="dp_ep/collectives")


BUILTIN_LAYOUTS: Dict[str, Callable[..., List[Finding]]] = {
    "dcn_dp_tp": analyze_dcn_dp_tp,
    "dcn_pp_fsdp": analyze_dcn_pp_fsdp,
    "dp_pp": analyze_dp_pp,
    "dp_sp": analyze_dp_sp,
    "dp_ep": analyze_dp_ep,
}


def analyze_builtin_layouts(
        n_devices: int = 8) -> Dict[str, List[Finding]]:
    """Findings per built-in dryrun layout. All of them must come back
    with nothing above INFO — the dryrun path asserts exactly that before
    running a single step."""
    return {name: fn(n_devices) for name, fn in BUILTIN_LAYOUTS.items()}


def trace_builtin_layouts(n_devices: int = 8) -> Dict[str, LayoutTrace]:
    """Oracle inputs (layout + traced collectives + rough work terms)
    for every built-in dryrun layout — the backend of
    ``observability.roofline.predict_builtin_layouts`` and
    ``ray_tpu analyze --predict-step-time``."""
    fsdp = n_devices // 2
    pp_flat = 4
    return {
        "dcn_dp_tp": _trace_dcn_dp_tp(n_devices),
        "dcn_pp_fsdp": _trace_pipeline(
            HybridMeshConfig(fsdp=fsdp, dcn_pp=2), n_devices,
            num_slices=2, pp=2, data_parallel=fsdp, name="dcn_pp_fsdp"),
        "dp_pp": _trace_pipeline(
            MeshConfig(dp=max(1, n_devices // pp_flat), pp=pp_flat),
            n_devices, num_slices=1, pp=pp_flat,
            data_parallel=max(1, n_devices // pp_flat), name="dp_pp"),
        "dp_sp": _trace_dp_sp(n_devices),
        "dp_ep": _trace_dp_ep(n_devices),
    }


__all__ = ["BUILTIN_LAYOUTS", "LayoutTrace", "analyze_builtin_layouts",
           "analyze_layout", "analyze_dcn_dp_tp", "analyze_dcn_pp_fsdp",
           "analyze_dp_ep", "analyze_dp_pp", "analyze_dp_sp",
           "trace_builtin_layouts"]
