"""Structured findings for the shardlint static analyzer.

Every check in ray_tpu.analysis reports `Finding` records instead of
raising: a finding names the RULE that fired (a stable kebab-case id the
tests and CI assert on), a SEVERITY, a human location (file:line for AST
lint, layout/param path for shard analysis), the message, and a fix hint.
The callers decide policy — the CLI exits nonzero on errors, the dryrun
path refuses to run a layout with errors/warnings, TrainStep raises on
errors only.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Sequence

# Severity levels, most severe first. Plain strings (not an Enum) so
# findings serialize to JSON without adapters.
ERROR = "error"
WARNING = "warning"
INFO = "info"
SEVERITIES: Sequence[str] = (ERROR, WARNING, INFO)
_RANK: Dict[str, int] = {s: i for i, s in enumerate(SEVERITIES)}

# Rule registry: id -> one-line description (the README table is derived
# from this). Default severities are noted where fixed; collective-over-dcn
# severity depends on which axes are involved.
RULES: Dict[str, str] = {
    "unknown-axis": "PartitionSpec names an axis the mesh does not have",
    "rank-exceeds-ndim": "PartitionSpec has more entries than array dims",
    "non-dividing-dim": "mesh axis size does not divide the array dim",
    "duplicate-axis": "same mesh axis used on two dims of one spec",
    "replicated-large-param":
        "large param fully replicated on every device (HBM blow-up)",
    "collective-over-dcn":
        "bandwidth-heavy collective spans a slow DCN axis",
    "unmodeled-collective":
        "collective primitive without a cost-model entry; byte and "
        "step-time estimates fall back to its raw input size",
    "pipeline-bubble":
        "pipeline schedule's analytic bubble fraction (S-1)/(M+S-1); "
        "warning past 20%",
    "blocking-in-async":
        "blocking call (time.sleep / ray_tpu.get / Queue.get) inside "
        "an async def",
    "unsupervised-actor-call":
        "bare call on a serve tier-replica target bypasses the "
        "failover wrapper (replica death raises unsupervised)",
    "unkeyed-tenant-cache":
        "prefix-cache lookup in LoRA-aware code without the tenant in "
        "the key (one tenant's cached KV could serve another)",
    "undonated-pool-write":
        "write into a pool-shaped device stack outside a donated jit "
        "(copies the whole pool per write instead of O(row) in place)",
    "host-sync-in-jit":
        "host synchronization (.item() / device_get / print) inside a "
        "jitted function",
    "sync-io-in-gateway-handler":
        "synchronous decode call (.generate(...) / .decode_from(...)) "
        "inside an async HTTP handler freezes every stream on the "
        "gateway's event loop",
    "lock-discipline":
        "in a lock-using class, a self._* attribute mutated both "
        "under `with self._lock` and outside it — a data race "
        "candidate, both sites cited",
    "surface-parity":
        "a conductor subsystem missing part of the full surface "
        "treatment (state accessor == CLI == dashboard == Prometheus "
        "== timeline lane)",
    "env-knob-inconsistent-default":
        "one RAY_TPU_* knob parsed with different literal defaults at "
        "different sites",
    "env-knob-hot-path":
        "RAY_TPU_* knob parsed inside a loop / per-tick path without "
        "the cached-env pattern",
    "env-knob-undocumented":
        "RAY_TPU_* knob read in code but absent from the README knob "
        "table",
    "undonated-jit-pool-arg":
        "jitted function updates a pool/cache/slab-shaped argument "
        "without donate_argnums (O(pool) copy per call instead of "
        "O(row) in place)",
}


@dataclass(frozen=True)
class Finding:
    """One analyzer finding. `rule` is the stable id from RULES."""

    rule: str
    severity: str
    location: str
    message: str
    fix_hint: str = ""

    def __post_init__(self):
        if self.severity not in _RANK:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got "
                f"{self.severity!r}")

    def to_dict(self) -> Dict[str, str]:
        return {"rule": self.rule, "severity": self.severity,
                "location": self.location, "message": self.message,
                "fix_hint": self.fix_hint}

    def __str__(self) -> str:
        hint = f"  [fix: {self.fix_hint}]" if self.fix_hint else ""
        return (f"{self.severity.upper():<7} {self.rule:<22} "
                f"{self.location}: {self.message}{hint}")


def at_least(findings: Iterable[Finding], severity: str) -> List[Finding]:
    """Findings at `severity` or more severe."""
    cut = _RANK[severity]
    return [f for f in findings if _RANK[f.severity] <= cut]


def errors(findings: Iterable[Finding]) -> List[Finding]:
    return at_least(findings, ERROR)


def max_severity(findings: Iterable[Finding]) -> str:
    """Most severe level present; INFO for an empty list."""
    ranks = [_RANK[f.severity] for f in findings]
    return SEVERITIES[min(ranks)] if ranks else INFO


def sort_findings(findings: Iterable[Finding]) -> List[Finding]:
    return sorted(findings,
                  key=lambda f: (_RANK[f.severity], f.location, f.rule))


def format_report(findings: Sequence[Finding]) -> str:
    """Human report: findings most-severe first plus a summary line."""
    lines = [str(f) for f in sort_findings(findings)]
    counts = {s: sum(1 for f in findings if f.severity == s)
              for s in SEVERITIES}
    lines.append(f"{len(findings)} finding(s): {counts[ERROR]} error, "
                 f"{counts[WARNING]} warning, {counts[INFO]} info")
    return "\n".join(lines)


__all__ = ["Finding", "RULES", "SEVERITIES", "ERROR", "WARNING", "INFO",
           "at_least", "errors", "max_severity", "sort_findings",
           "format_report"]
