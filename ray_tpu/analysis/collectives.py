"""Static collective-cost analysis — which collectives a function runs,
over which mesh axes, and how many bytes would cross the slow DCN links.

The trace is abstract: `jax.make_jaxpr` over `jax.ShapeDtypeStruct`
inputs never touches a device, and `shard_map` programs trace against a
`jax.sharding.AbstractMesh` built from the `MeshLayout`, so a multi-slice
pod layout is analyzable on a dev box with zero accelerators. Explicit
collectives (`psum` / `all_gather` / `all_to_all` / `ppermute` /
`reduce_scatter` — the shard_map vocabulary this repo's pipeline, ring
attention, and MoE paths use) appear as jaxpr primitives carrying their
axis names; the walker recurses through pjit/scan/cond sub-jaxprs to find
them all.

Cost model (ring algorithms, DCN share only): for a collective over axes
with total size n and DCN span d (product of `MeshLayout.dcn_factors`),
the bytes that must cross a slice boundary are

    psum            2 * B * (d-1)/d      (reduce-scatter + all-gather)
    all_gather      B * n * (d-1)/d      (output is n times the input)
    reduce_scatter  B * (d-1)/d
    all_to_all      B * (d-1)/d          (uniform shuffle)
    ppermute        B                    (upper bound: every hop DCN)

"Exploring the limits of Concurrency in ML Training on Google TPUs"
(arXiv:2011.03641) measures the ICI/DCN bandwidth asymmetry that makes
these bytes dominate multi-slice step time — hence severity: collectives
over the declared DCN axes (dp/fsdp/pp, `multislice.DCN_AXES`) are INFO
(that placement is the hybrid design), while tp/sp/ep spanning DCN is a
WARNING: those axes are ICI-bandwidth-hungry and a layout that stretches
them across slices is almost always a mistake.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Sequence, Tuple

import numpy as np

from ..parallel.multislice import DCN_AXES
from .findings import Finding, INFO, WARNING
from .shardcheck import MeshLayout, _nbytes

# Mesh axes whose collectives must stay on ICI (model parallelism).
HEAVY_AXES = ("tp", "sp", "ep")

#: primitive name -> fn(bytes, n, d) -> bytes over DCN
_COST_MODEL: Dict[str, Callable[[float, int, int], float]] = {
    "psum": lambda b, n, d: 2.0 * b * (d - 1) / d,
    "all_gather": lambda b, n, d: float(b) * n * (d - 1) / d,
    "all_gather_invariant": lambda b, n, d: float(b) * n * (d - 1) / d,
    "reduce_scatter": lambda b, n, d: float(b) * (d - 1) / d,
    "all_to_all": lambda b, n, d: float(b) * (d - 1) / d,
    "ppermute": lambda b, n, d: float(b),
    "pmin": lambda b, n, d: 2.0 * b * (d - 1) / d,
    "pmax": lambda b, n, d: 2.0 * b * (d - 1) / d,
    # jax 0.4.x traces psum as psum2 under check_rep — same ring cost
    "psum2": lambda b, n, d: 2.0 * b * (d - 1) / d,
}

# Named-axis primitives that move no payload (replication/VMA
# bookkeeping and index queries; pbroadcast is jax 0.4.x's check_rep
# marker, pvary the newer name): never collected, never costed.
_NON_COMM = frozenset({"pvary", "pbroadcast", "axis_index"})


@dataclass(frozen=True)
class CollectiveUse:
    """One collective equation found in the trace."""

    primitive: str
    axes: Tuple[str, ...]
    in_bytes: int

    def modeled(self) -> bool:
        """False for a collective the cost table doesn't cover — its
        byte estimates fall back to the raw input size (an upper-ish
        bound with no ring discount), and `check_collectives` emits an
        `unmodeled-collective` INFO finding naming it so oracle
        predictions surface the blind spot instead of absorbing it."""
        return self.primitive in _COST_MODEL

    def spans(self, layout: MeshLayout) -> Tuple[int, int]:
        """(n, d): total participant count over this use's axes and its
        DCN span (1 = entirely on ICI)."""
        n = int(np.prod([layout.axis_size(a) for a in self.axes],
                        dtype=np.int64)) or 1
        d = int(np.prod([layout.dcn_factor(a) for a in self.axes],
                        dtype=np.int64)) or 1
        return n, d

    def link_bytes(self, layout: MeshLayout) -> Tuple[float, float]:
        """(ici_bytes, dcn_bytes): the per-chip ring traffic split by
        link class — one spans() evaluation for both shares (the
        oracle's comms numerators)."""
        n, d = self.spans(layout)
        if n <= 1:
            return 0.0, 0.0
        total = self._ring_share(n, n)  # span=n makes every hop count
        dcn = self._ring_share(n, d) if d > 1 else 0.0
        return max(0.0, total - dcn), dcn

    def dcn_bytes(self, layout: MeshLayout) -> float:
        return self.link_bytes(layout)[1]

    def ring_bytes(self, layout: MeshLayout) -> float:
        """Total per-chip ring traffic over ALL links."""
        ici, dcn = self.link_bytes(layout)
        return ici + dcn

    def _ring_share(self, n: int, span: int) -> float:
        model = _COST_MODEL.get(self.primitive)
        return model(self.in_bytes, n, span) if model \
            else float(self.in_bytes)


def _axis_names(params: Dict[str, Any]) -> Tuple[str, ...]:
    raw = params.get("axes", params.get("axis_name", ()))
    if raw is None:
        return ()
    if isinstance(raw, (tuple, list)):
        return tuple(a for a in raw if isinstance(a, str))
    return (raw,) if isinstance(raw, str) else ()


def _walk_jaxpr(jaxpr: Any, out: List[CollectiveUse]) -> None:
    try:
        from jax.extend.core import ClosedJaxpr, Jaxpr
    except ImportError:  # jax < 0.4.38
        from jax.core import ClosedJaxpr, Jaxpr
    def _sub_jaxprs(params):
        subs = []
        for v in params.values():
            for item in v if isinstance(v, (tuple, list)) else (v,):
                if isinstance(item, ClosedJaxpr):
                    subs.append(item.jaxpr)
                elif isinstance(item, Jaxpr):
                    subs.append(item)
        return subs

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        subs = _sub_jaxprs(eqn.params)
        # Any primitive carrying named mesh axes is a collective to the
        # walker — including ones the cost table does not model yet
        # (collected so `check_collectives` can NAME the blind spot
        # instead of the byte estimate silently falling back). Call-like
        # primitives (pjit / scan / xla_pmap — anything wrapping a
        # sub-jaxpr) are NOT collectives even when they carry an
        # axis_name: their bodies are priced by the recursion below,
        # counting the wrapper too would double-charge the whole input.
        if name not in _NON_COMM and not subs:
            axes = _axis_names(eqn.params)
            if axes:
                nbytes = sum(_nbytes(v.aval) for v in eqn.invars
                             if hasattr(v, "aval"))
                out.append(CollectiveUse(name, axes, nbytes))
        for sub in subs:
            _walk_jaxpr(sub, out)


def scan_collectives(fn: Callable, *abstract_args: Any,
                     **abstract_kwargs: Any) -> List[CollectiveUse]:
    """Trace `fn` abstractly and return every collective it runs.
    Arguments are abstract (ShapeDtypeStruct / eval_shape outputs); no
    device is touched."""
    import jax

    uses: List[CollectiveUse] = []
    jaxpr = jax.make_jaxpr(fn)(*abstract_args, **abstract_kwargs)
    _walk_jaxpr(jaxpr.jaxpr, uses)
    return uses


def abstract_mesh(layout: MeshLayout) -> Any:
    """A `jax.sharding.AbstractMesh` with the layout's axis names/sizes —
    shard_map programs trace against it with no devices. Returns None on
    jax versions without AbstractMesh (callers fall back to a real
    mesh or skip the collective scan)."""
    import jax

    cls = getattr(jax.sharding, "AbstractMesh", None)
    if cls is None:
        return None
    items = tuple(layout.axis_sizes.items())
    try:
        return cls(tuple((name, size) for name, size in items))
    except TypeError:
        # newer signature: AbstractMesh(axis_sizes, axis_names)
        return cls(tuple(s for _, s in items), tuple(n for n, _ in items))


def _fmt_bytes(n: float) -> str:
    for unit, div in (("GiB", 2 ** 30), ("MiB", 2 ** 20), ("KiB", 2 ** 10)):
        if n >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n:.0f} B"


def check_collectives(layout: MeshLayout, uses: Sequence[CollectiveUse],
                      where: str = "") -> List[Finding]:
    """Findings for collectives that cross DCN. Heavy axes (tp/sp/ep)
    over DCN are warnings; the declared DCN axes (dp/fsdp/pp) are info —
    routing those over DCN is the hybrid-mesh design, the finding just
    carries the bytes estimate."""
    findings: List[Finding] = []
    loc = where or layout.name
    for use in uses:
        if not use.modeled():
            findings.append(Finding(
                "unmodeled-collective", INFO, loc,
                f"{use.primitive} over {use.axes} has no entry in the "
                "collective cost model — byte estimates fall back to "
                f"its raw input size ({_fmt_bytes(float(use.in_bytes))})"
                " and oracle step-time predictions treat it as opaque",
                "add the primitive to analysis.collectives._COST_MODEL"))
        dcn_axes = [a for a in use.axes if layout.dcn_factor(a) > 1]
        if not dcn_axes:
            continue
        heavy = [a for a in dcn_axes if a in HEAVY_AXES]
        cost = _fmt_bytes(use.dcn_bytes(layout))
        if heavy:
            findings.append(Finding(
                "collective-over-dcn", WARNING, loc,
                f"{use.primitive} over {use.axes} crosses DCN on the "
                f"ICI-hungry axis(es) {tuple(heavy)} — est. {cost} "
                "over DCN per call",
                f"keep {tuple(heavy)} inside a slice: put the cross-"
                f"slice parallelism on {tuple(DCN_AXES)} "
                "(HybridMeshConfig dcn_dp/dcn_fsdp/dcn_pp)"))
        elif layout.declared_dcn:
            findings.append(Finding(
                "collective-over-dcn", INFO, loc,
                f"{use.primitive} over {use.axes} rides DCN by design — "
                f"est. {cost} over DCN per call"))
        else:
            # data-like axis crossing slices on a FLAT mesh: acceptable
            # placement, but nobody declared it — say so
            findings.append(Finding(
                "collective-over-dcn", INFO, loc,
                f"{use.primitive} over {use.axes} crosses DCN on a flat "
                f"mesh (nothing declared this placement) — est. {cost} "
                "over DCN per call",
                "declare the cross-slice placement explicitly: "
                "HybridMeshConfig dcn_dp/dcn_fsdp/dcn_pp"))
    return findings


def estimate_training_dcn_traffic(layout: MeshLayout,
                                  abstract_params: Any) -> float:
    """Per-step gradient-sync bytes over DCN for a data-parallel training
    layout: every param's gradient is psum'd over the data axes, so the
    DCN share is 2 * bytes * (d-1)/d with d the dp/fsdp DCN span (the
    total ring-allreduce traffic is independent of how the params
    themselves are sharded)."""
    import jax

    d = layout.dcn_factor("dp") * layout.dcn_factor("fsdp")
    if d <= 1:
        return 0.0
    total = sum(_nbytes(leaf)
                for leaf in jax.tree_util.tree_leaves(abstract_params))
    return 2.0 * total * (d - 1) / d


__all__ = ["CollectiveUse", "HEAVY_AXES", "abstract_mesh",
           "check_collectives", "estimate_training_dcn_traffic",
           "scan_collectives"]
