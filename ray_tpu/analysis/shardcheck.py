"""Static PartitionSpec / mesh validation — the "shard" half of shardlint.

Everything here is deviceless: a `MeshLayout` is just named axis sizes
plus per-axis DCN factors (from `multislice.dcn_axis_factors`), and the
arrays are abstract (`jax.ShapeDtypeStruct` / anything with .shape and
.dtype, e.g. the output of `jax.eval_shape`). That means a pod layout can
be linted on a laptop before a single chip is reserved.

Rules:
- unknown-axis        spec names an axis the mesh does not have (error)
- rank-exceeds-ndim   spec longer than the array's rank (error)
- non-dividing-dim    axis size does not divide the sharded dim (error)
- duplicate-axis      one mesh axis on two dims of the same spec (error)
- replicated-large-param  param above the byte threshold with every
                      sharding axis of size 1 — a full copy per device
                      (warning)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..parallel.mesh import MESH_AXES, MeshConfig
from ..parallel.multislice import (HybridMeshConfig, SliceTopology,
                                   dcn_axis_factors)
from .findings import ERROR, Finding, WARNING

# Default HBM blow-up threshold: a fully-replicated param larger than this
# many bytes is flagged. 64 MiB ≈ a GPT-2-small embedding in bf16.
DEFAULT_REPLICATED_THRESHOLD = 64 * 2 ** 20


@dataclass(frozen=True)
class MeshLayout:
    """Deviceless mesh description: axis name -> size, axis name -> DCN
    span factor (1 = the axis lives entirely on ICI)."""

    axis_sizes: Dict[str, int]
    dcn_factors: Dict[str, int] = field(default_factory=dict)
    name: str = "mesh"
    # True when the DCN placement was DECLARED (HybridMeshConfig dcn_*)
    # rather than discovered by stride analysis of a flat mesh — the
    # collective findings word themselves accordingly.
    declared_dcn: bool = False

    @staticmethod
    def from_config(config: MeshConfig, n_devices: int,
                    num_slices: int = 1, name: str = "") -> "MeshLayout":
        if isinstance(config, HybridMeshConfig) and num_slices > 1:
            per_slice = n_devices // num_slices
            ici = config.sizes(per_slice)
            dcn = config.dcn_sizes(num_slices)
            sizes = {a: ici[a] * dcn[a] for a in MESH_AXES}
        else:
            sizes = config.sizes(n_devices)
        return MeshLayout(
            axis_sizes=sizes,
            dcn_factors=dcn_axis_factors(config, n_devices, num_slices),
            name=name or type(config).__name__,
            declared_dcn=isinstance(config, HybridMeshConfig))

    @staticmethod
    def from_mesh(mesh: Any,
                  topology: Optional[SliceTopology] = None,
                  name: str = "") -> "MeshLayout":
        """Layout of a built `jax.sharding.Mesh`. With a SliceTopology the
        DCN factors are EXACT: each device maps to its slice and the span
        of every axis is counted on the actual device array (works for
        hybrid block assembly and topology-optimized orders alike)."""
        sizes = dict(mesh.shape)
        factors = {a: 1 for a in sizes}
        if topology is not None and topology.num_slices > 1:
            slice_of = {d: i for i, s in enumerate(topology.slices)
                        for d in s}
            ids = np.vectorize(lambda d: slice_of[d])(
                np.asarray(mesh.devices, dtype=object))
            for i, a in enumerate(mesh.axis_names):
                lines = np.moveaxis(ids, i, -1).reshape(-1, ids.shape[i])
                factors[a] = max(len(set(line)) for line in lines)
        return MeshLayout(axis_sizes=sizes, dcn_factors=factors,
                          name=name or "mesh")

    def axis_size(self, axis: str) -> int:
        return self.axis_sizes.get(axis, 1)

    def dcn_factor(self, axis: str) -> int:
        return self.dcn_factors.get(axis, 1)

    def dcn_axes(self) -> List[str]:
        return [a for a in self.axis_sizes
                if self.dcn_factors.get(a, 1) > 1]


def spec_entries(spec: Any) -> List[Tuple[Any, ...]]:
    """Normalize a PartitionSpec-like into per-dim tuples of axis names:
    P('dp', ('fsdp','tp'), None) -> [('dp',), ('fsdp','tp'), ()]."""
    out: List[Tuple[Any, ...]] = []
    for entry in tuple(spec):
        if entry is None:
            out.append(())
        elif isinstance(entry, (tuple, list)):
            out.append(tuple(entry))
        else:
            out.append((entry,))
    return out


def _nbytes(aval: Any) -> int:
    shape = tuple(getattr(aval, "shape", ()) or ())
    dtype = np.dtype(getattr(aval, "dtype", np.float32))
    return int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape \
        else dtype.itemsize


def check_spec(spec: Any, aval: Any, layout: MeshLayout,
               where: str = "") -> List[Finding]:
    """Validate one PartitionSpec against one abstract array."""
    findings: List[Finding] = []
    loc = where or "spec"
    entries = spec_entries(spec)
    shape = tuple(getattr(aval, "shape", ()) or ())

    if len(entries) > len(shape):
        findings.append(Finding(
            "rank-exceeds-ndim", ERROR, loc,
            f"spec {spec} has {len(entries)} entries for a rank-"
            f"{len(shape)} array of shape {shape}",
            "drop the extra entries (trailing dims default to "
            "replicated)"))
        entries = entries[:len(shape)]

    seen: Dict[str, int] = {}
    for dim, axes in enumerate(entries):
        for ax in axes:
            if ax not in layout.axis_sizes:
                findings.append(Finding(
                    "unknown-axis", ERROR, loc,
                    f"spec {spec} names axis {ax!r} which is not in the "
                    f"mesh (axes: {tuple(layout.axis_sizes)})",
                    f"use one of the canonical MESH_AXES {MESH_AXES}"))
                continue
            if ax in seen:
                findings.append(Finding(
                    "duplicate-axis", ERROR, loc,
                    f"spec {spec} uses mesh axis {ax!r} on both dim "
                    f"{seen[ax]} and dim {dim}",
                    "an axis may shard at most one dim; compose with a "
                    "second axis instead"))
                continue
            seen[ax] = dim
        group = int(np.prod([layout.axis_size(a) for a in axes
                             if a in layout.axis_sizes], dtype=np.int64)) \
            if axes else 1
        if group > 1 and shape[dim] % group != 0:
            findings.append(Finding(
                "non-dividing-dim", ERROR, loc,
                f"dim {dim} of shape {shape} is {shape[dim]}, not "
                f"divisible by the sharding group {axes} of size {group}",
                "pad the dim to a multiple (cf. GPT2Config."
                "vocab_pad_multiple) or reshard on a smaller axis"))
    return findings


def _is_replicated(spec: Any, layout: MeshLayout) -> bool:
    """True when every device holds the full array: all named axes (after
    dropping unknown ones) have size 1."""
    for axes in spec_entries(spec):
        for ax in axes:
            if layout.axis_size(ax) > 1:
                return False
    return True


def check_specs(spec_tree: Any, abstract_tree: Any, layout: MeshLayout,
                replicated_threshold: int = DEFAULT_REPLICATED_THRESHOLD,
                where: str = "params") -> List[Finding]:
    """Validate a PartitionSpec pytree against a matching abstract-array
    pytree (e.g. `gpt2_partition_specs(cfg)` vs `jax.eval_shape` of the
    init). Adds the replicated-large-param HBM check on top of the
    per-leaf spec checks."""
    import jax

    is_spec = _spec_leaf_predicate()
    spec_leaves = jax.tree_util.tree_flatten_with_path(
        spec_tree, is_leaf=is_spec)[0]
    aval_leaves = jax.tree_util.tree_flatten(abstract_tree)[0]
    if len(spec_leaves) != len(aval_leaves):
        raise ValueError(
            f"spec tree has {len(spec_leaves)} leaves but abstract tree "
            f"has {len(aval_leaves)} — the trees must match")

    findings: List[Finding] = []
    for (path, spec), aval in zip(spec_leaves, aval_leaves):
        loc = where + jax.tree_util.keystr(path)
        leaf_findings = check_spec(spec, aval, layout, where=loc)
        findings.extend(leaf_findings)
        if any(f.rule == "unknown-axis" for f in leaf_findings):
            # the user DID try to shard this leaf — a replication
            # warning on top of the typo'd-axis error would misdirect
            continue
        nbytes = _nbytes(aval)
        if nbytes >= replicated_threshold and _is_replicated(spec, layout):
            mib = nbytes / 2 ** 20
            findings.append(Finding(
                "replicated-large-param", WARNING, loc,
                f"{mib:.1f} MiB param is fully replicated — every device "
                f"holds a complete copy (threshold "
                f"{replicated_threshold / 2 ** 20:.0f} MiB)",
                "shard it: infer_fsdp_specs() or a 'tp' dim spec"))
    return findings


def _spec_leaf_predicate():
    from jax.sharding import PartitionSpec
    return lambda x: isinstance(x, PartitionSpec)


__all__ = ["MeshLayout", "DEFAULT_REPLICATED_THRESHOLD", "check_spec",
           "check_specs", "spec_entries"]
