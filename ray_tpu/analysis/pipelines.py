"""Schedule-aware pipeline lint: the analytic bubble-fraction estimate
(rule ``pipeline-bubble``, the ROADMAP-named shardlint follow-up).

Pure stdlib — usable wherever the AST lint is, no jax required. Both
pipeline execution models report through here: the SPMD GPipe transform
(``parallel.pipeline`` via the layout analysis) and the MPMD stage-gangs
(``ray_tpu.mpmd`` — ``PipelineConductor.form`` lints its schedule before
spawning a single actor).

The estimate: with S stages and M microbatches, every stage idles for
S-1 of the M+S-1 tick slots — (S-1)/(M+S-1) for GPipe's fill-drain, and
the identical warm-up + cool-down bubble for non-interleaved 1F1B (1F1B
bounds activation memory at O(S); it does not shrink the bubble). Above
20% the finding escalates to a warning with the M >= 4*S sizing rule
from ``parallel/pipeline.py``'s docstring as the fix hint.
"""
from __future__ import annotations

from typing import List

from .findings import Finding, INFO, WARNING

#: schedules the estimator knows; both share the warm-up bubble
PIPELINE_SCHEDULES = ("gpipe", "1f1b")

#: estimates above this fraction escalate INFO -> WARNING
BUBBLE_WARN_FRACTION = 0.20


def estimate_bubble_fraction(schedule: str, num_stages: int,
                             num_microbatches: int) -> float:
    """(S-1)/(M+S-1): GPipe's fill-drain bubble and 1F1B's equal
    warm-up/cool-down bubble."""
    s, m = int(num_stages), int(num_microbatches)
    if s < 1 or m < 1:
        raise ValueError(
            f"need num_stages >= 1 and num_microbatches >= 1, got "
            f"S={num_stages} M={num_microbatches}")
    if schedule not in PIPELINE_SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                         f"one of {sorted(PIPELINE_SCHEDULES)}")
    return (s - 1) / (m + s - 1)


def check_pipeline_schedule(num_stages: int, num_microbatches: int,
                            schedule: str = "gpipe", *,
                            where: str = "") -> List[Finding]:
    """Findings for one pipeline schedule: always an INFO naming the
    estimate, escalated to WARNING past ``BUBBLE_WARN_FRACTION`` with
    the M >= 4*S fix hint."""
    frac = estimate_bubble_fraction(schedule, num_stages,
                                    num_microbatches)
    s, m = int(num_stages), int(num_microbatches)
    loc = where or f"pipeline/{schedule}"
    label = ("1F1B warm-up bubble" if schedule == "1f1b"
             else "GPipe fill-drain bubble")
    msg = (f"{label}: est. {frac:.1%} idle per stage "
           f"((S-1)/(M+S-1) with S={s} stages, M={m} microbatches)")
    if frac > BUBBLE_WARN_FRACTION:
        return [Finding(
            "pipeline-bubble", WARNING, loc,
            msg + f" — exceeds {BUBBLE_WARN_FRACTION:.0%}",
            fix_hint=f"choose M >= 4*S (here M >= {4 * s}) to keep the "
                     "bubble under ~20% (parallel/pipeline.py)")]
    return [Finding("pipeline-bubble", INFO, loc, msg)]


__all__ = ["BUBBLE_WARN_FRACTION", "PIPELINE_SCHEDULES",
           "check_pipeline_schedule", "estimate_bubble_fraction"]
