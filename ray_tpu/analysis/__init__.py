"""ray_tpu.analysis — shardlint: static sharding, collective-cost, and
actor-code analysis.

The runtime's thesis makes distributed bugs statically decidable: sharding
is declarative (named mesh axes + PartitionSpecs), multi-slice placement
is declarative (`HybridMeshConfig` / `multislice.DCN_AXES`), and actor
code is plain Python. So before a single chip is reserved, this package
catches:

- PartitionSpecs that cannot work: unknown axis names, rank overflow,
  axis sizes that do not divide array dims, one axis on two dims
  (`shardcheck`, via `jax.eval_shape` — no devices needed);
- HBM blow-ups: large params left fully replicated (`shardcheck`);
- bandwidth-heavy collectives routed over slow DCN links, with a
  bytes-over-DCN estimate per layout (`collectives`, jaxpr inspection
  against an `AbstractMesh`);
- event-loop stalls: blocking calls inside `async def` actor/serve
  methods, and host syncs inside jitted functions (`astlint`);
- cross-module invariants (`invariants`): lock-discipline races
  (a `self._*` attr mutated both under `with self._lock` and bare),
  conductor↔CLI↔dashboard↔metrics↔timeline surface-parity drift,
  the env-knob registry (`RAY_TPU_*` reads — hot-path re-parses,
  inconsistent defaults, undocumented knobs), and jitted pool updaters
  missing `donate_argnums`.

Surfaces: `python -m ray_tpu analyze` (CLI), the dryrun path in
`__graft_entry__.py` (every hybrid layout is linted before it runs), and
`TrainStep.init_state` (spec errors raise before compilation).

`findings` and `astlint` are dependency-free (pure stdlib): the AST lint
runs even where jax is broken or absent. The jax-backed halves
(shardcheck/collectives/layouts) load lazily on first attribute access
(PEP 562), so `from ray_tpu.analysis import lint_path` costs no jax
import.
"""
from .findings import (  # noqa: F401
    ERROR,
    Finding,
    INFO,
    RULES,
    SEVERITIES,
    WARNING,
    at_least,
    errors,
    format_report,
    max_severity,
    sort_findings,
)
from .astlint import lint_file, lint_path, lint_source  # noqa: F401
from .invariants import (  # noqa: F401
    PARITY_WAIVERS,
    SURFACE_ALIASES,
    analyze_invariants,
    check_env_knobs,
    check_surface_parity,
    collect_env_reads,
    discover_subsystems,
    format_knob_table,
    knob_table,
    scan_env_reads,
)
from .pipelines import (  # noqa: F401
    BUBBLE_WARN_FRACTION,
    PIPELINE_SCHEDULES,
    check_pipeline_schedule,
    estimate_bubble_fraction,
)

# name -> submodule for the jax-dependent surface, resolved on demand.
_LAZY = {
    "DEFAULT_REPLICATED_THRESHOLD": "shardcheck",
    "MeshLayout": "shardcheck",
    "check_spec": "shardcheck",
    "check_specs": "shardcheck",
    "CollectiveUse": "collectives",
    "HEAVY_AXES": "collectives",
    "abstract_mesh": "collectives",
    "check_collectives": "collectives",
    "estimate_training_dcn_traffic": "collectives",
    "scan_collectives": "collectives",
    "BUILTIN_LAYOUTS": "layouts",
    "LayoutTrace": "layouts",
    "analyze_builtin_layouts": "layouts",
    "analyze_layout": "layouts",
    "trace_builtin_layouts": "layouts",
}


def __getattr__(name):
    submodule = _LAZY.get(name)
    if submodule is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module("." + submodule, __name__),
                   name)


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
