"""AST lint — the "actor code" half of shardlint.

Two rule families, both pure `ast` walks (no imports of the linted code,
so broken or dependency-heavy modules still lint):

- blocking-in-async (error): a blocking call — `time.sleep`,
  sync `ray_tpu.get` / `ray.get`, or `.get()` on a `queue.Queue` bound in
  the same scope — lexically inside an `async def`. One blocking call
  freezes the actor's entire event loop: every other coroutine on that
  replica stalls ("Scaling Deep Learning Training with MPMD Pipeline
  Parallelism" shows exactly this class of stall deadlocking stage
  handoffs). Nested sync `def`s are their own execution context and are
  not flagged.
- host-sync-in-jit (error for `.item()` / `jax.device_get`, warning for
  `print`): host synchronization inside a function that is jitted —
  decorated with `@jax.jit` / `@functools.partial(jax.jit, ...)` or
  passed to a `jax.jit(...)` call in the same file. `.item()` on a tracer
  aborts tracing; `print` runs at trace time and shows a tracer, not the
  value (fix: `jax.debug.print`).
- unsupervised-actor-call (info): in modules using serve.disagg's
  ``_call`` dispatch helper, a bare ``_call(<replica>.target, ...)`` /
  ``_call(<replica>["target"], ...)`` outside the router's
  ``_tier_call`` failover wrapper. The wrapper is what turns a replica
  death into corpse removal + bounded failover; a bare call raises the
  raw ActorDiedError to the caller, silently dropping the request's
  fault-tolerance guarantee. Advisory: call sites that are already
  supervised (probe loops in try/except, fire-and-forget acks) suppress
  with a justification comment.

- unkeyed-tenant-cache (info): in LoRA-aware modules (anything
  importing from serve.lora), a prefix-cache ``.lookup(...)`` without
  a ``namespace=`` keyword. The paged KV cache keys prefixes by
  (namespace, prompt) so KV prefilled under one tenant's adapter can
  never serve another tenant's request; a tenant-blind lookup in a
  multi-tenant code path silently reintroduces exactly that leak.

- undonated-pool-write (warning): a write into a pool-named device
  stack — ``<pool>.at[...].set/add(...)`` or
  ``dynamic_update_slice(<pool>, ...)`` — OUTSIDE a function jitted
  with ``donate_argnums``. The repo's pool discipline
  (models/kvcache.py, serve/lora.py) is that every mutation of a
  ``[L, num_blocks, ...]`` / ``[slots, ...]``-shaped pool goes through
  a donated jit so XLA updates O(row) in place; an undonated write
  copies the WHOLE pool per call — invisible at toy sizes, wrong at
  64-slot x 32-layer production scale.

- sync-io-in-gateway-handler (info): in aiohttp-serving modules
  (anything importing aiohttp — the HTTP front door in
  serve/gateway.py, the dashboard, serve proxies), a synchronous
  decode call — ``<anything>.generate(...)`` or
  ``<anything>.decode_from(...)`` — lexically inside an ``async def``.
  A router/engine decode blocks for the request's ENTIRE decode
  (seconds), freezing every concurrent SSE stream on that gateway's
  single event loop; the gateway discipline is to run decodes on the
  executor (a nested sync ``def work():`` is its own scope and is not
  flagged) and bridge tokens back through the loop. ``time.sleep`` in
  the same position is already the blocking-in-async ERROR. Advisory:
  a provably-instant call suppresses with a justification comment.

- unpropagated-request-context (info): in modules importing the
  request-trace API (observability/requests.py), a cross-tier serve
  dispatch — ``_tier_call(<replica>, <tier>, "prefill"/"start_decode",
  ...)`` or ``_call(<target>, "prefill"/"start_decode", ...)`` — inside
  a function scope that never touches the trace API. The flight
  recorder attributes tail latency per phase ONLY for hops recorded
  under the request's id; a serve dispatch from a trace-blind scope
  drops the context, so that hop's time silently vanishes from the
  p99-attribution report. Advisory: dispatches that are genuinely
  requestless (warmup, health probes) suppress with a justification.

- unregistered-prefix-publish (info): in KV-plane-aware modules
  (anything importing serve.kvplane or models.kvcache), an
  ``<cache>.export_prefix(...)`` call in a function scope that never
  registers the result — no ``kvplane_publish`` conductor commit and
  no ``publish_prefix`` helper call in the same scope. An exported
  prefix pushed into the chunk fabric without the directory commit is
  invisible to every other replica (nothing can ever look it up) while
  its chunk refs pin host memory until the holder dies — the worst of
  both tiers. The sanctioned path is serve/kvplane.publish_prefix,
  which pairs the export with the atomic directory commit. Advisory:
  genuinely local exports (tests, offline serialization) suppress with
  a justification comment.

Suppression: append `# shardlint: ok` to the flagged line, or
`# shardlint: disable=<rule-id>` to suppress one rule on that line.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .findings import ERROR, Finding, INFO, WARNING

# Module-attribute calls that block the calling thread.
_BLOCKING_ATTRS: Dict[Tuple[str, str], str] = {
    ("time", "sleep"): "await asyncio.sleep(...) instead",
    ("ray_tpu", "get"): "await on a thread: "
                        "loop.run_in_executor(None, ray_tpu.get, ref)",
    ("ray", "get"): "await on a thread: "
                    "loop.run_in_executor(None, ray.get, ref)",
}

_SUPPRESS_RE = re.compile(
    r"#\s*shardlint:\s*"
    r"(ok(?:=[a-z0-9-]+)?|disable=([a-z0-9-]+(?:\s*,\s*[a-z0-9-]+)*))")


def _suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    """line number -> None (suppress all) or set of suppressed rule ids."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        if m.group(1).startswith("ok"):
            # `ok` and the tagged `ok=<reason>` form (e.g. ok=lock-free)
            # both suppress every rule on the line; the tag is the
            # human-readable justification, not a rule filter.
            out[i] = None
        else:
            out[i] = {r.strip() for r in m.group(2).split(",")}
    return out


class _Aliases:
    """Import alias tracking: maps local names to canonical module names
    and remembers `from time import sleep`-style direct imports."""

    def __init__(self, tree: ast.AST):
        self.module_alias: Dict[str, str] = {}
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.module_alias[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = \
                        (node.module, a.name)

    def resolve_call(self, call: ast.Call) -> Optional[Tuple[str, str]]:
        """(module, attr) for `mod.attr(...)` and `from mod import attr`
        call forms; None otherwise."""
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            mod = self.module_alias.get(f.value.id)
            if mod:
                return (mod, f.attr)
        if isinstance(f, ast.Name) and f.id in self.from_imports:
            return self.from_imports[f.id]
        return None


def _queue_names(fn: ast.AST, aliases: _Aliases) -> Set[str]:
    """Names assigned a `queue.Queue(...)` (alias-aware) anywhere in the
    function — their `.get()` blocks."""
    names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):  # q: queue.Queue = Queue()
            targets = [node.target]
        else:
            continue
        if not isinstance(node.value, ast.Call):
            continue
        resolved = aliases.resolve_call(node.value)
        if resolved in {("queue", "Queue"), ("queue", "LifoQueue"),
                        ("queue", "PriorityQueue"),
                        ("multiprocessing", "Queue")}:
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
    return names


def _iter_scope_calls(fn: ast.AST):
    """Call nodes lexically in `fn`'s own execution scope: descends
    expressions and control flow but NOT nested def/async def/lambda
    (they run in their own context, possibly off-loop)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


# ------------------------------------------------------ blocking-in-async


def _lint_blocking_in_async(tree: ast.AST, aliases: _Aliases,
                            path: str) -> List[Finding]:
    findings: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        queues = _queue_names(fn, aliases)
        for call in _iter_scope_calls(fn):
            resolved = aliases.resolve_call(call)
            if resolved in _BLOCKING_ATTRS:
                mod, attr = resolved
                findings.append(Finding(
                    "blocking-in-async", ERROR,
                    f"{path}:{call.lineno}",
                    f"blocking {mod}.{attr}() inside "
                    f"'async def {fn.name}' stalls the event loop",
                    _BLOCKING_ATTRS[resolved]))
                continue
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr == "get" and \
                    isinstance(f.value, ast.Name) and f.value.id in queues:
                findings.append(Finding(
                    "blocking-in-async", ERROR,
                    f"{path}:{call.lineno}",
                    f"blocking {f.value.id}.get() (queue.Queue) inside "
                    f"'async def {fn.name}' stalls the event loop",
                    "use asyncio.Queue, or offload with "
                    "loop.run_in_executor"))
    return findings


# -------------------------------------------------------- host-sync-in-jit


def _is_jax_jit(node: ast.AST, aliases: _Aliases) -> bool:
    """True for expressions denoting jax.jit: `jax.jit`, `jit` imported
    from jax, or `functools.partial(jax.jit, ...)`."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            aliases.module_alias.get(node.value.id) == "jax" and \
            node.attr == "jit":
        return True
    if isinstance(node, ast.Name) and \
            aliases.from_imports.get(node.id) == ("jax", "jit"):
        return True
    if isinstance(node, ast.Call):
        resolved = aliases.resolve_call(node)
        if resolved and resolved[1] == "partial" and node.args:
            return _is_jax_jit(node.args[0], aliases)
        # jax.jit(...) used directly as a decorator factory
        return _is_jax_jit(node.func, aliases)
    return False


def _jitted_functions(tree: ast.AST,
                      aliases: _Aliases) -> List[ast.FunctionDef]:
    """Defs that are jitted: decorated with jax.jit (possibly through
    functools.partial) or referenced by name in a jax.jit(<name>, ...)
    call anywhere in the file. Name matching excludes class-body methods
    — `jax.jit(step)` refers to a plain function binding, and a
    same-named method elsewhere in the file must not be falsely flagged
    (decorated methods are still caught via their decorator)."""
    jit_called: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jax_jit(node.func, aliases):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name):
                    jit_called.add(arg.id)
    method_ids = {id(item) for node in ast.walk(tree)
                  if isinstance(node, ast.ClassDef)
                  for item in node.body
                  if isinstance(item, ast.FunctionDef)}
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        if (fn.name in jit_called and id(fn) not in method_ids) or \
                any(_is_jax_jit(d, aliases) for d in fn.decorator_list):
            out.append(fn)
    return out


def _lint_host_sync_in_jit(tree: ast.AST, aliases: _Aliases,
                           path: str) -> List[Finding]:
    findings: List[Finding] = []
    for fn in _jitted_functions(tree, aliases):
        for call in _iter_scope_calls(fn):
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr == "item" and \
                    not call.args:
                findings.append(Finding(
                    "host-sync-in-jit", ERROR, f"{path}:{call.lineno}",
                    f".item() inside jitted '{fn.name}' aborts tracing "
                    "(host sync on a tracer)",
                    "return the array and call .item() outside the jit"))
            elif aliases.resolve_call(call) == ("jax", "device_get"):
                findings.append(Finding(
                    "host-sync-in-jit", ERROR, f"{path}:{call.lineno}",
                    f"jax.device_get inside jitted '{fn.name}' forces a "
                    "host round-trip on a tracer",
                    "move the transfer outside the jitted function"))
            elif isinstance(f, ast.Name) and f.id == "print":
                findings.append(Finding(
                    "host-sync-in-jit", WARNING, f"{path}:{call.lineno}",
                    f"print() inside jitted '{fn.name}' runs at trace "
                    "time and shows a tracer, not values",
                    "use jax.debug.print(...) for runtime values"))
    return findings


# ------------------------------------------------- unsupervised-actor-call


def _is_tier_target(expr: ast.AST) -> bool:
    """`<anything>.target` or `<anything>["target"]` — the shapes a
    router-side replica handle takes (a `_TierReplica` object or its
    `snapshot()` dict)."""
    if isinstance(expr, ast.Attribute) and expr.attr == "target":
        return True
    return (isinstance(expr, ast.Subscript)
            and isinstance(expr.slice, ast.Constant)
            and expr.slice.value == "target")


def _lint_unsupervised_actor_call(tree: ast.AST, aliases: _Aliases,
                                  path: str) -> List[Finding]:
    """Active only in modules where serve.disagg's `_call` dispatch
    helper is in scope (defined locally, or imported from the disagg
    module) — everywhere else a `_call` name is someone else's
    function."""
    defines = any(isinstance(n, ast.FunctionDef) and n.name == "_call"
                  for n in ast.iter_child_nodes(tree))
    imp = aliases.from_imports.get("_call")
    imported = imp is not None and imp[1] == "_call" \
        and imp[0].endswith("disagg")
    if not (defines or imported):
        return []
    # every node lexically inside the sanctioned failover wrapper
    sanctioned = set()
    for fn in ast.walk(tree):
        if isinstance(fn, ast.FunctionDef) and fn.name == "_tier_call":
            sanctioned.update(id(n) for n in ast.walk(fn))
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or id(node) in sanctioned:
            continue
        f = node.func
        if not (isinstance(f, ast.Name) and f.id == "_call"):
            continue
        if not node.args or not _is_tier_target(node.args[0]):
            continue
        findings.append(Finding(
            "unsupervised-actor-call", INFO, f"{path}:{node.lineno}",
            "bare _call() on a tier-replica target bypasses the "
            "failover wrapper — a replica death here raises "
            "unsupervised to the caller",
            "route through DisaggRouter._tier_call, or suppress with "
            "a justification when the site is already supervised"))
    return findings


# --------------------------------------------------- unkeyed-tenant-cache


def _receiver_mentions_cache(expr: ast.AST) -> bool:
    """True when the call receiver's dotted chain names a cache
    (``kv_cache.lookup``, ``self.kv_cache.lookup``, ``cache.lookup``) —
    the shapes a prefix-cache handle takes in this tree."""
    while isinstance(expr, ast.Attribute):
        if "cache" in expr.attr.lower():
            return True
        expr = expr.value
    return isinstance(expr, ast.Name) and "cache" in expr.id.lower()


def _lint_unkeyed_tenant_cache(tree: ast.AST, aliases: _Aliases,
                               path: str) -> List[Finding]:
    """Active only in LoRA-aware modules — anywhere that imports from
    serve.lora (the adapter pool in scope means tenants exist in this
    code path). There, a prefix-cache ``.lookup(...)`` without a
    ``namespace=`` keyword hashes the prompt against the TENANT-BLIND
    root: KV prefilled under one tenant's adapter could silently serve
    another tenant's request. models/kvcache.py keys by (namespace,
    prompt) precisely so lora-aware callers pass the tenant."""
    lora_aware = any(
        mod.endswith("lora")
        for mod, _name in aliases.from_imports.values()
    ) or any(mod.endswith(".lora") or mod == "lora"
             for mod in aliases.module_alias.values())
    if not lora_aware:
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "lookup"):
            continue
        if not _receiver_mentions_cache(f.value):
            continue
        if any(kw.arg == "namespace" for kw in node.keywords):
            continue
        findings.append(Finding(
            "unkeyed-tenant-cache", INFO, f"{path}:{node.lineno}",
            "prefix-cache lookup in a LoRA-aware module without "
            "namespace= — one tenant's cached KV could serve another "
            "tenant's prompt",
            "pass namespace=<tenant> (and the same namespace to the "
            "paired commit()), or suppress with a justification when "
            "the code path is provably single-tenant"))
    return findings


# -------------------------------------------- sync-io-in-gateway-handler


_SYNC_DECODE_ATTRS = ("generate", "decode_from")


def _lint_sync_io_in_gateway_handler(tree: ast.AST, aliases: _Aliases,
                                     path: str) -> List[Finding]:
    """Active only in aiohttp-serving modules — importing aiohttp means
    async HTTP handlers share one event loop here. There, a synchronous
    decode call (``router.generate(...)``, ``server.decode_from(...)``)
    lexically inside an ``async def`` holds the loop for the whole
    decode: every other stream on the gateway stalls. Nested sync defs
    (the executor-offload idiom) are their own scope via
    _iter_scope_calls and stay clean."""
    aiohttp_aware = any(mod == "aiohttp" or mod.startswith("aiohttp.")
                        for mod in aliases.module_alias.values()) or any(
        mod == "aiohttp" or mod.startswith("aiohttp.")
        for mod, _name in aliases.from_imports.values())
    if not aiohttp_aware:
        return []
    findings: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for call in _iter_scope_calls(fn):
            f = call.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _SYNC_DECODE_ATTRS):
                continue
            findings.append(Finding(
                "sync-io-in-gateway-handler", INFO,
                f"{path}:{call.lineno}",
                f"synchronous .{f.attr}() inside "
                f"'async def {fn.name}' holds the gateway event loop "
                "for the whole decode — every concurrent stream "
                "stalls",
                "run the decode on the executor (nested sync def + "
                "run_in_executor / ThreadPoolExecutor.submit) and "
                "bridge tokens back via call_soon_threadsafe"))
    return findings


# --------------------------------------------------- undonated-pool-write


def _is_donating_jit(dec: ast.AST, aliases: _Aliases) -> bool:
    """True for decorators that jit WITH donation:
    ``functools.partial(jax.jit, donate_argnums=...)`` or
    ``jax.jit(..., donate_argnums=...)`` (donate_argnames counts)."""
    if not isinstance(dec, ast.Call) or not _is_jax_jit(dec, aliases):
        return False
    return any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in dec.keywords)


def _mentions_pool(expr: ast.AST) -> bool:
    """The receiver's dotted/subscripted chain names a pool
    (``self._pool_k``, ``pool_k``, ``pools["a"]``) — the shapes a
    device block/adapter pool takes in this tree."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        if isinstance(expr, ast.Attribute):
            if "pool" in expr.attr.lower():
                return True
        expr = expr.value
    return isinstance(expr, ast.Name) and "pool" in expr.id.lower()


def _lint_undonated_pool_write(tree: ast.AST, aliases: _Aliases,
                               path: str) -> List[Finding]:
    donated: Set[int] = set()
    for fn in ast.walk(tree):
        if isinstance(fn, ast.FunctionDef) and any(
                _is_donating_jit(d, aliases) for d in fn.decorator_list):
            donated.update(id(n) for n in ast.walk(fn))
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or id(node) in donated:
            continue
        f = node.func
        # <pool>.at[...].set(...) / .add(...): a copying scatter update
        if isinstance(f, ast.Attribute) and f.attr in ("set", "add") \
                and isinstance(f.value, ast.Subscript) \
                and isinstance(f.value.value, ast.Attribute) \
                and f.value.value.attr == "at" \
                and _mentions_pool(f.value.value.value):
            findings.append(Finding(
                "undonated-pool-write", WARNING, f"{path}:{node.lineno}",
                f"pool write via .at[...].{f.attr}() outside a donated "
                "jit copies the whole pool per call",
                "route the write through a donated-jit helper "
                "(donate_argnums on the pool) dispatched under the "
                "pool lock — the models/kvcache.py write discipline"))
            continue
        # dynamic_update_slice(<pool>, ...): same copy, lax spelling
        is_dus = (isinstance(f, ast.Attribute)
                  and f.attr == "dynamic_update_slice") or (
            isinstance(f, ast.Name) and f.id == "dynamic_update_slice")
        if is_dus and node.args and _mentions_pool(node.args[0]):
            findings.append(Finding(
                "undonated-pool-write", WARNING, f"{path}:{node.lineno}",
                "dynamic_update_slice on a pool outside a donated jit "
                "copies the whole pool per call",
                "wrap the update in a donated-jit helper "
                "(donate_argnums on the pool) so XLA lowers it to an "
                "in-place O(row) write"))
    return findings


# ------------------------------------------- unpropagated-request-context


_SERVE_DISPATCH_METHODS = ("prefill", "start_decode")


def _reqtrace_aliases(aliases: _Aliases) -> Set[str]:
    """Local names bound to the request-trace API: ``from
    ray_tpu.observability import requests as reqtrace`` and ``import
    ray_tpu.observability.requests [as x]`` spellings."""
    names: Set[str] = set()
    for local, (mod, orig) in aliases.from_imports.items():
        if orig == "requests" and mod.endswith("observability"):
            names.add(local)
        if mod.endswith("observability.requests"):
            names.add(local)
    for local, mod in aliases.module_alias.items():
        if mod.endswith("observability.requests"):
            names.add(local)
    return names


def _scope_references(fn: ast.AST, names: Set[str]) -> bool:
    """True when `fn`'s own execution scope (not nested defs) loads any
    of `names` — the trace API is in play on this code path."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Name) and node.id in names:
            return True
        stack.extend(ast.iter_child_nodes(node))
    return False


def _serve_dispatch_method(call: ast.Call) -> Optional[str]:
    """The string-literal serve method a cross-tier dispatch targets,
    or None when `call` is not one. Shapes:
    ``self._tier_call(rep, tier, "prefill", ...)`` (method is the
    third arg) and ``_call(target, "start_decode", ...)`` (second)."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "_tier_call":
        idx = 2
    elif isinstance(f, ast.Name) and f.id in ("_tier_call", "_call"):
        idx = 2 if f.id == "_tier_call" else 1
    else:
        return None
    if len(call.args) <= idx:
        return None
    arg = call.args[idx]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
            and arg.value in _SERVE_DISPATCH_METHODS:
        return arg.value
    return None


def _lint_unpropagated_request_context(tree: ast.AST, aliases: _Aliases,
                                       path: str) -> List[Finding]:
    """Active only in modules importing the request-trace API — a
    module that never imports observability/requests.py has opted out
    of tracing wholesale, which is a different (cross-module) story;
    this rule catches the sharper bug of a TRACED module with one
    untraced dispatch path."""
    rt_names = _reqtrace_aliases(aliases)
    if not rt_names:
        return []
    findings: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if _scope_references(fn, rt_names):
            continue
        for call in _iter_scope_calls(fn):
            method = _serve_dispatch_method(call)
            if method is None:
                continue
            findings.append(Finding(
                "unpropagated-request-context", INFO,
                f"{path}:{call.lineno}",
                f"cross-tier '{method}' dispatch in trace-blind scope "
                f"'{fn.name}' — this module records request traces, "
                "but this hop drops the context, so its time vanishes "
                "from the p99 phase attribution",
                "record the hop under the active trace "
                "(reqtrace.phase(...) around the dispatch, or "
                "push_remote_phase from the callee), or suppress with "
                "a justification when the dispatch is genuinely "
                "requestless (warmup, health probes)"))
    return findings


# ------------------------------------------- unregistered-prefix-publish


def _scope_registers_prefix(fn: ast.AST) -> bool:
    """Does this scope commit to the prefix directory — a
    ``"kvplane_publish"`` conductor-call literal, or a call through the
    sanctioned ``publish_prefix`` helper (which commits internally)?"""
    for node in ast.walk(fn):
        if isinstance(node, ast.Constant) and \
                node.value == "kvplane_publish":
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                (f.id if isinstance(f, ast.Name) else None)
            if name == "publish_prefix":
                return True
    return False


def _lint_unregistered_prefix_publish(tree: ast.AST, aliases: _Aliases,
                                      path: str) -> List[Finding]:
    """Active only in KV-plane-aware modules — anything importing
    serve.kvplane (the tiered plane) or models.kvcache (the cache whose
    export_prefix produces the publishable payload). There, an
    ``export_prefix(...)`` whose scope never commits the result to the
    conductor's prefix directory publishes chunk-fabric objects nobody
    can ever discover: the refs pin host memory, the prefix serves no
    one."""
    kvp_aware = any(
        mod.endswith("kvplane") or mod.endswith("kvcache")
        for mod, _name in aliases.from_imports.values()
    ) or any(mod.endswith((".kvplane", ".kvcache"))
             or mod in ("kvplane", "kvcache")
             for mod in aliases.module_alias.values()) or any(
        name in ("kvplane", "kvcache")
        for name in aliases.from_imports)
    if not kvp_aware:
        return []
    findings: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        calls = [c for c in _iter_scope_calls(fn)
                 if isinstance(c.func, ast.Attribute)
                 and c.func.attr == "export_prefix"]
        if not calls or _scope_registers_prefix(fn):
            continue
        for call in calls:
            findings.append(Finding(
                "unregistered-prefix-publish", INFO,
                f"{path}:{call.lineno}",
                f"export_prefix in '{fn.name}' with no directory "
                "commit in scope — the exported prefix enters the "
                "chunk fabric unregistered: no replica can ever look "
                "it up, and its refs pin host memory until the holder "
                "dies",
                "publish through serve/kvplane.publish_prefix (export "
                "+ atomic kvplane_publish commit), or suppress with a "
                "justification when the export is genuinely local "
                "(tests, offline serialization)"))
    return findings


# ---------------------------------------------------------------- drivers


def lint_source(source: str, path: str = "<string>") -> List[Finding]:
    """Lint one Python source string. Returns [] for unparsable files —
    syntax errors are a different tool's job."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    aliases = _Aliases(tree)
    findings = _lint_blocking_in_async(tree, aliases, path)
    findings += _lint_host_sync_in_jit(tree, aliases, path)
    findings += _lint_unsupervised_actor_call(tree, aliases, path)
    findings += _lint_unkeyed_tenant_cache(tree, aliases, path)
    findings += _lint_sync_io_in_gateway_handler(tree, aliases, path)
    findings += _lint_undonated_pool_write(tree, aliases, path)
    findings += _lint_unpropagated_request_context(tree, aliases, path)
    findings += _lint_unregistered_prefix_publish(tree, aliases, path)
    # the per-file halves of the cross-module invariant engine
    # (shardlint v2): lock-discipline races and the donation auditor
    from . import invariants

    findings += invariants.lint_lock_discipline(tree, path)
    findings += invariants.lint_donation_audit(tree, aliases, path)
    if not findings:
        return findings
    suppressed = _suppressions(source)
    out = []
    for f in findings:
        try:
            line = int(f.location.rsplit(":", 1)[1])
        except (IndexError, ValueError):
            line = -1
        rules = suppressed.get(line, "absent")
        if rules == "absent" or (rules is not None and
                                 f.rule not in rules):
            out.append(f)
    return out


def lint_file(path: str) -> List[Finding]:
    # errors="replace": a stray non-UTF-8 byte must not abort the whole
    # lint run (lint_source already treats unparsable sources as [])
    with open(path, encoding="utf-8", errors="replace") as fh:
        return lint_source(fh.read(), path)


# Directories no linter should crawl: caches, VCS internals, virtualenvs
# and vendored trees (third-party async internals legitimately block and
# would flip the exit code for code the user does not own).
_SKIP_DIRS = frozenset({"__pycache__", "node_modules", "venv", "build",
                        "dist", "site-packages", "egg-info"})


def lint_path(path: str) -> List[Finding]:
    """Lint a file or every .py file under a directory (skipping hidden
    directories, virtualenvs, and vendored trees)."""
    if os.path.isfile(path):
        return lint_file(path)
    findings: List[Finding] = []
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in _SKIP_DIRS and not d.startswith(".")
                       and not d.endswith(".egg-info")]
        for name in sorted(filenames):
            if name.endswith(".py"):
                findings.extend(lint_file(os.path.join(dirpath, name)))
    return findings


__all__ = ["lint_source", "lint_file", "lint_path"]
