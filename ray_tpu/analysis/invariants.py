"""Cross-module invariant engine — shardlint v2.

The per-file AST rules in `astlint` catch bugs a single screenful of
code can prove. The invariants here are different: each one is a
repo-wide convention whose violation is only visible when you look at
SEVERAL sites (or several modules) at once — the way race detectors and
aliasing analyses work in mature runtimes. Four rule families:

- **lock-discipline** (warning, per class): in a class that guards
  state with ``with self._lock`` (or a Condition wrapping it), every
  mutation of a ``self._*`` attribute must happen under the lock. An
  attribute mutated at least once under the lock and at least once
  outside it is a data race candidate: the finding cites both sites.
  ``__init__``/``__new__`` are exempt (no concurrent aliases exist
  yet), as are helpers that document the convention — a docstring
  containing "must hold" / "caller holds" naming the lock, or a
  ``*_locked`` name suffix. Deliberate lock-free reads/writes (e.g.
  monotonic counters read for telemetry) suppress with
  ``# shardlint: ok=lock-free`` plus a one-line justification.

- **surface-parity** (error, per subsystem): the ROADMAP convention —
  "every new subsystem gets the full surface treatment" — as a lint.
  Every conductor stats aggregation (``report_<X>_stats`` /
  ``get_<X>_status`` pair) must come with the matching
  ``util.state.<X>_status()`` accessor, ``ray_tpu <X>`` CLI
  subcommand, dashboard ``/api/<X>`` route, ``ray_tpu_<X>_*``
  Prometheus family, and merged-timeline lane
  (``<X>_trace_events``). Names are matched fuzzily (``kvcache`` ↔
  ``kv_cache_stats``, ``speculation`` ↔ ``speculate``) plus a small
  documented alias table for surfaces that deliberately share
  (``servefault`` recovery markers ride the ``resilience`` timeline
  lane) or abbreviate (``ray_tpu_spec_*``).

- **env-knob registry** (warnings): every ``RAY_TPU_*`` environment
  read in the package, cross-referenced. Three rules:
  ``env-knob-inconsistent-default`` — one knob parsed with different
  literal defaults at different sites (the two sites WILL disagree
  someday); ``env-knob-hot-path`` — a knob parsed lexically inside a
  loop, or inside a same-module function that is called from inside a
  loop, without the cached-env pattern (``util/envknobs.py`` or an
  ``lru_cache``-decorated accessor); ``env-knob-undocumented`` — a
  knob missing from the README knob table. ``knob_table()`` emits the
  canonical registry (the README table is generated from it).

- **undonated-jit-pool-arg** (warning): the donation auditor,
  extending ``undonated-pool-write``. A jitted function that takes a
  pool/cache/slab/arena-shaped argument and builds an updated
  full-size copy (``arg.at[...].set``, ``dynamic_update_slice(arg,
  ...)``) without ``donate_argnums``/``donate_argnames`` pays an
  O(pool) copy per call; donation lets XLA update O(row) in place.

Pure stdlib (``ast`` + ``re``), no imports of the linted code — broken
or dependency-heavy modules still lint. Per-file families
(lock-discipline, undonated-jit-pool-arg) also run under
``astlint.lint_source``; the cross-module families run from
``analyze_invariants(package_root)`` — the ``ray_tpu analyze
--invariants`` CLI mode and the tier-1 self-lint suite.

Suppression works exactly like astlint: append ``# shardlint: ok``
(optionally ``ok=<reason>``, e.g. ``ok=lock-free``) or ``# shardlint:
disable=<rule-id>`` to the cited line.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import ERROR, Finding, WARNING

# ------------------------------------------------------- lock-discipline

# Attribute names that denote a mutual-exclusion guard when used as
# `with self.<attr>`: locks, reentrant locks, conditions, mutexes.
_LOCKISH_RE = re.compile(r"lock|mutex|^_cv$|^cv$|cond", re.IGNORECASE)

# Method calls that mutate their receiver in place (list/set/dict/deque
# surface) — `self._xs.append(...)` is as much a write as `self._xs = `.
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse",
})

# A helper documented to run under the caller's lock: its writes are
# locked by convention, not lexically.
_HOLDS_LOCK_RE = re.compile(r"must hold|caller holds|holding self\._",
                            re.IGNORECASE)


def _self_attr(expr: ast.AST) -> Optional[str]:
    """`self.<attr>` -> attr name, else None."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return expr.attr
    return None


def _self_attr_base(expr: ast.AST) -> Optional[str]:
    """The `self._x` at the root of a subscript/attribute chain:
    `self._d[k]`, `self._d[k][j]` -> `_d`."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    return _self_attr(expr)


def _with_lock_items(node: ast.With, lockish: Set[str],
                     cond_aliases: Set[str]) -> bool:
    """True when any context manager of this With is a recognized lock:
    `self.<lockish>` or a local Condition alias bound from one."""
    for item in node.items:
        ctx = item.context_expr
        attr = _self_attr(ctx)
        if attr is not None and attr in lockish:
            return True
        if isinstance(ctx, ast.Name) and ctx.id in cond_aliases:
            return True
        # `self._lock.acquire()`-style context or `self._cv` wait forms
        if isinstance(ctx, ast.Call):
            recv = _self_attr(ctx.func.value) if isinstance(
                ctx.func, ast.Attribute) else None
            if recv is not None and recv in lockish:
                return True
    return False


@dataclass
class _AttrSites:
    locked: List[int] = field(default_factory=list)
    unlocked: List[int] = field(default_factory=list)


def _method_holds_lock_by_convention(fn: ast.AST) -> bool:
    name = getattr(fn, "name", "")
    if name.endswith("_locked"):
        return True
    doc = ast.get_docstring(fn) if isinstance(
        fn, (ast.FunctionDef, ast.AsyncFunctionDef)) else None
    return bool(doc and _HOLDS_LOCK_RE.search(doc))


def _collect_mutations(fn: ast.AST, lockish: Set[str],
                       cond_aliases: Set[str],
                       sites: Dict[str, _AttrSites]) -> None:
    """Walk one method, recording every `self._*` mutation with whether
    it is lexically under a recognized lock context."""

    def visit(node: ast.AST, locked: bool) -> None:
        if isinstance(node, ast.With):
            inner = locked or _with_lock_items(node, lockish,
                                               cond_aliases)
            for child in node.body:
                visit(child, inner)
            return
        attrs_lines: List[Tuple[str, int]] = []
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                for sub in ast.walk(tgt):
                    attr = _self_attr_base(sub)
                    if attr is None and isinstance(sub, ast.Attribute):
                        attr = _self_attr(sub)
                    if attr is not None and attr.startswith("_") \
                            and attr not in lockish:
                        attrs_lines.append((attr, node.lineno))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                attr = _self_attr_base(tgt)
                if attr is not None and attr.startswith("_") \
                        and attr not in lockish:
                    attrs_lines.append((attr, node.lineno))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATOR_METHODS:
            attr = _self_attr_base(node.func.value)
            if attr is not None and attr.startswith("_") \
                    and attr not in lockish:
                attrs_lines.append((attr, node.lineno))
        for attr, line in attrs_lines:
            rec = sites.setdefault(attr, _AttrSites())
            (rec.locked if locked else rec.unlocked).append(line)
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    if getattr(fn, "name", "") in ("__init__", "__new__"):
        return  # no concurrent aliases exist during construction
    held = _method_holds_lock_by_convention(fn)
    for child in ast.iter_child_nodes(fn):
        visit(child, held)


def lint_lock_discipline(tree: ast.AST, path: str) -> List[Finding]:
    """Per-class dataflow over `self._*` mutations in lock-using
    classes: any attribute mutated both under and outside the class's
    lock is a race candidate. One finding per unlocked site, citing a
    locked site, so each can be individually suppressed
    (`# shardlint: ok=lock-free`) with its own justification."""
    findings: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        # lock attrs: assigned a threading lock OR used as `with self.x`
        lockish: Set[str] = set()
        cond_aliases: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                fname = node.value.func
                callee = fname.attr if isinstance(fname, ast.Attribute) \
                    else (fname.id if isinstance(fname, ast.Name)
                          else "")
                if callee in ("Lock", "RLock", "Condition", "Semaphore",
                              "BoundedSemaphore"):
                    for tgt in node.targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            lockish.add(attr)
                        # local alias: cv = threading.Condition(self._l)
                        elif isinstance(tgt, ast.Name) and any(
                                _self_attr(a) is not None
                                for a in node.value.args):
                            cond_aliases.add(tgt.id)
            if isinstance(node, ast.With):
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None and _LOCKISH_RE.search(attr):
                        lockish.add(attr)
        guarded = {a for a in lockish if _LOCKISH_RE.search(a)}
        if not guarded:
            continue  # not a lock-disciplined class
        sites: Dict[str, _AttrSites] = {}
        for fn in methods:
            _collect_mutations(fn, lockish, cond_aliases, sites)
        for attr in sorted(sites):
            rec = sites[attr]
            if not rec.locked or not rec.unlocked:
                continue
            locked_at = min(rec.locked)
            for line in sorted(set(rec.unlocked)):
                findings.append(Finding(
                    "lock-discipline", WARNING, f"{path}:{line}",
                    f"{cls.name}.{attr} is mutated under the lock at "
                    f"{path}:{locked_at} but WITHOUT it here — a "
                    "concurrent caller can observe or lose this write",
                    "wrap the mutation in `with self._lock:` (or move "
                    "it into a locked helper); a deliberate lock-free "
                    "path suppresses with `# shardlint: ok=lock-free` "
                    "+ a one-line justification"))
    return findings


# ------------------------------------------------- undonated-jit-pool-arg

_POOLISH_ARG_RE = re.compile(r"pool|cache|slab|arena")


def lint_donation_audit(tree: ast.AST, aliases, path: str
                        ) -> List[Finding]:
    """Donation auditor: a jitted function taking a pool/cache/slab/
    arena-shaped argument and building an updated full-size copy of it
    without donate_argnums pays an O(pool) device copy every call —
    the same latent cost `undonated-pool-write` catches outside jits,
    now audited INSIDE the jit boundary where the donation belongs."""
    from .astlint import _is_donating_jit, _jitted_functions

    findings: List[Finding] = []
    for fn in _jitted_functions(tree, aliases):
        if any(_is_donating_jit(d, aliases) for d in fn.decorator_list):
            continue
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args +
                                  fn.args.kwonlyargs)
                  if _POOLISH_ARG_RE.search(a.arg.lower())}
        if not params:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # <param>.at[...].set/add(...)
            if isinstance(f, ast.Attribute) and f.attr in ("set", "add") \
                    and isinstance(f.value, ast.Subscript) \
                    and isinstance(f.value.value, ast.Attribute) \
                    and f.value.value.attr == "at" \
                    and isinstance(f.value.value.value, ast.Name) \
                    and f.value.value.value.id in params:
                pname = f.value.value.value.id
            # dynamic_update_slice(<param>, ...)
            elif ((isinstance(f, ast.Attribute)
                   and f.attr == "dynamic_update_slice")
                  or (isinstance(f, ast.Name)
                      and f.id == "dynamic_update_slice")) \
                    and node.args \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in params:
                pname = node.args[0].id
            else:
                continue
            findings.append(Finding(
                "undonated-jit-pool-arg", WARNING,
                f"{path}:{node.lineno}",
                f"jitted '{fn.name}' updates pool-shaped arg "
                f"'{pname}' without donating it — XLA materializes a "
                "full O(pool) copy per call instead of an in-place "
                "O(row) write",
                "add donate_argnums=<index of "
                f"'{pname}'> (functools.partial(jax.jit, "
                "donate_argnums=...)) and never reuse the donated "
                "buffer after the call"))
    return findings


# ------------------------------------------------------- env-knob registry

@dataclass(frozen=True)
class EnvRead:
    """One RAY_TPU_* environment read site."""

    knob: str
    path: str
    line: int
    default: Optional[str]     # literal default repr, None = no default
    required: bool             # os.environ[...] form (raises if unset)
    hot: bool                  # lexically in a loop / loop-called fn
    cached: bool               # lru_cache'd accessor or envknobs module


_CACHED_DECORATORS = frozenset({"lru_cache", "cache", "cached_property"})


def _is_cached_fn(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        node = dec.func if isinstance(dec, ast.Call) else dec
        name = node.attr if isinstance(node, ast.Attribute) else (
            node.id if isinstance(node, ast.Name) else "")
        if name in _CACHED_DECORATORS:
            return True
    return False


_KNOB_ACCESSORS = frozenset(
    {"get_str", "get_int", "get_float", "get_bool"})


def _env_key(node: ast.Call
             ) -> Optional[Tuple[str, Optional[str], bool, bool]]:
    """(knob, default_repr, required, cached) for env-read call forms:
    os.environ.get(K[, d]) / os.getenv(K[, d]), plus the cached
    util/envknobs accessors get_str/get_int/get_float/get_bool(K[, d])
    — recognizing the accessor keeps a migrated knob in the registry
    and marks the site as following the cached-env pattern."""
    f = node.func
    is_get = (isinstance(f, ast.Attribute) and f.attr == "get"
              and isinstance(f.value, ast.Attribute)
              and f.value.attr == "environ")
    is_getenv = (isinstance(f, ast.Attribute) and f.attr == "getenv")
    fname = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    is_knob_accessor = fname in _KNOB_ACCESSORS
    if not (is_get or is_getenv or is_knob_accessor) or not node.args:
        return None
    key = node.args[0]
    if not (isinstance(key, ast.Constant) and isinstance(key.value, str)
            and key.value.startswith("RAY_TPU_")):
        return None
    default: Optional[str] = None
    if len(node.args) > 1:
        d = node.args[1]
        default = repr(d.value) if isinstance(d, ast.Constant) \
            else "<dynamic>"
    return key.value, default, False, is_knob_accessor


def scan_env_reads(tree: ast.AST, path: str) -> List[EnvRead]:
    """Every RAY_TPU_* environment read in one module, annotated with
    loop/hot-path and caching context. Hot = lexically inside a
    for/while loop, or inside a function that the SAME module calls
    from inside a loop (one-hop: the `while not stop.wait(interval())`
    pattern)."""
    module_is_cache = path.replace(os.sep, "/").endswith(
        "util/envknobs.py")
    # names called from inside any loop body in this module
    loop_called: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    f = sub.func
                    name = f.id if isinstance(f, ast.Name) else (
                        f.attr if isinstance(f, ast.Attribute) else "")
                    if name:
                        loop_called.add(name)
    reads: List[EnvRead] = []

    def visit(node: ast.AST, in_loop: bool, cached: bool) -> None:
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            in_loop = True
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cached = cached or _is_cached_fn(node)
            in_loop = node.name in loop_called
        if isinstance(node, ast.Call):
            hit = _env_key(node)
            if hit is not None:
                knob, default, required, via_accessor = hit
                reads.append(EnvRead(
                    knob, path, node.lineno, default, required,
                    hot=in_loop,
                    cached=cached or module_is_cache or via_accessor))
        elif isinstance(node, ast.Subscript):
            base, key = node.value, node.slice
            if isinstance(base, ast.Attribute) \
                    and base.attr == "environ" \
                    and isinstance(key, ast.Constant) \
                    and isinstance(key.value, str) \
                    and key.value.startswith("RAY_TPU_") \
                    and not isinstance(getattr(node, "ctx", None),
                                       (ast.Store, ast.Del)):
                reads.append(EnvRead(
                    key.value, path, node.lineno, None, True,
                    hot=in_loop, cached=cached or module_is_cache))
        for child in ast.iter_child_nodes(node):
            visit(child, in_loop, cached)

    visit(tree, False, False)
    return reads


def check_env_knobs(reads: Sequence[EnvRead],
                    readme_text: Optional[str] = None) -> List[Finding]:
    """Cross-module knob rules over the collected read sites."""
    findings: List[Finding] = []
    by_knob: Dict[str, List[EnvRead]] = {}
    for r in reads:
        by_knob.setdefault(r.knob, []).append(r)
    for knob in sorted(by_knob):
        sites = by_knob[knob]
        # inconsistent literal defaults across sites
        defaults = {}
        for r in sites:
            if r.default is not None and r.default != "<dynamic>" \
                    and not r.required:
                defaults.setdefault(r.default, r)
        if len(defaults) > 1:
            first = min(defaults.values(), key=lambda r: (r.path, r.line))
            cited = ", ".join(
                f"{r.path}:{r.line} default={d}"
                for d, r in sorted(defaults.items(), key=lambda kv: (
                    kv[1].path, kv[1].line)))
            findings.append(Finding(
                "env-knob-inconsistent-default", WARNING,
                f"{first.path}:{first.line}",
                f"{knob} is parsed with {len(defaults)} different "
                f"defaults: {cited} — whichever site runs first wins, "
                "silently",
                "route every read through ONE cached accessor in "
                "util/envknobs.py carrying the canonical default"))
        # hot-path parse without the cached-env pattern
        for r in sites:
            if r.hot and not r.cached:
                findings.append(Finding(
                    "env-knob-hot-path", WARNING, f"{r.path}:{r.line}",
                    f"{knob} is parsed inside a loop / per-tick path — "
                    "an environ dict probe plus str parse on every "
                    "iteration",
                    "hoist the read, or use the util/envknobs.py "
                    "cached accessor (parse memoized on the raw "
                    "string, still live-retunable)"))
        # knob absent from the README knob table
        if readme_text is not None and knob not in readme_text:
            first = min(sites, key=lambda r: (r.path, r.line))
            findings.append(Finding(
                "env-knob-undocumented", WARNING,
                f"{first.path}:{first.line}",
                f"{knob} is read here but appears nowhere in the "
                "README — an operator cannot discover it",
                "add it to the README environment-knob table "
                "(`ray_tpu analyze --invariants --knob-table` emits "
                "the canonical rows)"))
    return findings


def knob_table(reads: Sequence[EnvRead]) -> List[Dict[str, object]]:
    """The canonical env-knob registry: one row per knob with its
    default(s) and read sites — `analyze --invariants --json` embeds
    this, and the README table is generated from it."""
    by_knob: Dict[str, List[EnvRead]] = {}
    for r in reads:
        by_knob.setdefault(r.knob, []).append(r)
    rows = []
    for knob in sorted(by_knob):
        sites = by_knob[knob]
        defaults = sorted({r.default for r in sites
                           if r.default not in (None, "<dynamic>")})
        rows.append({
            "knob": knob,
            "default": defaults[0] if len(defaults) == 1 else (
                "(required)" if all(r.required for r in sites)
                else " / ".join(defaults) if defaults else "(unset)"),
            "required": all(r.required for r in sites),
            "sites": sorted({f"{r.path}:{r.line}" for r in sites}),
            "modules": sorted({os.path.basename(r.path)
                               for r in sites}),
        })
    return rows


def format_knob_table(rows: Sequence[Dict[str, object]],
                      root: Optional[str] = None) -> str:
    """Markdown knob table (the generated README section)."""
    out = ["| knob | default | read from |", "|---|---|---|"]
    for row in rows:
        mods = ", ".join(f"`{m}`" for m in row["modules"])
        out.append(f"| `{row['knob']}` | `{row['default']}` | {mods} |")
    return "\n".join(out)


# --------------------------------------------------------- surface-parity

# Subsystems whose push/get channel predates the surface convention and
# is deliberately CLI/dashboard-less — each waiver carries its reason.
PARITY_WAIVERS: Dict[str, str] = {
    "task": "core task-event channel; surfaced via the timeline/"
            "summary endpoints, not a per-subsystem page",
    "rpc": "control-plane dispatch diagnostics (get_rpc_stats) — an "
           "internal latency probe, deliberately unexposed",
}

# (subsystem, surface) -> extra accepted stems, for surfaces that
# deliberately abbreviate or share. Everything else matches fuzzily.
SURFACE_ALIASES: Dict[Tuple[str, str], Tuple[str, ...]] = {
    # engines push spec counters under ray_tpu_spec_* (the metric names
    # predate the subsystem name)
    ("speculation", "prometheus"): ("spec",),
    # recovery markers share one lane whether they heal a training gang
    # or a serving tier (see observability/timeline.py docstring)
    ("servefault", "timeline"): ("resilience",),
    # the flight recorder's metric family abbreviates to reqtrace
    # (ray_tpu_reqtrace_phase_ms etc — observability/requests.py)
    ("requesttrace", "prometheus"): ("reqtrace",),
}

_SURFACE_FILES = {
    "state": os.path.join("util", "state.py"),
    "cli": os.path.join("scripts", "cli.py"),
    "dashboard": os.path.join("dashboard", "__init__.py"),
    "timeline": os.path.join("observability", "timeline.py"),
}

_SURFACE_FIX = {
    "state": "add a util.state.<x>_status() accessor reading the "
             "conductor aggregate",
    "cli": "add the `ray_tpu <x>` subcommand (scripts/cli.py) over the "
           "state accessor",
    "dashboard": "add the dashboard /api/<x> route over the same "
                 "aggregate",
    "prometheus": "emit a ray_tpu_<x>_* Prometheus family from the "
                  "subsystem's metrics module",
    "timeline": "add a <x>_trace_events lane to "
                "observability/timeline.py and merge it in "
                "merged_chrome_trace",
}


def _norm(name: str) -> str:
    return re.sub(r"[^a-z0-9]", "", name.lower())


def _stem_matches(stem: str, candidate: str) -> bool:
    """Fuzzy subsystem-name match: normalized common prefix covers the
    shorter name entirely (>= 4 chars), or all but a short suffix of
    both (kvcache ~ kv_cache_stats, speculation ~ speculate)."""
    a, b = _norm(stem), _norm(candidate)
    if not a or not b:
        return False
    lcp = 0
    for x, y in zip(a, b):
        if x != y:
            break
        lcp += 1
    if lcp == min(len(a), len(b)) and lcp >= 4:
        return True
    return lcp >= max(5, min(len(a), len(b)) - 3)


def _match_any(stem: str, surface: str,
               candidates: Iterable[str]) -> bool:
    stems = (stem,) + SURFACE_ALIASES.get((stem, surface), ())
    return any(_stem_matches(s, c) for s in stems for c in candidates)


_REPORT_RE = re.compile(r"^report_(\w+?)_(stats|events?)$")
_GET_RE = re.compile(r"^get_(\w+?)_(status|stats)$")


def discover_subsystems(conductor_tree: ast.AST) -> Dict[str, int]:
    """Subsystem stem -> defining line, discovered from the conductor's
    report/get method names. A stem qualifies via a worker-push channel
    (report_<X>_stats / report_<X>_event) or a status aggregate
    (get_<X>_status / get_<X>_stats); waived stems are dropped."""
    stems: Dict[str, int] = {}
    for node in ast.walk(conductor_tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        m = _GET_RE.match(node.name) or _REPORT_RE.match(node.name)
        if not m:
            continue
        stem = m.group(1)
        if stem in PARITY_WAIVERS:
            continue
        if stem not in stems or node.lineno < stems[stem]:
            stems[stem] = node.lineno
    return stems


def check_surface_parity(package_root: str) -> List[Finding]:
    """Assert every conductor subsystem ships the full surface
    treatment: state accessor, CLI subcommand, dashboard route,
    Prometheus family, merged-timeline lane. One ERROR per missing
    surface, anchored at the subsystem's conductor method so the
    convention fails review as a lint, not folklore."""
    conductor_path = os.path.join(package_root, "_private",
                                  "conductor.py")
    if not os.path.isfile(conductor_path):
        return []
    trees: Dict[str, Tuple[str, ast.AST]] = {}
    for role, rel in _SURFACE_FILES.items():
        full = os.path.join(package_root, rel)
        if not os.path.isfile(full):
            return []  # not a ray_tpu-shaped tree: rule is inert
        with open(full, encoding="utf-8", errors="replace") as fh:
            src = fh.read()
        try:
            trees[role] = (full, ast.parse(src))
        except SyntaxError:
            return []
    with open(conductor_path, encoding="utf-8",
              errors="replace") as fh:
        try:
            conductor_tree = ast.parse(fh.read())
        except SyntaxError:
            return []
    stems = discover_subsystems(conductor_tree)
    if not stems:
        return []

    # candidate names per surface
    state_defs = [n.name for n in ast.walk(trees["state"][1])
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))]
    cli_cmds = []
    for node in ast.walk(trees["cli"][1]):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "add_parser" and node.args and \
                isinstance(node.args[0], ast.Constant):
            cli_cmds.append(str(node.args[0].value))
    api_routes = []
    for node in ast.walk(trees["dashboard"][1]):
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str):
            api_routes.extend(re.findall(r"/api/([\w-]+)", node.value))
        elif isinstance(node, ast.JoinedStr):
            for part in node.values:
                if isinstance(part, ast.Constant) and \
                        isinstance(part.value, str):
                    api_routes.extend(
                        re.findall(r"/api/([\w-]+)", part.value))
    lanes = [m.group(1) for n in ast.walk(trees["timeline"][1])
             if isinstance(n, ast.FunctionDef)
             for m in [re.match(r"^(\w+)_trace_events$", n.name)] if m]
    prom_families: Set[str] = set()
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if not d.startswith(".")
                       and d not in ("__pycache__", "analysis")]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            with open(os.path.join(dirpath, name), encoding="utf-8",
                      errors="replace") as fh:
                prom_families.update(
                    re.findall(r"\"ray_tpu_([a-z0-9_]+)\"", fh.read()))

    surface_candidates = {
        "state": state_defs,
        "cli": cli_cmds,
        "dashboard": api_routes,
        "prometheus": sorted(prom_families),
        "timeline": lanes,
    }
    findings: List[Finding] = []
    for stem in sorted(stems):
        missing = [surface for surface, cands
                   in surface_candidates.items()
                   if not _match_any(stem, surface, cands)]
        if not missing:
            continue
        hints = "; ".join(_SURFACE_FIX[s].replace("<x>", stem)
                          for s in missing)
        findings.append(Finding(
            "surface-parity", ERROR,
            f"{conductor_path}:{stems[stem]}",
            f"subsystem '{stem}' is missing the full surface "
            f"treatment: no {', no '.join(missing)} — the one-set-of-"
            "numbers discipline (state == CLI == dashboard == "
            "Prometheus == timeline) is broken",
            hints))
    return findings


# ---------------------------------------------------------------- driver

_SKIP_DIRS = frozenset({"__pycache__", "node_modules", "venv", "build",
                        "dist", "site-packages", "egg-info"})


def _iter_package_sources(package_root: str):
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in _SKIP_DIRS and not d.startswith(".")
                       and not d.endswith(".egg-info")]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            full = os.path.join(dirpath, name)
            with open(full, encoding="utf-8", errors="replace") as fh:
                yield full, fh.read()


def _find_readme(package_root: str) -> Optional[str]:
    for base in (os.path.dirname(os.path.abspath(package_root)),
                 package_root):
        candidate = os.path.join(base, "README.md")
        if os.path.isfile(candidate):
            with open(candidate, encoding="utf-8",
                      errors="replace") as fh:
                return fh.read()
    return None


def collect_env_reads(package_root: str) -> List[EnvRead]:
    reads: List[EnvRead] = []
    for path, src in _iter_package_sources(package_root):
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        reads.extend(scan_env_reads(tree, path))
    return reads


def analyze_invariants(package_root: str,
                       readme_text: Optional[str] = None
                       ) -> List[Finding]:
    """Run the cross-module families over a package tree: the env-knob
    registry and the surface-parity checker. (The per-file families —
    lock-discipline and the donation auditor — already run under
    `lint_path`/`lint_source`; running them here too would double-
    report.) Suppression comments on the cited lines are honored."""
    from .astlint import _suppressions

    findings: List[Finding] = []
    readme = readme_text if readme_text is not None \
        else _find_readme(package_root)
    findings.extend(check_env_knobs(collect_env_reads(package_root),
                                    readme))
    findings.extend(check_surface_parity(package_root))
    # honor per-line suppressions at each finding's cited site
    out: List[Finding] = []
    suppress_cache: Dict[str, Dict[int, Optional[Set[str]]]] = {}
    for f in findings:
        try:
            path, line_s = f.location.rsplit(":", 1)
            line = int(line_s)
        except ValueError:
            out.append(f)
            continue
        if path not in suppress_cache:
            try:
                with open(path, encoding="utf-8",
                          errors="replace") as fh:
                    suppress_cache[path] = _suppressions(fh.read())
            except OSError:
                suppress_cache[path] = {}
        rules = suppress_cache[path].get(line, "absent")
        if rules == "absent" or (rules is not None
                                 and f.rule not in rules):
            out.append(f)
    return out


__all__ = [
    "EnvRead", "PARITY_WAIVERS", "SURFACE_ALIASES",
    "analyze_invariants", "check_env_knobs", "check_surface_parity",
    "collect_env_reads", "discover_subsystems", "format_knob_table",
    "knob_table", "lint_donation_audit", "lint_lock_discipline",
    "scan_env_reads",
]
