"""GKE / Kubernetes node providers — the KubeRay-analog provisioning
path for TPU clusters.

Reference anchor:
/root/reference/python/ray/autoscaler/_private/kuberay/node_provider.py —
KubeRay's provider drives worker pods through the Kubernetes REST API
(in-cluster service-account token, label-selected pods, patch-based
scaling). For TPU the equivalent surfaces are:

- `KubernetesPodProvider`: one worker-host per k8s Pod, created/deleted
  directly through the core v1 pods API with cluster/type labels — the
  right shape for GKE TPU node pools where each pod binds the node's
  chips via the TPU device plugin.
- `TpuQueuedResourceProvider`: GKE/Cloud-TPU "queued resources"
  (tpu.googleapis.com) — the provisioning surface Google recommends for
  obtainable TPU capacity: a create enqueues a slice request; capacity
  arrives asynchronously and the node shows up when the request turns
  ACTIVE.

Both take an injectable `http` callable (method, url, body) -> dict so
unit tests run against a fake transport, and production uses the
in-cluster token / metadata-server token respectively — the same
auth model as the reference's KubernetesHttpApiClient
(node_provider.py:232 loads the service-account token + CA bundle).
"""
from __future__ import annotations

import json
import os
import time
import urllib.request
import uuid
from typing import Any, Callable, Dict, List, Optional

from . import NodeProvider
from .gcp import TPU_API, _default_http, _metadata_token, accelerator_chips

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def _parse_cpu_quantity(quantity: Any) -> float:
    """k8s CPU quantity -> cores. '500m' is 500 MILLIcpu = 0.5 cores
    (k8s resource-quantity suffix), '8'/'8.0' are cores."""
    s = str(quantity).strip()
    if not s:
        return 1.0
    if s.endswith("m"):
        return float(s[:-1]) / 1000.0
    return float(s)


def _incluster_http() -> Callable:
    """k8s REST transport using the pod's mounted service account
    (reference kuberay node_provider.py load_k8s_secrets)."""
    with open(os.path.join(SA_DIR, "token")) as f:
        token = f.read().strip()
    ca = os.path.join(SA_DIR, "ca.crt")
    import ssl

    ctx = ssl.create_default_context(
        cafile=ca if os.path.exists(ca) else None)

    def http(method: str, url: str,
             body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method, headers={
            "Authorization": f"Bearer {token}",
            "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30, context=ctx) as r:
            payload = r.read()
            return json.loads(payload) if payload else {}

    return http


class KubernetesPodProvider(NodeProvider):
    """Worker hosts as label-selected k8s Pods.

    node_config per node type:
      image             container image with ray_tpu installed (required)
      resources         k8s resource requests/limits, e.g.
                        {"google.com/tpu": 8, "cpu": "8", "memory": "16Gi"}
      node_selector     e.g. {"cloud.google.com/gke-tpu-topology": "2x4"}
      env / tolerations / service_account  passthrough
    """

    LABEL_CLUSTER = "ray-tpu-cluster"
    LABEL_TYPE = "ray-tpu-node-type"

    def __init__(self, namespace: str, cluster_name: str, head_address: str,
                 node_configs: Dict[str, Dict[str, Any]],
                 api_server: str = "https://kubernetes.default.svc",
                 http: Optional[Callable] = None):
        self.namespace = namespace
        self.cluster_name = cluster_name
        self.head_address = head_address
        self.node_configs = dict(node_configs)
        self.api_server = api_server.rstrip("/")
        self._http = http or _incluster_http()

    def _pods_url(self, suffix: str = "") -> str:
        return (f"{self.api_server}/api/v1/namespaces/{self.namespace}"
                f"/pods{suffix}")

    def _pod_manifest(self, node_id: str, node_type: str,
                      cfg: Dict[str, Any]) -> Dict[str, Any]:
        chips = cfg.get("resources", {}).get("google.com/tpu", 0)
        command = ["python", "-m", "ray_tpu", "start",
                   "--address", self.head_address,
                   "--resources", json.dumps({"TPU": float(chips)})
                   if chips else "{}",
                   "--node-id", node_id]
        container = {
            "name": "ray-tpu-worker",
            "image": cfg["image"],
            "command": command,
            "resources": {"requests": dict(cfg.get("resources") or {}),
                          "limits": dict(cfg.get("resources") or {})},
        }
        if cfg.get("env"):
            container["env"] = [{"name": k, "value": str(v)}
                                for k, v in cfg["env"].items()]
        spec: Dict[str, Any] = {"containers": [container],
                                "restartPolicy": "Never"}
        if cfg.get("node_selector"):
            spec["nodeSelector"] = dict(cfg["node_selector"])
        if cfg.get("tolerations"):
            spec["tolerations"] = list(cfg["tolerations"])
        if cfg.get("service_account"):
            spec["serviceAccountName"] = cfg["service_account"]
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": node_id,
                "labels": {self.LABEL_CLUSTER: self.cluster_name,
                           self.LABEL_TYPE: node_type},
            },
            "spec": spec,
        }

    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        cfg = self.node_configs[node_type]
        node_id = (f"ray-tpu-{self.cluster_name}-{node_type}-"
                   f"{uuid.uuid4().hex[:8]}")
        self._http("POST", self._pods_url(),
                   self._pod_manifest(node_id, node_type, cfg))
        return node_id

    def terminate_node(self, node_id: str) -> None:
        self._http("DELETE", self._pods_url(f"/{node_id}"))

    def non_terminated_nodes(self) -> List[Dict[str, Any]]:
        selector = f"{self.LABEL_CLUSTER}%3D{self.cluster_name}"
        resp = self._http("GET",
                          self._pods_url(f"?labelSelector={selector}"))
        out: List[Dict[str, Any]] = []
        for pod in resp.get("items", []):
            phase = pod.get("status", {}).get("phase")
            if phase in ("Succeeded", "Failed"):
                continue
            meta = pod.get("metadata", {})
            labels = meta.get("labels") or {}
            node_type = labels.get(self.LABEL_TYPE, "worker")
            cfg = self.node_configs.get(node_type, {})
            chips = float(cfg.get("resources", {})
                          .get("google.com/tpu", 0))
            out.append({
                "node_id": meta.get("name"),
                "node_type": node_type,
                "resources": {"TPU": chips} if chips else
                             {"CPU": _parse_cpu_quantity(
                                 cfg.get("resources", {}).get("cpu", 1))},
                "state": phase,
                "ip": pod.get("status", {}).get("podIP"),
            })
        return out


class TpuQueuedResourceProvider(NodeProvider):
    """TPU slices via Cloud TPU queued resources.

    create_node files a queued-resource request (the obtainability
    surface for TPU capacity — spot or guaranteed); the slice counts as
    provisioning until the request turns ACTIVE, which the autoscaler's
    bootstrap watchdog already tolerates via its register-within-timeout
    logic. node_config adds to GcpTpuNodeProvider's keys:
      spot            bool — best-effort/preemptible capacity
      valid_until_s   give up if unprovisioned after this many seconds
    """

    def __init__(self, project: str, zone: str, cluster_name: str,
                 head_address: str,
                 node_configs: Dict[str, Dict[str, Any]],
                 http: Optional[Callable] = None,
                 token_fn: Optional[Callable[[], str]] = None):
        self.project = project
        self.zone = zone
        self.cluster_name = cluster_name
        self.head_address = head_address
        self.node_configs = dict(node_configs)
        self._http = http or _default_http(token_fn or _metadata_token)

    @property
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _qr_url(self, qr_id: str = "") -> str:
        base = f"{TPU_API}/{self._parent}/queuedResources"
        return f"{base}/{qr_id}" if qr_id else base

    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        cfg = self.node_configs[node_type]
        qr_id = (f"ray-tpu-{self.cluster_name}-"
                 f"{uuid.uuid4().hex[:8]}")
        chips = accelerator_chips(cfg["accelerator_type"])
        node = {
            "acceleratorType": cfg["accelerator_type"],
            "runtimeVersion": cfg["runtime_version"],
            "metadata": {"startup-script": cfg.get("startup_script") or (
                "#! /bin/bash\n"
                f"python3 -m ray_tpu start --address {self.head_address} "
                f"--resources '{{\"TPU\": {chips}}}'\n")},
            "labels": {"ray-cluster": self.cluster_name,
                       "ray-node-type": node_type},
        }
        body: Dict[str, Any] = {
            "tpu": {"nodeSpec": [{"parent": self._parent,
                                  "nodeId": qr_id, "node": node}]},
        }
        if cfg.get("spot"):
            body["spot"] = {}
        else:
            body["guaranteed"] = {}
        if cfg.get("valid_until_s"):
            body["queueingPolicy"] = {
                "validUntilDuration": f"{int(cfg['valid_until_s'])}s"}
        self._http("POST", self._qr_url() + f"?queuedResourceId={qr_id}",
                   body)
        return qr_id

    def terminate_node(self, node_id: str) -> None:
        # force=true also tears down a slice already provisioned from
        # the request, not just the queue entry
        self._http("DELETE", self._qr_url(node_id) + "?force=true")

    def non_terminated_nodes(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        page_token = None
        while True:
            resp = self._http(
                "GET", self._qr_url() + (f"?pageToken={page_token}"
                                         if page_token else ""))
            for qr in resp.get("queuedResources", []):
                state = (qr.get("state") or {}).get("state")
                if state in ("SUSPENDED", "FAILED", "DELETING"):
                    continue
                spec = (qr.get("tpu", {}).get("nodeSpec") or [{}])[0]
                node = spec.get("node", {})
                labels = node.get("labels") or {}
                if labels.get("ray-cluster") != self.cluster_name:
                    continue
                acct = node.get("acceleratorType", "")
                out.append({
                    "node_id": qr["name"].rsplit("/", 1)[-1],
                    "node_type": labels.get("ray-node-type", "tpu"),
                    "resources": {
                        "TPU": float(accelerator_chips(acct))},
                    "state": state,
                })
            page_token = resp.get("nextPageToken")
            if not page_token:
                return out

    def wait_active(self, qr_id: str, timeout: float = 1800.0,
                    poll_s: float = 10.0) -> bool:
        """Queued capacity can take minutes-to-hours; ACTIVE means the
        slice exists and the startup script is joining the cluster."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            qr = self._http("GET", self._qr_url(qr_id))
            if (qr.get("state") or {}).get("state") == "ACTIVE":
                return True
            time.sleep(poll_s)
        return False
