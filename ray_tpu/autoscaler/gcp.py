"""GCE TPU-VM NodeProvider: provisions real TPU slices behind the
autoscaler (reference python/ray/autoscaler/_private/gcp/node_provider.py
+ the TPU-pod support in gcp/config.py).

Design: a "node" is one TPU VM (single-host slice like v5litepod-8) or
pod slice; creation goes through the Cloud TPU REST API
(tpu.googleapis.com/v2). The booted VM joins the cluster itself via its
startup script (`python -m ray_tpu start --address <head>`), so the
provider never registers accounting entries — node identity flows
VM -> NodeAgent -> conductor.

The HTTP layer is injectable: unit tests run the full lifecycle against
a canned transport, and zero-egress environments never dial out.

STATUS: EXPERIMENTAL. The provider has only ever run against the canned
transport — the wait_ready + startup-script flow has not created a real
TPU VM from this environment (zero egress). Treat the REST payloads as
reviewed-but-unproven until exercised against live GCP."""
from __future__ import annotations

import json
import time
import urllib.request
import uuid
from typing import Any, Callable, Dict, List, Optional

from . import NodeProvider

TPU_API = "https://tpu.googleapis.com/v2"

# acceleratorType generation -> chips per host-VM (reference
# accelerators/tpu.py: 4 chips per host for v2-v4, 8 for v5e/v6e)
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5litepod": 8, "v5p": 4,
                   "v6e": 8}
# generations whose "-N" suffix counts TensorCores (2 per chip), not chips
# (reference accelerators/tpu.py: 'v{generation}-{cores}'); v5e/v6e have
# single-core chips so their suffix is the chip count
_CORE_SUFFIX_GENS = ("v2", "v3", "v4", "v5p")


def accelerator_chips(accelerator_type: str) -> int:
    """TOTAL chips in a slice of `accelerator_type`. For v2/v3/v4 the
    numeric suffix counts TensorCores (2 per chip: "v4-16" = 8 chips);
    for v5litepod/v5p/v6e it counts chips ("v5litepod-8" = 8 chips)."""
    gen, _, count = accelerator_type.partition("-")
    try:
        n = int(count)
    except ValueError:
        return _CHIPS_PER_HOST.get(gen, 4)
    if gen in _CORE_SUFFIX_GENS:
        return max(1, n // 2)
    return n


def chips_per_host(accelerator_type: str) -> int:
    """Chips each host VM of the slice exposes — what its NodeAgent must
    advertise (startup scripts run per VM; advertising the whole-slice
    count on every host multiplies capacity by the host count)."""
    gen, _, _ = accelerator_type.partition("-")
    per_host = _CHIPS_PER_HOST.get(gen, 4)
    total = accelerator_chips(accelerator_type)
    return min(per_host, total) if total > 0 else per_host


def slice_hosts(accelerator_type: str) -> int:
    """Host VMs in the slice."""
    total = accelerator_chips(accelerator_type)
    per_host = chips_per_host(accelerator_type)
    return max(1, -(-total // per_host))


def _metadata_token() -> str:
    req = urllib.request.Request(
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "service-accounts/default/token",
        headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=5) as r:
        return json.loads(r.read())["access_token"]


def _default_http(token_fn: Callable[[], str]):
    def http(method: str, url: str,
             body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method, headers={
            "Authorization": f"Bearer {token_fn()}",
            "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            payload = r.read()
            return json.loads(payload) if payload else {}
    return http


class GcpTpuNodeProvider(NodeProvider):
    """Cloud TPU slices as autoscaler nodes.

    node_config (per node type, passed at construction) supports:
      accelerator_type   e.g. "v5litepod-8" (required)
      runtime_version    e.g. "v2-alpha-tpuv5-lite" (required)
      startup_script     shell run on boot; defaults to joining the head
      network / subnetwork / service_account / labels  passthrough
    """

    def __init__(self, project: str, zone: str, cluster_name: str,
                 head_address: str,
                 node_configs: Dict[str, Dict[str, Any]],
                 http: Optional[Callable] = None,
                 token_fn: Optional[Callable[[], str]] = None):
        self.project = project
        self.zone = zone
        self.cluster_name = cluster_name
        self.head_address = head_address
        self.node_configs = dict(node_configs)
        self._http = http or _default_http(token_fn or _metadata_token)

    # ------------------------------------------------------------ helpers

    @property
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _node_url(self, node_id: str) -> str:
        return f"{TPU_API}/{self._parent}/nodes/{node_id}"

    def _startup_script(self, cfg: Dict[str, Any], chips: int) -> str:
        return cfg.get("startup_script") or (
            "#! /bin/bash\n"
            f"python3 -m ray_tpu start --address {self.head_address} "
            f"--resources '{{\"TPU\": {chips}}}'\n")

    # ----------------------------------------------------- NodeProvider API

    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        cfg = self.node_configs[node_type]
        # the startup script runs on EVERY host VM of a multi-host slice:
        # each must advertise only its own chips
        chips = int(chips_per_host(cfg["accelerator_type"]))
        node_id = f"ray-tpu-{self.cluster_name}-{uuid.uuid4().hex[:8]}"
        body = {
            "acceleratorType": cfg["accelerator_type"],
            "runtimeVersion": cfg["runtime_version"],
            "networkConfig": {
                "network": cfg.get("network", "default"),
                "subnetwork": cfg.get("subnetwork", "default"),
                "enableExternalIps": bool(cfg.get("external_ips", False)),
            },
            "metadata": {
                "startup-script": self._startup_script(cfg, chips),
            },
            "labels": dict(cfg.get("labels") or {},
                           **{"ray-cluster": self.cluster_name,
                              "ray-node-type": node_type}),
        }
        if cfg.get("service_account"):
            body["serviceAccount"] = {"email": cfg["service_account"]}
        self._http("POST",
                   f"{TPU_API}/{self._parent}/nodes?nodeId={node_id}", body)
        return node_id

    def terminate_node(self, node_id: str) -> None:
        self._http("DELETE", self._node_url(node_id))

    def non_terminated_nodes(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        url = f"{TPU_API}/{self._parent}/nodes"
        page_token = None
        while True:
            resp = self._http(
                "GET", url + (f"?pageToken={page_token}" if page_token
                              else ""))
            for node in resp.get("nodes", []):
                labels = node.get("labels") or {}
                if labels.get("ray-cluster") != self.cluster_name:
                    continue
                if node.get("state") in ("DELETING", "TERMINATED",
                                         "PREEMPTED"):
                    continue
                acct = node.get("acceleratorType", "")
                out.append({
                    "node_id": node["name"].rsplit("/", 1)[-1],
                    "node_type": labels.get("ray-node-type", "tpu"),
                    # whole-slice chips: the autoscaler launches and
                    # terminates slices, so slice-level capacity is the
                    # accounting unit here (per-host advertising happens
                    # in the startup script)
                    "resources": {"TPU": float(accelerator_chips(acct))},
                    "hosts": slice_hosts(acct),
                    "state": node.get("state"),
                })
            page_token = resp.get("nextPageToken")
            if not page_token:
                return out

    # ------------------------------------------------------------ extras

    def wait_ready(self, node_id: str, timeout: float = 600.0,
                   poll_s: float = 5.0) -> bool:
        """Block until a slice reports READY (TPU creation is minutes)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            node = self._http("GET", self._node_url(node_id))
            if node.get("state") == "READY":
                return True
            time.sleep(poll_s)
        return False
