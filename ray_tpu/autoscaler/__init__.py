"""ray_tpu.autoscaler — analog of the reference's autoscaler v2
(python/ray/autoscaler/v2/: autoscaler.py + scheduler.py, driven by GCS
pending demand) with the v1 concepts users configure (node_types with
min/max_workers, idle timeout — python/ray/autoscaler/_private/
autoscaler.py:172 StandardAutoscaler, resource_demand_scheduler.py:102).

TPU-first shape: a "node" is an accelerator slice (e.g. one v4-8 host
group) — homogeneous, topology-known, reserved/released as a unit. The
provider is the cloud hook (GKE/GCE TPU pools); FakeNodeProvider fakes it
against the live conductor exactly like the reference's
FakeMultiNodeProvider (node_provider.py:237) so the real reconcile loop is
testable on one machine.

This is the NODE-level autoscaler (hosts in, hosts out). The
SERVING-level autoscaler — replica counts against a TTFT SLO — lives in
serve/autoscale.py; the two compose: serve scale-up creates actor
demand, which lands here as pending demand when no host can fit it."""
from __future__ import annotations

import threading
import time
import uuid
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Any, Dict, List, Optional


@dataclass
class NodeTypeConfig:
    """One entry of available_node_types — reference autoscaler config."""
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig]
    idle_timeout_s: float = 60.0
    update_interval_s: float = 1.0
    # only scale for demand that has waited at least this long (debounce)
    min_demand_age_s: float = 0.0
    # bootstrap watchdog (reference _private/updater.py NodeUpdater):
    # a launched node must register with the conductor within this long
    # or it is torn down and relaunched, up to max_bootstrap_retries;
    # after that its node type backs off before any new launch
    bootstrap_timeout_s: float = 300.0
    max_bootstrap_retries: int = 2
    bootstrap_backoff_s: float = 60.0


class NodeProvider(ABC):
    """Cloud hook — reference python/ray/autoscaler/node_provider.py."""

    @abstractmethod
    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        """Provision one node; returns provider node id."""

    @abstractmethod
    def terminate_node(self, node_id: str) -> None:
        ...

    @abstractmethod
    def non_terminated_nodes(self) -> List[Dict[str, Any]]:
        """[{node_id, node_type, resources}]"""


class FakeNodeProvider(NodeProvider):
    """Registers accounting nodes directly with the live conductor — the
    single-machine test double (reference FakeMultiNodeProvider)."""

    def __init__(self, conductor_client=None):
        if conductor_client is None:
            from ray_tpu._private import worker as worker_mod

            conductor_client = worker_mod.global_worker.conductor
        self._conductor = conductor_client
        self._nodes: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        node_id = f"fake_{node_type}_{uuid.uuid4().hex[:8]}"
        # address=None -> accounting node: leases placed here are served by
        # the head's worker pool (no NodeAgent to RPC).
        self._conductor.call("register_node", node_id, dict(resources),
                             None, timeout=10.0)
        with self._lock:
            self._nodes[node_id] = {"node_id": node_id,
                                    "node_type": node_type,
                                    "resources": dict(resources)}
        return node_id

    def terminate_node(self, node_id: str) -> None:
        ok = self._conductor.call("deregister_node", node_id, timeout=10.0)
        if ok:
            with self._lock:
                self._nodes.pop(node_id, None)

    def non_terminated_nodes(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._nodes.values())


class CommandNodeProvider(NodeProvider):
    """Launch/terminate nodes by running shell commands — the analog of
    the reference's SSH NodeUpdater (autoscaler/_private/updater.py,
    which ssh's into the host and runs `ray start --address=...`). The
    up command receives the node's identity and the cluster address via
    environment variables, so an ssh one-liner makes it multi-host:

        CommandNodeProvider(
            up_command="ssh $NODE_HOST ray_tpu start "
                       "--address $RAY_TPU_HEAD_ADDRESS "
                       "--node-id $RAY_TPU_NODE_ID "
                       "--resources \"$RAY_TPU_NODE_RESOURCES\"")

    Bootstrap VERIFICATION is the autoscaler's watchdog: the launched
    node must register under RAY_TPU_NODE_ID within bootstrap_timeout_s
    or it is torn down and retried. `down_command` (same env) tears a
    node down; without one, the locally launched process group is
    killed — only meaningful when the command itself is the node."""

    def __init__(self, up_command: str,
                 down_command: Optional[str] = None,
                 head_address: Optional[str] = None,
                 extra_env: Optional[Dict[str, str]] = None):
        import os

        if head_address is None:
            from ray_tpu._private import worker as worker_mod

            h, p = worker_mod.global_worker.conductor_address
            head_address = f"{h}:{p}"
        self._up = up_command
        self._down = down_command
        self._head = head_address
        self._env = dict(extra_env or {})
        self._nodes: Dict[str, Dict[str, Any]] = {}
        self._procs: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._environ = os.environ

    def _node_env(self, node_id: str, resources: Dict[str, float]):
        import json as _json

        env = dict(self._environ)
        env.update(self._env)
        env.update({"RAY_TPU_NODE_ID": node_id,
                    "RAY_TPU_HEAD_ADDRESS": self._head,
                    "RAY_TPU_NODE_RESOURCES": _json.dumps(resources)})
        return env

    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        import subprocess

        node_id = f"cmd_{node_type}_{uuid.uuid4().hex[:8]}"
        proc = subprocess.Popen(
            self._up, shell=True, start_new_session=True,
            env=self._node_env(node_id, resources))
        with self._lock:
            self._nodes[node_id] = {"node_id": node_id,
                                    "node_type": node_type,
                                    "resources": dict(resources)}
            self._procs[node_id] = proc
        return node_id

    def terminate_node(self, node_id: str) -> None:
        import os
        import signal
        import subprocess

        with self._lock:
            rec = self._nodes.pop(node_id, None)
            proc = self._procs.pop(node_id, None)
        if rec is None:
            return
        if self._down:
            subprocess.run(self._down, shell=True, timeout=60.0,
                           env=self._node_env(node_id,
                                              rec["resources"]))
        elif proc is not None and proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except OSError:
                pass

    def non_terminated_nodes(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._nodes.values())


def _fits(avail: Dict[str, float], req: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in req.items())


def _subtract(avail: Dict[str, float], req: Dict[str, float]) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) - v


@dataclass
class _TrackedNode:
    node_id: str
    node_type: str
    idle_since: Optional[float] = None


@dataclass
class _PendingLaunch:
    """A created-but-not-yet-registered node under the bootstrap
    watchdog."""
    node_type: str
    resources: Dict[str, float]
    launched_at: float
    attempt: int = 0


class StandardAutoscaler:
    """The reconcile loop — reference autoscaler.py:172 update():
    read demand → enforce min_workers → bin-pack unmet demand onto node
    types → launch → terminate long-idle nodes."""

    def __init__(self, config: AutoscalerConfig, provider: NodeProvider,
                 conductor_client=None):
        if conductor_client is None:
            from ray_tpu._private import worker as worker_mod

            conductor_client = worker_mod.global_worker.conductor
        self._conductor = conductor_client
        self.config = config
        self.provider = provider
        self._tracked: Dict[str, _TrackedNode] = {}
        # nodes we launched that haven't shown up in the cluster view yet —
        # their capacity must count as free or every reconcile round
        # re-launches for the same demand (the reference tracks pending
        # launches for exactly this reason); each carries its bootstrap
        # deadline/attempt for the watchdog
        self._provisioning: Dict[str, _PendingLaunch] = {}
        # node_type -> monotonic time before which no new launches
        # (bootstrap repeatedly failed — stop the relaunch storm)
        self._type_backoff: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _launch(self, type_name: str, resources: Dict[str, float],
                attempt: int = 0) -> str:
        nid = self.provider.create_node(type_name, dict(resources))
        self._tracked.setdefault(nid, _TrackedNode(nid, type_name))
        self._provisioning[nid] = _PendingLaunch(
            type_name, dict(resources), time.monotonic(), attempt)
        return nid

    def _launchable(self, type_name: str, now: float) -> bool:
        return now >= self._type_backoff.get(type_name, 0.0)

    def _bootstrap_watchdog(self, now: float, cluster_nodes) -> List[str]:
        """Tear down nodes that never registered within
        bootstrap_timeout_s; relaunch up to max_bootstrap_retries, then
        back the node type off (reference updater.py: a NodeUpdater that
        fails marks the node failed and the node is terminated)."""
        failed: List[str] = []
        for nid, p in list(self._provisioning.items()):
            if nid in cluster_nodes:
                # REGISTERING ends bootstrap — a later death is the
                # failure-detection domain, and its capacity must not
                # keep counting as provisioning-free
                del self._provisioning[nid]
                continue
            if now - p.launched_at < self.config.bootstrap_timeout_s:
                continue
            try:
                self.provider.terminate_node(nid)
            except Exception:  # noqa: BLE001 — may not exist anymore
                pass
            del self._provisioning[nid]
            self._tracked.pop(nid, None)
            failed.append(nid)
            if p.attempt < self.config.max_bootstrap_retries and \
                    self._launchable(p.node_type, now):
                self._launch(p.node_type, p.resources, p.attempt + 1)
            else:
                self._type_backoff[p.node_type] = \
                    now + self.config.bootstrap_backoff_s
        return failed

    # -- one reconcile round -------------------------------------------------
    def update(self) -> Dict[str, Any]:
        now = time.monotonic()
        demand = [d["resources"] for d in
                  self._conductor.call("get_pending_demand", timeout=10.0)
                  if d["age_s"] >= self.config.min_demand_age_s]
        cluster_nodes = {n["node_id"]: n for n in
                        self._conductor.call("nodes", timeout=10.0)}
        provider_nodes = {n["node_id"]: n
                          for n in self.provider.non_terminated_nodes()}
        # adopt/forget provider nodes
        for nid, n in provider_nodes.items():
            self._tracked.setdefault(
                nid, _TrackedNode(nid, n["node_type"]))
        for nid in list(self._tracked):
            if nid not in provider_nodes:
                del self._tracked[nid]
        # provider forgot a node we thought was provisioning
        for nid in list(self._provisioning):
            if nid not in provider_nodes:
                del self._provisioning[nid]

        bootstrap_failed = self._bootstrap_watchdog(now, cluster_nodes)

        counts: Dict[str, int] = {t: 0 for t in self.config.node_types}
        for t in self._tracked.values():
            counts[t.node_type] = counts.get(t.node_type, 0) + 1

        launched: List[str] = []
        free: List[Dict[str, float]] = [
            dict(n["available"]) for n in cluster_nodes.values()
            if n.get("alive")]
        free += [dict(p.resources) for p in self._provisioning.values()]

        # 1) enforce min_workers (respecting bootstrap backoff)
        for type_name, cfg in self.config.node_types.items():
            while counts.get(type_name, 0) < cfg.min_workers and \
                    self._launchable(type_name, now):
                self._launch(type_name, cfg.resources)
                counts[type_name] = counts.get(type_name, 0) + 1
                launched.append(type_name)
                free.append(dict(cfg.resources))

        # 2) bin-pack unmet demand (first-fit over current free + planned
        #    nodes, largest demands first — resource_demand_scheduler.py)
        unmet: List[Dict[str, float]] = []
        for req in sorted(demand, key=lambda r: -sum(r.values())):
            for avail in free:
                if _fits(avail, req):
                    _subtract(avail, req)
                    break
            else:
                unmet.append(req)
        for req in unmet:
            for type_name, cfg in self.config.node_types.items():
                if counts.get(type_name, 0) >= cfg.max_workers:
                    continue
                if not self._launchable(type_name, now):
                    continue
                if _fits(dict(cfg.resources), req):
                    self._launch(type_name, cfg.resources)
                    counts[type_name] += 1
                    launched.append(type_name)
                    free.append(dict(cfg.resources))
                    _subtract(free[-1], req)
                    break

        # 3) terminate long-idle autoscaled nodes above min_workers.
        # "idle" is NOT just available == total: that was a
        # FakeNodeProvider-era assumption from when accounting nodes
        # never hosted live work. Zero-resource actor leases (0-CPU
        # serve replicas, disagg tiers) take nothing from the node's
        # resource pool, so a node can read available == total while
        # actively serving — check for live workers leased against the
        # node before calling it idle.
        try:
            workers = self._conductor.call("list_workers", timeout=10.0)
        except Exception:  # noqa: BLE001 — conductor briefly away: skip
            workers = None  # termination this round, never guess idle
        busy_nodes = set()
        if workers is not None:
            busy_nodes = {
                w.get("lease_node_id") or w.get("node_id")
                for w in workers
                if w.get("state") in ("ACTOR", "BUSY")}
        terminated: List[str] = []
        for nid, t in list(self._tracked.items()):
            if workers is None:
                # can't tell busy from idle: skip termination this
                # round WITHOUT resetting idle clocks — a conductor
                # hiccup must not make every idle node re-earn its
                # whole idle_timeout_s
                break
            n = cluster_nodes.get(nid)
            if n is None:
                continue
            idle = (n.get("alive") and n["available"] == n["total"]
                    and nid not in busy_nodes)
            if not idle:
                t.idle_since = None
                continue
            if t.idle_since is None:
                t.idle_since = now
                continue
            cfg = self.config.node_types.get(t.node_type)
            if cfg is None:
                continue  # foreign node type (pre-existing provider node)
            if now - t.idle_since >= self.config.idle_timeout_s and \
                    counts.get(t.node_type, 0) > cfg.min_workers and \
                    not demand:
                self.provider.terminate_node(nid)
                counts[t.node_type] -= 1
                del self._tracked[nid]
                self._provisioning.pop(nid, None)
                terminated.append(nid)
        stats = {"pending_demand": len(demand), "launched": launched,
                 "terminated": terminated, "counts": counts,
                 "bootstrap_failed": bootstrap_failed}
        self._publish_status(stats)
        return stats

    def _publish_status(self, stats: Dict[str, Any]) -> None:
        """Mirror reconcile results into the conductor KV so the
        dashboard's autoscaler view works from any process (the analog
        of the reference's `ray status` debug-state output,
        autoscaler/_private/monitor.py)."""
        import json as _json

        status = {
            "timestamp": time.time(),
            "counts": stats["counts"],
            "pending_demand": stats["pending_demand"],
            "last_launched": stats["launched"],
            "last_terminated": stats["terminated"],
            "bootstrap_failed": stats["bootstrap_failed"],
            "provisioning": [
                {"node_id": nid, "node_type": p.node_type,
                 "attempt": p.attempt}
                for nid, p in self._provisioning.items()],
            "node_types": {
                name: {"min_workers": c.min_workers,
                       "max_workers": c.max_workers,
                       "resources": c.resources}
                for name, c in self.config.node_types.items()},
        }
        try:
            self._conductor.call(
                "kv_put", b"autoscaler:status",
                _json.dumps(status).encode(), True, "autoscaler",
                timeout=5.0)
        except Exception:  # noqa: BLE001 — status mirror is best-effort
            pass

    # -- loop ----------------------------------------------------------------
    def start(self) -> "StandardAutoscaler":
        def loop():
            import traceback

            while not self._stop.wait(self.config.update_interval_s):
                try:
                    self.update()
                except Exception:  # noqa: BLE001 — keep reconciling, loudly
                    traceback.print_exc()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
