"""ray_tpu.workflow — durable DAG execution, analog of the reference's
python/ray/workflow/ (api.py workflow.run/resume, workflow_executor.py,
workflow_state.py step state machine, workflow_storage.py idempotent
storage).

A workflow is a ray_tpu.dag graph run with per-step checkpointing: each
step's result is persisted before dependents run, so `resume()` after a
crash (or cluster restart) re-executes only unfinished steps. Steps execute
as normal tasks/actor calls; independent steps run concurrently."""
from __future__ import annotations

import threading
import time
import traceback
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

from ray_tpu.dag.dag_node import (ClassMethodNode, DAGNode, FunctionNode,
                                  InputAttributeNode, InputNode,
                                  MultiOutputNode)

from . import storage as _storage
from .events import (EventListener, HTTPListener,  # noqa: F401
                     TimerListener, get_event, http_event_provider,
                     wait_for_event)
from .storage import WorkflowStorage, delete_workflow, list_workflow_ids

__all__ = ["run", "run_async", "resume", "resume_async", "get_status",
           "get_output", "list_all", "cancel", "delete", "WorkflowStatus",
           "EventListener", "TimerListener", "HTTPListener",
           "wait_for_event", "http_event_provider", "get_event"]


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    CANCELED = "CANCELED"


_cancel_flags: Dict[str, threading.Event] = {}
_cancel_lock = threading.Lock()


def _step_key(node: DAGNode, index: int) -> str:
    """Stable step identity across resumes: topo position + symbolic name
    (reference workflow_state_from_storage.py keys steps by name)."""
    if isinstance(node, FunctionNode):
        name = getattr(node._remote_fn, "__name__", "fn")
    elif isinstance(node, ClassMethodNode):
        name = node._method_name
    else:
        name = type(node).__name__
    return f"{index:04d}_{name}"


def _cancel_refs(pending) -> None:
    """Cooperatively cancel every submitted-but-unconsumed step."""
    import ray_tpu

    for _node, _key, ref in pending:
        try:
            ray_tpu.cancel(ref)
        except Exception:  # noqa: BLE001 — may already be done
            pass


def _execute_workflow(workflow_id: str, store: WorkflowStorage) -> Any:
    """Run (or finish) the stored DAG, checkpointing each step."""
    import ray_tpu

    dag, run_args, run_kwargs = store.load_dag()
    with _cancel_lock:
        cancel = _cancel_flags.setdefault(workflow_id, threading.Event())

    topo = dag._topo_order()
    keys = {n._id: _step_key(n, i) for i, n in enumerate(topo)}
    resolved: Dict[int, Any] = {}
    pending: List[tuple] = []  # (node_id, key, ref) awaiting checkpoint
    try:
        # Submit eagerly: uncheckpointed steps get ObjectRefs that chain
        # through downstream submissions, so independent steps execute
        # concurrently; checkpointing trails in topo order below. A crash
        # between completion and checkpoint just re-runs that step on
        # resume (steps must be idempotent — same contract as the
        # reference's workflow_executor).
        for node in topo:
            if cancel.is_set():
                store.update_meta(status=WorkflowStatus.CANCELED,
                                  finished=time.time())
                _cancel_refs(pending)
                raise RuntimeError(f"workflow {workflow_id} canceled")
            key = keys[node._id]
            if isinstance(node, (InputNode, InputAttributeNode,
                                 MultiOutputNode)):
                # structural nodes are recomputed, never checkpointed
                resolved[node._id] = node._execute_impl(
                    resolved, run_args, run_kwargs)
                continue
            if store.has_step(key):  # idempotent resume: skip finished work
                resolved[node._id] = store.load_step(key)
                continue
            ref = node._execute_impl(resolved, run_args, run_kwargs)
            resolved[node._id] = ref
            pending.append((node, key, ref))
        for node, key, ref in pending:
            # bounded waits so a cancel interrupts even a step that will
            # never finish (e.g. wait_for_event with no event coming) —
            # and the in-flight tasks are cooperatively cancelled so
            # they stop occupying workers
            while True:
                if cancel.is_set():
                    store.update_meta(status=WorkflowStatus.CANCELED,
                                      finished=time.time())
                    _cancel_refs(pending)
                    raise RuntimeError(f"workflow {workflow_id} canceled")
                try:
                    value = ray_tpu.get(ref, timeout=1.0)
                    break
                except ray_tpu.exceptions.GetTimeoutError:
                    continue
            store.save_step(key, value)
            resolved[node._id] = value
            listener_cls = getattr(node, "_wf_event_listener", None)
            if listener_cls is not None:
                # the event is durably recorded: let the provider drop
                # its copy (exactly-once into the workflow — see
                # events.py module docstring)
                try:
                    listener_cls().event_checkpointed(value)
                except Exception:  # noqa: BLE001 — commit hook is
                    pass           # best-effort; re-delivery is benign
        output = resolved[dag._id]
        if isinstance(output, list):  # MultiOutputNode members
            output = [resolved[n._id] for n in dag._outputs] \
                if isinstance(dag, MultiOutputNode) else output
        store.save_output(output)
        store.update_meta(status=WorkflowStatus.SUCCESSFUL,
                          finished=time.time())
        return output
    except Exception:
        if (store.load_meta() or {}).get("status") != WorkflowStatus.CANCELED:
            store.update_meta(status=WorkflowStatus.FAILED,
                              finished=time.time(),
                              error=traceback.format_exc())
        raise


def run(dag: DAGNode, *args, workflow_id: Optional[str] = None,
        **kwargs) -> Any:
    """Execute a DAG durably and return its output — reference
    workflow/api.py run()."""
    import uuid

    if not isinstance(dag, DAGNode):
        raise TypeError("workflow.run takes a DAG built with .bind()")
    import hashlib

    import cloudpickle

    workflow_id = workflow_id or f"workflow_{uuid.uuid4().hex[:12]}"
    store = WorkflowStorage(workflow_id)
    meta = store.load_meta()
    if meta is not None and meta.get("status") == WorkflowStatus.RUNNING:
        raise RuntimeError(f"workflow {workflow_id} is already running")
    if meta is not None and meta.get("status") == WorkflowStatus.SUCCESSFUL:
        return store.load_output()
    dag_bytes = cloudpickle.dumps((dag, args, kwargs))
    fingerprint = hashlib.sha256(dag_bytes).hexdigest()
    if meta is not None and meta.get("fingerprint") != fingerprint:
        # re-run under the same id with a DIFFERENT dag/args: step keys may
        # collide, so stale checkpoints would be silently mixed in — clear
        # them (conservative: any pickle difference clears)
        for key in store.list_steps():
            try:
                import os as _os

                _os.unlink(store._step_path(key))
            except OSError:
                pass
    store.save_dag(dag, args, kwargs)
    store.update_meta(status=WorkflowStatus.RUNNING, started=time.time(),
                      fingerprint=fingerprint)
    with _cancel_lock:
        _cancel_flags[workflow_id] = threading.Event()
    return _execute_workflow(workflow_id, store)


def run_async(dag: DAGNode, *args, workflow_id: Optional[str] = None,
              **kwargs) -> "Future[Any]":
    fut: "Future[Any]" = Future()

    def body():
        try:
            fut.set_result(run(dag, *args, workflow_id=workflow_id,
                               **kwargs))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=body, daemon=True,
                     name=f"workflow-{workflow_id}").start()
    return fut


def resume(workflow_id: str) -> Any:
    """Re-run only the unfinished steps of a stored workflow — reference
    workflow/api.py resume() + workflow_state_from_storage.py."""
    store = WorkflowStorage(workflow_id)
    meta = store.load_meta()
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    if meta.get("status") == WorkflowStatus.SUCCESSFUL:
        return store.load_output()
    store.update_meta(status=WorkflowStatus.RUNNING, resumed=time.time())
    with _cancel_lock:
        _cancel_flags[workflow_id] = threading.Event()
    return _execute_workflow(workflow_id, store)


def resume_async(workflow_id: str) -> "Future[Any]":
    fut: "Future[Any]" = Future()

    def body():
        try:
            fut.set_result(resume(workflow_id))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=body, daemon=True).start()
    return fut


def get_status(workflow_id: str) -> str:
    meta = WorkflowStorage(workflow_id).load_meta()
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    return meta.get("status", WorkflowStatus.RUNNING)


def get_output(workflow_id: str) -> Any:
    store = WorkflowStorage(workflow_id)
    if not store.has_output():
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status={get_status(workflow_id)})")
    return store.load_output()


def get_error(workflow_id: str) -> Optional[str]:
    meta = WorkflowStorage(workflow_id).load_meta() or {}
    return meta.get("error")


def list_all(status_filter: Optional[str] = None
             ) -> List[Dict[str, Any]]:
    out = []
    for wid in list_workflow_ids():
        meta = WorkflowStorage(wid).load_meta() or {"workflow_id": wid}
        if status_filter is None or meta.get("status") == status_filter:
            out.append(meta)
    return out


def cancel(workflow_id: str) -> None:
    """Best-effort: running executors observe the flag between steps —
    reference workflow.cancel. No-op on already-terminal workflows."""
    store = WorkflowStorage(workflow_id)
    status = (store.load_meta() or {}).get("status")
    if status in (WorkflowStatus.SUCCESSFUL, WorkflowStatus.FAILED,
                  WorkflowStatus.CANCELED):
        return
    with _cancel_lock:
        flag = _cancel_flags.get(workflow_id)
    if flag is not None:
        flag.set()
    store.update_meta(status=WorkflowStatus.CANCELED, finished=time.time())


def delete(workflow_id: str) -> bool:
    return delete_workflow(workflow_id)
