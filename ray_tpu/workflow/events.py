"""Workflow event integration — wait_for_event + event providers.

Reference: python/ray/workflow/event_listener.py (EventListener,
TimerListener) and workflow/http_event_provider.py (HTTPEventProvider, a
Serve deployment external systems POST events to, + HTTPListener). The
same contract here: a `wait_for_event(ListenerType, ...)` DAG node polls
the listener inside a durable step; after the step's result is
CHECKPOINTED the executor calls `event_checkpointed(event)` so the
provider may discard its copy — exactly-once delivery into the workflow
(crash before checkpoint → the event is still held and re-polled;
crash after → resume skips the step entirely).

The HTTP provider stores events in the conductor KV (namespace
"workflow_events"), so listeners poll one RPC, not the Serve replica.
"""
from __future__ import annotations

import time
from typing import Any, Optional, Tuple

from ..dag import FunctionNode

_KV_NAMESPACE = "workflow_events"


class EventListener:
    """Subclass with `poll_for_event(*args, **kwargs) -> event` (block
    until available) and optionally `event_checkpointed(event)` (called
    once the workflow has durably recorded it)."""

    def poll_for_event(self, *args, **kwargs) -> Any:
        raise NotImplementedError

    def event_checkpointed(self, event: Any) -> None:  # noqa: B027
        """Post-checkpoint commit hook; default: nothing to release."""


class TimerListener(EventListener):
    """Fires at an absolute unix timestamp (reference TimerListener)."""

    def poll_for_event(self, timestamp: float) -> float:
        time.sleep(max(0.0, timestamp - time.time()))
        return timestamp


def _kv(method: str, *args):
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        raise RuntimeError("workflow events need ray_tpu.init()")
    return w.conductor.call(method, *args, timeout=10.0)


class HTTPListener(EventListener):
    """Waits for an event POSTed to the HTTPEventProvider under
    `event_key` (reference http_event_provider.py HTTPListener)."""

    poll_interval_s = 0.2

    def poll_for_event(self, event_key: str) -> Tuple[str, Any]:
        while True:
            msg = _kv("kv_get", f"event:{event_key}", _KV_NAMESPACE)
            if msg is not None:
                return (event_key, msg)
            time.sleep(self.poll_interval_s)

    def event_checkpointed(self, event: Tuple[str, Any]) -> None:
        _kv("kv_del", f"event:{event[0]}", _KV_NAMESPACE)


def wait_for_event(listener_type: type, *args, **kwargs) -> FunctionNode:
    """A DAG node that resolves to the listener's event (reference
    workflow/api.py:607 wait_for_event). Compose it like any other bound
    step:

        event = wait_for_event(HTTPListener, event_key="approved")
        result = decide.bind(event)
        workflow.run(result)
    """
    if not (isinstance(listener_type, type)
            and issubclass(listener_type, EventListener)):
        raise TypeError(f"{listener_type!r} is not an EventListener "
                        "subclass")
    import ray_tpu

    @ray_tpu.remote
    def _poll_event(*a, **kw):
        return listener_type().poll_for_event(*a, **kw)

    # stable step identity across resumes (_step_key reads __name__)
    _poll_event.__name__ = f"event_{listener_type.__name__}"
    node = FunctionNode(_poll_event, args, kwargs)
    node._wf_event_listener = listener_type
    return node


def http_event_provider():
    """The Serve deployment external systems POST events to (reference
    HTTPEventProvider — bind and `serve.run` it):

        serve.run(http_event_provider().bind(),
                  name="event_provider", route_prefix="/event")

    POST {"event_key": "...", "event_payload": ...} to /event/send_event;
    the provider stores the payload for the matching HTTPListener and
    replies 200. Replays before the workflow checkpoints overwrite the
    stored copy (same at-least-once ingest as the reference)."""
    from ray_tpu import serve

    @serve.deployment
    class HTTPEventProvider:
        def __call__(self, request):
            if not request.path.rstrip("/").endswith("send_event"):
                return (404, {"error": "POST to <prefix>/send_event"})
            body = request.json()
            key = body.get("event_key")
            if not key:
                return (400, {"error": "missing event_key"})
            _kv("kv_put", f"event:{key}", body.get("event_payload"),
                True, _KV_NAMESPACE)
            return {"status": "ok", "event_key": key}

    return HTTPEventProvider


def get_event(event_key: str) -> Optional[Any]:
    """Peek at a stored, not-yet-consumed event (debugging aid)."""
    return _kv("kv_get", f"event:{event_key}", _KV_NAMESPACE)


__all__ = ["EventListener", "TimerListener", "HTTPListener",
           "wait_for_event", "http_event_provider", "get_event"]
