"""Workflow storage — analog of the reference's
python/ray/workflow/workflow_storage.py: a filesystem layout holding the
serialized DAG, per-step checkpoints, and workflow metadata, addressed by
workflow_id and durable across cluster restarts."""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

import cloudpickle


def storage_root() -> str:
    return os.environ.get(
        "RAY_TPU_WORKFLOW_STORAGE",
        os.path.join(os.path.expanduser("~"), ".ray_tpu", "workflows"))


def _validate_id(workflow_id: str) -> str:
    """Reject ids that could escape the storage root ('..', separators)."""
    if not workflow_id or workflow_id in (".", "..") or \
            "/" in workflow_id or "\\" in workflow_id or \
            os.sep in workflow_id:
        raise ValueError(f"bad workflow id {workflow_id!r}")
    return workflow_id


class WorkflowStorage:
    def __init__(self, workflow_id: str):
        _validate_id(workflow_id)
        self.workflow_id = workflow_id
        self.root = os.path.join(storage_root(), workflow_id)
        os.makedirs(os.path.join(self.root, "steps"), exist_ok=True)

    # -- atomic file helpers -------------------------------------------------
    def _write(self, path: str, data: bytes) -> None:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
        os.replace(tmp, path)

    # -- metadata ------------------------------------------------------------
    def save_meta(self, meta: Dict[str, Any]) -> None:
        self._write(os.path.join(self.root, "meta.json"),
                    json.dumps(meta).encode())

    def load_meta(self) -> Optional[Dict[str, Any]]:
        try:
            with open(os.path.join(self.root, "meta.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def update_meta(self, **kv: Any) -> Dict[str, Any]:
        meta = self.load_meta() or {"workflow_id": self.workflow_id,
                                    "created": time.time()}
        meta.update(kv)
        self.save_meta(meta)
        return meta

    # -- DAG -----------------------------------------------------------------
    def save_dag(self, dag: Any, run_args: tuple, run_kwargs: dict) -> None:
        self._write(os.path.join(self.root, "dag.pkl"),
                    cloudpickle.dumps((dag, run_args, run_kwargs)))

    def load_dag(self):
        with open(os.path.join(self.root, "dag.pkl"), "rb") as f:
            return cloudpickle.load(f)

    # -- steps ---------------------------------------------------------------
    def _step_path(self, step_key: str) -> str:
        return os.path.join(self.root, "steps", f"{step_key}.pkl")

    def has_step(self, step_key: str) -> bool:
        return os.path.exists(self._step_path(step_key))

    def save_step(self, step_key: str, result: Any) -> None:
        self._write(self._step_path(step_key), cloudpickle.dumps(result))

    def load_step(self, step_key: str) -> Any:
        with open(self._step_path(step_key), "rb") as f:
            return cloudpickle.load(f)

    def list_steps(self) -> List[str]:
        return [f[:-4] for f in os.listdir(os.path.join(self.root, "steps"))
                if f.endswith(".pkl")]

    # -- output --------------------------------------------------------------
    def save_output(self, value: Any) -> None:
        self._write(os.path.join(self.root, "output.pkl"),
                    cloudpickle.dumps(value))

    def load_output(self) -> Any:
        with open(os.path.join(self.root, "output.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def has_output(self) -> bool:
        return os.path.exists(os.path.join(self.root, "output.pkl"))


def list_workflow_ids() -> List[str]:
    root = storage_root()
    try:
        return sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
    except FileNotFoundError:
        return []


def delete_workflow(workflow_id: str) -> bool:
    import shutil

    _validate_id(workflow_id)
    path = os.path.join(storage_root(), workflow_id)
    if not os.path.isdir(path):
        return False
    shutil.rmtree(path)
    return True
