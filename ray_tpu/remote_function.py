"""@remote functions — analog of the reference's
python/ray/remote_function.py (RemoteFunction._remote :266): wrap a callable,
give it `.remote()` returning ObjectRefs, and `.options()` for per-call
overrides."""
from __future__ import annotations

import functools
import os
from typing import Any, Dict, Optional

from ._private import worker as worker_mod
from ._private.worker import DEFAULT_MAX_RETRIES
from .util import scheduling_strategies as _sched


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._options = dict(options or {})
        # function bytes serialized once per RemoteFunction, not per
        # .remote() call (reference: function table export happens once,
        # function_manager.py) — per-call cloudpickle was a measurable
        # share of submission cost in the pipelined microbench
        self._fn_bytes: Optional[bytes] = None
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._fn.__name__} cannot be called directly; "
            f"use {self._fn.__name__}.remote(...)")

    def options(self, **overrides) -> "RemoteFunction":
        opts = dict(self._options)
        opts.update(overrides)
        return RemoteFunction(self._fn, opts)

    def remote(self, *args, **kwargs):
        w = worker_mod.global_worker
        if w is None:
            raise RuntimeError("ray_tpu.init() must be called first")
        o = self._options
        resources = dict(o.get("resources") or {})
        if o.get("num_cpus") is not None:
            resources["CPU"] = float(o["num_cpus"])
        if o.get("num_tpus") is not None:
            resources["TPU"] = float(o["num_tpus"])
        pg = o.get("placement_group")
        pg_id = getattr(pg, "id", pg) if pg is not None else None
        if self._fn_bytes is None:
            from ._private import serialization
            self._fn_bytes = serialization.dumps(self._fn)
        name = o.get("name") or self._fn.__name__

        def submit():
            return w.submit_task(
                self._fn, args, kwargs,
                fn_bytes=self._fn_bytes,
                name=name,
                num_returns=int(o.get("num_returns", 1)),
                resources=resources,
                max_retries=o.get("max_retries", DEFAULT_MAX_RETRIES),
                placement_group_id=pg_id,
                runtime_env=o.get("runtime_env"),
                scheduling_strategy=_sched.to_wire(
                    o.get("scheduling_strategy", "DEFAULT")))

        # Unified timeline: with tracing on, submission gets its own span
        # so a trace shows submit -> worker execute as parent -> child
        # (the traceparent captured in the TaskSpec is THIS span's). The
        # env gate keeps the common tracing-off path import-free.
        if os.environ.get("RAY_TPU_TRACING") == "1":
            from .util import tracing

            with tracing.submit_span(name):
                return submit()
        return submit()

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node — reference python/ray/dag/function_node.py
        via remote_function.py bind()."""
        from .dag import FunctionNode
        return FunctionNode(self, args, kwargs)

    @property
    def underlying_function(self):
        return self._fn
