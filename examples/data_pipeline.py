"""Dataset example: streaming transforms, join, groupby, device feed.

    python examples/data_pipeline.py
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import ray_tpu
from ray_tpu import data as rd


def main() -> None:
    ray_tpu.init(num_cpus=4)
    try:
        users = rd.from_items(
            [{"uid": i, "region": "us" if i % 2 else "eu"}
             for i in range(100)])
        events = rd.range(1000, parallelism=8).map(
            lambda r: {"uid": r["id"] % 100, "value": float(r["id"])})

        joined = events.join(users, on="uid")
        by_region = joined.groupby("region").mean("value")
        print(by_region.take_all())

        # stream batches toward a training loop
        it = joined.select_columns(["value"]).iter_batches(
            batch_size=128, batch_format="numpy")
        total = sum(b["value"].sum() for b in it)
        print(f"sum over stream: {total:.0f}")
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
