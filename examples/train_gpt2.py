"""Minimal end-to-end training example: GPT-2 on a device mesh.

Run (CPU, virtual 8-device mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_gpt2.py --platform cpu
On a TPU slice, drop --platform (the mesh spans the local chips).
"""
import argparse

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="",
                    help="force a jax platform (e.g. cpu)")
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import optax

    from ray_tpu.models.gpt2 import (GPT2Config, gpt2_init, gpt2_loss,
                                     gpt2_partition_specs)
    from ray_tpu.parallel.mesh import MeshConfig, make_mesh
    from ray_tpu.train.trainer import TrainStep

    cfg = GPT2Config.tiny()
    devices = jax.devices()
    # dp fills whatever tp=2 leaves (single device => dp=1, tp=1)
    tp = 2 if len(devices) % 2 == 0 else 1
    mesh = make_mesh(MeshConfig(dp=-1, tp=tp), devices=devices)
    print(f"mesh: {dict(mesh.shape)} on {devices[0].platform}")

    step = TrainStep(
        lambda p, b: gpt2_loss(p, b["tokens"], b["targets"], cfg),
        optax.adamw(1e-3), mesh, gpt2_partition_specs(cfg))
    state = step.init_state(gpt2_init(cfg, jax.random.PRNGKey(0)))

    rng = np.random.default_rng(0)
    dp_total = mesh.shape["dp"] * mesh.shape["fsdp"]
    tok = rng.integers(0, cfg.vocab_size, (2 * dp_total, 65),
                      dtype=np.int32)
    batch = {"tokens": tok[:, :-1], "targets": tok[:, 1:]}
    for i in range(args.steps):
        state, metrics = step(state, batch)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  loss {float(metrics['loss']):.4f}")


if __name__ == "__main__":
    main()
