"""A Serve application for the declarative deploy example.

    python -m ray_tpu serve run examples/serve_config.yaml
    curl -X POST localhost:8000/classify -d '{"x": [1.0, 2.0]}'
"""
from ray_tpu import serve


@serve.deployment
class Preprocessor:
    def transform(self, xs):
        return [float(x) * 2 for x in xs]


@serve.deployment
class Model:
    def __init__(self, preprocessor, bias: float = 0.0):
        self.pre = preprocessor
        self.bias = bias

    def __call__(self, request):
        xs = self.pre.transform.remote(request.json()["x"]).result()
        return {"score": sum(xs) + self.bias}


app = Model.bind(Preprocessor.bind())


def build(args):
    """Builder entry point: YAML `args` configure the app."""
    return Model.bind(Preprocessor.bind(), float(args.get("bias", 0.0)))
