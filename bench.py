"""Headline benchmark: GPT-2 125M training throughput, tokens/sec/chip.

Runs the full JaxTrainer TrainStep (fwd+bwd+adamw, donated state, bf16
params, flash attention) on all local devices with a dp mesh, and prints
ONE JSON line {metric, value, unit, vs_baseline, ...} as the LAST stdout
line.

Self-checking (a round-1 recording was physically impossible — 72x over
chip peak): the script computes the implied model FLOP/s from the
transformer FLOP count and the measured token rate, prints `implied_mfu`,
hard-fails if it exceeds 1.0 of the chip's bf16 peak, and runs the timing
loop twice requiring agreement within 10%.

Baseline: the reference has no in-repo absolute numbers (BASELINE.md —
nightly metrics go to an external DB); the north-star is "within 1.3x of
Ray+NCCL+A100" on GPT-2 125M DDP. We take 140k tokens/sec/chip as the
A100-class reference point (bf16+flash-attention GPT-2 124M DDP, public
nanoGPT-scale numbers), so vs_baseline = measured / 140000.

Wedge-resistance (the axon TPU relay is fragile: a killed mid-flight
pallas compile can wedge it for the whole session, and one wedged child
previously burned the entire 900 s budget and left no number). The
supervisor therefore:
  1. sweeps stale /dev/shm/rtpu_a_* slabs (leaked segments degrade or
     break the shm arena and the measurement);
  2. enables the persistent XLA compilation cache under .xla_cache/ so
     a retry never pays the same cold compile twice;
  3. spends ~2 min on a tiny-jit HEALTH child before committing the big
     budget — a wedged relay is detected for pennies;
  4. runs the MEASURE child with known-good defaults only (flash blocks
     1024/1024, per-chip batch 32, no autotune sweep, no fused-bwd
     probe): the minimal risk path to a number on disk;
  5. runs kernel exploration (fused-bwd probe, block autotune) only
     AFTER the headline number has been PRINTED, each in its own
     bounded child; an improved record is printed as a later line (the
     driver takes the last one), so exploration can only improve the
     result, never lose it. BENCH_EXPLORE=0 disables;
  6. on TPU-path failure, sweeps shm and retries the measure child ONCE
     (retry-with-reset), then — if still failing — emits the headline
     metric at value 0.0 with the failure named and, when a
     JAX_PLATFORMS=cpu probe succeeds, nests that record under a loudly
     marked "cpu_fallback" key. A CPU number can never masquerade as
     the tokens/s/chip trajectory headline (the r04/r05 lie);
  7. runs a supervised SERVE stage (same child runner) that replays a
     Zipf shared-system-prompt workload through the continuous-batching
     engine and grafts tokens/s + TTFT p50/p99 + paged-KV prefix hit
     rate into the final record under "serve" — never as the headline,
     so a CPU serve fallback cannot masquerade as the trajectory
     number. BENCH_SERVE=0 disables;
  8. embeds the step-time oracle's predicted-vs-measured numbers
     ("oracle": roofline prediction + residual_ratio) and attributes
     any regression vs the most recent prior BENCH_r*.json to the
     step_breakdown phase that moved ("regression": {phase, delta_ms,
     pct}) — never attributing against a record whose headline was a
     CPU fallback or a failure.
"""
from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import time

REF_TOKENS_PER_SEC_PER_CHIP = 140_000.0

# Child exit code for a measurement the bench itself declared invalid
# (implied-MFU over chip peak, unstable timing). The supervisor must fail
# loudly on this — a CPU-fallback "success" would silently swallow the
# validity guard.
INVALID_MEASUREMENT_RC = 3

def _chip_peak(device) -> float:
    """bf16 peak FLOP/s per chip — the per-generation table lives in
    ray_tpu.observability.flops (the flight recorder's MFU denominator);
    unknown TPUs map to v4-class so the validity guard stays active."""
    from ray_tpu.observability.flops import device_peak_flops

    return device_peak_flops(device)


def _model_flops_per_token(cfg) -> float:
    """Training FLOPs per token: 6*N_active for the matmuls plus the
    attention score/value terms (12*L*d*T per token fwd+bwd)."""
    n_params = (cfg.padded_vocab * cfg.d_model            # wte (tied head)
                + cfg.max_seq_len * cfg.d_model           # wpe
                + cfg.num_layers * (4 * cfg.d_model * cfg.d_model  # attn
                                    + 8 * cfg.d_model * cfg.d_model))  # mlp
    return 6.0 * n_params


def _attn_flops_per_token(cfg, seq: int, causal: bool = True) -> float:
    # per token: 2 matmuls (QK^T, PV) * 2 * d_model * seq, fwd+bwd = 3x,
    # halved for causal masking.
    per = 12.0 * cfg.num_layers * cfg.d_model * seq
    return per / 2 if causal else per


_TUNED_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           ".bench_tuned.json")


def _load_tuned() -> dict:
    """Kernel settings a previous explore run proved best on this chip
    (committed so a later round's first measurement starts from them
    instead of re-sweeping). Env overrides always win."""
    try:
        with open(_TUNED_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _save_tuned(rec: dict) -> None:
    tuned = {}
    if rec.get("flash_blocks"):
        tuned["flash_blocks"] = rec["flash_blocks"]
    if rec.get("fused_flash_bwd"):
        tuned["fused_flash_bwd"] = True
    if not tuned:
        return
    tuned["tokens_per_sec_per_chip"] = rec.get("value")
    try:
        with open(_TUNED_PATH, "w") as f:
            json.dump(tuned, f, indent=1)
    except OSError:
        pass


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache under the repo: a retried child
    (or a later explore child) skips the cold compile a previous attempt
    already paid for. Best-effort — the experimental axon platform may
    not support it."""
    import jax
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".xla_cache")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        print(f"bench: compile cache unavailable ({e})", file=sys.stderr)


def _time_loop(step, state, batch, iters: int) -> tuple:
    # float() forces a device-to-host read: a real synchronization point
    # even on backends whose block_until_ready is asynchronous (remote
    # tunnels) — without it the loop can time dispatch, not execution.
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch)
    float(metrics["loss"])
    return time.perf_counter() - t0, state, metrics


def _probe_fused_flash_bwd() -> bool:
    """Opt into the fused single-pass flash backward iff it compiles AND
    matches the two-pass backward numerically on this chip — an
    unvalidated kernel must degrade to the slower path, never crash or
    corrupt the benchmark."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.ops.attention import flash_attention

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((2, 256, 2, 64)), jnp.bfloat16)

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True).astype(
            jnp.float32).sum()

    try:
        os.environ["RAY_TPU_FLASH_FUSED_BWD"] = "0"
        ref = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, q, q)
        ref = [np.asarray(g, np.float32) for g in ref]
        os.environ["RAY_TPU_FLASH_FUSED_BWD"] = "1"
        got = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, q, q)
        got = [np.asarray(g, np.float32) for g in got]
        for a, b in zip(got, ref):
            np.testing.assert_allclose(a, b, rtol=3e-2, atol=3e-2)
        return True
    except Exception as e:  # noqa: BLE001 — fall back to two-pass
        os.environ["RAY_TPU_FLASH_FUSED_BWD"] = "0"
        print(f"bench: fused flash bwd disabled ({type(e).__name__}: "
              f"{str(e)[:200]})", file=sys.stderr)
        return False


def _autotune_flash_blocks(make_step, params, batch, warmup: int = 2,
                           iters: int = 6):
    """On-chip sweep of flash-attention block sizes: time the FULL train
    step under each candidate and leave the winner as the module default
    (the attention kernel is the known MFU limiter — BENCH_BLOCKS="q,k"
    pins without sweeping). Each candidate pays one recompile; a failing
    candidate scores 0 and is skipped."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops import attention

    pinned = os.environ.get("BENCH_BLOCKS")
    if pinned:
        bq, bk = (int(x) for x in pinned.split(","))
        attention.set_default_blocks(bq, bk)
        return (bq, bk)
    configs = ((1024, 1024), (512, 1024), (1024, 512), (512, 512),
               (256, 512))
    orig = (attention.DEFAULT_BLOCK_Q, attention.DEFAULT_BLOCK_K)
    best = (0.0, None)
    for bq, bk in configs:
        attention.set_default_blocks(bq, bk)
        try:
            step = make_step()
            state = step.init_state(jax.tree.map(jnp.copy, params))
            _, state, _ = _time_loop(step, state, batch, warmup)
            dt, state, _ = _time_loop(step, state, batch, iters)
            rate = iters / dt
        except Exception as e:  # noqa: BLE001 — candidate failed
            print(f"bench: blocks ({bq},{bk}) failed "
                  f"({type(e).__name__}: {str(e)[:120]})", file=sys.stderr)
            continue
        print(f"bench: blocks ({bq},{bk}) -> {rate:.2f} steps/s",
              file=sys.stderr)
        if rate > best[0]:
            best = (rate, (bq, bk))
    # no winner (every candidate failed): restore the documented
    # defaults rather than leaving the last-swept config installed
    attention.set_default_blocks(*(best[1] or orig))
    return best[1]


def _health_main() -> None:
    """Tiny-jit relay health probe: import jax, list devices, compile and
    run one small matmul. Finishes in seconds on a healthy backend; hangs
    on a wedged relay — which the supervisor detects for ~2 min instead
    of burning the full measurement budget."""
    forced = os.environ.get("_BENCH_PLATFORM")
    import jax
    if forced:
        jax.config.update("jax_platforms", forced)
    import jax.numpy as jnp
    x = jnp.ones((256, 256), jnp.bfloat16)
    y = jax.jit(lambda a: (a @ a).astype(jnp.float32).sum())(x)
    print(json.dumps({"health": "ok", "value": float(y),
                      "platform": jax.devices()[0].platform}))


def main() -> None:
    # The axon sitecustomize force-sets JAX_PLATFORMS, so the cpu
    # fallback must win through jax.config (same guard as tests/conftest):
    # env alone still initializes the (possibly wedged) tunnel plugin.
    forced = os.environ.get("_BENCH_PLATFORM")
    import jax
    if forced:
        jax.config.update("jax_platforms", forced)
    _enable_compile_cache()
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models.gpt2 import (GPT2Config, gpt2_init, gpt2_loss,
                                     gpt2_partition_specs)
    from ray_tpu.parallel.mesh import MeshConfig, make_mesh
    from ray_tpu.train.trainer import TrainStep

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    tuned = _load_tuned() if on_tpu else {}
    # Fused-bwd probe runs when explicitly requested OR when a previous
    # explore run proved the fused kernel out on this chip; the probe
    # costs two extra kernel compiles on the fragile relay, so the
    # default measurement path otherwise skips it.
    fused_env = os.environ.get("RAY_TPU_FLASH_FUSED_BWD")
    fused_bwd = False
    if on_tpu and (fused_env == "1"
                   or (fused_env is None and tuned.get("fused_flash_bwd"))):
        fused_bwd = _probe_fused_flash_bwd()
    cfg = GPT2Config.small() if on_tpu else GPT2Config.tiny()
    seq = cfg.max_seq_len if on_tpu else 64
    per_chip_batch = int(os.environ.get(
        "BENCH_BATCH", "32" if on_tpu else "2"))
    # remat off: with the fused-CE and flash kernels activation memory
    # fits at batch 32, and rematerialization only adds recompute FLOPs
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    warmup, iters = (5, 60) if on_tpu else (2, 5)

    devices = jax.devices()
    mesh = make_mesh(MeshConfig(dp=-1), devices=devices)
    n_chips = len(devices)

    def make_step():
        return TrainStep(
            lambda p, b: gpt2_loss(p, b["tokens"], b["targets"], cfg,
                                   remat=remat),
            optax.adamw(3e-4, weight_decay=0.1), mesh,
            gpt2_partition_specs(cfg))

    params0 = gpt2_init(cfg, jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    batch_np = rng.integers(
        0, cfg.vocab_size, (per_chip_batch * n_chips, seq + 1),
        dtype=np.int32)
    batch = {"tokens": jnp.asarray(batch_np[:, :-1]),
             "targets": jnp.asarray(batch_np[:, 1:])}
    tokens_per_step = per_chip_batch * n_chips * seq

    # Autotune is opt-in (BENCH_AUTOTUNE=1): the known-good blocks
    # (1024/1024, measured best in round 3) are the module defaults, and
    # the sweep's several recompiles belong in an explore child that runs
    # only after a headline number exists. BENCH_BLOCKS="q,k" pins.
    flash_blocks = None
    if on_tpu and (os.environ.get("BENCH_AUTOTUNE", "0") == "1"
                   or os.environ.get("BENCH_BLOCKS")):
        flash_blocks = _autotune_flash_blocks(make_step, params0, batch)
    elif on_tpu:
        from ray_tpu.ops import attention

        if tuned.get("flash_blocks") and not os.environ.get(
                "RAY_TPU_FLASH_BLOCK_Q"):
            attention.set_default_blocks(*tuned["flash_blocks"])
        flash_blocks = (attention.DEFAULT_BLOCK_Q, attention.DEFAULT_BLOCK_K)

    step = make_step()
    state = step.init_state(jax.tree.map(jnp.copy, params0))

    # first call timed apart: it is compile + one step, and the compile
    # share belongs in the record's step_breakdown, not in the average
    compile_dt, state, metrics = _time_loop(step, state, batch, 1)
    if warmup > 1:
        _, state, metrics = _time_loop(step, state, batch, warmup - 1)

    dt1, state, _ = _time_loop(step, state, batch, iters)
    dt2, state, _ = _time_loop(step, state, batch, iters)
    if abs(dt1 - dt2) / max(dt1, dt2) > 0.10:
        print(f"bench: timing runs disagree >10% ({dt1:.3f}s vs {dt2:.3f}s)"
              " — rerunning once", file=sys.stderr)
        dt1, state, _ = _time_loop(step, state, batch, iters)
        dt2, state, _ = _time_loop(step, state, batch, iters)
        if abs(dt1 - dt2) / max(dt1, dt2) > 0.10:
            print(f"bench: unstable measurement ({dt1:.3f}s vs {dt2:.3f}s)",
                  file=sys.stderr)
            sys.exit(INVALID_MEASUREMENT_RC)
    dt = (dt1 + dt2) / 2

    # flight-recorder derivation shared with the oracle harness and the
    # conductor's train_progress: one record per timing run, summarized
    # by step_timer.summarize_records instead of re-deriving inline
    from ray_tpu.observability.step_timer import summarize_records

    run_records = [{"device_step_ms": dt1 / iters * 1e3},
                   {"device_step_ms": dt2 / iters * 1e3}]
    device_summary = summarize_records(run_records)["phases"]["device_step"]

    tok_per_sec_per_chip = tokens_per_step * iters / dt / n_chips
    flops_per_token = (_model_flops_per_token(cfg)
                       + _attn_flops_per_token(cfg, seq))
    implied_flops = tok_per_sec_per_chip * flops_per_token
    peak = _chip_peak(devices[0]) if on_tpu else float("inf")
    implied_mfu = implied_flops / peak
    if implied_mfu > 1.0:
        print(
            f"bench: implied {implied_flops / 1e12:.1f} TFLOP/s/chip exceeds "
            f"chip peak {peak / 1e12:.0f} TFLOP/s (MFU {implied_mfu:.2f}) — "
            "measurement invalid, refusing to report", file=sys.stderr)
        sys.exit(INVALID_MEASUREMENT_RC)

    # Step-time oracle (observability.roofline): the analytic roofline's
    # predicted-vs-measured for this dp layout, embedded so every BENCH
    # record names how far reality sat from the model. The dp grad sync
    # is one psum of the param pytree; on one chip there is no comms
    # term and the prediction is the pure compute roofline.
    from ray_tpu.analysis.collectives import CollectiveUse
    from ray_tpu.analysis.shardcheck import MeshLayout
    from ray_tpu.observability import roofline

    param_bytes = int(sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(params0)
        if hasattr(x, "size")))
    grad_sync = [CollectiveUse("psum", ("dp",), param_bytes)] \
        if n_chips > 1 else []
    predicted = roofline.predict_step_time(
        MeshLayout({"dp": n_chips}, name="bench_dp"), grad_sync,
        flops_per_token * tokens_per_step,
        _chip_peak(devices[0]) * n_chips,
        links=roofline.device_link_constants(devices[0]),
        name="bench_dp")
    measured_ms = device_summary["mean_ms"]
    oracle = {
        "predicted": {k: round(predicted[k], 4) for k in
                      ("device_step_ms", "ici_wait_ms", "dcn_wait_ms",
                       "predicted_step_ms")},
        "measured_device_step_ms": round(measured_ms, 3),
        "residual_ratio": round(
            measured_ms / predicted["predicted_step_ms"], 4)
        if predicted["predicted_step_ms"] > 0 else None,
    }

    print(json.dumps({
        "metric": "gpt2_125m_train_tokens_per_sec_per_chip" if on_tpu
        else f"gpt2_tiny_train_tokens_per_sec_per_chip_{platform}",
        "value": round(tok_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tok_per_sec_per_chip
                             / REF_TOKENS_PER_SEC_PER_CHIP, 3),
        "implied_mfu": round(implied_mfu, 4) if on_tpu else None,
        "per_chip_batch": per_chip_batch,
        "seq_len": seq,
        "remat": remat,
        "n_chips": n_chips,
        "fused_flash_bwd": fused_bwd,
        "flash_blocks": list(flash_blocks) if flash_blocks else None,
        # flight-recorder step breakdown (observability.StepTimer
        # schema) so the BENCH_*.json perf trajectory is self-describing;
        # data_wait is 0 by construction (the synthetic batch is
        # device-resident before the loop).
        "step_breakdown": {
            "data_wait_ms": 0.0,
            "compile_ms": round(compile_dt * 1e3, 1),
            "device_step_ms": round(device_summary["mean_ms"], 3),
            "device_step_p99_ms": round(device_summary["p99_ms"], 3),
            "mfu": round(implied_flops / _chip_peak(devices[0]), 6),
        },
        "oracle": oracle,
    }))


def _serve_main() -> None:
    """Serving benchmark child (`_BENCH_MODE=serve`): replay a
    Zipf-popularity workload of prompts sharing a block-aligned system
    prompt through ContinuousBatchingEngine and report tokens/s, TTFT
    p50/p99, and the paged-KV prefix hit rate. Runs under the same
    supervised subprocess/wedge-detect runner as the training headline;
    its record rides INSIDE the headline JSON under "serve" so a CPU
    fallback here can never become the trajectory headline."""
    forced = os.environ.get("_BENCH_PLATFORM")
    import jax
    if forced:
        jax.config.update("jax_platforms", forced)
    _enable_compile_cache()
    import threading

    import numpy as np

    from ray_tpu.models.engine import ContinuousBatchingEngine
    from ray_tpu.models.llama import LlamaConfig, llama_init

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    cfg = LlamaConfig.small() if on_tpu else LlamaConfig.tiny()
    block = int(os.environ.get("RAY_TPU_KV_BLOCK_SIZE", "16"))
    params = llama_init(cfg, jax.random.PRNGKey(0))
    n_requests = int(os.environ.get(
        "BENCH_SERVE_REQUESTS", "128" if on_tpu else "24"))
    n_distinct = 8
    max_new = 48 if on_tpu else 8
    rng = np.random.default_rng(0)
    # shared system prompt, block-aligned so prefix reuse can bite
    sys_len = 8 * block if on_tpu else 2 * block
    sys_prompt = rng.integers(1, cfg.vocab_size, sys_len).tolist()
    distinct = [sys_prompt + rng.integers(
        1, cfg.vocab_size, int(rng.integers(4, 2 * block))).tolist()
        for _ in range(n_distinct)]
    # Zipf popularity over the distinct prompts (rank^-1.1)
    pop = 1.0 / np.arange(1, n_distinct + 1) ** 1.1
    order = rng.choice(n_distinct, size=n_requests, p=pop / pop.sum())

    eng = ContinuousBatchingEngine(params, cfg, max_batch=8)
    try:
        list(eng.stream(distinct[0], 2))  # compile warmup, not measured
        ttfts, produced = [], [0] * n_requests
        lock = threading.Lock()

        def one(i: int, prompt) -> None:
            t0 = time.perf_counter()
            first = None
            n = 0
            for _ in eng.stream(prompt, max_new):
                if first is None:
                    first = time.perf_counter() - t0
                n += 1
            with lock:
                ttfts.append(first if first is not None else 0.0)
                produced[i] = n

        t_start = time.perf_counter()
        threads = [threading.Thread(target=one,
                                    args=(i, distinct[int(d)]))
                   for i, d in enumerate(order)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start
        stats = eng.kv_stats()
    finally:
        eng.stop()

    total_tokens = int(sum(produced))
    print(json.dumps({
        "metric": f"serve_decode_tokens_per_sec_{platform}",
        "value": round(total_tokens / wall, 1),
        "unit": "tokens/s",
        "platform": platform,
        "n_requests": n_requests,
        "max_new_tokens": max_new,
        "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 2),
        "ttft_p99_ms": round(float(np.percentile(ttfts, 99)) * 1e3, 2),
        "prefix_hit_rate": round(stats.get("hit_rate", 0.0), 4),
        "token_reuse_rate": round(stats.get("token_reuse_rate", 0.0), 4),
        "reused_tokens": stats.get("reused_tokens", 0),
        "prefilled_tokens": stats.get("prefilled_tokens", 0),
        "kv_pool_utilization": round(stats.get("pool_utilization", 0.0),
                                     4),
    }))


def _attach_serve(rec: dict, extra_env: dict = None) -> dict:
    """Run the supervised serve stage and graft its record into the
    final headline JSON under "serve" (the driver keys on the LAST
    line, so the training headline metric stays the headline)."""
    if os.environ.get("BENCH_SERVE", "1") != "1":
        return rec
    timeout = float(os.environ.get("BENCH_SERVE_TIMEOUT", "600"))
    env = {"_BENCH_MODE": "serve"}
    env.update(extra_env or {})
    srec, serr, _rc = _run_child(env, timeout)
    rec = dict(rec)
    rec["serve"] = srec if srec is not None else {"error": serr}
    if srec is None:
        sys.stderr.write(f"bench: serve stage failed ({serr})\n")
    return rec


def _prior_bench_records(bench_dir: str = None):
    """(filename, record) pairs of prior BENCH_r*.json rounds beside
    this script, newest round first (by the driver wrapper's "n" round
    counter — lexical filename order misplaces r100 vs r99). The driver
    wraps each round's parsed record under "parsed"; bare records are
    accepted too."""
    base = bench_dir or os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for path in glob.glob(os.path.join(base, "BENCH_r*.json")):
        try:
            with open(path) as f:
                raw = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(raw, dict):
            continue
        parsed = raw.get("parsed") if isinstance(raw.get("parsed"),
                                                 dict) else raw
        if isinstance(parsed, dict):
            n = raw.get("n") if isinstance(raw.get("n"), int) else -1
            rounds.append((n, os.path.basename(path), parsed))
    rounds.sort(key=lambda r: (r[0], r[1]), reverse=True)
    return [(fname, parsed) for _, fname, parsed in rounds]


def _attribute_regression(rec: dict, bench_dir: str = None) -> dict:
    """Perf-regression attribution: diff this run's step_breakdown
    against the most recent prior BENCH record and name the phase that
    moved. A record whose headline is a CPU fallback (the r04/r05 lie),
    a failure, or a different metric is never the baseline; a prior
    round without a step_breakdown is skipped the same way. regression
    is None when no phase got slower."""
    cur = rec.get("step_breakdown")
    if not isinstance(cur, dict):
        return rec
    for fname, prior in _prior_bench_records(bench_dir):
        if ("cpu_fallback" in prior or "error" in prior
                or "tpu_error" in prior
                or not prior.get("value")
                or prior.get("metric") != rec.get("metric")):
            continue
        prev = prior.get("step_breakdown")
        if not isinstance(prev, dict):
            continue
        # phases only: the breakdown also carries summary keys
        # (device_step_p99_ms) that would double-count their phase and
        # attribute a "regression" to 2-sample noise
        phases = ("data_wait", "bubble_wait", "compile", "device_step",
                  "checkpoint", "report", "other")
        deltas = {
            p: float(cur[f"{p}_ms"]) - float(prev[f"{p}_ms"])
            for p in phases
            if isinstance(cur.get(f"{p}_ms"), (int, float))
            and isinstance(prev.get(f"{p}_ms"), (int, float))}
        if not deltas:
            continue
        phase, delta = max(deltas.items(), key=lambda kv: kv[1])
        rec = dict(rec)
        if delta <= 0:
            rec["regression"] = None  # explicitly: nothing got slower
            return rec
        base = float(prev.get(f"{phase}_ms") or 0.0)
        rec["regression"] = {
            "phase": phase,
            "delta_ms": round(delta, 3),
            "pct": round(100.0 * delta / base, 2) if base > 0 else None,
            "vs": fname,
        }
        return rec
    return rec


def _sweep_stale_shm() -> int:
    """Remove leaked rtpu arena slabs from earlier crashed runs: stale
    segments eat /dev/shm and have previously degraded or broken the
    measurement. Only this framework's prefix is touched."""
    n = 0
    for path in glob.glob("/dev/shm/rtpu_a_*"):
        try:
            os.unlink(path)
            n += 1
        except OSError:
            pass
    if n:
        print(f"bench: swept {n} stale /dev/shm/rtpu_a_* segment(s)",
              file=sys.stderr)
    return n


def _run_child(extra_env: dict, timeout: float):
    """Run this script as a child stage; return (json_dict | None,
    reason, returncode | None). The last stdout line must be the JSON
    record; stderr is passed through for diagnostics.

    On timeout the child gets SIGTERM plus a grace period before SIGKILL:
    hard-killing a pallas compile mid-flight is known to wedge the axon
    relay for the rest of the session."""
    env = dict(os.environ, _BENCH_CHILD="1", **extra_env)
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            stdout, stderr = proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            stdout, stderr = proc.communicate()
        if stderr:
            sys.stderr.write(stderr)
        return None, f"timeout after {timeout:.0f}s (backend wedged?)", None
    if stderr:
        sys.stderr.write(stderr)
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    if proc.returncode != 0:
        # Tracebacks/SystemExit messages land on stderr; stdout is
        # usually empty on failure — diagnose from the stderr tail.
        err_lines = [ln for ln in (stderr or "").strip().splitlines()
                     if ln.strip()]
        tail = (err_lines[-1] if err_lines
                else lines[-1] if lines else "")[:300]
        return None, f"rc={proc.returncode}: {tail}", proc.returncode
    try:
        rec = json.loads(lines[-1])
        if "value" not in rec:
            raise ValueError("no 'value' key")
        return rec, "", 0
    except Exception:
        return (None, f"rc=0 but no JSON record in output: {stdout[-300:]}",
                proc.returncode)


def _supervise() -> int:
    """Parent entry: never initializes a jax backend in-process. Stages:
    shm sweep -> health child -> measure child (known-good defaults) ->
    optional explore children (BENCH_EXPLORE=1) -> cpu fallback if the
    TPU path failed. Always emits one parsed JSON line last."""
    health_timeout = float(os.environ.get("BENCH_HEALTH_TIMEOUT", "150"))
    tpu_timeout = float(os.environ.get("BENCH_TPU_TIMEOUT", "900"))
    cpu_timeout = float(os.environ.get("BENCH_CPU_TIMEOUT", "240"))

    _sweep_stale_shm()

    rec, tpu_err = None, ""
    hrec, herr, _hrc = _run_child({"_BENCH_MODE": "health"}, health_timeout)
    if hrec is None:
        tpu_err = f"health probe failed: {herr}"
        sys.stderr.write(f"bench: {tpu_err}; skipping TPU measurement\n")
    else:
        # healthy backend (TPU, or the default platform on a bare-CPU
        # dev box — main() labels the metric by platform either way)
        rec, tpu_err, tpu_rc = _run_child({}, tpu_timeout)
        if rec is None and tpu_rc != INVALID_MEASUREMENT_RC:
            # retry-with-reset (the dryrun supervisor's pattern): a
            # wedged relay or leaked shm segment from the failed child
            # must not burn the round — sweep and retry ONCE before
            # falling back
            _sweep_stale_shm()
            sys.stderr.write(f"bench: measure child failed ({tpu_err}); "
                             "retrying once after shm reset\n")
            rec, tpu_err, tpu_rc = _run_child({}, tpu_timeout)
        if rec is None and tpu_rc == INVALID_MEASUREMENT_RC:
            # The bench's own validity guard fired (impossible MFU /
            # unstable timing). Fail loudly — a CPU-fallback "success"
            # would bury it.
            print(json.dumps({
                "metric": "gpt2_125m_train_tokens_per_sec_per_chip",
                "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0,
                "error": f"measurement declared invalid by child: {tpu_err}",
            }))
            return 1
        if (rec is not None and rec.get("implied_mfu")
                and os.environ.get("BENCH_EXPLORE", "1") == "1"):
            # headline first, THEN explore: the driver parses the LAST
            # complete JSON line, so a killed/timed-out exploration can
            # only fail to improve the record, never lose it
            print(json.dumps(rec), flush=True)
            best = _explore(rec, tpu_timeout)
            if best is not rec:
                _save_tuned(best)  # next round starts from the winner
            # serve stage LAST (after the headline is safe on stdout):
            # its record rides inside the final line's "serve" key
            print(json.dumps(_attach_serve(_attribute_regression(best))))
            return 0

    if rec is not None:
        print(json.dumps(_attach_serve(_attribute_regression(rec))))
        return 0

    sys.stderr.write(f"bench: default-backend run failed ({tpu_err}); "
                     "probing cpu for diagnostics\n")
    rec, cpu_err, cpu_rc = _run_child(
        {"JAX_PLATFORMS": "cpu", "_BENCH_PLATFORM": "cpu",
         "_BENCH_MODE": "measure"}, cpu_timeout)
    # A CPU fallback is NEVER the trajectory headline (the r04/r05
    # silent-CPU lie: a wedged TPU produced a "successful" CPU number
    # the trajectory read as the chip's). The headline stays the TPU
    # metric at value 0.0 with the failure named; the CPU record rides
    # under "cpu_fallback" with a loud marker, diagnostics only.
    out = {
        "metric": "gpt2_125m_train_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,
        "error": f"tpu path failed: {tpu_err}",
    }
    if rec is not None:
        rec["WARNING"] = ("CPU FALLBACK — not comparable to the "
                          "tokens/s/chip trajectory headline")
        out["cpu_fallback"] = rec
    else:
        out["error"] += f"; cpu fallback also failed: {cpu_err}"
    print(json.dumps(out))
    return 1


def _explore(rec: dict, timeout: float) -> dict:
    """Opt-in kernel exploration, run only once a headline number is
    already in hand: fused-bwd probe child, then block-autotune child.
    Keeps whichever child's record is fastest; failures leave the
    headline record untouched."""
    best = rec
    probe, perr, _ = _run_child({"RAY_TPU_FLASH_FUSED_BWD": "1"}, timeout)
    if probe is not None and probe.get("value", 0) > best.get("value", 0):
        best = probe
    elif probe is None:
        sys.stderr.write(f"bench: fused-bwd explore failed ({perr})\n")
    tuned, terr, _ = _run_child({"BENCH_AUTOTUNE": "1"}, timeout)
    if tuned is not None and tuned.get("value", 0) > best.get("value", 0):
        best = tuned
    elif tuned is None:
        sys.stderr.write(f"bench: autotune explore failed ({terr})\n")
    return best


if __name__ == "__main__":
    if os.environ.get("_BENCH_CHILD") == "1":
        if os.environ.get("_BENCH_MODE") == "health":
            _health_main()
        elif os.environ.get("_BENCH_MODE") == "serve":
            _serve_main()
        else:
            main()
    else:
        sys.exit(_supervise())
