"""Headline benchmark: GPT-2 125M training throughput, tokens/sec/chip.

Runs the full JaxTrainer TrainStep (fwd+bwd+adamw, donated state, bf16
params, flash attention) on all local devices with a dp mesh, and prints
ONE JSON line {metric, value, unit, vs_baseline}.

Baseline: the reference has no in-repo absolute numbers (BASELINE.md —
nightly metrics go to an external DB); the north-star is "within 1.3x of
Ray+NCCL+A100" on GPT-2 125M DDP. We take 140k tokens/sec/chip as the
A100-class reference point (bf16+flash-attention GPT-2 124M DDP, public
nanoGPT-scale numbers), so vs_baseline = measured / 140000.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

REF_TOKENS_PER_SEC_PER_CHIP = 140_000.0


def main() -> None:
    import optax

    from ray_tpu.models.gpt2 import (GPT2Config, gpt2_init, gpt2_loss,
                                     gpt2_partition_specs)
    from ray_tpu.parallel.mesh import MeshConfig, make_mesh
    from ray_tpu.train.trainer import TrainStep

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    cfg = GPT2Config.small() if on_tpu else GPT2Config.tiny()
    seq = cfg.max_seq_len if on_tpu else 64
    per_chip_batch = 16 if on_tpu else 2
    warmup, iters = (5, 30) if on_tpu else (2, 5)

    devices = jax.devices()
    mesh = make_mesh(MeshConfig(dp=-1), devices=devices)
    n_chips = len(devices)

    step = TrainStep(
        lambda p, b: gpt2_loss(p, b["tokens"], b["targets"], cfg),
        optax.adamw(3e-4, weight_decay=0.1), mesh,
        gpt2_partition_specs(cfg))
    state = step.init_state(gpt2_init(cfg, jax.random.PRNGKey(0)))

    rng = np.random.default_rng(0)
    batch_np = rng.integers(
        0, cfg.vocab_size, (per_chip_batch * n_chips, seq + 1),
        dtype=np.int32)
    batch = {"tokens": jnp.asarray(batch_np[:, :-1]),
             "targets": jnp.asarray(batch_np[:, 1:])}
    tokens_per_step = per_chip_batch * n_chips * seq

    for _ in range(warmup):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    tok_per_sec_per_chip = tokens_per_step * iters / dt / n_chips
    print(json.dumps({
        "metric": "gpt2_125m_train_tokens_per_sec_per_chip" if on_tpu
        else f"gpt2_tiny_train_tokens_per_sec_per_chip_{platform}",
        "value": round(tok_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tok_per_sec_per_chip
                             / REF_TOKENS_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
