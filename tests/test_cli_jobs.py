"""CLI + job submission tests — modeled on the reference's
python/ray/tests/test_cli.py and dashboard/modules/job/tests."""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def head():
    """A standalone head via `python -m ray_tpu start --head`."""
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("RAY_TPU_ADDRESS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", "start", "--head",
         "--num-cpus", "4"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    address = None
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        m = re.search(r"started at ([\d.]+:\d+)", line or "")
        if m:
            address = m.group(1)
            break
    assert address, "head did not start"
    yield address
    subprocess.run([sys.executable, "-m", "ray_tpu", "stop",
                    "--address", address], env=env, timeout=30)
    proc.wait(timeout=10)


def _cli(*args, address=None, check=True, timeout=120):
    env = dict(os.environ, PYTHONPATH=REPO)
    cmd = [sys.executable, "-m", "ray_tpu", *args]
    if address:
        env["RAY_TPU_ADDRESS"] = address
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=timeout)
    if check:
        assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout


def test_connect_to_standalone_head(head):
    import ray_tpu

    ray_tpu.init(address=head)
    try:
        @ray_tpu.remote
        def f(x):
            return x * 2

        assert ray_tpu.get(f.remote(21)) == 42
    finally:
        ray_tpu.shutdown()


def test_cli_status_and_list(head):
    out = json.loads(_cli("status", address=head))
    assert out["resources_total"]["CPU"] == 4.0
    nodes = json.loads(_cli("list", "nodes", address=head))
    assert len(nodes) >= 1


def test_job_submit_and_logs(head):
    from ray_tpu.job_submission import JobSubmissionClient, JobStatus

    client = JobSubmissionClient(head)
    script = ("import ray_tpu; ray_tpu.init(address='auto'); "
              "print('job-result:', ray_tpu.get(ray_tpu.remote("
              "lambda: 6 * 7).remote()))")
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"{script}\"",
        runtime_env={"env_vars": {"PYTHONPATH": REPO}})
    status = client.wait_until_finished(job_id, timeout=120.0)
    logs = client.get_job_logs(job_id)
    assert status == JobStatus.SUCCEEDED, logs
    assert "job-result: 42" in logs
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id for j in jobs)


def test_job_failure_status(head):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(head)
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'")
    assert client.wait_until_finished(job_id, timeout=60.0) == "FAILED"


def test_job_stop(head):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(head)
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(600)'")
    deadline = time.monotonic() + 30.0
    while client.get_job_status(job_id) != "RUNNING" and \
            time.monotonic() < deadline:
        time.sleep(0.1)
    assert client.stop_job(job_id)
    assert client.wait_until_finished(job_id, timeout=30.0) == "STOPPED"


def test_cli_job_roundtrip(head):
    job_id = _cli("job", "--address", head, "submit",
                  sys.executable, "-c", "print('cli-job-ok')").strip()
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        status = _cli("job", "--address", head, "status", job_id).strip()
        if status in ("SUCCEEDED", "FAILED", "STOPPED"):
            break
        time.sleep(0.2)
    assert status == "SUCCEEDED"
    assert "cli-job-ok" in _cli("job", "--address", head, "logs", job_id)
