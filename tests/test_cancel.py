"""Task / actor-call cancellation (reference CoreWorker::CancelTask,
python/ray/_private/worker.py ray.cancel: cooperative interrupt,
force-kill, queued-actor-call drop)."""
from __future__ import annotations

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import TaskCancelledError


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_cancel_sleeping_task_returns_fast(cluster):
    @ray_tpu.remote
    def sleeper():
        time.sleep(30)
        return "done"

    ref = sleeper.remote()
    time.sleep(0.5)  # let it start executing
    t0 = time.monotonic()
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=10.0)
    assert time.monotonic() - t0 < 1.0


def test_cancel_force_kills_worker(cluster):
    @ray_tpu.remote
    def sleeper():
        time.sleep(30)
        return "done"

    ref = sleeper.remote()
    time.sleep(0.5)
    t0 = time.monotonic()
    ray_tpu.cancel(ref, force=True)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=10.0)
    assert time.monotonic() - t0 < 1.0
    # the cluster still works afterwards (death path cleaned up)
    @ray_tpu.remote
    def ok():
        return 42

    assert ray_tpu.get(ok.remote(), timeout=60.0) == 42


def test_cancel_interrupts_python_loop(cluster):
    """A running pure-Python loop sees the injected TaskCancelledError
    (the cooperative path actually stops execution, not just the caller)."""
    @ray_tpu.remote
    def spin():
        x = 0
        for i in range(10 ** 10):
            x += i
        return x

    ref = spin.remote()
    time.sleep(0.7)
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=10.0)
    # worker is idle again quickly — the loop actually stopped
    @ray_tpu.remote
    def ok():
        return 1

    t0 = time.monotonic()
    assert ray_tpu.get(ok.remote(), timeout=60.0) == 1
    assert time.monotonic() - t0 < 30.0


def test_cancel_before_execution(cluster):
    """Cancelling while the task is still queued (deps unresolved) aborts
    in the submit thread."""
    @ray_tpu.remote
    def dep():
        time.sleep(5)
        return 1

    @ray_tpu.remote
    def consumer(x):
        return x + 1

    d = dep.remote()
    ref = consumer.remote(d)
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=10.0)


def test_actor_survives_cancel_of_queued_call(cluster):
    @ray_tpu.remote
    class A:
        def slow(self):
            time.sleep(2)
            return "slow"

        def fast(self):
            return "fast"

    a = A.remote()
    running = a.slow.remote()   # occupies the single-concurrency actor
    queued = a.fast.remote()    # waits in the dispatch queue
    time.sleep(0.3)
    ray_tpu.cancel(queued)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(queued, timeout=10.0)
    # the running call and the actor itself are unaffected
    assert ray_tpu.get(running, timeout=30.0) == "slow"
    assert ray_tpu.get(a.fast.remote(), timeout=30.0) == "fast"


def test_cancel_completed_task_is_noop(cluster):
    @ray_tpu.remote
    def f():
        return 7

    ref = f.remote()
    assert ray_tpu.get(ref, timeout=60.0) == 7
    ray_tpu.cancel(ref)  # no effect
    assert ray_tpu.get(ref, timeout=10.0) == 7


def test_cancel_force_on_actor_call_rejected(cluster):
    """force=True would kill the whole actor (failing every other caller)
    — rejected with ValueError like the reference's ray.cancel."""
    @ray_tpu.remote
    class A:
        def slow(self):
            time.sleep(30)

    a = A.remote()
    ref = a.slow.remote()
    time.sleep(0.3)
    with pytest.raises(ValueError):
        ray_tpu.cancel(ref, force=True)
    ray_tpu.cancel(ref)  # non-force still works
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=10.0)


def test_cancel_one_of_multi_return_delivers_siblings(cluster):
    """Cancelling one return ref must not abandon the sibling ids."""
    @ray_tpu.remote(num_returns=2)
    def pair():
        time.sleep(0.8)
        return "a", "b"

    r1, r2 = pair.remote()
    time.sleep(0.2)
    ray_tpu.cancel(r1)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(r1, timeout=10.0)
    # the sibling still resolves: either the computed value (cancel landed
    # too late to stop execution) or a cancel error — never a hang or a
    # fabricated watchdog error
    try:
        assert ray_tpu.get(r2, timeout=10.0) == "b"
    except TaskCancelledError:
        pass


def test_cancel_borrowed_ref_forwards_to_owner(cluster):
    """cancel() of a ref owned by another process reaches the owner and
    stops the task (reference: CancelTask RPC to the owning worker)."""
    @ray_tpu.remote
    def sleeper():
        time.sleep(30)
        return "done"

    @ray_tpu.remote
    class Owner:
        def start(self):
            self.ref = sleeper.remote()
            return [self.ref]

        def probe(self):
            try:
                return ray_tpu.get(self.ref, timeout=0.1)
            except Exception as e:  # noqa: BLE001
                return type(e).__name__

    o = Owner.remote()
    [ref] = ray_tpu.get(o.start.remote())
    time.sleep(0.5)  # task is executing on some worker now
    ray_tpu.cancel(ref)  # we are a borrower: must forward to the owner
    deadline = time.monotonic() + 10
    seen = None
    while time.monotonic() < deadline:
        seen = ray_tpu.get(o.probe.remote())
        if seen == "TaskCancelledError":
            break
        time.sleep(0.2)
    assert seen == "TaskCancelledError", seen


def test_borrowed_dep_wait_releases_submit_slots(cluster):
    """Regression: a submitter thread waiting on a borrowed (other-owner,
    still pending) dep must use a bounded wait + re-check loop, not one
    unbounded RPC. With only 16 submit threads, 17+ cancelled tasks stuck
    on never-ready deps would otherwise pin every slot and stall all
    further submission from that worker (worker._wait_dep_ready)."""
    @ray_tpu.remote
    def never():
        time.sleep(120)
        return 1

    @ray_tpu.remote
    def child(x):
        return x

    @ray_tpu.remote
    class Spawner:
        def spawn(self, refs):
            # children are owned by THIS actor's worker; each dep is a
            # borrowed driver-owned ref that is still pending
            return [child.remote(r) for r in refs]

        def probe(self):
            # submitted through the same 16-slot submit pool
            return ray_tpu.get(child.remote(ray_tpu.put("pong")))

    s = Spawner.remote()
    dep = never.remote()
    children = ray_tpu.get(s.spawn.remote([dep] * 20), timeout=30.0)
    assert len(children) == 20
    time.sleep(1.0)  # let the submit pool fill with dep waiters
    for c in children:
        ray_tpu.cancel(c)
    # cancelled waiters must drain from the pool: an unrelated task
    # submitted by the same owner completes promptly
    t0 = time.monotonic()
    assert ray_tpu.get(s.probe.remote(), timeout=30.0) == "pong"
    assert time.monotonic() - t0 < 15.0
    ray_tpu.cancel(dep, force=True)
