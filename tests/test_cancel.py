"""Task / actor-call cancellation (reference CoreWorker::CancelTask,
python/ray/_private/worker.py ray.cancel: cooperative interrupt,
force-kill, queued-actor-call drop)."""
from __future__ import annotations

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import TaskCancelledError


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_cancel_sleeping_task_returns_fast(cluster):
    @ray_tpu.remote
    def sleeper():
        time.sleep(30)
        return "done"

    ref = sleeper.remote()
    time.sleep(0.5)  # let it start executing
    t0 = time.monotonic()
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=10.0)
    assert time.monotonic() - t0 < 1.0


def test_cancel_force_kills_worker(cluster):
    @ray_tpu.remote
    def sleeper():
        time.sleep(30)
        return "done"

    ref = sleeper.remote()
    time.sleep(0.5)
    t0 = time.monotonic()
    ray_tpu.cancel(ref, force=True)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=10.0)
    assert time.monotonic() - t0 < 1.0
    # the cluster still works afterwards (death path cleaned up)
    @ray_tpu.remote
    def ok():
        return 42

    assert ray_tpu.get(ok.remote(), timeout=60.0) == 42


def test_cancel_interrupts_python_loop(cluster):
    """A running pure-Python loop sees the injected TaskCancelledError
    (the cooperative path actually stops execution, not just the caller)."""
    @ray_tpu.remote
    def spin():
        x = 0
        for i in range(10 ** 10):
            x += i
        return x

    ref = spin.remote()
    time.sleep(0.7)
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=10.0)
    # worker is idle again quickly — the loop actually stopped
    @ray_tpu.remote
    def ok():
        return 1

    t0 = time.monotonic()
    assert ray_tpu.get(ok.remote(), timeout=60.0) == 1
    assert time.monotonic() - t0 < 30.0


def test_cancel_before_execution(cluster):
    """Cancelling while the task is still queued (deps unresolved) aborts
    in the submit thread."""
    @ray_tpu.remote
    def dep():
        time.sleep(5)
        return 1

    @ray_tpu.remote
    def consumer(x):
        return x + 1

    d = dep.remote()
    ref = consumer.remote(d)
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=10.0)


def test_actor_survives_cancel_of_queued_call(cluster):
    @ray_tpu.remote
    class A:
        def slow(self):
            time.sleep(2)
            return "slow"

        def fast(self):
            return "fast"

    a = A.remote()
    running = a.slow.remote()   # occupies the single-concurrency actor
    queued = a.fast.remote()    # waits in the dispatch queue
    time.sleep(0.3)
    ray_tpu.cancel(queued)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(queued, timeout=10.0)
    # the running call and the actor itself are unaffected
    assert ray_tpu.get(running, timeout=30.0) == "slow"
    assert ray_tpu.get(a.fast.remote(), timeout=30.0) == "fast"


def test_cancel_completed_task_is_noop(cluster):
    @ray_tpu.remote
    def f():
        return 7

    ref = f.remote()
    assert ray_tpu.get(ref, timeout=60.0) == 7
    ray_tpu.cancel(ref)  # no effect
    assert ray_tpu.get(ref, timeout=10.0) == 7
