"""JaxTrainer(mode="workers") gang semantics: the trainer performs the
jax.distributed rendezvous FOR train_fn (reference
python/ray/train/torch/config.py:64-117 does process-group setup in the
backend) and aggregates every rank's reports, not just rank 0's."""
from __future__ import annotations

import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _gang_train_fn(cfg):
    # NB: no setup_jax_distributed() call anywhere in here — the trainer
    # must have already assembled the global world.
    import jax

    from ray_tpu.train import get_context, report

    ctx = get_context()
    assert jax.process_count() == ctx.get_world_size(), \
        f"gang not formed: {jax.process_count()} processes"
    # a cross-process global reduction must see every rank's contribution
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    world = ctx.get_world_size()
    n_local = jax.local_device_count()
    mesh = Mesh(np.array(jax.devices()).reshape(world * n_local), ("dp",))
    arr = jax.make_array_from_callback(
        (world * n_local,), NamedSharding(mesh, P("dp")),
        lambda idx: np.array([float(ctx.get_world_rank() + 1)], np.float32))
    total = float(jax.jit(jnp.sum, out_shardings=NamedSharding(
        mesh, P()))(arr))
    report({"rank": ctx.get_world_rank(), "total": total,
            "procs": jax.process_count()})


def test_workers_mode_forms_gang_and_aggregates(cluster, tmp_path):
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    world, n_local = 2, 8  # each worker inherits the 8-device CPU mesh
    result = JaxTrainer(
        _gang_train_fn,
        scaling_config=ScalingConfig(num_workers=world),
        run_config=RunConfig(storage_path=str(tmp_path)),
        mode="workers").fit()

    assert result.metrics["procs"] == world
    # sum over global devices: n_local devices carry rank0+1=1, n_local
    # carry rank1+1=2
    assert result.metrics["total"] == float(n_local * (1 + 2))
    # every rank's report surfaced, with distinct ranks
    ranks = {m["rank"] for m in result.metrics["rank_metrics"]}
    assert ranks == {0, 1}


def test_workers_mode_opt_out(cluster, tmp_path):
    """setup_jax_distributed=False: train_fn sees NO formed gang."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def fn(cfg):
        from ray_tpu.parallel.distributed import \
            is_jax_distributed_initialized
        from ray_tpu.train import report

        report({"initialized": is_jax_distributed_initialized()})

    result = JaxTrainer(
        fn,
        scaling_config=ScalingConfig(num_workers=2,
                                     setup_jax_distributed=False),
        run_config=RunConfig(storage_path=str(tmp_path)),
        mode="workers").fit()
    assert result.metrics["initialized"] is False
