"""Parallelism-strategy tests on the virtual 8-device CPU mesh (the
unit-test analog of a TPU slice, SURVEY.md §4): pipeline parallelism,
expert-parallel MoE, Ulysses sequence parallelism, FSDP spec inference.
Each strategy is checked for exact (or tight-tolerance) agreement with its
single-device reference computation."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel import (MeshConfig, make_mesh, make_pipeline_fn,
                              infer_fsdp_specs, shard_map,
                              stack_stage_params)
from ray_tpu.ops import moe_ffn, mha_reference, ulysses_attention
from ray_tpu.ops.ring_attention import ring_attention


# ------------------------------------------------------------- pipeline


def _stage(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def test_pipeline_matches_sequential(cpu_mesh8):
    mesh = make_mesh(MeshConfig(dp=2, pp=4), devices=cpu_mesh8)
    key = jax.random.PRNGKey(0)
    d = 16
    stages = []
    for i in range(4):
        k1, k2, key = jax.random.split(key, 3)
        stages.append((jax.random.normal(k1, (d, d)) * 0.3,
                       jax.random.normal(k2, (d,)) * 0.1))
    stacked = stack_stage_params(stages)
    x = jax.random.normal(key, (16, d))

    # sequential reference
    ref = x
    for p in stages:
        ref = _stage(p, ref)

    pipe = make_pipeline_fn(_stage, mesh, num_microbatches=4)
    out = jax.jit(pipe)(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_grads_match(cpu_mesh8):
    mesh = make_mesh(MeshConfig(pp=4, dp=1), devices=cpu_mesh8[:4])
    key = jax.random.PRNGKey(1)
    d = 8
    stages = []
    for i in range(4):
        k1, k2, key = jax.random.split(key, 3)
        stages.append((jax.random.normal(k1, (d, d)) * 0.3,
                       jnp.zeros((d,))))
    stacked = stack_stage_params(stages)
    x = jax.random.normal(key, (8, d))
    pipe = make_pipeline_fn(_stage, mesh, num_microbatches=2)

    def loss_pipe(p):
        return jnp.sum(pipe(p, x) ** 2)

    def loss_ref(p):
        h = x
        for i in range(4):
            h = _stage(jax.tree.map(lambda a: a[i], p), h)
        return jnp.sum(h ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_ref = jax.grad(loss_ref)(stacked)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5), g_pipe, g_ref)


# ------------------------------------------------------------------ moe


def test_moe_expert_parallel_matches_dense(cpu_mesh8):
    mesh = make_mesh(MeshConfig(ep=4, dp=1), devices=cpu_mesh8[:4])
    key = jax.random.PRNGKey(2)
    t_local, d, f, e, k = 8, 16, 32, 8, 2
    keys = jax.random.split(key, 5)
    gate_w = jax.random.normal(keys[0], (d, e)) * 0.5
    w_in = jax.random.normal(keys[1], (e, d, f)) * 0.2
    w_out = jax.random.normal(keys[2], (e, f, d)) * 0.2
    # tokens sharded over ep: 4 ranks x t_local tokens
    x = jax.random.normal(keys[3], (4 * t_local, d))

    # capacity high enough that nothing drops in either layout
    cf = float(e)  # capacity = ceil(k*T*cf/e) >= k*T

    def sharded(x_, gw, wi, wo):
        return moe_ffn(x_, gw, wi, wo, top_k=k, capacity_factor=cf,
                       axis_name="ep")

    out_sharded = jax.jit(shard_map(
        sharded, mesh=mesh,
        in_specs=(P("ep"), P(), P("ep"), P("ep")),
        out_specs=P("ep"), check_vma=False))(x, gate_w, w_in, w_out)

    # dense reference on each rank's token shard independently
    outs = [moe_ffn(x[i * t_local:(i + 1) * t_local], gate_w, w_in, w_out,
                    top_k=k, capacity_factor=cf) for i in range(4)]
    ref = jnp.concatenate(outs)
    np.testing.assert_allclose(np.asarray(out_sharded), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_moe_routes_to_best_expert():
    # gate hard-selects expert j for token j: output == that expert's FFN
    d, f, e = 4, 8, 4
    key = jax.random.PRNGKey(3)
    w_in = jax.random.normal(key, (e, d, f)) * 0.3
    w_out = jax.random.normal(jax.random.PRNGKey(4), (e, f, d)) * 0.3
    x = jnp.eye(e, d)
    gate_w = jnp.eye(d, e) * 50.0  # token j -> expert j, hard
    out = moe_ffn(x, gate_w, w_in, w_out, top_k=1, capacity_factor=4.0)
    for j in range(e):
        ref = jax.nn.gelu(x[j] @ w_in[j]) @ w_out[j]
        np.testing.assert_allclose(np.asarray(out[j]), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)


def test_load_balancing_loss_uniform_is_one():
    from ray_tpu.ops import load_balancing_loss

    # perfectly uniform router -> loss == 1.0 (E * E*(1/E * 1/E))
    logits = jnp.zeros((64, 8))
    lb = load_balancing_loss(logits, top_k=8)
    assert abs(float(lb) - 1.0) < 1e-5


# -------------------------------------------------------------- ulysses


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_reference(cpu_mesh8, causal):
    mesh = make_mesh(MeshConfig(sp=4, dp=1), devices=cpu_mesh8[:4])
    key = jax.random.PRNGKey(5)
    b, t, h, d = 2, 32, 8, 16
    q, k, v = (jax.random.normal(kk, (b, t, h, d))
               for kk in jax.random.split(key, 3))
    ref = mha_reference(q, k, v, causal)

    fn = shard_map(
        functools.partial(ulysses_attention, axis_name="sp", causal=causal),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_and_ulysses_agree(cpu_mesh8):
    mesh = make_mesh(MeshConfig(sp=4, dp=1), devices=cpu_mesh8[:4])
    key = jax.random.PRNGKey(6)
    b, t, h, d = 1, 16, 4, 8
    q, k, v = (jax.random.normal(kk, (b, t, h, d))
               for kk in jax.random.split(key, 3))
    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False)
    uly = shard_map(
        functools.partial(ulysses_attention, axis_name="sp", causal=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False)
    np.testing.assert_allclose(np.asarray(jax.jit(ring)(q, k, v)),
                               np.asarray(jax.jit(uly)(q, k, v)),
                               rtol=2e-4, atol=2e-4)


# ----------------------------------------------------------------- fsdp


def test_infer_fsdp_specs_shards_largest_free_dim():
    params = {
        "w": jnp.zeros((512, 1024)),
        "b": jnp.zeros((8,)),               # too small: replicated
        "emb": jnp.zeros((1000, 512)),      # largest dim 1000 sharded
        "odd": jnp.zeros((1001, 512)),      # 1001 % 4 != 0 -> shard 512
    }
    specs = infer_fsdp_specs(params, 4, min_size_to_shard=1024)
    assert specs["w"] == P(None, "fsdp")
    assert specs["b"] == P(None)
    assert specs["emb"] == P("fsdp", None)
    assert specs["odd"] == P(None, "fsdp")


def test_infer_fsdp_composes_with_tp():
    params = {"w": jnp.zeros((512, 1024))}
    base = {"w": P(None, "tp")}
    specs = infer_fsdp_specs(params, 4, base_specs=base,
                             min_size_to_shard=1024)
    assert specs["w"] == P("fsdp", "tp")


def test_fsdp_train_step_runs(cpu_mesh8):
    import optax

    from ray_tpu.train.trainer import TrainStep

    mesh = make_mesh(MeshConfig(dp=2, fsdp=4), devices=cpu_mesh8)
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (16, 16)),
              "b": jnp.zeros((16,))}
    specs = infer_fsdp_specs(params, 4, min_size_to_shard=1)

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    step = TrainStep(loss_fn, optax.sgd(0.1), mesh, specs)
    state = step.init_state(params)
    batch = {"x": jnp.ones((8, 16)), "y": jnp.zeros((8, 16))}
    l0 = None
    for _ in range(5):
        state, m = step(state, batch)
        l0 = l0 or float(m["loss"])
    assert float(m["loss"]) < l0
