"""ray_tpu.data — Dataset transforms, shuffles, groupby, iteration
(reference python/ray/data/tests/)."""
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(autouse=True)
def _cluster(ray_start_shared):
    yield


def test_range_count_take():
    ds = rd.range(100)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]
    assert ds.num_blocks() > 1


def test_map_filter_flatmap():
    ds = rd.range(20).map(lambda r: {"id": r["id"] * 2})
    assert ds.take(3) == [{"id": 0}, {"id": 2}, {"id": 4}]
    ds = rd.range(20).filter(lambda r: r["id"] % 2 == 0)
    assert ds.count() == 10
    ds = rd.from_items([1, 2]).flat_map(
        lambda r: [{"item": r["item"]}, {"item": r["item"] * 10}])
    assert sorted(r["item"] for r in ds.take_all()) == [1, 2, 10, 20]


def test_map_batches_formats():
    ds = rd.range(32).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2}, batch_size=8)
    rows = ds.take_all()
    assert all(r["sq"] == r["id"] ** 2 for r in rows)
    # pandas format
    ds2 = rd.range(10).map_batches(
        lambda df: df.assign(y=df["id"] + 1), batch_format="pandas")
    assert ds2.take(2)[1]["y"] == 2


def test_map_batches_callable_class():
    class Doubler:
        def __init__(self):
            self.calls = 0

        def __call__(self, batch):
            self.calls += 1
            return {"id": batch["id"] * 2}

    ds = rd.range(16).map_batches(Doubler, batch_size=4, concurrency=2)
    assert sorted(r["id"] for r in ds.take_all()) == \
        sorted(i * 2 for i in range(16))


def test_columns_ops():
    ds = rd.range(10).add_column("b", lambda df: df["id"] + 1)
    assert ds.take(1)[0]["b"] == 1
    assert set(ds.columns()) == {"id", "b"}
    assert ds.select_columns(["b"]).columns() == ["b"]
    assert ds.drop_columns(["b"]).columns() == ["id"]
    assert ds.rename_columns({"id": "x"}).columns()[0] in ("x", "b")


def test_repartition_shuffle_sort_limit():
    ds = rd.range(100).repartition(4)
    assert ds.num_blocks() == 4
    assert ds.count() == 100

    shuffled = rd.range(50).random_shuffle(seed=7)
    ids = [r["id"] for r in shuffled.take_all()]
    assert sorted(ids) == list(range(50))
    assert ids != list(range(50))

    ds = rd.from_items([{"v": x} for x in [5, 3, 8, 1, 9, 2]]).sort("v")
    assert [r["v"] for r in ds.take_all()] == [1, 2, 3, 5, 8, 9]
    desc = rd.from_items([{"v": x} for x in [5, 3, 8]]).sort(
        "v", descending=True)
    assert [r["v"] for r in desc.take_all()] == [8, 5, 3]

    assert rd.range(100).limit(7).count() == 7


def test_aggregates_and_groupby():
    ds = rd.from_items([{"k": i % 3, "v": float(i)} for i in range(12)])
    assert ds.sum("v") == sum(range(12))
    assert ds.min("v") == 0.0
    assert ds.max("v") == 11.0
    assert abs(ds.mean("v") - 5.5) < 1e-9

    g = ds.groupby("k").sum("v").take_all()
    got = {r["k"]: r["sum(v)"] for r in g}
    assert got == {0: 0 + 3 + 6 + 9, 1: 1 + 4 + 7 + 10, 2: 2 + 5 + 8 + 11}

    cnt = ds.groupby("k").count().take_all()
    assert all(r["count()"] == 4 for r in cnt)


def test_iter_batches_and_split():
    ds = rd.range(64)
    batches = list(ds.iter_batches(batch_size=16))
    assert len(batches) == 4
    assert all(len(b["id"]) == 16 for b in batches)

    shards = ds.split(4)
    assert sum(s.count() for s in shards) == 64
    its = ds.streaming_split(2)
    total = sum(len(b["id"]) for it in its
                for b in it.iter_batches(batch_size=8))
    assert total == 64


def test_local_shuffle_and_drop_last():
    ds = rd.range(50)
    b = list(ds.iter_batches(batch_size=20, drop_last=True))
    assert len(b) == 2
    b = list(ds.iter_batches(batch_size=20, local_shuffle_buffer_size=50,
                             local_shuffle_seed=3))
    all_ids = np.concatenate([x["id"] for x in b])
    assert sorted(all_ids.tolist()) == list(range(50))


def test_zip_union():
    a = rd.range(10).repartition(2).materialize()
    b = a.map(lambda r: {"y": r["id"] * 3}).materialize()
    z = a.zip(b)
    rows = z.take_all()
    assert all(r["y"] == r["id"] * 3 for r in rows)
    u = rd.range(5).union(rd.range(5))
    assert u.count() == 10


def test_tensor_columns():
    arr = np.arange(24, dtype=np.float32).reshape(6, 2, 2)
    ds = rd.from_numpy(arr)
    batch = next(iter(ds.iter_batches(batch_size=6)))
    assert batch["data"].shape == (6, 2, 2)
    np.testing.assert_allclose(
        np.sort(batch["data"].ravel()), np.arange(24, dtype=np.float32))


def test_read_write_roundtrip(tmp_path):
    ds = rd.range(30).map(lambda r: {"id": r["id"], "v": r["id"] * 1.5})
    p = str(tmp_path / "pq")
    ds.write_parquet(p)
    back = rd.read_parquet(p)
    assert back.count() == 30
    assert abs(back.sum("v") - ds.sum("v")) < 1e-9

    c = str(tmp_path / "csv")
    ds.write_csv(c)
    assert rd.read_csv(c).count() == 30

    j = str(tmp_path / "json")
    ds.write_json(j)
    assert rd.read_json(j).count() == 30

    t = str(tmp_path / "t.txt")
    with open(t, "w") as f:
        f.write("a\nb\nc\n")
    assert rd.read_text(t).count() == 3


def test_train_test_split():
    tr, te = rd.range(100).train_test_split(0.2)
    assert tr.count() == 80 and te.count() == 20
    ids = sorted(r["id"] for r in tr.take_all() + te.take_all())
    assert ids == list(range(100))


def test_iter_jax_batches():
    import jax

    ds = rd.range(32)
    batches = list(ds.iter_jax_batches(batch_size=8))
    assert len(batches) == 4
    assert all(isinstance(b["id"], jax.Array) for b in batches)


def test_sort_empty_and_empty_partition_schema():
    # all rows filtered out: sort/groupby must not crash
    ds = rd.range(10).filter(lambda r: False)
    assert ds.sort("id").take_all() == []
    assert ds.groupby("id").count().take_all() == []
    # empty partitions keep the schema
    ds = rd.range(3).repartition(8)
    assert ds.select_columns(["id"]).count() == 3
    assert ds.schema() is not None and "id" in ds.schema().names


def test_sort_descending_balanced():
    ds = rd.range(1000).repartition(8).sort("id", descending=True)
    ids = [r["id"] for r in ds.take_all()]
    assert ids == list(reversed(range(1000)))
    # partitions stay balanced (no collapse into 2 blocks)
    counts = [b.num_rows for b in ds._blocks()]
    assert max(counts) < 500, counts


def test_zip_name_collision():
    a = rd.range(8).repartition(2).materialize()
    z = a.zip(a)
    rows = z.take_all()
    assert all(r["id"] == r["id_1"] for r in rows)


def test_union_lazy_with_limit():
    calls = {"n": 0}
    ds = rd.range(100).map(lambda r: r)
    u = ds.union(rd.range(100))
    assert u.limit(5).count() == 5
    assert u.count() == 200


def test_empty_tensor_batch():
    ds = rd.from_numpy(np.ones((8, 3), np.float32)).map_batches(
        lambda b: {"data": b["data"][:0]})
    assert ds.count() == 0


class _StatefulUDF:
    """Identity-carrying stateful UDF: tags rows with the constructing
    instance so tests can count constructions and observe reuse."""

    def __init__(self):
        import uuid

        self.inst = uuid.uuid4().hex
        self.calls = 0

    def __call__(self, batch):
        self.calls += 1
        n = len(batch["id"])
        batch["inst"] = np.array([self.inst] * n)
        batch["call_no"] = np.array([self.calls] * n)
        return batch


def test_map_batches_actor_pool_strategy():
    """compute=ActorPoolStrategy(2): at most 2 UDF instances exist
    (bounded pool of dedicated actors) and each is REUSED across batches
    (reference _internal/compute.py:65)."""
    ds = (rd.range(64, parallelism=8)
          .map_batches(_StatefulUDF,
                       compute=rd.ActorPoolStrategy(min_size=2,
                                                    max_size=2)))
    rows = ds.take_all()
    assert len(rows) == 64
    insts = {r["inst"] for r in rows}
    assert 1 <= len(insts) <= 2, f"{len(insts)} instances for pool of 2"
    # reuse: with 8 blocks on <=2 actors some instance saw >= 4 batches
    assert max(r["call_no"] for r in rows) >= 4


def test_actor_pool_autoscales_and_tears_down():
    """Pool grows from min_size toward max_size under backlog, results
    stay correct and ordered, and pool actors are gone afterwards."""
    from ray_tpu.util import state as rstate

    before = {a["actor_id"] for a in rstate.list_actors()}
    ds = (rd.range(48, parallelism=12)
          .map_batches(_StatefulUDF,
                       compute=rd.ActorPoolStrategy(
                           min_size=1, max_size=3,
                           max_tasks_in_flight_per_actor=1)))
    rows = ds.take_all()
    assert [r["id"] for r in rows] == list(range(48))
    assert 1 <= len({r["inst"] for r in rows}) <= 3
    import time

    time.sleep(1.0)
    after = rstate.list_actors()
    alive_new = [a for a in after
                 if a["actor_id"] not in before and a["state"] == "ALIVE"]
    assert not alive_new, f"pool actors leaked: {alive_new}"


def test_actor_pool_with_plain_fn():
    """A plain function also runs on the pool (no constructor needed)."""
    ds = rd.range(16, parallelism=4).map_batches(
        lambda b: {"id": b["id"] + 1},
        compute=rd.ActorPoolStrategy(min_size=2))
    assert [r["id"] for r in ds.take_all()] == list(range(1, 17))


def test_groupby_distributed_high_cardinality():
    """Groupby stays correct when groups span many input blocks (the
    shuffle-based map/merge path, no driver-side combine)."""
    n = 500
    ds = (rd.range(n, parallelism=10)
          .map(lambda r: {"k": int(r["id"]) % 7, "v": int(r["id"])}))
    out = ds.groupby("k").sum("v").take_all()
    expect = {}
    for i in range(n):
        expect[i % 7] = expect.get(i % 7, 0) + i
    got = {r["k"]: r["sum(v)"] for r in out}
    assert got == expect


def test_target_block_size_splitting():
    """Oversized map/source outputs split into ~target-size row ranges
    (reference DataContext.target_max_block_size); in-target blocks pass
    through untouched."""
    from ray_tpu.data.executor import StreamingExecutor
    from ray_tpu.data import plan as P

    # one fat block: 1000 rows x ~4KB = ~4MB, target 1MB -> ~4 splits
    ds = rd.range(1000, parallelism=1).map_batches(
        lambda b: {"id": b["id"],
                   "pad": np.zeros((len(b["id"]), 1024), np.float32)})
    ex = StreamingExecutor(P.fuse(ds._ops), target_block_size=1 << 20)
    refs = list(ex.run())
    assert len(refs) >= 4, len(refs)
    rows = [ray_tpu.get(r).num_rows for r in refs]
    assert sum(rows) == 1000
    assert max(rows) < 1000  # actually split
    # ordering preserved across the splits
    first = ray_tpu.get(refs[0])
    import pyarrow as pa

    ids = first.column("id").to_pylist()
    assert ids == list(range(len(ids)))

    # small blocks: no splitting, same refs flow through
    ds2 = rd.range(100, parallelism=4)
    ex2 = StreamingExecutor(P.fuse(ds2._ops), target_block_size=1 << 20)
    assert len(list(ex2.run())) == 4


def test_dataset_stats():
    """stats() reports per-stage blocks + wall time for the last run
    (reference Dataset.stats())."""
    ds = (rd.range(100, parallelism=4)
          .map_batches(lambda b: {"id": b["id"] * 2})
          .materialize())
    s = ds.stats()
    assert "read" in s or "range" in s, s
    assert "map_batches" in s, s
    for line in s.splitlines()[1:]:
        assert int(line.split()[-3]) > 0  # every stage produced blocks
    # unexecuted dataset: plan summary fallback
    assert "range" in rd.range(5).stats()


def test_byte_budget_backpressure():
    """The operator byte budget (reference ResourceManager /
    ConcurrencyCapBackpressurePolicy) bounds concurrent in-flight bytes:
    with ~1MB source blocks and a 2.5MB budget, no more than 2 map
    tasks may overlap even though the count window allows 8."""
    import time

    @ray_tpu.remote
    class Gauge:
        def __init__(self):
            self.cur = 0
            self.peak = 0

        def enter(self):
            self.cur += 1
            self.peak = max(self.peak, self.cur)

        def exit(self):
            self.cur -= 1

        def peak_seen(self):
            return self.peak

    gauge = Gauge.remote()

    def tracked(r):
        ray_tpu.get(gauge.enter.remote())
        time.sleep(0.3)
        ray_tpu.get(gauge.exit.remote())
        return {"rows": int(r["data"].shape[0])}

    os.environ["RAY_TPU_DATA_MEMORY_BUDGET"] = str(int(2.5 * (1 << 20)))
    try:
        # 8 source blocks of ~1MB each (one 131072-float64 row per block):
        # the resize probe measures them, so the map stage's admission
        # charges ~1MB per in-flight task against the 2.5MB budget
        out = (rd.range_tensor(8, shape=(131072,), parallelism=8)
               .map(tracked)
               .take_all())
    finally:
        del os.environ["RAY_TPU_DATA_MEMORY_BUDGET"]
    assert len(out) == 8
    peak = ray_tpu.get(gauge.peak_seen.remote())
    assert peak <= 2, f"byte budget violated: {peak} tasks overlapped"


def test_byte_budget_default_does_not_throttle():
    """With the default 512MB budget, small-block pipelines keep full
    count-window concurrency (no accidental serialization)."""
    ds = rd.range(64, parallelism=16).map(
        lambda r: {"id": r["id"] + 1})
    assert sorted(r["id"] for r in ds.take_all()) == list(range(1, 65))
