"""Global KV plane (ISSUE-19 acceptance surface): the tiered prefix
cache (HBM -> host RAM -> object store) with the cluster-wide prefix
directory (serve/kvplane.py + models/kvcache.py tier hooks +
conductor-side directory).

Covered here: HostArena spill/pop semantics (LRU byte bound, exact-token
collision guard, longest-partial probe, per-request attribution), the
pool-level tier-2 round trip (int8 pools byte-identical, fp pools within
the int8 tolerance contract), tier-3 export/import bit-identity across
pools, namespace isolation across every tier, the conductor directory's
atomic commit / TTL reap / keep-last-K GC, router directory routing
(hit -> holder, holder death -> hash + tier-3 hint, miss -> hash
bit-identically), the evict_storm chaos op absorbed by the arena with
outputs unchanged, the speculation-aware autoscaler discount (never
over-scales, bit-identical without a signal), per-caller chunk-fabric
attribution, and the one-set-of-numbers check across state API == CLI
== dashboard == Prometheus == timeline.

The `kvplane` marker tags the scenarios; everything is tier-1-safe on
CPU — cluster tests run on a module-scoped cluster with
log_to_driver=0 per the established fixture pattern."""
from __future__ import annotations

import dataclasses
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu.models.engine import ContinuousBatchingEngine
from ray_tpu.models.llama import LlamaConfig, llama_init
from ray_tpu.serve.disagg import DecodeServer, DisaggRouter, PrefillServer
from ray_tpu.serve.kvplane import HostArena

pytestmark = pytest.mark.kvplane

CFG = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
BS = 4  # KV block size: small enough to spill/readopt multiple blocks


@pytest.fixture(scope="module")
def model():
    return llama_init(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def kvplane_cluster():
    ray_tpu.init(num_cpus=6, _system_config={"log_to_driver": 0})
    yield ray_tpu._private.worker.global_worker
    ray_tpu.shutdown()


# ------------------------------------------------- HostArena (tier 2)

def _fake_payload(digest: bytes, toks, *, ns=None, partial=False,
                  parent: bytes = b"parent", seed: int = 0):
    """A wire-format payload shaped like _payload_locked's output —
    int8 K/V plus f32 scales — keyed the way the pool keys it."""
    rng = np.random.default_rng(seed)
    n = len(toks)
    qk = rng.integers(-127, 127, (2, n, 2, 4)).astype(np.int8)
    qv = rng.integers(-127, 127, (2, n, 2, 4)).astype(np.int8)
    sk = rng.random((2, 1, 2, 4)).astype(np.float32)
    sv = rng.random((2, 1, 2, 4)).astype(np.float32)
    key = ("partial", parent, tuple(toks)) if partial \
        else ("full", digest)
    return {"index_key": key, "tokens": tuple(toks), "filled": n,
            "ns": ns, "parent_digest": parent,
            "qk": qk, "qv": qv, "sk": sk, "sv": sv}


def test_arena_roundtrip_pops_bit_identical_with_collision_guard():
    """accept -> take_full returns the exact arrays (and POPS — a hit
    moves the block back to tier 1, never double residency); a digest
    probe whose token tuple differs returns None and leaves the entry."""
    arena = HostArena(max_bytes=1 << 20, replica="unit")
    p = _fake_payload(b"d1", (1, 2, 3, 4))
    arena.accept(dict(p))
    # digest collision with different tokens must never re-adopt
    assert arena.take_full(b"d1", (9, 9, 9, 9)) is None
    got = arena.take_full(b"d1", (1, 2, 3, 4))
    assert got is not None
    for f in ("qk", "qv", "sk", "sv"):
        assert np.array_equal(got[f], p[f])
    assert got["tokens"] == p["tokens"]
    # POP semantics: the hit consumed the entry
    assert arena.take_full(b"d1", (1, 2, 3, 4)) is None
    st = arena.stats()
    assert st["spills"] == 1
    assert st["tier2_hits"] == 1
    assert st["tier2_probes"] == 3
    assert st["tier2_reused_tokens"] == 4
    assert st["entries"] == 0 and st["bytes"] == 0
    kinds = [e["kind"] for e in arena.drain_events()]
    assert kinds == ["spill", "tier2_hit"]


def test_arena_lru_byte_bound_and_oversize_reject():
    one = _fake_payload(b"a", (1, 2, 3, 4))
    size = sum(int(one[f].nbytes) for f in ("qk", "qv", "sk", "sv"))
    arena = HostArena(max_bytes=2 * size, replica="unit")
    arena.accept(_fake_payload(b"a", (1, 2, 3, 4)))
    arena.accept(_fake_payload(b"b", (5, 6, 7, 8)))
    arena.accept(_fake_payload(b"c", (9, 10, 11, 12)))  # evicts "a"
    st = arena.stats()
    assert st["arena_evictions"] == 1 and st["entries"] == 2
    assert st["bytes"] == 2 * size
    assert arena.take_full(b"a", (1, 2, 3, 4)) is None
    assert arena.take_full(b"b", (5, 6, 7, 8)) is not None
    # a payload bigger than the whole arena is refused outright
    tiny = HostArena(max_bytes=size - 1, replica="unit")
    tiny.accept(_fake_payload(b"x", (1, 2, 3, 4)))
    assert tiny.stats()["spills"] == 0
    assert tiny.stats()["entries"] == 0


def test_arena_partial_probe_longest_match_within_budget():
    arena = HostArena(max_bytes=1 << 20, replica="unit")
    arena.accept(_fake_payload(b"root", (7, 8), partial=True,
                               parent=b"root"))
    arena.accept(_fake_payload(b"root", (7, 8, 9), partial=True,
                               parent=b"root"))
    # longest prefix-matching tail within the token budget wins
    got = arena.take_partial(b"root", [7, 8, 9, 10], budget=3)
    assert got is not None and got["tokens"] == (7, 8, 9)
    # budget now excludes 3-token tails; the 2-token tail still matches
    got2 = arena.take_partial(b"root", [7, 8, 9, 10], budget=2)
    assert got2 is not None and got2["tokens"] == (7, 8)
    # tails that do not prefix-match the remainder never match
    arena.accept(_fake_payload(b"root", (7, 9), partial=True,
                               parent=b"root"))
    assert arena.take_partial(b"root", [7, 8], budget=4) is None


def test_arena_give_back_and_request_attribution():
    arena = HostArena(max_bytes=1 << 20, replica="unit")
    p = _fake_payload(b"d", (1, 2, 3, 4))
    size = sum(int(p[f].nbytes) for f in ("qk", "qv", "sk", "sv"))
    arena.accept(dict(p))
    arena.begin_request()
    got = arena.take_full(b"d", (1, 2, 3, 4))
    assert got is not None
    acc = arena.end_request()
    assert acc["blocks"] == 1 and acc["tokens"] == 4
    assert acc["nbytes"] == size and acc["ms"] >= 0.0
    # the accumulator resets with the bracket
    assert arena.end_request()["blocks"] == 0
    # give_back restores a failed re-adoption without counting a spill
    spills_before = arena.stats()["spills"]
    arena.give_back(got)
    st = arena.stats()
    assert st["spills"] == spills_before
    assert st["entries"] == 1 and st["bytes"] == size
    assert arena.take_full(b"d", (1, 2, 3, 4)) is not None


# -------------------------------------- pool-level tier-2 round trip

def _filled_pool(model, prompt: np.ndarray, *, int8: bool,
                 num_blocks: int = 16, arena_bytes: int = 64 << 20):
    """A PagedKVCache with `prompt` committed and an arena attached —
    the unit-scale stand-in for a prefill replica's tier-1 + tier-2."""
    from ray_tpu.models.engine import _prefill_paged
    from ray_tpu.models.kvcache import PagedKVCache

    empty = jnp.zeros((CFG.num_layers, 0, CFG.num_kv_heads,
                       CFG.head_dim), jnp.float32)
    _, ck, cv = _prefill_paged(model, prompt[None], CFG, empty, empty)
    kv = PagedKVCache(CFG, block_size=BS, num_blocks=num_blocks,
                      int8=int8)
    arena = HostArena(max_bytes=arena_bytes, replica="unit")
    kv.attach_arena(arena)
    m = kv.lookup(prompt, max_tokens=len(prompt) - 1)
    kv.release(kv.commit(prompt, ck, cv, m))
    return kv, arena, ck, cv


def test_pool_spill_readopt_bit_identical_int8(model):
    """The tier-2 correctness invariant at the pool level: evict a
    whole committed chain into the arena, walk the lookup back through
    it, and the re-exported wire bytes (int8 K/V + scales + digest) are
    EXACTLY what was there before the eviction."""
    prompt = np.arange(101, 117, dtype=np.int32)  # 4 full blocks
    kv, arena, _, _ = _filled_pool(model, prompt, int8=True)
    before = kv.export_prefix(prompt)
    assert before is not None and before[1] == 16
    evicted = kv.force_evict(100)
    assert evicted == 4
    # the chain is GONE from tier 1...
    assert kv.export_prefix(prompt) is None
    st = arena.stats()
    assert st["spills"] == 4 and st["entries"] == 4
    # ...and the lookup re-adopts every block from tier 2
    m = kv.lookup(prompt, max_tokens=16)
    assert m.outcome == "hit" and m.tokens == 16
    kv.release(m.bids)
    after = kv.export_prefix(prompt)
    assert after is not None and after[1] == 16
    packed_b, _, dig_b = before
    packed_a, _, dig_a = after
    assert dig_a == dig_b
    for f in ("qk", "qv", "sk", "sv", "tokens"):
        assert np.array_equal(packed_a[f], packed_b[f]), f
    st = arena.stats()
    assert st["tier2_hits"] == 4
    assert st["tier2_reused_tokens"] == 16
    assert st["entries"] == 0  # POPPED back to tier 1


def test_pool_spill_readopt_fp_within_tolerance(model):
    """fp pools quantize on spill and re-enter within the int8
    tolerance contract — the readopted chain still serves the lookup
    and its dequantized rows stay close to the exact fill."""
    prompt = np.arange(201, 213, dtype=np.int32)  # 3 full blocks
    kv, arena, ck, _ = _filled_pool(model, prompt, int8=False)
    assert kv.force_evict(100) == 3
    assert arena.stats()["spills"] == 3
    m = kv.lookup(prompt, max_tokens=12)
    assert m.outcome == "hit" and m.tokens == 12
    gk, _ = kv.gather(m)
    ref = np.asarray(ck[:, :12], np.float32)
    got = np.asarray(gk, np.float32)
    assert np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9) < 0.05
    kv.release(m.bids)


def test_tier3_export_import_bit_identical_across_pools(model):
    """Tier 3's packed wire format survives a pool-to-pool hop
    byte-for-byte on int8 pools: export from A, adopt into a fresh B,
    re-export from B — identical arrays, identical chain digest. A
    prompt that does not match the packed tokens adopts NOTHING (a
    directory collision must never seed wrong KV)."""
    from ray_tpu.models.kvcache import PagedKVCache

    prompt = np.arange(301, 313, dtype=np.int32)  # 3 full blocks
    kv_a, _, _, _ = _filled_pool(model, prompt, int8=True)
    out = kv_a.export_prefix(prompt)
    assert out is not None
    packed, n_tokens, digest_hex = out
    assert n_tokens == 12 and packed["qk"].shape[0] == 3
    kv_b = PagedKVCache(CFG, block_size=BS, num_blocks=16, int8=True)
    assert kv_b.import_prefix(prompt, packed) == 3
    out_b = kv_b.export_prefix(prompt)
    assert out_b is not None
    packed_b, n_b, dig_b = out_b
    assert n_b == 12 and dig_b == digest_hex
    for f in ("qk", "qv", "sk", "sv", "tokens"):
        assert np.array_equal(packed_b[f], packed[f]), f
    # adopting the prefix makes the next prefill lookup a hit
    m = kv_b.lookup(prompt, max_tokens=11)
    assert m.tokens == 8 and m.outcome == "hit"
    kv_b.release(m.bids)
    # token-verification guard: wrong prompt adopts nothing
    kv_c = PagedKVCache(CFG, block_size=BS, num_blocks=16, int8=True)
    other = np.arange(401, 413, dtype=np.int32)
    assert kv_c.import_prefix(other, packed) == 0


def test_namespace_isolation_across_tiers(model):
    """Digest chains are namespace-rooted, so isolation is inherited by
    every tier: blocks spilled under one namespace can never serve
    another namespace's lookup, and export under a foreign namespace
    finds nothing."""
    from ray_tpu.models.kvcache import prefix_digests

    prompt = np.arange(501, 517, dtype=np.int32)
    kv, arena, _, _ = _filled_pool(model, prompt, int8=True)
    # the chains themselves differ at the root
    assert prefix_digests(prompt, BS, None) \
        != prefix_digests(prompt, BS, "tenantA|v1")
    assert kv.export_prefix(prompt, namespace="tenantA|v1") is None
    kv.force_evict(100)
    # foreign-namespace lookup misses tier 2 entirely...
    m_other = kv.lookup(prompt, max_tokens=16, namespace="tenantA|v1")
    assert m_other.tokens == 0 and m_other.outcome == "miss"
    assert arena.stats()["tier2_hits"] == 0
    # ...while the owning namespace re-adopts the full chain
    m_same = kv.lookup(prompt, max_tokens=16)
    assert m_same.tokens == 16
    kv.release(m_same.bids)


# ------------------------------------ e2e spill/readopt bit-identity

def test_outputs_bit_identical_under_pool_pressure(model):
    """The headline invariant: a prefill tier whose pool is too small
    for the working set (evictions -> arena spills -> readopts) serves
    outputs BIT-IDENTICAL to a single-tier engine whose pool holds
    everything. int8 pools make the tier-2 round trip lossless, so the
    hit/miss pattern — and therefore every output — matches."""
    pf = PrefillServer(model, CFG, kv_block_size=BS, kv_pool_blocks=8,
                       kv_int8=True, kvplane=True,
                       kvplane_arena_bytes=64 << 20)
    dec = DecodeServer(model, CFG, max_batch=2)
    colo = ContinuousBatchingEngine(model, CFG, max_batch=4,
                                    kv_block_size=BS,
                                    kv_pool_blocks=32, kv_int8=True)
    router = DisaggRouter(decode=[dec], prefill=[pf], max_queue_depth=4,
                          affinity_tokens=BS)
    prompts = [list(range(10 * i + 1, 10 * i + 13)) for i in range(4)]
    try:
        for p in prompts:                       # overflow the 8-block pool
            assert router.generate(p, 5) == colo.generate(p, 5), p
        # the repeats walk back through the arena (their blocks were
        # evicted) — still bit-identical to the big-pool engine's hits
        for p in prompts:
            assert router.generate(p, 5) == colo.generate(p, 5), p
    finally:
        dec.stop()
        colo.stop()
    kst = pf.kvplane_stats()
    assert kst["spills"] > 0, kst
    assert kst["tier2_hits"] > 0, kst
    assert kst["tier2_reused_tokens"] > 0


def test_evict_storm_absorbed_by_arena_outputs_unchanged(model):
    """The evict_storm chaos op: a scripted force-eviction fires before
    request 2's lookup, the arena catches every victim, and every
    output (including the stormed repeat) stays bit-identical — a storm
    sheds capacity, never correctness."""
    plan = json.dumps([{"action": "evict_storm", "role": "prefill",
                        "blocks": 6, "at": "request:2"}])
    pf = PrefillServer(model, CFG, kv_block_size=BS, kv_pool_blocks=32,
                       kv_int8=True, kvplane=True,
                       kvplane_arena_bytes=64 << 20, chaos=plan)
    dec = DecodeServer(model, CFG, max_batch=2)
    colo = ContinuousBatchingEngine(model, CFG, max_batch=4,
                                    kv_block_size=BS,
                                    kv_pool_blocks=32, kv_int8=True)
    router = DisaggRouter(decode=[dec], prefill=[pf], max_queue_depth=4,
                          affinity_tokens=BS)
    base = list(range(601, 613))
    try:
        assert router.generate(base, 5) == colo.generate(base, 5)
        # request 2: the storm evicts the chain, tier 2 hands it back
        assert router.generate(base, 5) == colo.generate(base, 5)
        tail = base + [99]
        assert router.generate(tail, 5) == colo.generate(tail, 5)
    finally:
        dec.stop()
        colo.stop()
    kst = pf.kvplane_stats()
    assert kst["evict_storms"] == 1
    assert kst["storm_evicted_blocks"] >= 1
    assert kst["spills"] >= kst["storm_evicted_blocks"]
    assert kst["tier2_hits"] > 0


def test_evict_storm_action_validation():
    from ray_tpu.resilience.chaos import ChaosAction

    a = ChaosAction.from_dict({"action": "evict_storm",
                               "role": "prefill", "blocks": 3,
                               "at": "request:2"})
    assert a.blocks == 3
    with pytest.raises(ValueError):
        ChaosAction.from_dict({"action": "evict_storm",
                               "role": "prefill", "at": "request:1"})
    with pytest.raises(ValueError):
        ChaosAction.from_dict({"action": "evict_storm",
                               "role": "decode", "blocks": 2,
                               "at": "request:1"})


# ------------------------------- speculation-aware autoscaler demand

def test_speculation_discount_never_over_scales():
    """A decode tier emitting f tokens per verify step drains its queue
    f x faster: the backlog is discounted by the measured factor before
    the policy sizes the tier, so speculation never over-scales — and
    no signal (or factor <= 1) leaves every decision bit-identical."""
    from ray_tpu.serve.autoscale import DisaggPolicy

    pol = DisaggPolicy(target_p99_ms=500.0)
    base = {"queue_depth_p99": 12.0, "decode_cap_per_replica": 4,
            "decode_busy_p99": 4.0}
    want_up = pol.desired_decode(dict(base), 1)
    assert want_up[0] == 3  # proportional jump: ceil(12 / 4)
    # measured 3 tokens/verify: the same backlog fits the tier
    n_spec, reason = pol.desired_decode(
        dict(base, spec_tokens_per_verify=3.0), 1)
    assert n_spec == 1 and n_spec <= want_up[0]
    # partial discount scales LESS, and says why
    n_mid, reason_mid = pol.desired_decode(
        dict(base, spec_tokens_per_verify=2.0), 1)
    assert n_mid == 2 < want_up[0]
    assert "speculation" in reason_mid
    # no signal / degenerate factors: bit-identical decisions
    for f in (None, 0.0, 1.0, 0.6):
        sig = dict(base)
        if f is not None:
            sig["spec_tokens_per_verify"] = f
        assert pol.desired_decode(sig, 1) == want_up


def test_speculation_discount_spares_queue_not_busy_slots():
    """Only QUEUED demand is discounted — an occupied slot is occupied
    whatever its token rate, so busy-slot demand blocks scale-down at
    any speculation factor, while a queue-only backlog may drain."""
    from ray_tpu.serve.autoscale import DisaggPolicy

    pol = DisaggPolicy(target_p99_ms=500.0)
    busy = {"decode_busy_p99": 10.0, "decode_cap_per_replica": 4,
            "queue_depth_p99": 0.0, "spec_tokens_per_verify": 4.0}
    n, _ = pol.desired_decode(dict(busy), 3)
    assert n == 3  # 10 busy slots never fit 2 replicas, factor or not
    queued = {"decode_busy_p99": None, "decode_cap_per_replica": 4,
              "queue_depth_p99": 10.0, "spec_tokens_per_verify": 4.0}
    queued = {k: v for k, v in queued.items() if v is not None}
    n2, reason2 = pol.desired_decode(queued, 3)
    assert n2 == 2, reason2  # 10/4 = 2.5 fits one-fewer replicas


# --------------------------------- conductor directory (cluster)

def test_directory_atomic_commit_and_namespace_isolation(
        kvplane_cluster):
    w = kvplane_cluster
    dig = "ab" * 32
    meta = {"holder": "pf-first", "desc": {"n": 1}, "tokens": 8,
            "nbytes": 123}
    assert w.conductor.call("kvplane_publish", "", dig, meta) \
        == {"status": "committed"}
    # atomic commit: the SECOND publisher loses, first holder serves
    res2 = w.conductor.call("kvplane_publish", "", dig,
                            dict(meta, holder="pf-second"))
    assert res2["status"] == "already" and res2["holder"] == "pf-first"
    # longest-first scan returns the registered entry, sans clock
    entry = w.conductor.call("kvplane_lookup", "", ["ff" * 32, dig])
    assert entry["holder"] == "pf-first" and entry["digest"] == dig
    assert entry["tokens"] == 8 and "started" not in entry
    # namespace isolation: the key includes the namespace
    assert w.conductor.call("kvplane_lookup", "tenantA|v1",
                            [dig]) is None
    # malformed commits are error dicts, never raises
    bad = w.conductor.call("kvplane_publish", "", "cd" * 32, {"n": 1})
    assert bad.get("error")
    # retraction: the holder's refs died, lookups stop routing to it
    assert w.conductor.call("kvplane_unpublish", "", dig) is True
    assert w.conductor.call("kvplane_lookup", "", [dig]) is None


def test_directory_ttl_reap_and_gc(kvplane_cluster, monkeypatch):
    w = kvplane_cluster
    meta = {"holder": "pf-ttl", "desc": {}, "tokens": 8, "nbytes": 1}
    assert w.conductor.call("kvplane_publish", "ttl", "aa" * 32,
                            meta)["status"] == "committed"
    # lazy TTL reap inside the lookup itself (conductor runs in this
    # process, so the env knob takes effect immediately)
    monkeypatch.setenv("RAY_TPU_KVPLANE_T3_TTL_S", "0.05")
    time.sleep(0.1)
    assert w.conductor.call("kvplane_lookup", "ttl",
                            ["aa" * 32]) is None
    monkeypatch.delenv("RAY_TPU_KVPLANE_T3_TTL_S")
    # explicit reap: age 0 drops everything left in any namespace
    for i in range(2):
        w.conductor.call("kvplane_publish", "ttl", f"{i:02d}" * 32,
                         meta)
    assert w.conductor.call("kvplane_reap", 0.0) >= 2
    # keep-last-K GC, namespace-scoped
    for i in range(5):
        w.conductor.call("kvplane_publish", "gcns", f"b{i}" * 32, meta)
    assert w.conductor.call("kvplane_gc", 2, "gcns") == 3
    st = w.conductor.call("get_kvplane_status")
    assert st["directory"]["namespaces"].get("gcns") == 2
    ctr = st["directory"]["counters"]
    assert ctr["reaped"] >= 3 and ctr["gced"] >= 3


def test_router_directory_hit_and_holder_death_fallback(
        kvplane_cluster, model):
    """Routing upgrades from hash-guess to directory truth: a live
    holder wins outright; an entry whose holder left the pool degrades
    to the hash plus a tier-3 hint the replica fetches (and a bogus
    descriptor fails harmlessly — tier 3 is an accelerator, not a
    dependency); a miss falls back to the hash bit-identically."""
    from ray_tpu.models.kvcache import prefix_digests

    w = kvplane_cluster
    pf = PrefillServer(model, CFG, kv_block_size=BS,
                       kv_pool_blocks=32, kvplane=True)
    dec = DecodeServer(model, CFG, max_batch=2)
    router = DisaggRouter(decode=[dec], prefill=[pf],
                          max_queue_depth=4, affinity_tokens=BS)
    prompt = list(range(701, 713))  # 3 full blocks > publish floor
    try:
        out1 = router.generate(prompt, 4)  # miss; prefill publishes t3
        out2 = router.generate(prompt, 4)  # directory hit -> holder
        assert out2 == out1
        # an entry whose holder is gone: hash + hint, bogus desc is
        # swallowed, the request still completes
        ghost = list(range(801, 813))
        digs = prefix_digests(ghost, BS, None)
        assert w.conductor.call(
            "kvplane_publish", "", digs[0],
            {"holder": "pf-ghost", "desc": {"bogus": True},
             "tokens": 8, "nbytes": 0})["status"] == "committed"
        out3 = router.generate(ghost, 4)
        assert len(out3) == 4
    finally:
        dec.stop()
    rs = router.stats()
    assert rs["directory_misses"] >= 1
    assert rs["directory_hits"] >= 1
    assert rs["directory_fallbacks"] >= 1
    kst = pf.kvplane_stats()
    assert kst["tier3_publishes"] >= 1
    assert kst["t3_held_refs"] >= 1
    rks = router.kvplane_stats()
    assert rks["enabled"] and rks["kv_block_size"] == BS
    assert rks["directory_hits"] == rs["directory_hits"]


# --------------------------- chunk-fabric per-caller attribution

def test_chunk_fetcher_caller_attribution(kvplane_cluster):
    from ray_tpu.util import chunks

    def _reads(totals):
        return totals.get("chunks_local", 0) \
            + totals.get("chunks_fetched", 0)

    w = kvplane_cluster
    payload = {"x": np.arange(4096, dtype=np.int8)}
    refs, desc = chunks.put_tree(w, payload)
    before = _reads(chunks.caller_totals("kvplane"))
    f = chunks.ChunkFetcher(w, caller="kvplane")
    got = chunks.fetch_tree(w, desc, fetcher=f)
    assert np.array_equal(got["x"], payload["x"])
    st = f.stats()
    assert st["caller"] == "kvplane"
    # one host: the chunk rides the local path, but the READ is still
    # attributed to this fetcher's caller bucket
    assert _reads(st) >= 1
    after = _reads(chunks.caller_totals("kvplane"))
    assert after - before == _reads(st)
    # a differently-labeled fetcher accumulates in its own bucket
    kv_before = _reads(chunks.caller_totals("kv"))
    f2 = chunks.ChunkFetcher(w, caller="kv")
    chunks.fetch_tree(w, desc, fetcher=f2)
    assert _reads(chunks.caller_totals("kv")) \
        == kv_before + _reads(f2.stats())
    assert _reads(chunks.caller_totals("kvplane")) == after
    assert chunks.ChunkFetcher(w).stats()["caller"] == "unlabeled"
    del refs


# ----------------------------------------------- e2e surface check

def test_all_surfaces_report_consistent_numbers(kvplane_cluster,
                                                model, capsys):
    """kvplane_status() / CLI / /api/kvplane / Prometheus / timeline
    all report the SAME spill/hit/publish/directory numbers for one
    spill-heavy router+tiers workload."""
    import urllib.request

    from ray_tpu.dashboard import DashboardServer
    from ray_tpu.scripts import cli
    from ray_tpu.util import metrics as metrics_mod
    from ray_tpu.util import state

    w = kvplane_cluster
    pf = PrefillServer(model, CFG, kv_block_size=BS, kv_pool_blocks=8,
                       kv_int8=True, kvplane=True,
                       kvplane_arena_bytes=64 << 20)
    dec = DecodeServer(model, CFG, max_batch=2)
    router = DisaggRouter(decode=[dec], prefill=[pf],
                          max_queue_depth=4, affinity_tokens=BS)
    prompts = [list(range(30 * i + 1001, 30 * i + 1013))
               for i in range(4)]
    try:
        for p in prompts:            # overflow the pool -> spills
            router.generate(p, 4)
        for p in prompts:            # readopts + directory hits
            router.generate(p, 4)
    finally:
        dec.stop()
    pf.publish_telemetry(force=True)
    router.publish_telemetry(force=True)
    metrics_mod.flush()
    kst = pf.kvplane_stats()
    rks = router.kvplane_stats()
    assert kst["spills"] > 0 and kst["tier2_hits"] > 0
    assert kst["tier3_publishes"] >= 1
    assert rks["directory_hits"] >= 1

    # state API (fire-and-forget notify: poll until the snapshots land)
    deadline = time.monotonic() + 10.0
    while True:
        st = state.kvplane_status()
        mine = st["components"].get(pf.server_id)
        rt = st["components"].get(router.router_id)
        if mine is not None and rt is not None \
                and mine.get("spills") == kst["spills"] \
                and rt.get("directory_hits") == rks["directory_hits"]:
            break
        assert time.monotonic() < deadline, st
        time.sleep(0.1)
    assert mine["tier2_hits"] == kst["tier2_hits"]
    assert mine["tier3_publishes"] == kst["tier3_publishes"]
    assert mine["entries"] == kst["entries"]
    totals = st["totals"]
    assert totals["spills"] >= kst["spills"]
    assert totals["tier2_hits"] >= kst["tier2_hits"]
    assert totals["directory_hits"] >= rks["directory_hits"]
    assert totals["arena_entries"] >= kst["entries"]
    assert st["directory"]["entries"] >= 1
    assert st["directory"]["counters"]["publishes"] >= 1

    # CLI (same conductor snapshot)
    host, port = w.conductor_address
    cli.main(["kvplane", "--json", "--address", f"{host}:{port}"])
    cli_out = json.loads(capsys.readouterr().out)
    assert cli_out["totals"] == totals
    assert cli_out["directory"] == st["directory"]

    # dashboard /api/kvplane
    srv = DashboardServer(w.conductor_address, port=0).start()
    try:
        with urllib.request.urlopen(srv.url + "/api/kvplane",
                                    timeout=10.0) as r:
            dash = json.loads(r.read())
    finally:
        srv.stop()
    assert dash["totals"] == totals
    assert dash["directory"] == st["directory"]
    ev_kinds = {e.get("kind") for e in dash["events"]}
    assert {"spill", "tier2_hit", "tier3_publish"} <= ev_kinds

    # Prometheus: the kvplane families exist and cover this workload
    prom = state.prometheus_metrics()
    for family in ("ray_tpu_kvplane_spills_total",
                   "ray_tpu_kvplane_hits_total",
                   "ray_tpu_kvplane_reused_tokens_total",
                   "ray_tpu_kvplane_directory_total",
                   "ray_tpu_kvplane_arena_bytes"):
        assert family in prom, family
    spill_total = sum(
        float(line.rsplit(" ", 1)[1])
        for line in prom.splitlines()
        if line.startswith("ray_tpu_kvplane_spills_total"))
    assert spill_total >= kst["spills"]

    # merged timeline: the kvplane lane mirrors the event log
    trace = state.timeline(merged=True)
    markers = [e for e in trace if e.get("pid") == "kvplane"]
    assert markers and all(m["ph"] == "i" and m["cat"] == "kvplane"
                           for m in markers)
    tids = {m["tid"] for m in markers}
    assert {"spill", "tier2_hit", "tier3_publish",
            "directory_hit"} <= tids
    spills_here = [m for m in markers if m["tid"] == "spill"
                   and m["args"].get("replica") == pf.server_id]
    assert len(spills_here) == kst["spills"]
