"""Push-based object readiness: wait() subscribes once per remote ref and
the owner pushes object_available — no steady-state object_ready polling
(reference: ownership-based object directory callbacks,
src/ray/core_worker/object_recovery_manager / object_directory
subscriptions, replacing the r2 50ms probe loop)."""
from __future__ import annotations

import collections
import time

import pytest

import ray_tpu


def test_wait_remote_ref_push_not_poll(ray_start_regular, monkeypatch):
    from ray_tpu._private import rpc as rpc_mod

    @ray_tpu.remote
    def slow():
        time.sleep(1.0)
        return 42

    @ray_tpu.remote
    class Owner:
        def start(self):
            self.ref = slow.remote()
            return [self.ref]

    o = Owner.remote()
    [ref] = ray_tpu.get(o.start.remote())

    calls: collections.Counter = collections.Counter()
    orig = rpc_mod.RpcClient.call

    def counting(self, method, *a, **kw):
        calls[method] += 1
        return orig(self, method, *a, **kw)

    monkeypatch.setattr(rpc_mod.RpcClient, "call", counting)

    ready, not_ready = ray_tpu.wait([ref], timeout=10)
    assert [r.id for r in ready] == [ref.id] and not not_ready
    assert ray_tpu.get(ref) == 42
    # exactly one subscription RPC; zero polling probes over the ~1s wait
    assert calls["subscribe_object"] == 1
    assert calls["object_ready"] == 0


def test_wait_many_remote_refs_one_rpc_each(ray_start_regular, monkeypatch):
    from ray_tpu._private import rpc as rpc_mod

    @ray_tpu.remote
    def slow(i):
        time.sleep(0.5 + 0.05 * i)
        return i

    @ray_tpu.remote
    class Owner:
        def start(self, n):
            return [[slow.remote(i)] for i in range(n)]

    o = Owner.remote()
    refs = [r for (r,) in ray_tpu.get(o.start.remote(8))]

    calls: collections.Counter = collections.Counter()
    orig = rpc_mod.RpcClient.call

    def counting(self, method, *a, **kw):
        calls[method] += 1
        return orig(self, method, *a, **kw)

    monkeypatch.setattr(rpc_mod.RpcClient, "call", counting)

    ready, not_ready = ray_tpu.wait(refs, num_returns=len(refs), timeout=20)
    assert len(ready) == len(refs) and not not_ready
    assert sorted(ray_tpu.get(refs)) == list(range(8))
    assert calls["subscribe_object"] <= len(refs)
    assert calls["object_ready"] == 0


def test_wait_timeout_then_push_completes(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(1.0)
        return "done"

    @ray_tpu.remote
    class Owner:
        def start(self):
            return [slow.remote()]

    o = Owner.remote()
    [ref] = ray_tpu.get(o.start.remote())
    ready, not_ready = ray_tpu.wait([ref], timeout=0.15)
    assert not ready and [r.id for r in not_ready] == [ref.id]
    # second wait reuses the existing subscription and is woken by the push
    ready, not_ready = ray_tpu.wait([ref], timeout=10)
    assert ready and not not_ready
    assert ray_tpu.get(ref) == "done"


def test_wait_remote_error_pushes_ready(ray_start_regular):
    @ray_tpu.remote
    def boom():
        time.sleep(0.3)
        raise ValueError("bad")

    @ray_tpu.remote
    class Owner:
        def start(self):
            return [boom.remote()]

    o = Owner.remote()
    [ref] = ray_tpu.get(o.start.remote())
    # errors count as "ready" for wait(), exactly like the reference
    ready, not_ready = ray_tpu.wait([ref], timeout=10)
    assert ready and not not_ready
    with pytest.raises(Exception):
        ray_tpu.get(ref)
