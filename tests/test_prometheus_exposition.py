"""Prometheus text-exposition correctness (ISSUE-3 satellite): the
exposition rendered by util.state must satisfy the shapes a strict
scraper (prometheus_client.parser) requires — validated with
string-level assertions so no new dependency is added:

- every non-comment line is `name{labels} value` with a parseable float;
- label values escape backslash, double-quote and newline;
- one HELP/TYPE header per family, BEFORE its samples, families
  contiguous;
- histograms: cumulative buckets, a `+Inf` bucket equal to `_count`,
  and `_sum`/`_count` series present.

Clusterless on purpose (the tier-1 suite is timeout-bound): the pure
renderer `state._render_prometheus` is fed this process's live registry
snapshot — exactly the payload Worker pushes via report_metrics — while
`tests/test_state.py` covers the conductor round-trip.
"""
from __future__ import annotations

import re

import pytest

from ray_tpu.util import metrics, state
from ray_tpu.util.metrics import _registry

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{[^{}]*\})? (?P<value>[^ ]+)$')
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _scrape() -> str:
    """Render this process's registry exactly as prometheus_metrics()
    renders the conductor's per-worker snapshots."""
    return state._render_prometheus({"testworker": _registry.snapshot()})


def _parse_labels(blob: str) -> dict:
    inner = blob[1:-1]
    out = dict(_LABEL_RE.findall(inner))
    # the whole blob must be consumed by well-formed k="v" pairs
    rebuilt = ",".join(f'{k}="{v}"' for k, v in _LABEL_RE.findall(inner))
    assert rebuilt == inner, f"malformed label blob: {blob!r}"
    return out


def test_exposition_grammar():
    c = metrics.Counter("expo_requests_total", "help text",
                        tag_keys=("route",))
    c.inc(2, tags={"route": "/a"})
    text = _scrape()
    assert text.endswith("\n")
    seen_families = []
    current = None
    for line in text.splitlines():
        assert line.strip() == line and line
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            fam = parts[2]
            if fam != current:
                current = fam
                # families are contiguous: no family header reappears
                assert fam not in seen_families, f"split family {fam}"
                seen_families.append(fam)
            if line.startswith("# TYPE "):
                assert parts[3] in ("counter", "gauge", "histogram",
                                    "summary", "untyped")
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        # sample belongs to the current family (histograms suffix the
        # family name with _bucket/_sum/_count)
        assert current is not None and m.group("name").startswith(current)
        float(m.group("value"))  # value parses
        if m.group("labels"):
            _parse_labels(m.group("labels"))


def test_label_value_escaping():
    c = metrics.Counter("expo_escapes_total", "desc",
                        tag_keys=("k",))
    nasty = 'quote:" backslash:\\ newline:\nend, comma:,'
    c.inc(1, tags={"k": nasty})
    text = _scrape()
    line = next(ln for ln in text.splitlines()
                if ln.startswith("expo_escapes_total{"))
    m = _SAMPLE_RE.match(line)
    assert m, line  # the escaped newline must NOT split the line
    labels = _parse_labels(m.group("labels"))
    unescaped = (labels["k"].replace(r"\n", "\n").replace(r"\"", '"')
                 .replace("\\\\", "\\"))
    assert unescaped == nasty


def test_histogram_cumulative_with_inf_sum_count():
    h = metrics.Histogram("expo_latency_s", "lat",
                          boundaries=[0.01, 0.1, 1.0],
                          tag_keys=("path",))
    for v in (0.005, 0.005, 0.05, 0.5, 5.0, 7.0):
        h.observe(v, tags={"path": "/x"})
    text = _scrape()
    lines = [ln for ln in text.splitlines()
             if ln.startswith("expo_latency_s")]
    buckets, total, sums = [], None, None
    for ln in lines:
        m = _SAMPLE_RE.match(ln)
        assert m, ln
        labels = _parse_labels(m.group("labels") or "{}")
        if m.group("name") == "expo_latency_s_bucket":
            buckets.append((labels["le"], float(m.group("value"))))
        elif m.group("name") == "expo_latency_s_count":
            total = float(m.group("value"))
        elif m.group("name") == "expo_latency_s_sum":
            sums = float(m.group("value"))
    les = [b[0] for b in buckets]
    assert les == ["0.01", "0.1", "1.0", "+Inf"]
    counts = [b[1] for b in buckets]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert counts == [2.0, 3.0, 4.0, 6.0]
    assert total == 6.0 and buckets[-1][1] == total
    assert sums == pytest.approx(0.005 * 2 + 0.05 + 0.5 + 5.0 + 7.0)
    # TYPE header present and correct
    assert "# TYPE expo_latency_s histogram" in text


def test_help_escaping():
    metrics.Gauge("expo_multiline_help", "line1\nline2 \\ done").set(1.0)
    text = _scrape()
    help_line = next(ln for ln in text.splitlines()
                     if ln.startswith("# HELP expo_multiline_help"))
    assert "\n" not in help_line  # real newline would split the comment
    assert r"\n" in help_line
