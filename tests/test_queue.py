"""ray_tpu.util.queue.Queue — surface modeled on the reference's
python/ray/tests/test_queue.py (FIFO order, maxsize backpressure,
nowait/batch variants, cross-task sharing)."""
import threading
import time

import pytest

import ray_tpu
from ray_tpu.util.queue import Empty, Full, Queue


def test_queue_fifo_and_size(ray_start_regular):
    q = Queue()
    assert q.empty()
    for i in range(5):
        q.put(i)
    assert q.qsize() == 5
    assert q.size() == 5
    assert not q.empty()
    assert [q.get() for _ in range(5)] == [0, 1, 2, 3, 4]
    assert q.empty()
    q.shutdown()


def test_queue_nowait_and_batch(ray_start_regular):
    q = Queue(maxsize=3)
    q.put_nowait(1)
    q.put_nowait_batch([2, 3])
    assert q.full()
    # actor-side asyncio.QueueFull/QueueEmpty and remote queue.Full/Empty
    # all come back as the stdlib queue exceptions (reference parity)
    with pytest.raises(Full):
        q.put_nowait(4)
    with pytest.raises(Full):
        q.put_nowait_batch([4, 5])
    assert q.get_nowait_batch(2) == [1, 2]
    with pytest.raises(Empty):
        q.get_nowait_batch(5)
    assert q.get_nowait() == 3
    with pytest.raises(Empty):
        q.get_nowait()
    q.shutdown()


def test_queue_blocking_timeouts(ray_start_regular):
    q = Queue(maxsize=1)
    q.put("x")
    t0 = time.monotonic()
    with pytest.raises(Full):  # Full after the timeout
        q.put("y", timeout=0.3)
    assert time.monotonic() - t0 >= 0.25
    assert q.get() == "x"
    with pytest.raises(Empty):  # Empty after the timeout
        q.get(timeout=0.3)
    q.shutdown()


def test_queue_blocking_put_unblocks_on_get(ray_start_regular):
    q = Queue(maxsize=1)
    q.put(1)
    got = []

    def producer():
        q.put(2, timeout=30.0)  # blocks until the consumer drains

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.2)
    got.append(q.get(timeout=10.0))
    t.join(timeout=30)
    assert not t.is_alive()
    got.append(q.get(timeout=10.0))
    assert got == [1, 2]
    q.shutdown()


def test_queue_shared_across_tasks(ray_start_regular):
    q = Queue()

    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return n

    @ray_tpu.remote
    def consumer(q, n):
        return [q.get(timeout=30.0) for _ in range(n)]

    pref = producer.remote(q, 10)
    cref = consumer.remote(q, 10)
    assert ray_tpu.get(pref) == 10
    assert ray_tpu.get(cref) == list(range(10))
    q.shutdown()


def test_queue_exceptions_are_queue_module_types():
    assert issubclass(Full, Exception)
    assert issubclass(Empty, Exception)
