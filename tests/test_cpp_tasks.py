"""Native C++ tasks (reference: the C++ worker API, SURVEY §2.1):
bytes-ABI symbols from a g++-built shared library execute as cluster
tasks and actor methods."""
from __future__ import annotations

import os
import struct
import subprocess
import sys

import pytest

import ray_tpu
from ray_tpu.util.cpp import cpp_actor, cpp_function, header_path

CC_SRC = r"""
#include "ray_tpu_task.h"
#include <string>
#include <atomic>

extern "C" int64_t sum_doubles(const uint8_t* in, size_t in_len,
                               uint8_t** out, size_t* out_len) {
  if (in_len % sizeof(double)) return 22;  // EINVAL
  const double* xs = reinterpret_cast<const double*>(in);
  double acc = 0.0;
  for (size_t i = 0; i < in_len / sizeof(double); ++i) acc += xs[i];
  RAY_TPU_TASK_RETURN(out, out_len, &acc, sizeof(acc));
  return 0;
}

extern "C" int64_t shout(const uint8_t* in, size_t in_len,
                         uint8_t** out, size_t* out_len) {
  std::string s(reinterpret_cast<const char*>(in), in_len);
  for (auto& c : s) c = toupper(c);
  RAY_TPU_TASK_RETURN(out, out_len, s.data(), s.size());
  return 0;
}

extern "C" int64_t always_fails(const uint8_t*, size_t,
                                uint8_t**, size_t*) {
  return 42;
}

static std::atomic<int64_t> counter{0};

extern "C" int64_t reset_counter(const uint8_t* in, size_t in_len,
                                 uint8_t** out, size_t* out_len) {
  int64_t v = 0;
  if (in_len == sizeof(int64_t)) memcpy(&v, in, sizeof(v));
  counter.store(v);
  RAY_TPU_TASK_RETURN(out, out_len, &v, sizeof(v));
  return 0;
}

extern "C" int64_t bump(const uint8_t*, size_t,
                        uint8_t** out, size_t* out_len) {
  int64_t v = ++counter;
  RAY_TPU_TASK_RETURN(out, out_len, &v, sizeof(v));
  return 0;
}
"""


@pytest.fixture(scope="module")
def native_lib(tmp_path_factory):
    d = tmp_path_factory.mktemp("cpplib")
    src = d / "tasks.cc"
    src.write_text(CC_SRC)
    lib = d / "libtasks.so"
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC",
         f"-I{os.path.dirname(header_path())}",
         "-o", str(lib), str(src)],
        check=True, capture_output=True)
    return str(lib)


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_cpp_task_roundtrip(cluster, native_lib):
    f = cpp_function(native_lib, "sum_doubles")
    payload = struct.pack("<4d", 1.5, 2.5, 3.0, 3.0)
    out = ray_tpu.get(f.remote(payload))
    assert struct.unpack("<d", out)[0] == 10.0

    shout = cpp_function(native_lib, "shout")
    assert ray_tpu.get(shout.remote(b"tpu native")) == b"TPU NATIVE"


def test_cpp_task_parallel_fanout(cluster, native_lib):
    f = cpp_function(native_lib, "sum_doubles")
    refs = [f.remote(struct.pack("<2d", float(i), 1.0)) for i in range(16)]
    got = [struct.unpack("<d", b)[0] for b in ray_tpu.get(refs)]
    assert got == [i + 1.0 for i in range(16)]


def test_cpp_task_error_code_surfaces(cluster, native_lib):
    f = cpp_function(native_lib, "always_fails")
    with pytest.raises(Exception, match="code 42"):
        ray_tpu.get(f.remote(b""))
    g = cpp_function(native_lib, "sum_doubles")
    with pytest.raises(Exception, match="code 22"):
        ray_tpu.get(g.remote(b"odd"))


def test_cpp_actor_native_state(cluster, native_lib):
    A = cpp_actor(native_lib, ["bump", "reset_counter"],
                  init_symbol="reset_counter")
    a = A.remote(struct.pack("<q", 100))
    vals = [struct.unpack("<q", ray_tpu.get(a.bump.remote()))[0]
            for _ in range(3)]
    assert vals == [101, 102, 103]
    ray_tpu.get(a.reset_counter.remote(struct.pack("<q", 0)))
    assert struct.unpack("<q", ray_tpu.get(a.bump.remote()))[0] == 1


CC_API_SRC = r"""
#include "ray_tpu_api.h"
#include <cstring>

extern "C" int64_t double_bytes(const ray_tpu_api_t* api,
                                const uint8_t* in, size_t in_len,
                                uint8_t** out, size_t* out_len) {
  (void)api;
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(in_len));
  if (!buf) return 12;
  for (size_t i = 0; i < in_len; ++i) buf[i] = in[i] * 2;
  *out = buf; *out_len = in_len;
  return 0;
}

extern "C" int64_t orchestrate(const ray_tpu_api_t* api,
                               const uint8_t* in, size_t in_len,
                               uint8_t** out, size_t* out_len) {
  /* put -> get roundtrip, then fan a subtask out and await it — the
   * reference C++ driver surface (ray::Put/Get/Task().Remote()). */
  char id[RAY_TPU_OBJECT_ID_BUF];
  if (api->put(api->ctx, in, in_len, id)) return 101;
  uint8_t* got = nullptr; size_t got_len = 0;
  if (api->get(api->ctx, id, 10.0, &got, &got_len)) return 102;
  if (got_len != in_len || std::memcmp(got, in, in_len) != 0) return 103;

  char child[RAY_TPU_OBJECT_ID_BUF];
  if (api->submit(api->ctx, "double_bytes", got, got_len, child))
    return 104;
  api->free_buf(got); got = nullptr;
  if (api->get(api->ctx, child, 30.0, &got, &got_len)) return 105;

  if (api->release(api->ctx, id)) return 106;
  if (api->release(api->ctx, child)) return 107;
  /* unknown id after release */
  uint8_t* junk = nullptr; size_t junk_len = 0;
  if (api->get(api->ctx, id, 0.5, &junk, &junk_len) == 0) return 108;

  RAY_TPU_TASK_RETURN(out, out_len, got, got_len);
  api->free_buf(got);
  return 0;
}
"""


@pytest.fixture(scope="module")
def native_api_lib(tmp_path_factory):
    d = tmp_path_factory.mktemp("cppapilib")
    src = d / "api_tasks.cc"
    src.write_text(CC_API_SRC)
    lib = d / "libapitasks.so"
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC",
         f"-I{os.path.dirname(header_path())}",
         "-o", str(lib), str(src)],
        check=True, capture_output=True)
    return str(lib)


def test_cpp_api_put_get_submit(cluster, native_api_lib):
    """v2 ABI: native code puts objects, gets them back, fans a subtask
    out, releases its pins (reference cpp/include/ray/api.h surface)."""
    f = cpp_function(native_api_lib, "orchestrate", api=True)
    out = ray_tpu.get(f.remote(bytes([1, 2, 3, 40])), timeout=60.0)
    assert out == bytes([2, 4, 6, 80])


def test_cpp_api_pins_released(cluster, native_api_lib):
    """release() drops the worker-side pins (no unbounded growth)."""
    f = cpp_function(native_api_lib, "orchestrate", api=True)
    ray_tpu.get(f.remote(b"\x01\x02"), timeout=60.0)

    @ray_tpu.remote
    def pin_count():
        from ray_tpu.util.cpp import _API_REFS

        return len(_API_REFS)

    # run on every idle worker; the one that hosted orchestrate must
    # report zero pins (both ids were released)
    counts = ray_tpu.get([pin_count.remote() for _ in range(8)],
                         timeout=60.0)
    assert all(c == 0 for c in counts), counts


CC_TYPED_SRC = r"""
#include "ray_tpu.hpp"
#include <atomic>
#include <cstring>

struct Vec3 { double x, y, z; };

/* v1-ABI actor method symbols (per-worker native state) */
static std::atomic<long long> g_cell{0};

extern "C" int64_t cell_init(const uint8_t* in, size_t in_len,
                             uint8_t** out, size_t* out_len) {
  long long v = 0;
  if (in_len == sizeof(v)) std::memcpy(&v, in, sizeof(v));
  g_cell.store(v);
  RAY_TPU_TASK_RETURN(out, out_len, &v, sizeof(v));
  return 0;
}

extern "C" int64_t cell_add(const uint8_t* in, size_t in_len,
                            uint8_t** out, size_t* out_len) {
  long long d = 0;
  if (in_len == sizeof(d)) std::memcpy(&d, in, sizeof(d));
  long long v = (g_cell += d);
  RAY_TPU_TASK_RETURN(out, out_len, &v, sizeof(v));
  return 0;
}

extern "C" int64_t typed_actor_roundtrip(const ray_tpu_api_t* api,
                                         const uint8_t* in, size_t in_len,
                                         uint8_t** out, size_t* out_len) {
  /* reference ray::Actor(...).Remote() + ActorHandle::Task() shape:
   * create a stateful native actor, make typed calls, kill it. */
  (void)in; (void)in_len;
  ray_tpu::Runtime rt(api);
  ray_tpu::ActorHandle a;
  try {
    a = rt.CreateActor<long long>("cell_add", "cell_init", 5LL);
  } catch (const ray_tpu::RayError& e) { return 410 + e.code(); }
  ray_tpu::ObjectRef<long long> r1;
  try {
    r1 = a.Call<long long, long long>("cell_add", 3LL);
  } catch (const ray_tpu::RayError& e) { return 420 + e.code(); }
  long long v1;
  try {
    v1 = rt.Get(r1, 60.0);
  } catch (const ray_tpu::RayError& e) { return 430 + e.code(); }
  if (v1 != 8) return 301;
  long long v2;
  try {
    v2 = rt.Get(a.Call<long long, long long>("cell_add", 2LL), 60.0);
  } catch (const ray_tpu::RayError& e) { return 440 + e.code(); }
  if (v2 != 10) return 302;
  a.Kill();
  RAY_TPU_TASK_RETURN(out, out_len, &v2, sizeof(v2));
  return 0;
}

extern "C" int64_t vec_norm2(const ray_tpu_api_t* api,
                             const uint8_t* in, size_t in_len,
                             uint8_t** out, size_t* out_len) {
  (void)api;
  Vec3 v = ray_tpu::detail::Codec<Vec3>::decode(in, in_len);
  double n2 = v.x * v.x + v.y * v.y + v.z * v.z;
  RAY_TPU_TASK_RETURN(out, out_len, &n2, sizeof(n2));
  return 0;
}

extern "C" int64_t typed_roundtrip(const ray_tpu_api_t* api,
                                   const uint8_t* in, size_t in_len,
                                   uint8_t** out, size_t* out_len) {
  /* reference api.h surface through the typed wrappers:
   * Put(struct) -> ObjectRef<Vec3> -> Get, then a typed Submit whose
   * double result comes back via ObjectRef<double>. RAII releases
   * every pin when the refs leave scope. */
  (void)in; (void)in_len;
  try {
    ray_tpu::Runtime rt(api);
    Vec3 v{3.0, 4.0, 12.0};
    ray_tpu::ObjectRef<Vec3> ref = rt.Put(v);
    Vec3 back = rt.Get(ref, 10.0);
    if (back.x != v.x || back.y != v.y || back.z != v.z) return 201;

    ray_tpu::ObjectRef<double> child =
        rt.Submit<double, Vec3>("vec_norm2", back);
    double n2 = rt.Get(child, 30.0);
    if (n2 != 169.0) return 202;

    std::string s = "typed";
    ray_tpu::ObjectRef<std::string> sref = rt.Put(s);
    if (rt.Get(sref, 10.0) != s) return 203;

    std::vector<int32_t> xs{1, 2, 3};
    ray_tpu::ObjectRef<std::vector<int32_t>> vref = rt.Put(xs);
    if (rt.Get(vref, 10.0) != xs) return 204;

    RAY_TPU_TASK_RETURN(out, out_len, &n2, sizeof(n2));
    return 0;
  } catch (const ray_tpu::RayError&) {
    return 205;
  }
}
"""


@pytest.fixture(scope="module")
def typed_lib(tmp_path_factory):
    d = tmp_path_factory.mktemp("cpptypedlib")
    src = d / "typed_tasks.cc"
    src.write_text(CC_TYPED_SRC)
    lib = d / "libtypedtasks.so"
    subprocess.run(
        ["g++", "-O2", "-std=c++14", "-shared", "-fPIC",
         f"-I{os.path.dirname(header_path())}",
         "-o", str(lib), str(src)],
        check=True, capture_output=True)
    return str(lib)


def test_cpp_typed_object_refs(cluster, typed_lib):
    """Typed ObjectRef<T>/Put/Get/Submit over the C ABI — reference
    /root/reference/cpp/include/ray/api.h templated surface."""
    f = cpp_function(typed_lib, "typed_roundtrip", api=True)
    out = ray_tpu.get(f.remote(b""), timeout=60.0)
    (n2,) = struct.unpack("<d", out)
    assert n2 == 169.0


def test_cpp_typed_actor(cluster, typed_lib):
    """Native actor surface through the typed wrappers: CreateActor with
    an init symbol, stateful typed Calls, Kill (reference api.h
    ray::Actor/ActorHandle)."""
    f = cpp_function(typed_lib, "typed_actor_roundtrip", api=True)
    out = ray_tpu.get(f.remote(b""), timeout=120.0)
    (v,) = struct.unpack("<q", out)
    assert v == 10


def test_cpp_typed_pins_released(cluster, typed_lib):
    """RAII ObjectRef destruction releases every pin."""
    f = cpp_function(typed_lib, "typed_roundtrip", api=True)
    ray_tpu.get(f.remote(b""), timeout=60.0)

    @ray_tpu.remote
    def pin_count():
        from ray_tpu.util.cpp import _API_REFS

        return len(_API_REFS)

    counts = ray_tpu.get([pin_count.remote() for _ in range(8)],
                         timeout=60.0)
    assert all(c == 0 for c in counts), counts
