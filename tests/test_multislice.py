"""Multi-slice hybrid mesh subsystem on the virtual 8-device CPU mesh:
slice-topology discovery (RAY_TPU_VIRTUAL_SLICES partitioning), DCN x ICI
hybrid mesh assembly (DCN-major block structure), conductor-KV slice
rendezvous + state-API slice map, trainer config lowering, and the
dryrun hybrid layouts as the off-silicon tier-1 smoke."""
from __future__ import annotations

import os
import sys
import threading

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel import MeshConfig
from ray_tpu.parallel.distributed import (publish_slice_map,
                                          rendezvous_slices,
                                          slice_process_ids)
from ray_tpu.parallel.multislice import (HybridMeshConfig, SliceTopology,
                                         discover_slice_topology,
                                         make_hybrid_mesh)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root: __graft_entry__


# ------------------------------------------------------------ discovery


def test_virtual_slice_discovery(cpu_mesh8, monkeypatch):
    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICES", "2")
    topo = discover_slice_topology(cpu_mesh8)
    assert topo.num_slices == 2
    assert topo.devices_per_slice == 4
    assert topo.source == "virtual"
    assert topo.devices == list(cpu_mesh8)
    assert topo.slices[0] == tuple(cpu_mesh8[:4])
    assert topo.slices[1] == tuple(cpu_mesh8[4:])


def test_virtual_slices_must_divide(cpu_mesh8, monkeypatch):
    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICES", "3")
    with pytest.raises(ValueError, match="partition"):
        discover_slice_topology(cpu_mesh8)


def test_single_slice_default(cpu_mesh8, monkeypatch):
    monkeypatch.delenv("RAY_TPU_VIRTUAL_SLICES", raising=False)
    monkeypatch.delenv("MEGASCALE_NUM_SLICES", raising=False)
    topo = discover_slice_topology(cpu_mesh8)
    assert topo.num_slices == 1
    assert topo.source == "single"


def test_megascale_env_discovery(cpu_mesh8, monkeypatch):
    monkeypatch.delenv("RAY_TPU_VIRTUAL_SLICES", raising=False)
    monkeypatch.setenv("MEGASCALE_NUM_SLICES", "4")
    topo = discover_slice_topology(cpu_mesh8)
    assert topo.num_slices == 4
    assert topo.source == "megascale"


def test_slice_index_attr_discovery(monkeypatch):
    monkeypatch.delenv("RAY_TPU_VIRTUAL_SLICES", raising=False)

    class FakeDev:
        def __init__(self, i, s):
            self.id, self.slice_index = i, s

        def __repr__(self):
            return f"d{self.id}"

    devs = [FakeDev(i, i // 4) for i in range(8)]
    topo = discover_slice_topology(devs)
    assert topo.num_slices == 2
    assert topo.source == "slice_index"
    assert all(d.slice_index == 0 for d in topo.slices[0])
    assert all(d.slice_index == 1 for d in topo.slices[1])


def test_uniform_slice_index_beats_megascale_env(monkeypatch):
    """Devices that all report the SAME slice_index are one real ICI
    slice (e.g. jax.local_devices() on a multislice worker) — the
    MEGASCALE env var must not partition them into fake slices."""
    monkeypatch.delenv("RAY_TPU_VIRTUAL_SLICES", raising=False)
    monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")

    class FakeDev:
        def __init__(self, i):
            self.id, self.slice_index = i, 0

    topo = discover_slice_topology([FakeDev(i) for i in range(8)])
    assert topo.num_slices == 1
    assert topo.source == "single"


# ---------------------------------------------------------- hybrid mesh


def test_hybrid_mesh_dcn_dp_tp_block_structure(cpu_mesh8, monkeypatch):
    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICES", "2")
    mesh = HybridMeshConfig(dp=-1, tp=2, dcn_dp=2).build(cpu_mesh8)
    assert dict(mesh.shape) == {"dp": 4, "fsdp": 1, "pp": 1, "sp": 1,
                                "ep": 1, "tp": 2}
    # DCN-major on dp: the first dp half is slice 0, second half slice 1
    # (tp stays INSIDE a slice — ICI-hungry axes never cross DCN)
    devs = mesh.devices  # (4,1,1,1,1,2)
    assert set(devs[:2].ravel()) == set(cpu_mesh8[:4])
    assert set(devs[2:].ravel()) == set(cpu_mesh8[4:])


def test_hybrid_mesh_dcn_pp_fsdp_block_structure(cpu_mesh8, monkeypatch):
    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICES", "2")
    mesh = HybridMeshConfig(fsdp=4, dcn_pp=2).build(cpu_mesh8)
    assert dict(mesh.shape) == {"dp": 1, "fsdp": 4, "pp": 2, "sp": 1,
                                "ep": 1, "tp": 1}
    devs = mesh.devices  # (1,4,2,1,1,1); pp is axis 2
    assert set(devs[:, :, 0].ravel()) == set(cpu_mesh8[:4])
    assert set(devs[:, :, 1].ravel()) == set(cpu_mesh8[4:])


def test_hybrid_mesh_dcn_fill_axis(cpu_mesh8, monkeypatch):
    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICES", "2")
    mesh = HybridMeshConfig(tp=2, dcn_dp=-1).build(cpu_mesh8)
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2


def test_hybrid_mesh_dcn_mismatch_raises(cpu_mesh8, monkeypatch):
    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICES", "2")
    with pytest.raises(ValueError, match="DCN"):
        HybridMeshConfig(dp=-1, dcn_dp=3).build(cpu_mesh8)


def test_hybrid_mesh_single_slice_degrades_to_flat(cpu_mesh8,
                                                   monkeypatch):
    monkeypatch.delenv("RAY_TPU_VIRTUAL_SLICES", raising=False)
    monkeypatch.delenv("MEGASCALE_NUM_SLICES", raising=False)
    mesh = HybridMeshConfig(dp=-1, tp=2, dcn_dp=2).build(cpu_mesh8)
    # a dev box IS one slice: the hybrid request collapses onto ICI with
    # identical axis sizes, so hybrid-layout programs run unchanged
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2


def test_hybrid_mesh_explicit_topology(cpu_mesh8):
    topo = SliceTopology(slices=(tuple(cpu_mesh8[:4]),
                                 tuple(cpu_mesh8[4:])), source="virtual")
    mesh = make_hybrid_mesh(HybridMeshConfig(dp=-1, dcn_dp=2),
                            topology=topo)
    assert mesh.shape["dp"] == 8


def test_hybrid_mesh_runs_sharded_compute(cpu_mesh8, monkeypatch):
    """pjit'd compute with the canonical named axes works unchanged on a
    hybrid mesh (the MESH_AXES contract)."""
    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICES", "2")
    mesh = HybridMeshConfig(dp=-1, tp=2, dcn_dp=2).build(cpu_mesh8)
    from ray_tpu.parallel import named_sharding

    x = jnp.arange(8.0 * 4).reshape(8, 4)
    xs = jax.device_put(x, named_sharding(mesh, "dp", None))
    y = jax.jit(lambda a: (a * 2).sum())(xs)
    assert float(y) == float((x * 2).sum())


# --------------------------------------------------- slice rendezvous


class _FakeKV:
    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()

    def put(self, k, v, namespace="default"):
        with self._lock:
            self._d[(namespace, bytes(k))] = bytes(v)

    def get(self, k, namespace="default"):
        with self._lock:
            return self._d.get((namespace, bytes(k)))


def test_slice_rendezvous_assembles_map():
    kv = _FakeKV()
    slice_of = {0: 1, 1: 1, 2: 0, 3: 0}
    results = {}

    def run(rank):
        results[rank] = rendezvous_slices(
            kv.put, kv.get, "g", rank, 4, slice_of[rank], timeout=10.0)

    threads = [threading.Thread(target=run, args=(r,)) for r in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert all(not t.is_alive() for t in threads)
    expect = {0: [2, 3], 1: [0, 1]}
    assert all(results[r] == expect for r in range(4))


def test_slice_rendezvous_all_none_is_no_grouping():
    """A gang where no rank has a slice id (plain single-slice job)
    rendezvouses to None — no slice grouping, process ids untouched."""
    kv = _FakeKV()
    results = {}

    def run(rank):
        results[rank] = rendezvous_slices(
            kv.put, kv.get, "g0", rank, 3, None, timeout=10.0)

    threads = [threading.Thread(target=run, args=(r,)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert all(results[r] is None for r in range(3))


def test_slice_rendezvous_mixed_identity_fails_everywhere():
    """Slice identity must be all-or-none: a gang where only SOME ranks
    resolved a slice id fails fast with a clear error on every rank
    instead of deadlocking on mismatched process ids."""
    kv = _FakeKV()
    errors = {}

    def run(rank, sid):
        try:
            rendezvous_slices(kv.put, kv.get, "g1", rank, 3, sid,
                              timeout=10.0)
        except ValueError as e:
            errors[rank] = str(e)

    threads = [threading.Thread(target=run, args=(r, s))
               for r, s in [(0, 0), (1, None), (2, 1)]]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert set(errors) == {0, 1, 2}
    assert all("all-or-none" in e for e in errors.values())


def test_slice_process_ids_are_slice_major_rank0_first():
    # rank 0 lives in slice 1: its slice must still come FIRST so rank 0
    # keeps process id 0 (it hosts the jax.distributed coordinator)
    pids = slice_process_ids({0: [2, 3], 1: [0, 1]})
    assert pids == {0: 0, 1: 1, 2: 2, 3: 3}
    # plain case: slices in id order
    pids = slice_process_ids({0: [0, 1], 1: [2, 3]})
    assert pids == {0: 0, 1: 1, 2: 2, 3: 3}
    # interleaved ranks regroup contiguously per slice
    pids = slice_process_ids({0: [0, 2], 1: [1, 3]})
    assert pids == {0: 0, 2: 1, 1: 2, 3: 3}


def test_slice_map_visible_in_state_api(ray_start_regular):
    """publish_slice_map through the conductor KV, read back via the
    state API — the path rank 0 of a gang takes."""
    from ray_tpu._private import worker as wmod
    from ray_tpu.util import state

    w = wmod.global_worker

    def kv_put(k, v, namespace):
        w.conductor.call("kv_put", k, v, True, namespace, timeout=10.0)

    slice_map = {0: [0, 1], 1: [2, 3]}
    pids = slice_process_ids(slice_map)
    publish_slice_map(kv_put, "train-gang/test", slice_map, pids, 4)

    topo = state.slice_topology()
    assert "train-gang/test" in topo
    rec = topo["train-gang/test"]
    assert rec["slices"] == slice_map
    assert rec["process_ids"] == pids
    assert rec["world"] == 4
    assert state.slice_topology("train-gang/test")[
        "train-gang/test"]["slices"] == slice_map
    assert state.slice_topology("no-such-group") == {}


# ------------------------------------------------- trainer config path


def test_sharding_config_lowers_to_hybrid():
    from ray_tpu.train.config import ShardingConfig

    flat = ShardingConfig(tp=2).mesh_config()
    assert type(flat) is MeshConfig
    hybrid = ShardingConfig(tp=2, dcn_dp=2).mesh_config()
    assert isinstance(hybrid, HybridMeshConfig)
    assert hybrid.tp == 2 and hybrid.dcn_dp == 2


def test_sharding_config_builds_hybrid_mesh(cpu_mesh8, monkeypatch):
    from ray_tpu.train.config import ShardingConfig

    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICES", "2")
    mesh = ShardingConfig(dp=-1, tp=2, dcn_dp=2).build_mesh(cpu_mesh8)
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2


def test_scaling_config_slice_assignment():
    """The trainer's contiguous balanced slice assignment for worker
    gangs (rank order == host order under STRICT_PACK)."""
    from ray_tpu.train.config import assign_worker_slices

    assert assign_worker_slices(8, 2) == [0, 0, 0, 0, 1, 1, 1, 1]
    assert assign_worker_slices(6, 3) == [0, 0, 1, 1, 2, 2]
    assert assign_worker_slices(4, 1) == [None] * 4
    with pytest.raises(ValueError, match="not divisible"):
        assign_worker_slices(5, 2)


def test_train_step_on_hybrid_mesh(cpu_mesh8, monkeypatch):
    """FSDP spec inference + TrainStep work unchanged on a hybrid mesh
    (dcn_dp across fake slices, fsdp on the ICI within)."""
    import optax

    from ray_tpu.parallel import infer_fsdp_specs
    from ray_tpu.train.trainer import TrainStep

    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICES", "2")
    mesh = HybridMeshConfig(dp=-1, fsdp=4, dcn_dp=2).build(cpu_mesh8)
    assert mesh.shape["dp"] == 2 and mesh.shape["fsdp"] == 4

    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (16, 16)),
              "b": jnp.zeros((16,))}
    specs = infer_fsdp_specs(params, 4, min_size_to_shard=1)

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    step = TrainStep(loss_fn, optax.sgd(0.1), mesh, specs)
    state = step.init_state(params)
    batch = {"x": jnp.ones((8, 16)), "y": jnp.zeros((8, 16))}
    l0 = None
    for _ in range(3):
        state, m = step(state, batch)
        l0 = l0 or float(m["loss"])
    assert float(m["loss"]) < l0


# ------------------------------------------------------- dryrun smoke


def test_dryrun_hybrid_pp_fsdp_and_ep_smoke(cpu_mesh8):
    """Tier-1 smoke of the dryrun hybrid layouts without silicon: the
    same functions the driver's dryrun_multichip child runs, in-process
    on the virtual 8-device mesh."""
    import __graft_entry__ as ge

    ge._dryrun_hybrid_pp_fsdp(8)
    ge._dryrun_dp_ep(8)


@pytest.mark.slow
def test_dryrun_hybrid_dp_tp_smoke(cpu_mesh8):
    """Full GPT-2 tiny training step on the hybrid mesh — heavier than
    the tier-1 budget allows; the driver's dryrun_multichip runs the
    same layout, and the pp_fsdp/ep smoke above keeps one dryrun layout
    in `-m 'not slow'`."""
    import __graft_entry__ as ge

    ge._dryrun_hybrid_dp_tp(8)
