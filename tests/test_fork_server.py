"""Fork-server worker spawning (reference: raylet WorkerPool prestart,
worker_pool.h:343 — amortized worker start)."""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import pytest

from ray_tpu._private.worker_spawn import ForkedProc


def test_forked_proc_liveness_and_signals():
    proc = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(30)"])
    fp = ForkedProc(proc.pid)
    assert fp.poll() is None
    fp.terminate()
    # the real parent (us) reaps; ForkedProc sees the pid vanish
    proc.wait(timeout=10)
    deadline = time.monotonic() + 5
    while fp.poll() is None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert fp.poll() == 0
    # signalling a dead pid is a no-op, not an error
    fp.kill()
    assert fp.wait(timeout=1) == 0


def test_forked_proc_wait_timeout():
    proc = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(30)"])
    fp = ForkedProc(proc.pid)
    with pytest.raises(subprocess.TimeoutExpired):
        fp.wait(timeout=0.2)
    proc.kill()
    proc.wait(timeout=10)


def test_cluster_uses_fork_server_and_workers_die_fast(ray_start_regular):
    """Workers spawned through the template must appear and fully vanish
    (no zombie window — the template reaps via SIGCHLD) shortly after a
    cluster-initiated kill."""
    import ray_tpu
    from ray_tpu._private import worker as wmod

    @ray_tpu.remote
    def f():
        return os.getpid()

    pids = set(ray_tpu.get([f.remote() for _ in range(8)], timeout=60.0))
    assert pids
    gw = wmod.global_worker
    session = gw.session_dir
    # template process is alive for the session
    assert os.path.exists(os.path.join(session, "fork_server.sock")) or \
        os.environ.get("RAY_TPU_NO_FORK_SERVER")
    pid = next(iter(pids))
    os.kill(pid, signal.SIGTERM)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.05)
    else:
        state = "?"
        try:
            state = open(f"/proc/{pid}/status").read().splitlines()[1]
        except OSError:
            pass
        pytest.fail(f"worker {pid} still visible 5s after SIGTERM ({state})")


def test_cached_lease_survives_worker_crash():
    """A worker can die while its lease sits in the driver's reuse cache
    (worker.py _lease_recache); the next task must transparently fall
    back to a fresh lease via the crash-retry path instead of failing.

    The lease idle TTL is pinned up (default 0.1s) so the reaper cannot
    win the race against the cached-lease assertion on a loaded host."""
    import ray_tpu
    from ray_tpu._private import worker as wmod

    ray_tpu.init(num_cpus=4, _system_config={"lease_idle_ttl": 5.0})
    try:
        _assert_cached_lease_crash_retry(ray_tpu, wmod)
    finally:
        ray_tpu.shutdown()


def _assert_cached_lease_crash_retry(ray_tpu, wmod):
    @ray_tpu.remote
    def whoami():
        return os.getpid()

    pid = ray_tpu.get(whoami.remote(), timeout=60.0)
    gw = wmod.global_worker
    with gw._lease_cache_lock:
        cached = [wid for lst in gw._lease_cache.values()
                  for wid, _, _ in lst]
    assert cached, "lease was not recached after the task"

    # kill the worker while its lease is cached
    os.kill(pid, signal.SIGKILL)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.05)

    # next same-shape task pops the dead cached lease, hits
    # ConnectionLost on push, and retries through a fresh lease
    pid2 = ray_tpu.get(whoami.remote(), timeout=60.0)
    assert pid2 != pid
