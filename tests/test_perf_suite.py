"""Smoke for the micro-benchmark suite (reference ray_perf.py) — every
bench runs end-to-end at tiny scale and emits well-formed records."""
from __future__ import annotations

import json
import os


def test_microbench_smoke(tmp_path):
    from ray_tpu._private import perf

    out = str(tmp_path / "micro.json")
    sink = perf.run(scale=0.02, out=out)
    names = {r["name"] for r in sink}
    assert {"task_roundtrip_sync", "tasks_async", "actor_call_sync",
            "actor_calls_async", "put_1kb", "put_100mb",
            "task_result_fetch_100mb", "queue_drain",
            "actor_churn"} <= names
    for r in sink:
        assert r["iters"] > 0
        ops = [v for k, v in r.items()
               if k.endswith(("_per_s", "gb_per_s"))]
        assert ops and all(v > 0 for v in ops), r
    assert os.path.exists(out)
    with open(out) as f:
        data = json.load(f)
    assert data["results"] == sink
