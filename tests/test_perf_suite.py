"""Smoke for the micro-benchmark suite (reference ray_perf.py) — every
bench runs end-to-end at tiny scale and emits well-formed records."""
from __future__ import annotations

import json
import os


def test_microbench_smoke(tmp_path):
    from ray_tpu._private import perf

    out = str(tmp_path / "micro.json")
    sink = perf.run(scale=0.02, out=out)
    names = {r["name"] for r in sink}
    assert {"task_roundtrip_sync", "tasks_async", "actor_call_sync",
            "actor_calls_async", "put_1kb", "put_100mb",
            "task_result_fetch_100mb", "queue_drain",
            "actor_churn"} <= names
    for r in sink:
        assert r["iters"] > 0
        ops = [v for k, v in r.items()
               if k.endswith(("_per_s", "gb_per_s"))]
        assert ops and all(v > 0 for v in ops), r
    assert os.path.exists(out)
    with open(out) as f:
        data = json.load(f)
    assert data["results"] == sink


def test_pipelined_tasks_not_inverted(tmp_path):
    """Regression guard for the round-4 anomaly: pipelined task
    throughput (tasks_async) ran 5x BELOW serial round-trips because
    every task paid lease+return RPCs and parked submit threads woke in
    herds. With worker-lease reuse (worker.py _lease_recache) pipelined
    throughput must stay at least comparable to serial — the historic
    failure mode was a 5x inversion, so the 0.6 floor catches it while
    tolerating 1-core CI jitter."""
    import time

    import ray_tpu

    ray_tpu.init(num_cpus=8)
    try:
        @ray_tpu.remote
        def f():
            return b"ok"

        ray_tpu.get([f.remote() for _ in range(50)])  # warm pool
        n = 200
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(f.remote())
        sync_rate = n / (time.perf_counter() - t0)

        t0 = time.perf_counter()
        ray_tpu.get([f.remote() for _ in range(n)], timeout=120.0)
        async_rate = n / (time.perf_counter() - t0)
    finally:
        ray_tpu.shutdown()
    assert async_rate > 0.6 * sync_rate, (
        f"pipelined inversion returned: async {async_rate:.0f}/s vs "
        f"sync {sync_rate:.0f}/s")


def test_actor_churn_floor():
    """Regression guard for 4-actors/s churn: with the fork server
    (fork_server.py) create+call+kill waves must sustain >= 10/s even
    on a loaded 1-core CI host (measured ~36/s idle)."""
    import time

    import ray_tpu

    ray_tpu.init(num_cpus=8)
    try:
        @ray_tpu.remote
        class Cell:
            def __init__(self, v):
                self.v = v

            def get(self):
                return self.v

        a = Cell.remote(0)
        ray_tpu.get(a.get.remote())
        ray_tpu.kill(a)  # warm (fork server boots on first spawn)

        n, wave, done = 24, 8, 0
        t0 = time.perf_counter()
        while done < n:
            k = min(wave, n - done)
            actors = [Cell.remote(i) for i in range(k)]
            got = ray_tpu.get([x.get.remote() for x in actors],
                              timeout=120.0)
            assert got == list(range(k))
            for x in actors:
                ray_tpu.kill(x)
            done += k
        rate = n / (time.perf_counter() - t0)
    finally:
        ray_tpu.shutdown()
    assert rate >= 10.0, f"actor churn regressed to {rate:.1f}/s"
