"""Dashboard: HTTP state endpoints + SPA serving (reference dashboard/
head, dashboard/dashboard.py; the React SPA's role is played by one
self-contained index.html)."""
from __future__ import annotations

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.dashboard import DashboardServer


@pytest.fixture
def dashboard():
    ray_tpu.init(num_cpus=2)
    w = ray_tpu._private.worker.global_worker
    srv = DashboardServer(w.conductor_address, port=0).start()
    yield srv
    srv.stop()
    ray_tpu.shutdown()


def _get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        body = r.read()
        return r.status, r.headers.get_content_type(), body


def test_spa_and_summary(dashboard):
    status, ctype, body = _get(dashboard.url + "/")
    assert status == 200 and ctype == "text/html"
    assert b"ray_tpu" in body and b"/api/summary" in body

    status, ctype, body = _get(dashboard.url + "/api/summary")
    assert status == 200 and ctype == "application/json"
    s = json.loads(body)
    assert s["resources_total"]["CPU"] == 2.0
    assert len(s["nodes"]) == 1 and s["nodes"][0]["alive"]


def test_entity_endpoints_reflect_cluster(dashboard):
    @ray_tpu.remote
    def work(x):
        return x * 2

    assert ray_tpu.get([work.remote(i) for i in range(4)],
                       timeout=60.0) == [0, 2, 4, 6]

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.options(name="dash-actor").remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60.0) == "pong"

    _, _, body = _get(dashboard.url + "/api/actors")
    actors = json.loads(body)
    assert any(r["name"] == "dash-actor" and r["state"] == "ALIVE"
               for r in actors)

    _, _, body = _get(dashboard.url + "/api/workers")
    assert len(json.loads(body)) >= 1

    # task events reach the conductor in periodic batches — poll
    deadline = time.monotonic() + 15.0
    tasks = []
    while time.monotonic() < deadline:
        _, _, body = _get(dashboard.url + "/api/tasks")
        tasks = json.loads(body)
        if any(t["name"] == "work" and t["count"] == 4 for t in tasks):
            break
        time.sleep(0.3)
    assert any(t["name"] == "work" and t["count"] == 4 for t in tasks), tasks

    _, _, body = _get(dashboard.url + "/api/objects")
    assert isinstance(json.loads(body), list)

    _, _, body = _get(dashboard.url + "/api/timeline")
    trace = json.loads(body)
    assert any(ev["name"] == "work" for ev in trace)

    status, ctype, _ = _get(dashboard.url + "/api/metrics")
    assert status == 200 and ctype == "text/plain"


def test_logs_endpoint_carries_worker_prints(dashboard):
    @ray_tpu.remote
    def chatty():
        print("DASHBOARD_LOG_MARKER")
        return 1

    assert ray_tpu.get(chatty.remote()) == 1
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        status, _, body = _get(dashboard.url + "/api/logs")
        assert status == 200
        entries = json.loads(body)
        if any("DASHBOARD_LOG_MARKER" in e.get("line", "")
               for e in entries):
            assert all({"worker", "line", "ts"} <= set(e) for e in entries)
            return
        time.sleep(0.3)
    raise AssertionError("worker print never reached /api/logs")


def test_actor_drilldown_and_serve_view(dashboard):
    """/api/actors/{id} aggregates record+worker+events; /api/serve
    mirrors the Serve controller's KV-published status."""
    @ray_tpu.remote
    class Counter:
        def bump(self):
            return 1

    a = Counter.remote()
    ray_tpu.get(a.bump.remote())
    w = ray_tpu._private.worker.global_worker
    w._flush_task_events()
    actor_id = w.conductor.call("list_actors", timeout=5.0)[0]["actor_id"]

    status, _, body = _get(dashboard.url + f"/api/actors/{actor_id}")
    assert status == 200
    d = json.loads(body)
    assert d["actor"]["actor_id"] == actor_id
    assert d["worker"] is not None
    assert any(ev["name"].endswith(".bump")
               for ev in d["recent_tasks"]), d["recent_tasks"]

    status, _, body = _get(dashboard.url + "/api/actors/nope")
    assert json.loads(body)["error"]

    # serve view: empty before serve starts
    status, _, body = _get(dashboard.url + "/api/serve")
    assert status == 200 and json.loads(body)["applications"] == {}

    from ray_tpu import serve

    serve.start()
    try:
        @serve.deployment
        def hello(request):
            return "hi"

        serve.run(hello.bind(), name="dash_app", route_prefix="/h")
        deadline = time.monotonic() + 30.0
        apps = {}
        while time.monotonic() < deadline:
            apps = json.loads(_get(dashboard.url + "/api/serve")[2]).get(
                "applications", {})
            if "dash_app" in apps and \
                    apps["dash_app"]["status"] == "RUNNING":
                break
            time.sleep(0.5)
        assert "dash_app" in apps, apps
        assert "hello" in apps["dash_app"]["deployments"]
    finally:
        serve.shutdown()
    # shutdown clears the KV mirror: no ghost RUNNING apps
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        apps = json.loads(_get(dashboard.url + "/api/serve")[2]).get(
            "applications", {})
        if not apps:
            break
        time.sleep(0.5)
    assert apps == {}, apps


def test_rpc_and_autoscaler_views(dashboard):
    """/api/rpc serves per-method dispatch stats; /api/autoscaler serves
    the KV status mirror + live pending demand (empty-but-valid when no
    autoscaler runs)."""
    import json as _json

    status, _, body = _get(dashboard.url + "/api/rpc")
    assert status == 200
    stats = _json.loads(body)
    assert isinstance(stats, dict) and stats  # conductor has seen traffic
    method = next(iter(stats.values()))
    assert {"count", "mean_queue_ms", "mean_handler_ms"} <= set(method)

    status, _, body = _get(dashboard.url + "/api/autoscaler")
    assert status == 200
    a = _json.loads(body)
    assert "live_demand" in a and isinstance(a["live_demand"], list)

    # the SPA carries the new tabs
    status, _, html = _get(dashboard.url + "/")
    assert b"renderRpc" in html and b"renderAutoscaler" in html
