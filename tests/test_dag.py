"""DAG + compiled DAG tests — modeled on the reference's
python/ray/dag/tests/ (test_function_dag.py, test_accelerated_dag.py)."""
from __future__ import annotations

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import (ChannelClosedError, Channel, InputNode,
                         MultiOutputNode)


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Worker:
    def __init__(self, bias=0):
        self.bias = bias
        self.calls = 0

    def inc(self, x):
        self.calls += 1
        return x + 1 + self.bias

    def double(self, x):
        self.calls += 1
        return x * 2

    def add(self, a, b):
        self.calls += 1
        return a + b

    def get_calls(self):
        return self.calls

    def fail(self, x):
        raise ValueError(f"boom on {x}")


# -- channel unit tests ------------------------------------------------------

def test_channel_roundtrip():
    ch = Channel(1024)
    ch.write(b"hello")
    seq, data = ch.read(0)
    assert (seq, data) == (1, b"hello")
    ch.write(b"world")
    seq, data = ch.read(1)
    assert (seq, data) == (2, b"world")
    ch.destroy()


def test_channel_backpressure():
    ch = Channel(1024)
    ch.write(b"a")
    with pytest.raises(TimeoutError):
        ch.write(b"b", timeout=0.2)  # unread slot blocks the writer
    ch.read(0)
    ch.write(b"b", timeout=0.2)
    ch.destroy()


def test_channel_close_unblocks():
    ch = Channel(1024)
    with pytest.raises(ChannelClosedError):
        ch.close()
        ch.read(0, timeout=1.0)
    ch.destroy()


def test_channel_capacity_error():
    ch = Channel(16)
    with pytest.raises(ValueError):
        ch.write(b"x" * 64)
    ch.destroy()


# -- uncompiled DAG ----------------------------------------------------------

def test_dag_execute_chain(cluster):
    a = Worker.remote()
    b = Worker.remote()
    with InputNode() as inp:
        d = b.double.bind(a.inc.bind(inp))
    assert ray_tpu.get(d.execute(3)) == 8
    assert ray_tpu.get(d.execute(10)) == 22


def test_dag_execute_fanout_multi_output(cluster):
    a = Worker.remote()
    b = Worker.remote(bias=100)
    with InputNode() as inp:
        d = MultiOutputNode([a.inc.bind(inp), b.inc.bind(inp)])
    r1, r2 = d.execute(1)
    assert ray_tpu.get(r1) == 2
    assert ray_tpu.get(r2) == 102


def test_dag_function_nodes(cluster):
    @ray_tpu.remote
    def square(x):
        return x * x

    @ray_tpu.remote
    def plus(a, b):
        return a + b

    with InputNode() as inp:
        d = plus.bind(square.bind(inp), inp)
    assert ray_tpu.get(d.execute(4)) == 20


def test_dag_input_attribute(cluster):
    a = Worker.remote()
    with InputNode() as inp:
        d = a.add.bind(inp["x"], inp["y"])
    assert ray_tpu.get(d.execute({"x": 2, "y": 5})) == 7


# -- compiled DAG ------------------------------------------------------------

def test_compiled_chain(cluster):
    a = Worker.remote()
    b = Worker.remote()
    with InputNode() as inp:
        d = b.double.bind(a.inc.bind(inp))
    cd = d.experimental_compile()
    try:
        for i in range(10):
            assert cd.execute(i).get() == (i + 1) * 2
    finally:
        cd.teardown()


def test_compiled_same_actor_chain(cluster):
    a = Worker.remote()
    with InputNode() as inp:
        d = a.double.bind(a.inc.bind(inp))
    cd = d.experimental_compile()
    try:
        assert cd.execute(5).get() == 12
    finally:
        cd.teardown()


def test_compiled_fanout_fanin(cluster):
    a, b, c = Worker.remote(), Worker.remote(bias=10), Worker.remote()
    with InputNode() as inp:
        d = c.add.bind(a.inc.bind(inp), b.inc.bind(inp))
    cd = d.experimental_compile()
    try:
        # (x+1) + (x+11)
        assert cd.execute(0).get() == 12
        assert cd.execute(5).get() == 22
    finally:
        cd.teardown()


def test_compiled_multi_output(cluster):
    a, b = Worker.remote(), Worker.remote(bias=5)
    with InputNode() as inp:
        d = MultiOutputNode([a.inc.bind(inp), b.inc.bind(inp)])
    cd = d.experimental_compile()
    try:
        assert cd.execute(1).get() == [2, 7]
    finally:
        cd.teardown()


def test_compiled_numpy_payload(cluster):
    a = Worker.remote()
    with InputNode() as inp:
        d = a.double.bind(inp)
    cd = d.experimental_compile(buffer_size_bytes=8 * 1024 * 1024)
    try:
        x = np.arange(100_000, dtype=np.float32)
        np.testing.assert_allclose(cd.execute(x).get(), x * 2)
    finally:
        cd.teardown()


def test_compiled_actor_revisit(cluster):
    """A->B->A shape (the pipeline fwd/bwd pattern): actor A's loop must not
    block on the B->A edge before producing what B is waiting for."""
    a, b = Worker.remote(), Worker.remote()
    with InputNode() as inp:
        d = a.double.bind(b.inc.bind(a.inc.bind(inp)))
    cd = d.experimental_compile()
    try:
        # ((x+1)+1)*2
        assert cd.execute(3).get(timeout=10.0) == 10
        assert cd.execute(0).get(timeout=10.0) == 4
    finally:
        cd.teardown()


def test_compiled_duplicate_arg(cluster):
    """The same upstream node consumed twice by one op must not double-write
    its edge channel."""
    a, b = Worker.remote(), Worker.remote()
    with InputNode() as inp:
        up = a.inc.bind(inp)
        d = b.add.bind(up, up)
    cd = d.experimental_compile()
    try:
        assert cd.execute(1).get(timeout=10.0) == 4
        assert cd.execute(2).get(timeout=10.0) == 6
        assert cd.execute(3).get(timeout=10.0) == 8
    finally:
        cd.teardown()


def test_dag_kwargs_input(cluster):
    a = Worker.remote()
    with InputNode() as inp:
        d = a.add.bind(inp.x, inp.y)
    assert ray_tpu.get(d.execute(x=3, y=4)) == 7
    cd = d.experimental_compile()
    try:
        assert cd.execute(x=1, y=2).get(timeout=10.0) == 3
        with pytest.raises(TypeError, match="all-positional or all-keyword"):
            cd.execute(1, y=2)
    finally:
        cd.teardown()


def test_compiled_error_propagation(cluster):
    a, b = Worker.remote(), Worker.remote()
    with InputNode() as inp:
        d = b.double.bind(a.fail.bind(inp))
    cd = d.experimental_compile()
    try:
        with pytest.raises(RuntimeError, match="boom"):
            cd.execute(1).get()
        # The DAG survives a failed invocation.
        with pytest.raises(RuntimeError, match="boom"):
            cd.execute(2).get()
    finally:
        cd.teardown()


def test_compiled_actor_usable_after_teardown(cluster):
    a = Worker.remote()
    with InputNode() as inp:
        d = a.inc.bind(inp)
    cd = d.experimental_compile()
    assert cd.execute(1).get() == 2
    cd.teardown()
    # After teardown the pinned loop exits and normal calls flow again.
    assert ray_tpu.get(a.get_calls.remote()) >= 1


def test_compiled_throughput_beats_task_path(cluster):
    """The compiled path must be much faster than per-call actor RPC —
    the reference's whole reason for compiled graphs."""
    a = Worker.remote()
    with InputNode() as inp:
        d = a.inc.bind(inp)

    n = 200
    t0 = time.perf_counter()
    for i in range(n):
        ray_tpu.get(a.inc.remote(i))
    rpc_s = time.perf_counter() - t0

    cd = d.experimental_compile()
    try:
        cd.execute(0).get()  # warm
        t0 = time.perf_counter()
        for i in range(n):
            cd.execute(i).get()
        compiled_s = time.perf_counter() - t0
    finally:
        cd.teardown()
    assert compiled_s < rpc_s, (compiled_s, rpc_s)
