"""Step-time oracle (ISSUE-10 acceptance surface): the roofline model's
constants table pinned to the peak-FLOPs table, predicted step-time
breakdowns for every dryrun layout, the seeded calibration fit, the
unmodeled-collective blind-spot finding, bench regression attribution,
and the one-set-of-numbers consistency check across state API / CLI /
dashboard / Prometheus / merged-timeline counter track — with a real
predicted-vs-measured residual recorded for a real (virtual-cluster)
training run.

The `oracle` marker tags the scenarios; everything here is tier-1-safe
on CPU — cluster tests run on a module-scoped cluster with
log_to_driver=0 per the established fixture pattern. On CPU the
validation exercises plumbing and calibration math, not the absolute
TPU constants (the module's documented caveat)."""
from __future__ import annotations

import importlib.util
import json
import os
import time

import pytest

import ray_tpu
from ray_tpu.observability import flops, roofline
from ray_tpu.observability.gang import summarize_run
from ray_tpu.observability.step_timer import summarize_records

pytestmark = pytest.mark.oracle

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------- constants (property)

def test_link_constants_pin_to_peak_flops_table():
    """Every generation with a peak-FLOPs entry has ICI/DCN constants,
    and within each generation the link classes are ordered: ICI
    bandwidth above DCN bandwidth, ICI hop latency below DCN latency."""
    for gen in flops.PEAK_FLOPS_BF16:
        assert gen in roofline.LINK_CONSTANTS, \
            f"{gen} has peak FLOPs but no link constants"
        lc = roofline.LINK_CONSTANTS[gen]
        assert lc.ici_bw > lc.dcn_bw > 0, gen
        assert 0 < lc.ici_latency_s < lc.dcn_latency_s, gen
    for platform in flops.NOMINAL_PEAK_FLOPS:
        assert platform in roofline.NOMINAL_LINK_CONSTANTS, platform
        lc = roofline.NOMINAL_LINK_CONSTANTS[platform]
        assert lc.ici_bw > lc.dcn_bw > 0


def test_device_link_constants_prefix_match():
    class Fake:
        device_kind = "TPU v5 lite"
        platform = "tpu"

    assert roofline.device_link_constants(Fake()) == \
        roofline.LINK_CONSTANTS["TPU v5 lite"]
    Fake.device_kind = "TPU v9x"  # unknown TPU: conservative v4-class
    assert roofline.device_link_constants(Fake()) == \
        roofline.LINK_CONSTANTS["TPU v4"]
    Fake.device_kind, Fake.platform = "cpu", "cpu"
    assert roofline.device_link_constants(Fake()) == \
        roofline.NOMINAL_LINK_CONSTANTS["cpu"]


# ------------------------------------------------------------ prediction

def test_predict_builtin_layouts_all_five():
    preds = roofline.predict_builtin_layouts(8)
    assert set(preds) == {"dcn_dp_tp", "dcn_pp_fsdp", "dp_pp", "dp_sp",
                          "dp_ep"}
    for name, p in preds.items():
        assert p["predicted_step_ms"] > 0, name
        assert p["predicted_step_ms"] == pytest.approx(
            p["device_step_ms"] + p["ici_wait_ms"] + p["dcn_wait_ms"])
        for key in ("device_step_ms", "ici_wait_ms", "dcn_wait_ms"):
            assert p[key] >= 0, (name, key)
    # layouts that declare DCN parallelism pay a DCN share; flat
    # single-slice layouts cannot
    for name in ("dcn_dp_tp", "dcn_pp_fsdp"):
        assert preds[name]["dcn_wait_ms"] > 0, name
        assert preds[name]["dcn_bytes"] > 0, name
    for name in ("dp_pp", "dp_sp", "dp_ep"):
        assert preds[name]["dcn_wait_ms"] == 0.0, name
        assert preds[name]["dcn_bytes"] == 0.0, name


def test_prediction_scales_with_bytes_and_calibration():
    from ray_tpu.analysis.collectives import CollectiveUse
    from ray_tpu.analysis.shardcheck import MeshLayout

    layout = MeshLayout({"dp": 8}, {"dp": 2}, name="t")
    links = roofline.LINK_CONSTANTS["TPU v4"]

    def pred(nbytes, cal=1.0):
        return roofline.predict_step_time(
            layout, [CollectiveUse("psum", ("dp",), nbytes)],
            1e12, 8 * 275e12, links=links, calibration=cal)

    small, big = pred(2 ** 20), pred(2 ** 26)
    assert big["dcn_wait_ms"] > small["dcn_wait_ms"]
    assert big["ici_wait_ms"] > small["ici_wait_ms"]
    assert small["device_step_ms"] == pytest.approx(
        big["device_step_ms"])  # compute term independent of comms
    doubled = pred(2 ** 20, cal=2.0)
    assert doubled["predicted_step_ms"] == pytest.approx(
        2 * small["predicted_step_ms"])
    assert doubled["calibration"] == 2.0


def test_unmodeled_collective_is_named_not_absorbed():
    """Satellite: an unmodeled primitive's byte estimate falls back to
    its raw input size AND announces itself — an INFO finding from
    check_collectives and an `unmodeled_collectives` key on the
    prediction."""
    from ray_tpu.analysis.collectives import (CollectiveUse,
                                              check_collectives)
    from ray_tpu.analysis.shardcheck import MeshLayout

    layout = MeshLayout({"dp": 4}, {"dp": 2}, name="t",
                        declared_dcn=True)
    use = CollectiveUse("pgather", ("dp",), 4096)
    assert not use.modeled()
    assert use.dcn_bytes(layout) == 4096.0  # raw-size fallback
    findings = check_collectives(layout, [use])
    unmodeled = [f for f in findings if f.rule == "unmodeled-collective"]
    assert len(unmodeled) == 1
    assert unmodeled[0].severity == "info"
    assert "pgather" in unmodeled[0].message
    pred = roofline.predict_step_time(
        layout, [use], 0.0, 1e12,
        links=roofline.LINK_CONSTANTS["TPU v4"])
    assert pred["unmodeled_collectives"] == ["pgather"]
    # a modeled psum produces no such finding
    clean = check_collectives(layout,
                              [CollectiveUse("psum", ("dp",), 4096)])
    assert not [f for f in clean if f.rule == "unmodeled-collective"]


def test_checkrep_psum_trace_stays_modeled():
    """jax 0.4.x traces psum as `psum2` and inserts zero-payload
    `pbroadcast` markers under check_rep: the former must be priced
    like psum, the latter never collected — a plain psum trace must not
    flag the model's own core primitive as unmodeled."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ray_tpu.analysis.collectives import (abstract_mesh,
                                              check_collectives,
                                              scan_collectives)
    from ray_tpu.analysis.shardcheck import MeshLayout

    layout = MeshLayout({"dp": 8}, {"dp": 2}, name="t",
                        declared_dcn=True)
    mesh = abstract_mesh(layout)
    if mesh is None:
        pytest.skip("this jax has no AbstractMesh")
    fn = shard_map(lambda x: x * jax.lax.psum(x, "dp"), mesh=mesh,
                   in_specs=P("dp"), out_specs=P("dp"))
    uses = scan_collectives(fn, jax.ShapeDtypeStruct((64,), "float32"))
    assert uses and all(u.modeled() for u in uses)
    assert not any(u.primitive in ("pbroadcast", "pvary") for u in uses)
    findings = check_collectives(layout, uses)
    assert not [f for f in findings
                if f.rule == "unmodeled-collective"]
    # psum2 is priced exactly like psum (ring allreduce)
    psum_like = next(u for u in uses if u.primitive.startswith("psum"))
    assert psum_like.dcn_bytes(layout) == pytest.approx(
        2.0 * psum_like.in_bytes * (2 - 1) / 2)


def test_validate_rejects_empty_records():
    pred = {"layout": "t", "predicted_step_ms": 1.0,
            "device_step_ms": 1.0, "ici_wait_ms": 0.0,
            "dcn_wait_ms": 0.0}
    with pytest.raises(ValueError, match="no flight-recorder"):
        roofline.validate_run(pred, run_id="r", records=[])
    # records without any modeled phase must not land as a vacuous
    # calibration=1.0 "perfect fit"
    with pytest.raises(ValueError, match="no comparable phase"):
        roofline.validate_run(pred, run_id="r",
                              records=[{"step": 0, "data_wait_ms": 5.0}])


def test_validate_run_uses_lead_rank_only():
    """A multi-rank run's flattened records (one per rank per step) must
    not inflate n_steps or let a straggler rank skew the fit — the lead
    rank is the measurement, matching gang.summarize_run."""
    pred = {"layout": "t", "predicted_step_ms": 10.0,
            "device_step_ms": 10.0, "ici_wait_ms": 0.0,
            "dcn_wait_ms": 0.0}
    records = []
    for s in range(6):
        records.append({"step": s, "rank": 0, "device_step_ms": 10.0,
                        "total_ms": 11.0})
        records.append({"step": s, "rank": 1, "device_step_ms": 90.0,
                        "total_ms": 91.0})  # straggler
    val = roofline.validate_run(pred, run_id="multi", records=records)
    assert val["n_steps"] == 6
    assert val["calibration"] == pytest.approx(1.0)
    assert val["residuals"]["device_step"] == pytest.approx(1.0)


def test_pmap_wrapper_is_not_a_collective():
    """Call-like primitives wrapping a sub-jaxpr (xla_pmap carries the
    axis_name string) are priced through their BODY by the recursion —
    the wrapper itself must not appear as an unmodeled collective nor
    double-charge the whole input as comms bytes."""
    import jax

    from ray_tpu.analysis.collectives import scan_collectives

    fn = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")
    n = jax.local_device_count()
    uses = scan_collectives(
        fn, jax.ShapeDtypeStruct((n, 4), "float32"))
    assert uses, "the body psum must be collected"
    assert all(u.primitive not in ("xla_pmap", "pmap") for u in uses)
    assert all(u.modeled() for u in uses)


def test_cli_analyze_predict_step_time(tmp_path, capsys):
    """`ray_tpu analyze --predict-step-time` emits the predicted
    breakdown for all five dryrun layouts next to the findings — and
    plain --json keeps the historical bare findings list."""
    from ray_tpu.scripts.cli import main

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    main(["analyze", "--predict-step-time", "--json", str(clean)])
    payload = json.loads(capsys.readouterr().out)
    assert set(payload) == {"findings", "predicted_step_time"}
    preds = payload["predicted_step_time"]
    assert set(preds) == {"dcn_dp_tp", "dcn_pp_fsdp", "dp_pp", "dp_sp",
                          "dp_ep"}
    for p in preds.values():
        assert p["predicted_step_ms"] > 0
    main(["analyze", "--predict-step-time", str(clean)])
    text = capsys.readouterr().out
    assert "predicted step time per layout" in text
    assert "dcn_dp_tp" in text and "dcn " in text
    main(["analyze", "--json", str(clean)])  # no flag: bare list
    assert isinstance(json.loads(capsys.readouterr().out), list)


# ------------------------------------------------ calibration (seeded)

def test_calibration_fit_recovers_seeded_scale():
    """Seeded predicted-vs-measured residual test: measured steps are a
    noisy 1.7x of the prediction; the least-squares fit recovers the
    factor and the per-phase residual agrees."""
    import numpy as np

    rng = np.random.default_rng(7)
    predicted_ms = 12.5
    alpha = 1.7
    measured = alpha * predicted_ms * (1.0 + 0.05 * rng.standard_normal(64))
    pairs = [(predicted_ms, float(m)) for m in measured]
    fit = roofline.calibration_fit(pairs)
    assert fit == pytest.approx(alpha, rel=0.05)
    assert roofline.calibration_fit([]) == 1.0

    prediction = {"layout": "seeded", "device_step_ms": predicted_ms,
                  "ici_wait_ms": 0.0, "dcn_wait_ms": 0.0,
                  "predicted_step_ms": predicted_ms}
    records = [{"step": i, "device_step_ms": float(m),
                "total_ms": float(m) + 1.0}
               for i, m in enumerate(measured)]
    val = roofline.validate_records(prediction, records)
    assert val["n_steps"] == 64
    assert val["calibration"] == pytest.approx(alpha, rel=0.05)
    assert val["residuals"]["device_step"] == pytest.approx(alpha,
                                                            rel=0.1)
    assert val["residuals"]["total"] > val["residuals"]["device_step"]
    assert val["measured"]["summary"]["device_step"]["p99_ms"] >= \
        val["measured"]["summary"]["device_step"]["p50_ms"]


# ------------------------------------------- shared summarize (satellite)

def test_summarize_records_shape():
    records = [{"device_step_ms": float(v), "data_wait_ms": 1.0,
                "total_ms": float(v) + 1.0}
               for v in (10, 20, 30, 40, 100)]
    s = summarize_records(records)
    assert s["steps"] == 5
    dev = s["phases"]["device_step"]
    assert dev["p50_ms"] == 30.0
    assert dev["p99_ms"] == 100.0
    assert dev["mean_ms"] == pytest.approx(40.0)
    assert dev["last_ms"] == 100.0
    # trailing EMA weights the newest step but stays below the outlier
    assert dev["p50_ms"] < dev["ema_ms"] < dev["last_ms"]
    assert s["phases"]["data_wait"]["p99_ms"] == 1.0
    assert summarize_records([]) == {"steps": 0, "phases": {}}


def test_gang_phase_summary_uses_shared_summarize():
    """train_progress's aggregation carries the shared per-phase
    p50/p99/EMA summary instead of ad-hoc re-derivation."""
    steps = {s: {0: {"step": s, "rank": 0, "total_ms": 100.0 + s,
                     "device_step_ms": 90.0 + s, "data_wait_ms": 5.0}}
             for s in range(10)}
    run = summarize_run(steps, k=1.5)
    ps = run["phase_summary"]
    assert ps["device_step"]["p50_ms"] == pytest.approx(95.0, abs=1.0)
    assert ps["data_wait"]["p99_ms"] == 5.0
    expected = summarize_records(
        [steps[s][0] for s in sorted(steps)])["phases"]
    assert ps == expected


# -------------------------------------------- bench attribution (satellite)

def _load_bench_module():
    spec = importlib.util.spec_from_file_location(
        "bench_for_test", os.path.join(REPO_ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_regression_attribution(tmp_path):
    """Satellite: the newest valid prior record is the baseline, the
    phase with the largest positive delta is named, and cpu_fallback /
    failed / breakdown-less records are never attributed against."""
    bench = _load_bench_module()
    metric = "gpt2_125m_train_tokens_per_sec_per_chip"

    def write(name, parsed):
        (tmp_path / name).write_text(json.dumps({"parsed": parsed}))

    write("BENCH_r01.json", {
        "metric": metric, "value": 100000.0,
        "step_breakdown": {"data_wait_ms": 0.0, "compile_ms": 50.0,
                           "device_step_ms": 10.0}})
    # newer rounds that must all be SKIPPED as baselines:
    write("BENCH_r02.json", {"metric": metric, "value": 110000.0})
    write("BENCH_r03.json", {
        "metric": f"{metric}_cpu".replace(metric, "gpt2_tiny_cpu"),
        "value": 6000.0,
        "step_breakdown": {"device_step_ms": 400.0}})
    write("BENCH_r04.json", {
        "metric": metric, "value": 0.0, "error": "tpu path failed",
        "cpu_fallback": {"value": 6500.0}})

    rec = {"metric": metric, "value": 90000.0,
           "step_breakdown": {"data_wait_ms": 0.0, "compile_ms": 48.0,
                              "device_step_ms": 13.0,
                              # summary key, NOT a phase: must never be
                              # attributed (would double-count the
                              # device_step phase as 2-sample noise)
                              "device_step_p99_ms": 99.0}}
    out = bench._attribute_regression(rec, bench_dir=str(tmp_path))
    reg = out["regression"]
    assert reg["phase"] == "device_step"
    assert reg["delta_ms"] == pytest.approx(3.0)
    assert reg["pct"] == pytest.approx(30.0)
    assert reg["vs"] == "BENCH_r01.json"

    # a strictly faster run records regression=None, not a phantom phase
    fast = {"metric": metric, "value": 120000.0,
            "step_breakdown": {"data_wait_ms": 0.0, "compile_ms": 40.0,
                               "device_step_ms": 8.0}}
    assert bench._attribute_regression(
        fast, bench_dir=str(tmp_path))["regression"] is None

    # no valid baseline at all: the record passes through untouched
    lonely = {"metric": "other_metric", "value": 1.0,
              "step_breakdown": {"device_step_ms": 1.0}}
    assert "regression" not in bench._attribute_regression(
        lonely, bench_dir=str(tmp_path))


# --------------------------------------------- cluster (virtual) coverage

@pytest.fixture(scope="module")
def oracle_cluster():
    """ONE cluster for the cluster-backed oracle tests — log_to_driver
    off per the established tier-1 pattern (mirrored worker stderr
    corrupts the tier-1 dot count)."""
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                 _system_config={"log_to_driver": 0})
    yield ray_tpu._private.worker.global_worker
    ray_tpu.shutdown()


def _tiny_train_fn(cfg):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models import (GPT2Config, gpt2_init, gpt2_loss,
                                gpt2_partition_specs)
    from ray_tpu.parallel.mesh import MeshConfig, make_mesh
    from ray_tpu.train import TrainStep, get_step_timer, report

    mcfg = GPT2Config.tiny()
    mesh = make_mesh(MeshConfig(dp=-1))
    step = TrainStep(
        lambda p, b: gpt2_loss(p, b["tokens"], b["targets"], mcfg),
        optax.adamw(1e-3), mesh, gpt2_partition_specs(mcfg))
    state_ = step.init_state(gpt2_init(mcfg, jax.random.PRNGKey(0)))
    rng = np.random.default_rng(0)
    for _ in range(3):
        with get_step_timer().phase("data_wait"):
            raw = rng.integers(0, mcfg.vocab_size, (8, 65),
                               dtype=np.int32)
            batch = {"tokens": jnp.asarray(raw[:, :-1]),
                     "targets": jnp.asarray(raw[:, 1:])}
        state_, m = step(state_, batch)
        report({"loss": float(m["loss"])})


def test_oracle_e2e_one_set_of_numbers(oracle_cluster, tmp_path, capsys):
    """Acceptance: predictions for all five dryrun layouts land on every
    surface with ONE set of numbers (state API == CLI == dashboard ==
    Prometheus == merged-timeline counter track), and a real training
    run gets a recorded predicted-vs-measured residual + fitted
    calibration, persisted to disk."""
    from ray_tpu.dashboard import _ClusterData
    from ray_tpu.scripts import cli
    from ray_tpu.train import JaxTrainer, RunConfig
    from ray_tpu.util import metrics as metrics_mod
    from ray_tpu.util import state

    # 1. predictions for all five layouts, published to the cluster
    preds = roofline.predict_builtin_layouts(8)
    for name, p in preds.items():
        roofline.record_prediction(name, p)

    # 2. a real training run measured by the flight recorder
    result = JaxTrainer(
        _tiny_train_fn,
        run_config=RunConfig(name="oracle-accept",
                             storage_path=str(tmp_path))).fit()
    assert result.error is None
    deadline = time.monotonic() + 10.0
    run_id = None
    while time.monotonic() < deadline and run_id is None:
        for rid, run in state.train_progress().items():
            if rid.startswith("oracle-accept/") and \
                    run["steps_buffered"] >= 3:
                run_id = rid
        if run_id is None:
            time.sleep(0.2)
    assert run_id, "train records never reached the conductor"

    # 3. validate predicted-vs-measured for THAT run (CPU constants:
    # this validates plumbing + the calibration math, not TPU numbers)
    mcfg_pred = dict(preds["dcn_dp_tp"], layout="oracle-accept")
    persist = tmp_path / "oracle_validation.json"
    val = roofline.validate_run(mcfg_pred, run_id=run_id,
                                persist_path=str(persist))
    assert val["n_steps"] >= 3
    assert val["calibration"] > 0
    assert "device_step" in val["residuals"]
    on_disk = json.loads(persist.read_text())
    assert on_disk["calibration"] == pytest.approx(val["calibration"])

    # 4. one set of numbers across every surface
    st = state.oracle_status()
    assert set(st["predictions"]) == set(preds)
    assert st["totals"]["layouts"] == 5
    assert st["totals"]["validations"] >= 1
    assert st["validations"][-1]["calibration"] == pytest.approx(
        val["calibration"])
    for name, p in preds.items():
        assert st["predictions"][name]["predicted_step_ms"] == \
            pytest.approx(p["predicted_step_ms"])

    cli.main(["oracle", "--address", "ignored:0", "--json"])
    cli_payload = json.loads(capsys.readouterr().out)
    assert cli_payload["predictions"].keys() == st["predictions"].keys()
    for name in preds:
        assert cli_payload["predictions"][name]["predicted_step_ms"] == \
            pytest.approx(st["predictions"][name]["predicted_step_ms"])
    cli.main(["oracle", "--address", "ignored:0", "--events", "5"])
    text = capsys.readouterr().out
    assert "dcn_dp_tp" in text and "calibration" in text

    w = oracle_cluster
    dash = _ClusterData(w.conductor_address).oracle()
    assert dash["predictions"].keys() == st["predictions"].keys()
    assert dash["totals"]["validations"] == st["totals"]["validations"]
    assert dash["events"], "dashboard payload missing the event tail"
    json.dumps(dash)  # JSON-safe exactly as json_response applies it

    metrics_mod.flush()
    prom = state.prometheus_metrics()
    assert "ray_tpu_oracle_predicted_step_ms" in prom
    assert 'layout="dcn_dp_tp"' in prom
    assert "ray_tpu_oracle_residual_ratio" in prom
    assert 'phase="device_step"' in prom

    # 5. merged timeline: the predicted-step-time counter track + the
    # validation marker ride beside the run's train-step markers
    trace = state.timeline(str(tmp_path / "merged.json"), merged=True)
    counters = [e for e in trace if e.get("cat") == "oracle"
                and e.get("ph") == "C"]
    assert {e["name"] for e in counters} >= {
        f"predicted_step_ms:{name}" for name in preds}
    assert all(e["pid"] == "oracle" for e in counters)
    markers = [e for e in trace if e.get("cat") == "oracle"
               and e.get("ph") == "i"]
    assert any(e["args"].get("calibration") is not None
               for e in markers)
    assert any(e.get("cat") == "train_step" for e in trace)


def test_validate_run_without_records_raises(oracle_cluster):
    pred = {"layout": "missing", "predicted_step_ms": 1.0,
            "device_step_ms": 1.0, "ici_wait_ms": 0.0,
            "dcn_wait_ms": 0.0}
    with pytest.raises(ValueError, match="no flight-recorder"):
        roofline.validate_run(pred, run_id="no-such-run")
