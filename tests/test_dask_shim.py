"""Dask-on-ray_tpu scheduler (reference python/ray/util/dask/): executes
the dask graph protocol — dict of key -> (callable, *args) task tuples /
key refs / literals, nested arg structures — as cluster tasks. Tested
against hand-built graphs (dask is not baked into TPU images)."""
from __future__ import annotations

from operator import add, mul

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.dask import ray_dask_get


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_simple_chain(cluster):
    dsk = {"x": 1, "a": (add, "x", 2), "b": (mul, "a", 10)}
    assert ray_dask_get(dsk, "b") == 30
    assert ray_dask_get(dsk, ["a", "b"]) == [3, 30]
    assert ray_dask_get(dsk, [["a"], ["b", "x"]]) == [[3], [30, 1]]


def test_alias_and_literals(cluster):
    dsk = {"lit": [1, 2, 3], "alias": "lit",
           "sum": (sum, "alias")}
    assert ray_dask_get(dsk, "sum") == 6
    assert ray_dask_get(dsk, "alias") == [1, 2, 3]


def test_nested_args_and_tuple_keys(cluster):
    def total(parts):
        return sum(parts)

    dsk = {
        ("chunk", 0): 10,
        ("chunk", 1): (add, ("chunk", 0), 5),
        ("chunk", 2): (add, ("chunk", 1), 5),
        "tot": (total, [("chunk", 0), ("chunk", 1), ("chunk", 2)]),
    }
    assert ray_dask_get(dsk, "tot") == 45


def test_inline_subtasks(cluster):
    # fused graphs nest task tuples inside args
    dsk = {"x": 4, "y": (add, (mul, "x", 2), (mul, "x", 3))}
    assert ray_dask_get(dsk, "y") == 20


def test_wide_fanout_numpy(cluster):
    def part(i):
        return np.full(10, i)

    def combine(parts):
        return float(np.concatenate(parts).sum())

    dsk = {f"p{i}": (part, i) for i in range(16)}
    dsk["out"] = (combine, [f"p{i}" for i in range(16)])
    assert ray_dask_get(dsk, "out") == float(sum(range(16)) * 10)


def test_deep_graph_no_recursion_limit(cluster):
    """Scheduling is iterative: a graph deeper than the python recursion
    limit must not blow the stack. Alias chains exercise the driver-side
    traversal without paying one RPC per link."""
    import sys

    n = sys.getrecursionlimit() + 500
    dsk = {"k0": 123}
    for i in range(1, n):
        dsk[f"k{i}"] = f"k{i-1}"  # alias chain
    dsk["out"] = (add, f"k{n-1}", 1)
    assert ray_dask_get(dsk, "out") == 124


def test_moderately_deep_task_chain(cluster):
    dsk = {"k0": 0}
    for i in range(1, 60):
        dsk[f"k{i}"] = (add, f"k{i-1}", 1)
    assert ray_dask_get(dsk, "k59") == 59
