"""Workflow tests — modeled on the reference's
python/ray/workflow/tests/ (test_basic_workflows.py, test_recovery.py)."""
from __future__ import annotations

import os

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode, MultiOutputNode


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    os.environ["RAY_TPU_WORKFLOW_STORAGE"] = str(
        tmp_path_factory.mktemp("wf_storage"))
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_WORKFLOW_STORAGE", None)


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def double(x):
    return 2 * x


def test_basic_run(cluster):
    with InputNode() as inp:
        dag = add.bind(double.bind(inp), 1)
    assert workflow.run(dag, 5, workflow_id="wf_basic") == 11
    assert workflow.get_status("wf_basic") == "SUCCESSFUL"
    assert workflow.get_output("wf_basic") == 11


def test_rerun_returns_cached(cluster):
    calls_file = None  # results come from storage, steps don't re-run
    with InputNode() as inp:
        dag = double.bind(inp)
    assert workflow.run(dag, 4, workflow_id="wf_cache") == 8
    # second run with SAME id returns stored output without re-executing
    assert workflow.run(dag, 999, workflow_id="wf_cache") == 8
    assert calls_file is None


def test_multi_output(cluster):
    with InputNode() as inp:
        dag = MultiOutputNode([double.bind(inp), add.bind(inp, 10)])
    assert workflow.run(dag, 3, workflow_id="wf_multi") == [6, 13]


def test_failure_and_resume(cluster, tmp_path):
    marker = tmp_path / "fail_once"
    marker.write_text("1")

    @ray_tpu.remote
    def flaky(x, marker_path):
        if os.path.exists(marker_path):
            raise RuntimeError("transient failure")
        return x + 100

    with InputNode() as inp:
        dag = add.bind(flaky.bind(double.bind(inp), str(marker)), 1)

    with pytest.raises(Exception):
        workflow.run(dag, 2, workflow_id="wf_resume")
    assert workflow.get_status("wf_resume") == "FAILED"
    assert "transient failure" in (workflow.get_error("wf_resume") or "")

    marker.unlink()  # heal the fault, then resume: only flaky+add re-run
    assert workflow.resume("wf_resume") == 105  # (2*2)+100+1
    assert workflow.get_status("wf_resume") == "SUCCESSFUL"


def test_resume_skips_completed_steps(cluster, tmp_path):
    counter = tmp_path / "count"
    counter.write_text("0")

    @ray_tpu.remote
    def counted(x, path):
        n = int(open(path).read()) + 1
        open(path, "w").write(str(n))
        return x + n

    @ray_tpu.remote
    def boom(x):
        raise ValueError("always fails")

    with InputNode() as inp:
        dag = boom.bind(counted.bind(inp, str(counter)))
    with pytest.raises(Exception):
        workflow.run(dag, 0, workflow_id="wf_skip")
    assert counter.read_text() == "1"
    with pytest.raises(Exception):
        workflow.resume("wf_skip")
    # `counted` was checkpointed, so resume must NOT re-run it
    assert counter.read_text() == "1"


def test_list_and_delete(cluster):
    with InputNode() as inp:
        dag = double.bind(inp)
    workflow.run(dag, 1, workflow_id="wf_list_a")
    workflow.run(dag, 2, workflow_id="wf_list_b")
    ids = {m["workflow_id"] for m in workflow.list_all()}
    assert {"wf_list_a", "wf_list_b"} <= ids
    ok = {m["workflow_id"]
          for m in workflow.list_all(status_filter="SUCCESSFUL")}
    assert "wf_list_a" in ok
    assert workflow.delete("wf_list_a")
    assert "wf_list_a" not in {m["workflow_id"]
                               for m in workflow.list_all()}


def test_actor_method_steps(cluster):
    @ray_tpu.remote
    class Accum:
        def __init__(self):
            self.total = 0

        def add(self, x):
            self.total += x
            return self.total

    a = Accum.remote()
    with InputNode() as inp:
        dag = double.bind(a.add.bind(inp))
    assert workflow.run(dag, 5, workflow_id="wf_actor") == 10


def test_run_async(cluster):
    with InputNode() as inp:
        dag = add.bind(double.bind(inp), 7)
    fut = workflow.run_async(dag, 10, workflow_id="wf_async")
    assert fut.result(timeout=60) == 27


def test_kwargs_input(cluster):
    with InputNode() as inp:
        dag = add.bind(inp.x, inp.y)
    assert workflow.run(dag, x=2, y=3, workflow_id="wf_kw") == 5


def test_timer_event(cluster):
    """wait_for_event(TimerListener, t): the workflow blocks until the
    timestamp then proceeds (reference event_listener.py TimerListener)."""
    import time

    fire_at = time.time() + 1.0
    ev = workflow.wait_for_event(workflow.TimerListener, fire_at)
    out = workflow.run(double.bind(ev), workflow_id="wf_timer")
    assert out == fire_at * 2
    assert time.time() >= fire_at


def test_http_event_provider_end_to_end(cluster):
    """External POST -> HTTPEventProvider -> KV -> HTTPListener inside a
    durable step; the provider's copy is dropped once checkpointed
    (reference workflow/http_event_provider.py)."""
    import json
    import time
    import urllib.request

    from ray_tpu import serve

    serve.start()
    try:
        serve.run(workflow.http_event_provider().bind(),
                  name="event_provider", route_prefix="/event")
        ev = workflow.wait_for_event(workflow.HTTPListener,
                                     event_key="approval")
        fut = workflow.run_async(double.bind(ev),
                                 workflow_id="wf_http_event")
        time.sleep(1.0)  # listener is polling; no event yet
        assert not fut.done()

        host, port = serve.proxy_address()
        req = urllib.request.Request(
            f"http://{host}:{port}/event/send_event",
            data=json.dumps({"event_key": "approval",
                             "event_payload": 21}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert json.loads(r.read())["status"] == "ok"

        out = fut.result(timeout=30)
        # the event resolves to (key, payload); double(tuple) concatenates
        assert out == ("approval", 21, "approval", 21), out
        # checkpointed -> the provider's stored copy is gone
        deadline = time.time() + 10
        while time.time() < deadline and \
                workflow.get_event("approval") is not None:
            time.sleep(0.2)
        assert workflow.get_event("approval") is None
    finally:
        serve.shutdown()


def test_cancel_interrupts_event_wait(cluster):
    """cancel() must interrupt a workflow parked on an event that never
    arrives AND cooperatively stop the polling step so it frees its
    worker (events.py + bounded executor waits)."""
    import time

    ev = workflow.wait_for_event(workflow.HTTPListener,
                                 event_key="never_comes")
    fut = workflow.run_async(double.bind(ev), workflow_id="wf_cancelled")
    time.sleep(0.8)
    assert not fut.done()
    t0 = time.monotonic()
    workflow.cancel("wf_cancelled")
    with pytest.raises(Exception):
        fut.result(timeout=30)
    assert time.monotonic() - t0 < 10.0
    assert workflow.get_status("wf_cancelled") == \
        workflow.WorkflowStatus.CANCELED
    # the poller was cancelled, not orphaned: the cluster still has
    # capacity for fresh work
    assert ray_tpu.get(add.remote(1, 2), timeout=30.0) == 3
