"""Workflow tests — modeled on the reference's
python/ray/workflow/tests/ (test_basic_workflows.py, test_recovery.py)."""
from __future__ import annotations

import os

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode, MultiOutputNode


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    os.environ["RAY_TPU_WORKFLOW_STORAGE"] = str(
        tmp_path_factory.mktemp("wf_storage"))
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_WORKFLOW_STORAGE", None)


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def double(x):
    return 2 * x


def test_basic_run(cluster):
    with InputNode() as inp:
        dag = add.bind(double.bind(inp), 1)
    assert workflow.run(dag, 5, workflow_id="wf_basic") == 11
    assert workflow.get_status("wf_basic") == "SUCCESSFUL"
    assert workflow.get_output("wf_basic") == 11


def test_rerun_returns_cached(cluster):
    calls_file = None  # results come from storage, steps don't re-run
    with InputNode() as inp:
        dag = double.bind(inp)
    assert workflow.run(dag, 4, workflow_id="wf_cache") == 8
    # second run with SAME id returns stored output without re-executing
    assert workflow.run(dag, 999, workflow_id="wf_cache") == 8
    assert calls_file is None


def test_multi_output(cluster):
    with InputNode() as inp:
        dag = MultiOutputNode([double.bind(inp), add.bind(inp, 10)])
    assert workflow.run(dag, 3, workflow_id="wf_multi") == [6, 13]


def test_failure_and_resume(cluster, tmp_path):
    marker = tmp_path / "fail_once"
    marker.write_text("1")

    @ray_tpu.remote
    def flaky(x, marker_path):
        if os.path.exists(marker_path):
            raise RuntimeError("transient failure")
        return x + 100

    with InputNode() as inp:
        dag = add.bind(flaky.bind(double.bind(inp), str(marker)), 1)

    with pytest.raises(Exception):
        workflow.run(dag, 2, workflow_id="wf_resume")
    assert workflow.get_status("wf_resume") == "FAILED"
    assert "transient failure" in (workflow.get_error("wf_resume") or "")

    marker.unlink()  # heal the fault, then resume: only flaky+add re-run
    assert workflow.resume("wf_resume") == 105  # (2*2)+100+1
    assert workflow.get_status("wf_resume") == "SUCCESSFUL"


def test_resume_skips_completed_steps(cluster, tmp_path):
    counter = tmp_path / "count"
    counter.write_text("0")

    @ray_tpu.remote
    def counted(x, path):
        n = int(open(path).read()) + 1
        open(path, "w").write(str(n))
        return x + n

    @ray_tpu.remote
    def boom(x):
        raise ValueError("always fails")

    with InputNode() as inp:
        dag = boom.bind(counted.bind(inp, str(counter)))
    with pytest.raises(Exception):
        workflow.run(dag, 0, workflow_id="wf_skip")
    assert counter.read_text() == "1"
    with pytest.raises(Exception):
        workflow.resume("wf_skip")
    # `counted` was checkpointed, so resume must NOT re-run it
    assert counter.read_text() == "1"


def test_list_and_delete(cluster):
    with InputNode() as inp:
        dag = double.bind(inp)
    workflow.run(dag, 1, workflow_id="wf_list_a")
    workflow.run(dag, 2, workflow_id="wf_list_b")
    ids = {m["workflow_id"] for m in workflow.list_all()}
    assert {"wf_list_a", "wf_list_b"} <= ids
    ok = {m["workflow_id"]
          for m in workflow.list_all(status_filter="SUCCESSFUL")}
    assert "wf_list_a" in ok
    assert workflow.delete("wf_list_a")
    assert "wf_list_a" not in {m["workflow_id"]
                               for m in workflow.list_all()}


def test_actor_method_steps(cluster):
    @ray_tpu.remote
    class Accum:
        def __init__(self):
            self.total = 0

        def add(self, x):
            self.total += x
            return self.total

    a = Accum.remote()
    with InputNode() as inp:
        dag = double.bind(a.add.bind(inp))
    assert workflow.run(dag, 5, workflow_id="wf_actor") == 10


def test_run_async(cluster):
    with InputNode() as inp:
        dag = add.bind(double.bind(inp), 7)
    fut = workflow.run_async(dag, 10, workflow_id="wf_async")
    assert fut.result(timeout=60) == 27


def test_kwargs_input(cluster):
    with InputNode() as inp:
        dag = add.bind(inp.x, inp.y)
    assert workflow.run(dag, x=2, y=3, workflow_id="wf_kw") == 5
