"""Per-request flight recorder (observability/requests.py): a request
id minted at the gateway (or router for direct calls) carries
phase-stamped spans through QoS admission, router queue/reserve,
prefill, KV transfer, decode ticks, and SSE flush, so a completed
request ships its full latency breakdown. The invariants:

- the non-concurrent phases sum to ~the request's wall time (loose
  bounds — tier-1 runs share the machine);
- tail-based retention keeps EVERY anomalous outcome
  (shed/error/deadline/disconnect/preempt/replayed) and the slowest N,
  and probabilistically samples the rest under the
  ``RAY_TPU_REQTRACE_*`` budget;
- failover and preemption replays nest as attempt-tagged child spans
  under ONE request id;
- a scripted ``delay_chunk_fetch`` chaos stretch surfaces as
  ``kv_transfer`` dominating the slowed request's breakdown AND as the
  p99-attribution report's named tail owner;
- every surface reports one set of numbers: state API == CLI ==
  dashboard == Prometheus families == `requests` timeline lane.

The ``requesttrace`` marker tags the scenarios; everything is
tier-1-safe on CPU — cluster tests run on a module-scoped cluster with
log_to_driver=0 per the established fixture pattern."""
from __future__ import annotations

import dataclasses
import http.client
import json
import threading
import time

import jax
import jax.numpy as jnp
import pytest

import ray_tpu
from ray_tpu.models.engine import ContinuousBatchingEngine
from ray_tpu.models.llama import LlamaConfig, llama_init
from ray_tpu.observability import requests as reqtrace
from ray_tpu.serve.disagg import DecodeServer, DisaggRouter, PrefillServer
from ray_tpu.serve.gateway import GatewayServer
from ray_tpu.serve.handle import RequestShedError
from ray_tpu.serve.qos import QosGate

pytestmark = pytest.mark.requesttrace

CFG = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
BS = 4


@pytest.fixture(scope="module")
def model():
    return llama_init(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def reqtrace_cluster():
    ray_tpu.init(num_cpus=4, _system_config={"log_to_driver": 0})
    yield ray_tpu._private.worker.global_worker
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def fresh_store():
    """Each test starts from an empty process-local store (the global
    is rebuilt lazily) and a clean env-knob memo."""
    from ray_tpu.util import envknobs

    reqtrace._reset_store_for_tests()
    envknobs.clear()
    yield
    reqtrace._reset_store_for_tests()
    envknobs.clear()


def _mk_record(rid, total_ms, outcome="ok", replayed=False,
               preempts=0, phase_ms=None):
    """A finished-trace record shaped like RequestTrace.finish()."""
    return {"kind": "trace", "request_id": rid,
            "trace_id": "0" * 32, "source": "test",
            "ts": time.time(), "total_ms": float(total_ms),
            "outcome": outcome, "attempts": 2 if replayed else 1,
            "replayed": replayed, "preempts": preempts,
            "phases": [], "phase_ms": dict(phase_ms or {})}


# -------------------------------------------------------- trace object


def test_phase_sum_approximates_wall_time():
    tr = reqtrace.RequestTrace("r-sum")
    with tr.phase("prefill"):
        time.sleep(0.03)
    with tr.phase("kv_transfer"):
        time.sleep(0.02)
    with tr.phase("decode_steady"):
        time.sleep(0.01)
    tr.add_phase("sse_flush", 500.0)  # concurrent: excluded from sum
    rec = tr.finish("ok")
    seq_ms = sum(p["dur_ms"] for p in rec["phases"]
                 if not p.get("concurrent"))
    assert rec["phase_ms"]["prefill"] >= 25.0
    assert rec["phase_ms"]["kv_transfer"] >= 15.0
    # the non-concurrent phases happened inside the request window
    assert seq_ms <= rec["total_ms"] + 5.0, rec
    # sse_flush overlaps the decode stream; it must NOT break the
    # invariant even though it dwarfs the wall time here
    assert rec["phase_ms"]["sse_flush"] == 500.0
    conc = [p for p in rec["phases"] if p["phase"] == "sse_flush"]
    assert conc and conc[0]["concurrent"] is True


def test_annotate_accumulates_on_open_phase():
    tr = reqtrace.RequestTrace("r-ann")
    with tr.phase("kv_transfer"):
        tr.annotate(pull_ms=10.0, pulls=1)
        tr.annotate(pull_ms=5.5, pulls=1, server="d0")
    rec = tr.finish("ok")
    ph = next(p for p in rec["phases"] if p["phase"] == "kv_transfer")
    assert ph["pull_ms"] == 15.5
    assert ph["pulls"] == 2
    assert ph["server"] == "d0"


def test_finish_is_idempotent_first_wins():
    tr = reqtrace.RequestTrace("r-idem")
    first = tr.finish("disconnect", cause="client_gone")
    second = tr.finish("ok")
    assert second is first
    assert first["outcome"] == "disconnect"


def test_replays_and_preempts_nest_under_one_id():
    store = reqtrace.RequestTraceStore()
    tr = reqtrace.RequestTrace("r-replay", store=store)
    with pytest.raises(ConnectionError):
        with tr.phase("prefill"):
            raise ConnectionError("replica died")
    tr.begin_attempt()                      # failover replay
    with tr.phase("prefill"):
        pass
    with tr.phase("kv_transfer"):
        pass
    tr.mark_preempt()                       # preempted mid-decode
    with tr.phase("decode_steady"):
        pass
    rec = tr.finish("ok")
    assert rec["attempts"] == 3
    assert rec["replayed"] is True
    assert rec["preempts"] == 1
    by_attempt = [p["attempt"] for p in rec["phases"]]
    assert by_attempt == [1, 2, 2, 3]
    assert rec["phases"][0]["error"] == "ConnectionError"
    # replayed == anomalous: retained regardless of speed or sampling
    assert store.trace("r-replay") is not None


# ---------------------------------------------------- tail retention


def test_tail_retention_keeps_anomalies_and_slowest(monkeypatch):
    from ray_tpu.util import envknobs

    monkeypatch.setenv("RAY_TPU_REQTRACE_SAMPLE", "0.0")
    monkeypatch.setenv("RAY_TPU_REQTRACE_SLOWEST", "2")
    monkeypatch.setenv("RAY_TPU_REQTRACE_KEPT", "32")
    envknobs.clear()
    store = reqtrace.RequestTraceStore()
    # two slow requests claim the slowest-N slots
    store.record(_mk_record("slow-1", 900.0))
    store.record(_mk_record("slow-2", 800.0))
    # every anomalous outcome is kept at admission, however fast
    for i, outcome in enumerate(sorted(reqtrace.ANOMALOUS_OUTCOMES)):
        store.record(_mk_record(f"anom-{outcome}", 1.0 + i,
                                outcome=outcome))
    store.record(_mk_record("anom-replayed", 2.0, replayed=True))
    store.record(_mk_record("anom-preempted", 2.0, preempts=1))
    # plain fast ok traffic is sampled at 0.0 -> dropped
    for i in range(20):
        store.record(_mk_record(f"fast-{i}", 10.0 + i))
    assert store.trace("slow-1") is not None
    assert store.trace("slow-2") is not None
    for outcome in reqtrace.ANOMALOUS_OUTCOMES:
        assert store.trace(f"anom-{outcome}") is not None, outcome
    assert store.trace("anom-replayed") is not None
    assert store.trace("anom-preempted") is not None
    assert all(store.trace(f"fast-{i}") is None for i in range(20))
    st = store.stats()
    assert st["dropped"] == 20
    assert st["completed"] == 2 + len(reqtrace.ANOMALOUS_OUTCOMES) \
        + 2 + 20
    assert st["replayed_requests"] == 1
    assert st["preempted_requests"] == 1
    # the slowest list leads with the champions
    tops = [r["request_id"] for r in st["slowest"][:2]]
    assert tops == ["slow-1", "slow-2"]


def test_retention_cap_evicts_fifo_but_protects_slowest(monkeypatch):
    from ray_tpu.util import envknobs

    monkeypatch.setenv("RAY_TPU_REQTRACE_SAMPLE", "0.0")
    monkeypatch.setenv("RAY_TPU_REQTRACE_SLOWEST", "2")
    monkeypatch.setenv("RAY_TPU_REQTRACE_KEPT", "4")
    envknobs.clear()
    store = reqtrace.RequestTraceStore()
    store.record(_mk_record("champ-1", 5000.0))
    store.record(_mk_record("champ-2", 4000.0))
    # a storm of anomalies overflows the cap; the champions survive
    for i in range(10):
        store.record(_mk_record(f"shed-{i}", 1.0, outcome="shed"))
    assert store.trace("champ-1") is not None
    assert store.trace("champ-2") is not None
    st = store.stats()
    assert st["kept"] <= 4


def test_p99_attribution_names_the_tail_owner():
    mk = _mk_record
    rows = [mk(f"fast-{i}", 100.0,
               phase_ms={"prefill": 40.0, "decode_steady": 55.0})
            for i in range(50)]
    rows.append(mk("slow", 900.0,
                   phase_ms={"prefill": 45.0, "kv_transfer": 790.0,
                             "decode_steady": 60.0}))
    rep = reqtrace.p99_attribution(rows)
    assert rep["n"] == 51
    assert rep["tail_owner"] == "kv_transfer"
    assert rep["tail_share"] >= 0.9
    assert rep["phases"]["kv_transfer"]["delta_ms"] > 700.0
    # empty population degrades, not raises
    assert reqtrace.p99_attribution([])["tail_owner"] is None


# ------------------------------------------------- router serving path


def test_router_owned_trace_covers_the_serving_path(model):
    pf = PrefillServer(model, CFG, kv_block_size=BS, kv_pool_blocks=32)
    dec = DecodeServer(model, CFG, max_batch=2)
    router = DisaggRouter(decode=[dec], prefill=[pf],
                          max_queue_depth=2, affinity_tokens=BS)
    try:
        toks = router.generate([1, 2, 3, 4, 5], 6)
        assert len(toks) == 6
    finally:
        dec.stop()
    store = reqtrace.store()
    rows = store.summaries_since(0)
    assert len(rows) == 1
    phase_ms = rows[0]["phase_ms"]
    for ph in ("queue_reserve", "prefill", "kv_transfer",
               "decode_first_token"):
        assert ph in phase_ms, phase_ms
    assert rows[0]["outcome"] == "ok"
    # loose phase-sum bound (shared tier-1 machine): the recorded
    # phases live inside the wall clock and cover the dominant work
    kept = store.slowest(1)[0]
    seq_ms = sum(p["dur_ms"] for p in kept["phases"]
                 if not p.get("concurrent"))
    assert seq_ms <= kept["total_ms"] + 5.0
    assert seq_ms >= 0.35 * kept["total_ms"]


def test_router_deadline_shed_is_kept_with_cause(model):
    pf = PrefillServer(model, CFG, kv_block_size=BS, kv_pool_blocks=32)
    dec = DecodeServer(model, CFG, max_batch=2)
    router = DisaggRouter(decode=[dec], prefill=[pf],
                          max_queue_depth=2, affinity_tokens=BS)
    try:
        with pytest.raises(RequestShedError):
            router.generate([1, 2, 3, 4], 6, deadline_s=0.0)
    finally:
        dec.stop()
    store = reqtrace.store()
    rows = store.summaries_since(0)
    assert len(rows) == 1
    assert rows[0]["outcome"] == "deadline"
    kept = store.trace(rows[0]["request_id"])
    assert kept is not None                  # anomalous -> retained
    assert kept["cause"] == "deadline"


class _FlakyDecode:
    """Proxies a DecodeServer; dies after serving N tokens (the
    in-process stand-in for an actor death mid-stream)."""

    def __init__(self, inner, die_after=10**9):
        self._inner = inner
        self._served = 0
        self._die = die_after
        self.dead = False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def start_decode(self, *a, **k):
        if self.dead:
            raise ConnectionError("replica is dead")
        return self._inner.start_decode(*a, **k)

    def next_tokens(self, hid, max_tokens=64, wait_s=2.0):
        if self.dead:
            raise ConnectionError("replica is dead")
        out = self._inner.next_tokens(hid, 1, wait_s)
        self._served += len(out["tokens"])
        if self._served >= self._die and not out["done"]:
            self.dead = True
            raise ConnectionError("replica died mid-stream")
        return out


def test_failover_replay_is_a_child_span_under_one_id(model):
    pf = PrefillServer(model, CFG, kv_block_size=BS, kv_pool_blocks=32)
    d1 = DecodeServer(model, CFG, max_batch=4)
    d2 = DecodeServer(model, CFG, max_batch=4)
    # free-slot tie-break favors the LAST replica: the flaky one
    router = DisaggRouter(decode=[_FlakyDecode(d2),
                                  _FlakyDecode(d1, die_after=3)],
                          prefill=[pf], max_queue_depth=4,
                          affinity_tokens=BS)
    try:
        toks = router.generate([1, 2, 3, 4, 5, 6, 7, 8], 8)
        assert len(toks) == 8
    finally:
        d1.stop()
        d2.stop()
    store = reqtrace.store()
    rows = store.summaries_since(0)
    assert len(rows) == 1
    kept = store.trace(rows[0]["request_id"])
    assert kept is not None                  # replayed -> retained
    assert kept["outcome"] == "ok"
    assert kept["replayed"] is True
    assert kept["attempts"] >= 2
    attempts = {p["attempt"] for p in kept["phases"]}
    assert 1 in attempts and 2 in attempts
    # the replay re-prefilled under attempt 2 — a child span of the
    # SAME request id, not a second request
    a2 = [p["phase"] for p in kept["phases"] if p["attempt"] == 2]
    assert "prefill" in a2
    st = store.stats()
    assert st["replayed_requests"] == 1


# ---------------------------------------------------- gateway headers


@pytest.fixture(scope="module")
def gw_stack(model):
    engine = ContinuousBatchingEngine(model, CFG, max_batch=2)
    router = DisaggRouter(colocated=engine, max_queue_depth=8)
    gw = GatewayServer(router, model="tiny", vocab_size=CFG.vocab_size,
                       qos=QosGate(router=router), max_tokens_cap=64)
    host, port = gw.ready()
    yield {"host": host, "port": port, "engine": engine, "gw": gw}
    gw.stop()
    engine.stop()


def _post(host, port, path, body=None, headers=None, raw=None,
          timeout=60.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    payload = raw if raw is not None else json.dumps(body)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", path, payload, hdrs)
    return conn, conn.getresponse()


def test_gateway_honors_traceparent_and_stamps_request_id(gw_stack):
    incoming_trace = "ab" * 16
    tp = f"00-{incoming_trace}-{'12' * 8}-01"
    conn, resp = _post(gw_stack["host"], gw_stack["port"],
                       "/v1/completions",
                       body={"model": "tiny", "prompt": [1, 2, 3],
                             "max_tokens": 4},
                       headers={"traceparent": tp})
    assert resp.status == 200
    rid = resp.getheader("X-Request-Id")
    assert rid and rid.startswith("cmpl-")
    assert json.loads(resp.read())["id"] == rid
    conn.close()
    # the gateway-minted trace adopted the INCOMING W3C trace id
    kept = reqtrace.store().trace(rid)
    assert kept is not None
    assert kept["trace_id"] == incoming_trace
    assert kept["source"] == "gateway"
    assert "qos_admission" in kept["phase_ms"]


def test_request_id_header_on_errors_and_streams(gw_stack):
    host, port = gw_stack["host"], gw_stack["port"]
    # 400 invalid JSON
    conn, resp = _post(host, port, "/v1/completions",
                       raw=b"{not json")
    assert resp.status == 400
    assert resp.getheader("X-Request-Id")
    conn.close()
    # 404 unknown model
    conn, resp = _post(host, port, "/v1/completions",
                       body={"model": "nope", "prompt": [1]})
    assert resp.status == 404
    assert resp.getheader("X-Request-Id")
    conn.close()
    # SSE stream: header present on the live stream response
    conn, resp = _post(host, port, "/v1/completions",
                       body={"model": "tiny", "prompt": [4, 5],
                             "max_tokens": 4, "stream": True})
    assert resp.status == 200
    rid = resp.getheader("X-Request-Id")
    assert rid and rid.startswith("cmpl-")
    while resp.readline():          # drain so the slot frees cleanly
        pass
    conn.close()
    # non-completion routes get the middleware's fallback id
    c2 = http.client.HTTPConnection(host, port, timeout=30.0)
    c2.request("GET", "/v1/models")
    r2 = c2.getresponse()
    assert r2.getheader("X-Request-Id", "").startswith("req-")
    r2.read()
    c2.close()


def test_gateway_stream_records_sse_flush_and_tokens(gw_stack):
    conn, resp = _post(gw_stack["host"], gw_stack["port"],
                       "/v1/completions",
                       body={"model": "tiny", "prompt": [6, 7, 8],
                             "max_tokens": 5, "stream": True})
    assert resp.status == 200
    rid = resp.getheader("X-Request-Id")
    while resp.readline():
        pass
    conn.close()
    store = reqtrace.store()
    deadline = time.monotonic() + 10.0
    kept = None
    while time.monotonic() < deadline:
        kept = store.trace(rid)
        if kept is not None:
            break
        time.sleep(0.05)
    assert kept is not None, rid
    assert kept["outcome"] == "ok"
    assert kept.get("streamed") is True
    flush = [p for p in kept["phases"] if p["phase"] == "sse_flush"]
    assert flush and flush[0]["concurrent"] is True
    assert flush[0]["writes"] >= 1


# --------------------------------------------------------- chaos e2e


def test_chaos_chunk_delay_makes_kv_transfer_the_tail_owner(
        reqtrace_cluster, model, monkeypatch):
    """delay_chunk_fetch ms=200: the slowed request tops the slowest
    list with kv_transfer dominating its breakdown, and the
    p99-attribution report names kv_transfer as the tail owner."""
    from ray_tpu.resilience import chaos

    pf = PrefillServer(model, CFG, kv_block_size=BS, kv_pool_blocks=32)
    dec = DecodeServer(model, CFG, max_batch=2)
    router = DisaggRouter(decode=[dec], prefill=[pf],
                          max_queue_depth=2, affinity_tokens=BS)
    try:
        # warm up the jit caches first, then drop the warmup trace —
        # compile time would otherwise dwarf the chaos delay and own
        # the tail itself
        router.generate([1, 2, 3, 4], 4)
        reqtrace._reset_store_for_tests()
        # a baseline population (distinct prompts: no prefix-cache
        # shortcut hiding the transfer), then one chaos-slowed request
        for i in range(6):
            router.generate([10 + i, 20 + i, 30 + i, 40 + i], 4)
        monkeypatch.setenv(
            chaos.ENV_VAR,
            '[{"action": "delay_chunk_fetch", "ms": 200}]')
        router.generate([91, 92, 93, 94], 4)
        monkeypatch.delenv(chaos.ENV_VAR)
    finally:
        dec.stop()
    store = reqtrace.store()
    slowest = store.slowest(1)[0]
    # each leaf pull sleeps 200ms: kv_transfer dominates the slowed
    # request and owns its breakdown
    assert slowest["phase_ms"]["kv_transfer"] >= 300.0, slowest
    assert slowest["phase_ms"]["kv_transfer"] >= \
        0.5 * slowest["total_ms"]
    kv_phase = next(p for p in slowest["phases"]
                    if p["phase"] == "kv_transfer")
    assert kv_phase.get("pulls", 0) >= 2       # ChunkFetcher annotated
    assert kv_phase.get("pull_ms", 0.0) >= 300.0
    rep = store.stats()["attribution"]
    assert rep["tail_owner"] == "kv_transfer", rep


# ------------------------------------------------ preempted gateway


def test_preempted_stream_resumes_as_child_span_one_id(model):
    """A batch SSE stream preempted by an interactive arrival resumes
    and completes under ONE request id with the replay attempt-tagged
    (the acceptance scenario's gateway half)."""
    engine = ContinuousBatchingEngine(model, dataclasses.replace(
        CFG, max_seq_len=1024), max_batch=1)
    cfg = dataclasses.replace(CFG, max_seq_len=1024)
    router = DisaggRouter(colocated=engine, max_queue_depth=0)
    gw = GatewayServer(router, model="tiny", vocab_size=cfg.vocab_size,
                       qos=QosGate(router=router), max_tokens_cap=800)
    host, port = gw.ready()
    out = {}
    try:
        def batch_client():
            conn, resp = _post(host, port, "/v1/completions",
                               body={"model": "tiny",
                                     "prompt": [7, 8, 9],
                                     "max_tokens": 600, "stream": True,
                                     "priority": "batch"},
                               timeout=180.0)
            out["rid"] = resp.getheader("X-Request-Id")
            out["status"] = resp.status
            while resp.readline():
                pass
            conn.close()

        th = threading.Thread(target=batch_client, daemon=True)
        th.start()
        time.sleep(0.8)       # land inside the production window
        conn, resp = _post(host, port, "/v1/completions",
                           body={"model": "tiny", "prompt": [4, 5],
                                 "max_tokens": 16,
                                 "priority": "interactive"},
                           timeout=120.0)
        assert resp.status == 200
        resp.read()
        conn.close()
        th.join(timeout=120)
        assert not th.is_alive()
        assert out["status"] == 200
    finally:
        gw.stop()
        engine.stop()
    store = reqtrace.store()
    deadline = time.monotonic() + 10.0
    kept = None
    while time.monotonic() < deadline:
        kept = store.trace(out["rid"])
        if kept is not None:
            break
        time.sleep(0.05)
    assert kept is not None, out
    assert kept["outcome"] == "ok"
    assert kept["preempts"] >= 1             # preempted -> anomalous
    assert kept["attempts"] >= 2
    # the post-preemption decode is a child span under the SAME id
    replay = [p for p in kept["phases"] if p["attempt"] >= 2]
    assert any(p["phase"].startswith("decode") for p in replay), kept


# --------------------------------------------- e2e surface consistency


def test_all_surfaces_report_one_set_of_numbers(reqtrace_cluster,
                                                model, capsys):
    """requesttrace_status() == CLI --json == /api/requesttrace, the
    Prometheus reqtrace families cover the workload, and every kept
    trace renders as real spans in the merged timeline's `requests`
    lane."""
    import urllib.request

    from ray_tpu.dashboard import DashboardServer
    from ray_tpu.scripts import cli
    from ray_tpu.util import metrics as metrics_mod
    from ray_tpu.util import state

    pf = PrefillServer(model, CFG, kv_block_size=BS, kv_pool_blocks=32)
    dec = DecodeServer(model, CFG, max_batch=2)
    router = DisaggRouter(decode=[dec], prefill=[pf],
                          max_queue_depth=2, affinity_tokens=BS)
    try:
        for i in range(4):
            router.generate([50 + i, 60 + i, 70 + i], 4)
        with pytest.raises(RequestShedError):
            router.generate([1, 2, 3], 4, deadline_s=0.0)
    finally:
        dec.stop()
    store = reqtrace.store()
    local = store.stats()
    assert local["completed"] == 5
    assert local["outcomes"].get("deadline") == 1
    store.publish_telemetry(force=True)
    metrics_mod.flush()

    # state API (fire-and-forget notify: poll until the snapshot lands)
    deadline = time.monotonic() + 10.0
    while True:
        st = state.requesttrace_status()
        mine = st["stores"].get(store.component_id)
        if mine is not None and mine.get("completed") \
                == local["completed"]:
            break
        assert time.monotonic() < deadline, st
        time.sleep(0.1)
    totals = st["totals"]
    assert totals["completed"] >= local["completed"]
    assert totals["outcomes"].get("deadline", 0) >= 1
    assert st["attribution"]["n"] >= 5
    # settle past the publish throttle so the three reads below see
    # the SAME conductor aggregate
    time.sleep(0.6)
    st = state.requesttrace_status()

    # CLI --json (same conductor snapshot)
    w = reqtrace_cluster
    host, port = w.conductor_address
    cli.main(["requests", "--json", "--address", f"{host}:{port}"])
    cli_out = json.loads(capsys.readouterr().out)
    assert cli_out["totals"] == st["totals"]

    # per-id replay: CLI --trace reads the kept record back
    kept_id = st["slowest"][0]["request_id"]
    trc = state.request_trace(kept_id)
    assert trc is not None and trc["request_id"] == kept_id
    assert trc["phases"]

    # dashboard /api/requesttrace
    srv = DashboardServer(w.conductor_address, port=0).start()
    try:
        with urllib.request.urlopen(srv.url + "/api/requesttrace",
                                    timeout=10.0) as r:
            dash = json.loads(r.read())
    finally:
        srv.stop()
    assert dash["totals"] == st["totals"]
    assert [r["request_id"] for r in dash["slowest"]] \
        == [r["request_id"] for r in st["slowest"]]
    assert any(e.get("kind") == "trace" for e in dash["events"])

    # Prometheus: the reqtrace families cover this workload
    prom = state.prometheus_metrics()
    assert "ray_tpu_reqtrace_phase_ms" in prom
    assert "ray_tpu_reqtrace_requests_total" in prom
    assert "ray_tpu_reqtrace_kept_total" in prom
    assert "ray_tpu_reqtrace_slowest_ms" in prom
    req_total = sum(
        float(line.rsplit(" ", 1)[1])
        for line in prom.splitlines()
        if line.startswith("ray_tpu_reqtrace_requests_total{"))
    assert req_total >= local["completed"]

    # merged timeline: kept traces render as REAL spans in the
    # `requests` lane — enclosing request span + per-phase spans
    trace = state.timeline(merged=True)
    lane = [e for e in trace if e.get("pid") == "requests"]
    req_spans = [e for e in lane if e.get("cat") == "request"]
    phase_spans = [e for e in lane if e.get("cat") == "request_phase"]
    assert any(e["args"]["request_id"] == kept_id for e in req_spans)
    assert all(e["ph"] == "X" for e in req_spans + phase_spans)
    names = {e["name"] for e in phase_spans}
    assert "prefill" in names and "kv_transfer" in names


def test_remote_child_phases_merge_into_the_kept_trace(
        reqtrace_cluster):
    """An actor-mode tier pushes kind="phase" records under the
    originating id; get_request_trace merges them as remote_phases —
    the cross-process half of replay nesting."""
    from ray_tpu.util import state

    store = reqtrace.store()
    tr = reqtrace.RequestTrace("r-remote-1", store=store)
    with tr.phase("prefill"):
        pass
    tr.finish("preempt", cause="preempted")   # anomalous -> kept+event
    reqtrace.push_remote_phase("r-remote-1", "kv_transfer_remote",
                               12.5, attempt=2, server="dec-x")
    deadline = time.monotonic() + 10.0
    trc = None
    while time.monotonic() < deadline:
        trc = state.request_trace("r-remote-1")
        if trc is not None and trc.get("remote_phases"):
            break
        time.sleep(0.1)
    assert trc is not None
    remote = trc["remote_phases"]
    assert remote and remote[0]["phase"] == "kv_transfer_remote"
    assert remote[0]["attempt"] == 2
    assert remote[0]["server"] == "dec-x"
    assert state.request_trace("no-such-id") is None
