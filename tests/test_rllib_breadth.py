"""APPO, DQN, and multi-agent env runner (reference rllib/algorithms/
appo/, rllib/algorithms/dqn/, rllib/env/multi_agent_env_runner.py) —
the VERDICT r2 breadth items."""
from __future__ import annotations

import jax
import numpy as np
import pytest

from ray_tpu.rllib import (APPO, APPOConfig, DQN, DQNConfig,
                           MultiAgentCartPole, MultiAgentEnvRunner,
                           MultiAgentPPO, ReplayBuffer)


def _learn(algo, iters, target):
    best = -np.inf
    for _ in range(iters):
        result = algo.step()
        m = result["episode_return_mean"]
        if m == m:  # not NaN
            best = max(best, m)
        if best >= target:
            break
    return best


def test_appo_learns_cartpole_local():
    algo = (APPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=32)
            .training(lr=3e-3, gamma=0.99)
            .debugging(seed=0)
            .build())
    best = _learn(algo, 40, 150.0)
    assert best >= 150.0, f"APPO failed to learn CartPole: best={best}"


def test_appo_target_network_lags_then_syncs():
    algo = (APPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                         rollout_fragment_length=16)
            .training(target_update_freq=10**9)  # never sync in this test
            .debugging(seed=0)
            .build())
    before = jax.device_get(algo.target_params)
    algo.step()
    after_t = jax.device_get(algo.target_params)
    after_p = jax.device_get(algo.params)
    # target held fixed while online params moved
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after_t)):
        np.testing.assert_array_equal(a, b)
    assert any(not np.array_equal(a, b) for a, b in
               zip(jax.tree.leaves(after_t), jax.tree.leaves(after_p)))


def test_dqn_learns_cartpole_local():
    algo = (DQNConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                         rollout_fragment_length=32)
            .training(lr=1e-3, gamma=0.99)
            .debugging(seed=0)
            .build())
    best = _learn(algo, 120, 150.0)
    assert best >= 150.0, f"DQN failed to learn CartPole: best={best}"


def test_dqn_rejects_continuous():
    with pytest.raises(ValueError, match="discrete"):
        (DQNConfig().environment("Pendulum-v1")
         .env_runners(num_env_runners=0).build())


def test_replay_buffer_wraps_and_samples():
    buf = ReplayBuffer(capacity=100, obs_dim=4)
    T, N = 10, 3  # 30 transitions per fragment
    for frag in range(5):  # 150 > capacity: wraps
        batch = {
            "obs": np.full((T + 1, N, 4), frag, np.float32),
            "actions": np.full((T, N), frag % 2, np.int32),
            "rewards": np.full((T, N), float(frag), np.float32),
            "dones": np.zeros((T, N), np.bool_),
        }
        buf.add_fragment(batch)
    assert len(buf) == 100
    s = buf.sample(np.random.default_rng(0), 64)
    assert s["obs"].shape == (64, 4)
    # wrapped buffer holds only the newest fragments (0th was overwritten)
    assert s["rewards"].min() >= 1.0


def test_dqn_checkpoint_roundtrip():
    cfg = (DQNConfig().environment("CartPole-v1")
           .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                        rollout_fragment_length=16))
    algo = cfg.copy().build()
    algo.step()
    state = algo.save_checkpoint("/tmp/unused")
    algo2 = cfg.copy().build()
    algo2.load_checkpoint(state)
    for x, y in zip(jax.tree.leaves(algo.params),
                    jax.tree.leaves(algo2.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(jax.tree.leaves(algo.target_params),
                    jax.tree.leaves(algo2.target_params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------- multi-agent


def test_multi_agent_runner_per_policy_batches():
    """Per-policy batch grouping: 4 agents, 2 policies (even/odd) — each
    policy's batch concatenates its agents along the env axis."""
    runner = MultiAgentEnvRunner(
        "MultiAgentCartPole", num_envs=3, rollout_fragment_length=8,
        policy_mapping_fn=lambda aid: f"pol_{int(aid[-1]) % 2}",
        seed=0, env_config={"num_agents": 4})
    specs = runner.policies_needed()
    assert sorted(specs) == ["pol_0", "pol_1"]
    from ray_tpu.rllib import core
    params = {pid: core.policy_init(jax.random.PRNGKey(i), 4, 2)
              for i, pid in enumerate(specs)}
    batches = runner.sample(params)
    assert sorted(batches) == ["pol_0", "pol_1"]
    for pid, b in batches.items():
        # 2 agents x 3 envs = 6 env slots per policy
        assert b["obs"].shape == (9, 6, 4)
        assert b["actions"].shape == (8, 6)
        assert sorted(b["agent_ids"]) == sorted(
            a for a in [f"agent_{i}" for i in range(4)]
            if f"pol_{int(a[-1]) % 2}" == pid)


def test_multi_agent_mismatched_spaces_rejected():
    class WeirdEnv(MultiAgentCartPole):
        def agent_spec(self, agent_id):
            spec = dict(super().agent_spec(agent_id))
            if agent_id == "agent_1":
                spec["num_actions"] = 5
            return spec

    runner = MultiAgentEnvRunner(
        lambda num_envs, seed: WeirdEnv(2, num_envs, seed),
        num_envs=2, rollout_fragment_length=4,
        policy_mapping_fn=lambda aid: "shared")
    with pytest.raises(ValueError, match="mismatched"):
        runner.policies_needed()


def test_multi_agent_two_policies_learn_smoke():
    """2-policy smoke (VERDICT done-criterion): both policies improve on
    independent CartPoles."""
    algo = MultiAgentPPO(
        "MultiAgentCartPole", num_envs=16, rollout_fragment_length=64,
        policy_mapping_fn=lambda aid: aid,  # one policy per agent
        env_config={"num_agents": 2}, seed=0,
        lr=1e-3, entropy_coeff=0.01)
    best = {pid: -np.inf for pid in algo.policies}
    for _ in range(30):
        r = algo.step()
        for pid in algo.policies:
            m = r[pid]["episode_return_mean"]
            if m == m:
                best[pid] = max(best[pid], m)
        if all(b >= 80.0 for b in best.values()):
            break
    assert all(b >= 80.0 for b in best.values()), best


def test_sac_learns_pendulum():
    """SAC (twin soft-Q + squashed gaussian + auto-alpha) improves
    Pendulum well past random (~-1240) within the CI budget."""
    from ray_tpu.rllib import SACConfig

    algo = (SACConfig()
            .environment("Pendulum-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                         rollout_fragment_length=32)
            .training(lr=1e-3, updates_per_step=64, learning_starts=1000)
            .debugging(seed=0)
            .build())
    best = -np.inf
    for _ in range(170):
        result = algo.step()
        m = result["episode_return_mean"]
        if m == m:
            best = max(best, m)
        if best >= -400.0:
            break
    assert best >= -400.0, f"SAC failed to learn Pendulum: best={best}"


def test_sac_rejects_discrete():
    from ray_tpu.rllib import SACConfig

    with pytest.raises(ValueError, match="continuous"):
        (SACConfig().environment("CartPole-v1")
         .env_runners(num_env_runners=0).build())


def test_rllib_bench_smoke(tmp_path):
    """The env-steps/sec benchmark runs and emits well-formed records."""
    import json

    from ray_tpu.rllib.bench import main

    out = str(tmp_path / "bench.json")
    main(["--out", out, "--steps", "2"])
    with open(out) as f:
        data = json.load(f)
    algos = {r["algo"] for r in data["results"]}
    assert algos == {"ppo", "impala", "appo"}
    assert all(r["env_steps_per_sec"] > 0 for r in data["results"])


def test_connector_pipeline_units():
    """Connector math: running mean/std converges, state round-trips,
    action transforms map correctly (reference rllib/connectors/)."""
    import numpy as np

    from ray_tpu.rllib import (ClipActions, ConnectorPipeline,
                               NormalizeObservations, ScaleActions)

    rng = np.random.default_rng(0)
    norm = NormalizeObservations(clip=5.0)
    for _ in range(50):
        norm(rng.normal(3.0, 2.0, (64, 4)).astype(np.float32))
    assert np.allclose(norm.mean, 3.0, atol=0.2)
    assert np.allclose(np.sqrt(norm.m2 / norm.count), 2.0, atol=0.2)
    out = norm(np.full((2, 4), 3.0, np.float32), update=False)
    assert np.abs(out).max() < 0.2  # mean maps near zero
    # update=False must not advance the stats
    count_before = norm.count
    norm(np.zeros((8, 4), np.float32), update=False)
    assert norm.count == count_before

    pipe = ConnectorPipeline(NormalizeObservations(), )
    state = pipe.get_state()
    pipe2 = ConnectorPipeline(NormalizeObservations(), )
    pipe2.set_state(state)
    assert pipe2.connectors[0].count == 0.0

    clip = ClipActions(-2.0, 2.0)
    assert (clip(np.array([-5.0, 0.5, 9.0])) == [-2.0, 0.5, 2.0]).all()
    scale = ScaleActions(-2.0, 2.0)
    assert (scale(np.array([-1.0, 0.0, 1.0])) == [-2.0, 0.0, 2.0]).all()


def test_ppo_with_normalize_connector():
    """PPO trains through an env-to-module normalization pipeline; the
    recorded rollout obs are the transformed ones."""
    import numpy as np

    from ray_tpu.rllib import (ConnectorPipeline, NormalizeObservations,
                               PPOConfig)

    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=64,
                         env_to_module_connector=lambda:
                         ConnectorPipeline(NormalizeObservations()))
            .training(lr=1e-3).debugging(seed=0).build())
    best = -np.inf
    for _ in range(40):
        m = algo.step()["episode_return_mean"]
        if m == m:
            best = max(best, m)
        if best >= 80.0:
            break
    assert best >= 80.0, f"PPO with connector stalled at {best}"
    norm = algo.local_runner._env_to_module.connectors[0]
    assert norm.count > 0, "normalizer never updated"


def test_connector_fleet_sync_and_checkpoint():
    """Remote-runner connector stats merge into ONE statistic broadcast
    back to the fleet, and checkpoints carry the normalizer (reference
    mean-std filter sync through the driver)."""
    import numpy as np

    from ray_tpu.rllib import (ConnectorPipeline, NormalizeObservations,
                               PPOConfig)
    from ray_tpu.rllib.connectors import NormalizeObservations as NO

    # pure merge math: two disjoint runs merge to the pooled stats
    rng = np.random.default_rng(0)
    a, b = NO(), NO()
    xa = rng.normal(0.0, 1.0, (500, 3)).astype(np.float32)
    xb = rng.normal(4.0, 2.0, (500, 3)).astype(np.float32)
    a(xa); b(xb)
    merged = NO.merge_states([a.get_state(), b.get_state()])
    pooled = np.concatenate([xa, xb])
    assert np.allclose(merged["mean"], pooled.mean(0), atol=1e-4)
    assert np.allclose(np.sqrt(merged["m2"] / merged["count"]),
                       pooled.std(0), atol=1e-3)

    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    factory = lambda: ConnectorPipeline(NormalizeObservations())  # noqa: E731
    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=16,
                         env_to_module_connector=factory)
            .debugging(seed=0).build())
    algo.step()
    algo.step()
    states = ray_tpu.get([r.get_connector_states.remote()
                          for r in algo.runners])
    counts = [s["env_to_module"][0]["count"] for s in states]
    # after the broadcast both runners carry the same merged statistic
    assert counts[0] == counts[1] > 0, counts
    # delta-based sync: the pooled count equals the samples actually
    # observed (2 steps x 2 runners x T=16 x 4 envs), not an
    # every-round re-merge of shared history
    assert counts[0] == 2 * 2 * 16 * 4, counts
    ck = algo.save_checkpoint("/tmp/conn_ck")
    assert ck["connector_states"]["env_to_module"][0]["count"] == counts[0]

    algo2 = (PPOConfig().environment("CartPole-v1")
             .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                          rollout_fragment_length=16,
                          env_to_module_connector=factory)
             .debugging(seed=1).build())
    algo2.load_checkpoint(ck)
    st = algo2.local_runner.get_connector_states()
    assert st["env_to_module"][0]["count"] == counts[0]
    algo.cleanup()
    ray_tpu.shutdown()
