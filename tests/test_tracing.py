"""Tracing: span recording, W3C propagation across task/actor hops, and
chrome-trace/OTLP export (reference util/tracing/tracing_helper.py)."""
from __future__ import annotations

import time

import pytest

import ray_tpu
from ray_tpu.util import tracing


@pytest.fixture(autouse=True)
def clean_tracing(monkeypatch):
    monkeypatch.delenv("RAY_TPU_TRACING", raising=False)
    tracing._enabled = False
    tracing._finished.clear()
    yield
    tracing._enabled = False
    tracing._finished.clear()


def test_disabled_is_free():
    with tracing.span("noop") as s:
        assert s is None
    assert tracing.drain() == []


def test_span_nesting_and_drain():
    tracing.enable()
    with tracing.span("outer", job="j1") as outer:
        with tracing.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    spans = tracing.drain()
    assert [s["name"] for s in spans] == ["inner", "outer"]
    assert spans[1]["attrs"] == {"job": "j1"}
    assert all(s["end"] >= s["start"] for s in spans)
    assert tracing.drain() == []


def test_error_status_recorded():
    tracing.enable()
    with pytest.raises(ValueError):
        with tracing.span("boom"):
            raise ValueError("x")
    (s,) = tracing.drain()
    assert s["status"] == "ERROR: ValueError"


def test_traceparent_roundtrip():
    tracing.enable()
    with tracing.span("parent") as p:
        tp = tracing.current_traceparent()
        assert tp == p.traceparent()
    # a "remote" span built from the wire value joins the same trace
    with tracing.span("remote-child", traceparent=tp) as c:
        assert c.trace_id == p.trace_id
        assert c.parent_id == p.span_id


def test_exports():
    tracing.enable()
    with tracing.span("work", k="v"):
        time.sleep(0.01)
    spans = tracing.drain()
    trace = tracing.to_chrome_trace(spans)
    assert trace[0]["name"] == "work" and trace[0]["ph"] == "X"
    assert trace[0]["dur"] > 0
    otlp = tracing.to_otlp_json(spans)
    os_spans = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert os_spans[0]["name"] == "work"
    assert os_spans[0]["status"]["code"] == 1


def test_otlp_error_detail_carried():
    """to_otlp_json must not collapse failures to a bare code=2: the
    recorded `ERROR: <type>` detail rides as status.message."""
    tracing.enable()
    with pytest.raises(KeyError):
        with tracing.span("fails"):
            raise KeyError("missing")
    otlp = tracing.to_otlp_json(tracing.drain())
    (sp,) = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert sp["status"] == {"code": 2, "message": "KeyError"}
    # OK spans carry no message
    with tracing.span("fine"):
        pass
    otlp = tracing.to_otlp_json(tracing.drain())
    (sp,) = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert sp["status"] == {"code": 1}


@pytest.fixture
def traced_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRACING", "1")
    tracing._enabled = True
    # log_to_driver off: mirrored worker lines would interleave with the
    # tier-1 runner's dot-progress lines and corrupt its dot count
    ray_tpu.init(num_cpus=2, _system_config={"log_to_driver": 0})
    yield
    ray_tpu.shutdown()


def test_spans_cross_task_boundary(traced_cluster):
    """One trace spans the full chain: driver section -> automatic
    submit span -> worker-side task span, all in the conductor's span
    table with correct parentage."""
    @ray_tpu.remote
    def traced_work(x):
        return x + 1

    with tracing.span("driver-section") as root:
        assert ray_tpu.get(traced_work.remote(1), timeout=60.0) == 2

    w = ray_tpu._private.worker.global_worker
    deadline = time.monotonic() + 15.0
    spans = []
    while time.monotonic() < deadline:
        spans = w.conductor.call("get_spans", timeout=10.0)
        names = {s["name"] for s in spans}
        if {"task:traced_work", "submit:traced_work",
                "driver-section"} <= names:
            break
        time.sleep(0.3)
    by_name = {s["name"]: s for s in spans}
    assert "task:traced_work" in by_name, spans
    task_span = by_name["task:traced_work"]
    submit_span = by_name["submit:traced_work"]
    driver_span = by_name["driver-section"]
    assert task_span["trace_id"] == driver_span["trace_id"]
    assert submit_span["trace_id"] == driver_span["trace_id"]
    assert task_span["parent_id"] == submit_span["span_id"]
    assert submit_span["parent_id"] == driver_span["span_id"]


def test_spans_cross_actor_boundary(traced_cluster):
    @ray_tpu.remote
    class T:
        def m(self):
            return 1

    a = T.remote()
    with tracing.span("actor-call-site") as root:
        assert ray_tpu.get(a.m.remote(), timeout=60.0) == 1

    w = ray_tpu._private.worker.global_worker
    deadline = time.monotonic() + 15.0
    by_name = {}
    while time.monotonic() < deadline:
        by_name = {s["name"]: s
                   for s in w.conductor.call("get_spans", timeout=10.0)}
        if "actor:T.m" in by_name and "actor-call-site" in by_name:
            break
        time.sleep(0.3)
    assert "actor:T.m" in by_name
    assert by_name["actor:T.m"]["trace_id"] == \
        by_name["actor-call-site"]["trace_id"]


def test_conductor_span_buffer_capped(tmp_path):
    """report_spans is bounded the same way report_task_events is: the
    conductor's span table trims to 100k entries (half dropped at
    overflow), so a chatty tracer cannot grow head memory without
    limit. Exercised on a bare handler — no cluster needed."""
    from ray_tpu._private.conductor import ConductorHandler

    handler = ConductorHandler({"CPU": 1.0}, str(tmp_path))
    span = {"name": "s", "trace_id": "t", "span_id": "i",
            "parent_id": None, "start": 0.0, "end": 0.0,
            "attrs": {}, "status": "OK", "pid": 0}
    handler.report_spans([dict(span) for _ in range(60_000)])
    handler.report_spans([dict(span) for _ in range(60_000)])
    assert len(handler.get_spans(limit=200_000)) <= 100_000
