"""Offline RL (reference rllib/offline/ + algorithms/bc/): record
EnvRunner fragments to shards, load them as OfflineData, and behavior-
clone an expert policy that then performs on the live env."""
from __future__ import annotations

import numpy as np
import pytest

from ray_tpu.rllib import (BC, BCConfig, OfflineData, PPOConfig,
                           record_batches)


def test_record_and_load_roundtrip(tmp_path):
    paths = record_batches("CartPole-v1", 3, str(tmp_path / "shards"),
                           num_envs=4, rollout_fragment_length=16)
    assert len(paths) == 3
    data = OfflineData(str(tmp_path / "shards"))
    assert len(data) == 3 * 16 * 4
    assert data.obs_dim == 4 and data.num_actions == 2
    mbs = list(data.minibatches(32, 5))
    assert len(mbs) == 5 and mbs[0]["obs"].shape == (32, 4)


def test_bc_clones_expert(tmp_path):
    # train a quick expert with PPO
    expert = (PPOConfig().environment("CartPole-v1")
              .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                           rollout_fragment_length=64)
              .training(lr=1e-3, entropy_coeff=0.01)
              .debugging(seed=0).build())
    best = -np.inf
    for _ in range(45):
        r = expert.step()
        m = r["episode_return_mean"]
        if m == m:
            best = max(best, m)
        if best >= 100.0:
            break
    assert best >= 100.0, f"expert failed to train: {best}"

    record_batches("CartPole-v1", 8, str(tmp_path / "expert"),
                   params=expert.params, num_envs=8,
                   rollout_fragment_length=64)

    algo = (BCConfig().environment("CartPole-v1")
            .offline_data(str(tmp_path / "expert"))
            .training(lr=3e-3, updates_per_step=128, train_batch_size=512)
            .debugging(seed=1).build())
    first_loss, cloned = None, -np.inf
    for _ in range(10):
        r = algo.step()
        if first_loss is None:
            first_loss = r["bc_loss"]
        m = r["episode_return_mean"]
        if m == m:
            cloned = max(cloned, m)
    assert r["bc_loss"] < first_loss, (first_loss, r["bc_loss"])
    assert cloned >= 60.0, f"BC policy only reached {cloned}"


def test_bc_requires_input(tmp_path):
    with pytest.raises(ValueError, match="input_path"):
        (BCConfig().environment("CartPole-v1").build())


def test_continuous_actions_roundtrip(tmp_path):
    """Pendulum shards keep their act_dim through OfflineData and a BC
    update runs on them (the continuous head)."""
    from ray_tpu.rllib import BC

    record_batches("Pendulum-v1", 2, str(tmp_path / "pend"),
                   num_envs=4, rollout_fragment_length=16)
    data = OfflineData(str(tmp_path / "pend"))
    assert data.continuous
    assert data.actions.shape == (2 * 16 * 4, 1)
    algo = (BCConfig().environment("Pendulum-v1")
            .offline_data(str(tmp_path / "pend"))
            .training(updates_per_step=4).build())
    r = algo.step()
    assert np.isfinite(r["bc_loss"])


def test_space_mismatch_rejected(tmp_path):
    record_batches("Pendulum-v1", 1, str(tmp_path / "pend"),
                   num_envs=2, rollout_fragment_length=8)
    with pytest.raises(ValueError, match="obs_dim|action kind"):
        (BCConfig().environment("CartPole-v1")
         .offline_data(str(tmp_path / "pend")).build())
