"""Offline RL (reference rllib/offline/ + algorithms/bc/): record
EnvRunner fragments to shards, load them as OfflineData, and behavior-
clone an expert policy that then performs on the live env."""
from __future__ import annotations

import numpy as np
import pytest

from ray_tpu.rllib import (BC, BCConfig, OfflineData, PPOConfig,
                           record_batches)


def test_record_and_load_roundtrip(tmp_path):
    paths = record_batches("CartPole-v1", 3, str(tmp_path / "shards"),
                           num_envs=4, rollout_fragment_length=16)
    assert len(paths) == 3
    data = OfflineData(str(tmp_path / "shards"))
    assert len(data) == 3 * 16 * 4
    assert data.obs_dim == 4 and data.num_actions == 2
    mbs = list(data.minibatches(32, 5))
    assert len(mbs) == 5 and mbs[0]["obs"].shape == (32, 4)


def test_bc_clones_expert(tmp_path):
    # train a quick expert with PPO
    expert = (PPOConfig().environment("CartPole-v1")
              .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                           rollout_fragment_length=64)
              .training(lr=1e-3, entropy_coeff=0.01)
              .debugging(seed=0).build())
    best = -np.inf
    for _ in range(45):
        r = expert.step()
        m = r["episode_return_mean"]
        if m == m:
            best = max(best, m)
        if best >= 100.0:
            break
    assert best >= 100.0, f"expert failed to train: {best}"

    record_batches("CartPole-v1", 8, str(tmp_path / "expert"),
                   params=expert.params, num_envs=8,
                   rollout_fragment_length=64)

    algo = (BCConfig().environment("CartPole-v1")
            .offline_data(str(tmp_path / "expert"))
            .training(lr=3e-3, updates_per_step=128, train_batch_size=512)
            .debugging(seed=1).build())
    first_loss, cloned = None, -np.inf
    for _ in range(10):
        r = algo.step()
        if first_loss is None:
            first_loss = r["bc_loss"]
        m = r["episode_return_mean"]
        if m == m:
            cloned = max(cloned, m)
    assert r["bc_loss"] < first_loss, (first_loss, r["bc_loss"])
    assert cloned >= 60.0, f"BC policy only reached {cloned}"


def test_bc_requires_input(tmp_path):
    with pytest.raises(ValueError, match="input_path"):
        (BCConfig().environment("CartPole-v1").build())


def test_continuous_actions_roundtrip(tmp_path):
    """Pendulum shards keep their act_dim through OfflineData and a BC
    update runs on them (the continuous head)."""
    from ray_tpu.rllib import BC

    record_batches("Pendulum-v1", 2, str(tmp_path / "pend"),
                   num_envs=4, rollout_fragment_length=16)
    data = OfflineData(str(tmp_path / "pend"))
    assert data.continuous
    assert data.actions.shape == (2 * 16 * 4, 1)
    algo = (BCConfig().environment("Pendulum-v1")
            .offline_data(str(tmp_path / "pend"))
            .training(updates_per_step=4).build())
    r = algo.step()
    assert np.isfinite(r["bc_loss"])


def test_space_mismatch_rejected(tmp_path):
    record_batches("Pendulum-v1", 1, str(tmp_path / "pend"),
                   num_envs=2, rollout_fragment_length=8)
    with pytest.raises(ValueError, match="obs_dim|action kind"):
        (BCConfig().environment("CartPole-v1")
         .offline_data(str(tmp_path / "pend")).build())


def _record_heuristic_cartpole(out_dir, num_fragments=6, num_envs=8, T=64):
    """Shards from the lean-following heuristic (push toward the pole's
    fall: a = 1 if theta + theta_dot > 0) — a strong known policy
    recorded without any training, so imitation tests stay fast and
    deterministic."""
    import os

    from ray_tpu.rllib import CartPoleVectorEnv

    os.makedirs(out_dir, exist_ok=True)
    env = CartPoleVectorEnv(num_envs=num_envs, seed=0)
    obs = env.reset(seed=0)
    for i in range(num_fragments):
        o_buf = np.empty((T + 1, num_envs, 4), np.float32)
        a_buf = np.empty((T, num_envs), np.int64)
        r_buf = np.empty((T, num_envs), np.float32)
        d_buf = np.empty((T, num_envs), np.float32)
        for t in range(T):
            o_buf[t] = obs
            act = (obs[:, 2] + obs[:, 3] > 0).astype(np.int64)
            obs, rew, done = env.step(act)[:3]
            a_buf[t], r_buf[t], d_buf[t] = act, rew, done
        o_buf[T] = obs
        with open(os.path.join(out_dir, f"fragment_{i:05d}.npz"),
                  "wb") as f:
            np.savez(f, obs=o_buf, actions=a_buf,
                     logp=np.zeros_like(r_buf), rewards=r_buf,
                     dones=d_buf)


def test_offline_data_transitions(tmp_path):
    """OfflineData exposes full transitions and return-to-go."""
    _record_heuristic_cartpole(str(tmp_path), num_fragments=2, T=16)
    data = OfflineData(str(tmp_path), gamma=0.5)
    assert data.next_obs.shape == data.obs.shape
    assert data.rewards.shape == data.dones.shape == data.returns.shape
    # return recursion: R_t = r_t + gamma*(1-d_t)*R_{t+1} with the value
    # at the last fragment row equal to its reward
    mb = next(iter(data.minibatches(
        16, 1, keys=("obs", "actions", "rewards", "next_obs", "dones",
                     "returns"))))
    assert set(mb) == {"obs", "actions", "rewards", "next_obs", "dones",
                       "returns"}
    assert (mb["returns"] >= mb["rewards"] - 1e-6).all()


def test_marwil_learns_from_heuristic_data(tmp_path):
    """MARWIL clones the recorded heuristic well enough to control the
    live env (reference rllib/algorithms/marwil/), and its advantage
    normalizer actually moves."""
    from ray_tpu.rllib import MARWILConfig

    _record_heuristic_cartpole(str(tmp_path / "shards"))
    algo = (MARWILConfig().environment("CartPole-v1")
            .offline_data(str(tmp_path / "shards"))
            .training(lr=3e-3, updates_per_step=64, train_batch_size=512)
            .debugging(seed=1).build())
    first_pl, best = None, -np.inf
    for _ in range(12):
        r = algo.step()
        if first_pl is None:
            first_pl = r["policy_loss"]
        m = r["episode_return_mean"]
        if m == m:
            best = max(best, m)
    assert r["adv_norm"] != pytest.approx(1.0), "advantage EMA never moved"
    assert best >= 60.0, f"MARWIL policy only reached {best}"
    # checkpoint round-trips the normalizer
    ck = algo.save_checkpoint(str(tmp_path / "ck"))
    algo2 = (MARWILConfig().environment("CartPole-v1")
             .offline_data(str(tmp_path / "shards"))
             .debugging(seed=2).build())
    algo2.load_checkpoint(ck)
    assert float(algo2._c2) == pytest.approx(float(algo._c2))


def test_cql_is_conservative(tmp_path):
    """CQL's signature property (reference rllib/algorithms/cql/): after
    training on offline data, dataset actions score at least as high
    under Q as the policy's own (out-of-distribution) actions."""
    import jax.numpy as jnp

    from ray_tpu.rllib import CQLConfig, record_batches
    from ray_tpu.rllib.sac import _pi_dist, _q, _sample_squashed

    record_batches("Pendulum-v1", 6, str(tmp_path / "shards"),
                   num_envs=8, rollout_fragment_length=32, seed=0)
    algo = (CQLConfig().environment("Pendulum-v1")
            .offline_data(str(tmp_path / "shards"))
            .training(updates_per_step=64, train_batch_size=256)
            .debugging(seed=0).build())
    for _ in range(4):
        r = algo.step()
    assert np.isfinite(r["critic_loss"]) and np.isfinite(r["actor_loss"])

    import jax

    data = algo.data
    idx = np.arange(512)
    obs = jnp.asarray(data.obs[idx])
    a_data = jnp.asarray(data.actions[idx]) / algo.act_scale
    q_data = _q(algo.params["q1"], obs, a_data).mean()
    mean, log_std = _pi_dist(algo.params, obs)
    a_pi, _ = _sample_squashed(jax.random.PRNGKey(0), mean, log_std)
    q_pi = _q(algo.params["q1"], obs, a_pi).mean()
    assert float(q_data) >= float(q_pi) - 1.0, \
        f"no conservatism: Q(data)={float(q_data):.2f} < " \
        f"Q(pi)={float(q_pi):.2f}"


def test_obs_actions_only_shards_still_load(tmp_path):
    """Shards without rewards/dones stay valid for BC; transition keys
    fail with a clear error rather than a KeyError at load."""
    o = np.zeros((9, 2, 4), np.float32)
    a = np.zeros((8, 2), np.int64)
    with open(tmp_path / "fragment_00000.npz", "wb") as f:
        np.savez(f, obs=o, actions=a, logp=np.zeros((8, 2), np.float32))
    data = OfflineData(str(tmp_path))
    assert len(data) == 16 and data.returns is None
    assert next(iter(data.minibatches(4, 1)))["obs"].shape == (4, 4)
    with pytest.raises(ValueError, match="rewards/dones"):
        list(data.minibatches(4, 1, keys=("obs", "returns")))
