"""Memory monitor / OOM protection (reference memory_monitor.h:52 +
worker_killing_policy.cc): over-threshold nodes kill the greediest
worker, task workers before actors, and the submitter sees a typed
OutOfMemoryError instead of a generic crash."""
from __future__ import annotations

import time

import pytest

import ray_tpu
from ray_tpu._private.memory_monitor import (MemoryMonitor, node_usage,
                                             pid_rss)
from ray_tpu.exceptions import OutOfMemoryError, WorkerCrashedError


def test_threshold_logic():
    mon = MemoryMonitor(0.9, usage_fn=lambda: (95, 100))
    assert mon.over_threshold() == (95, 100)
    mon = MemoryMonitor(0.9, usage_fn=lambda: (50, 100))
    assert mon.over_threshold() is None
    # disabled
    mon = MemoryMonitor(0.0, usage_fn=lambda: (100, 100))
    assert mon.over_threshold() is None


def test_victim_prefers_busy_then_rss():
    rss = {1: 100, 2: 900, 3: 500, 4: 5000}
    mon = MemoryMonitor(0.9, rss_fn=lambda pid: rss.get(pid, 0))
    # BUSY beats ACTOR even at lower RSS (tasks are retriable, actors
    # lose state); within a class, highest RSS wins
    victim = mon.pick_victim([("w1", 1, "BUSY"), ("w2", 2, "BUSY"),
                              ("w3", 3, "ACTOR"), ("w4", 4, "ACTOR")])
    assert victim == ("w2", 2, 900)
    # no BUSY: greediest actor
    victim = mon.pick_victim([("w3", 3, "ACTOR"), ("w4", 4, "ACTOR")])
    assert victim == ("w4", 4, 5000)
    # dead pids (rss 0) skipped
    assert mon.pick_victim([("w9", 9, "BUSY")]) is None
    assert mon.pick_victim([]) is None


def test_real_readers_sane():
    used, total = node_usage()
    assert 0 < used <= total
    import os
    assert pid_rss(os.getpid()) > 1024 * 1024  # a python process > 1MB
    assert pid_rss(2**22 + 12345) == 0  # nonexistent pid


def test_oom_kill_surfaces_typed_error():
    """Threshold ~0 makes ANY usage 'over': the first running task's
    worker is killed by the monitor and the caller gets OutOfMemoryError
    naming the cause, not a bare WorkerCrashedError."""
    ray_tpu.init(num_cpus=2, _system_config={
        "memory_usage_threshold": 1e-9,
        "memory_monitor_refresh_ms": 100,
    })
    try:
        @ray_tpu.remote(max_retries=0)
        def hog():
            time.sleep(30)
            return "survived"

        ref = hog.remote()
        with pytest.raises(OutOfMemoryError) as ei:
            ray_tpu.get(ref, timeout=30.0)
        assert "oom" in str(ei.value)
    finally:
        ray_tpu.shutdown()


def test_oom_retries_then_fails_typed():
    """OOM kills consume retries like any worker death; the final error
    is still the typed one."""
    ray_tpu.init(num_cpus=2, _system_config={
        "memory_usage_threshold": 1e-9,
        "memory_monitor_refresh_ms": 100,
    })
    try:
        @ray_tpu.remote(max_retries=1)
        def hog():
            time.sleep(30)

        with pytest.raises(WorkerCrashedError):
            ray_tpu.get(hog.remote(), timeout=60.0)
    finally:
        ray_tpu.shutdown()
