"""Continuous-batching engine: ragged requests share one fixed-shape
decode loop; outputs must equal per-request generate() exactly
(greedy), including for requests that join mid-decode."""
from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.engine import ContinuousBatchingEngine
from ray_tpu.models.generate import generate
from ray_tpu.models.llama import LlamaConfig, llama_init

CFG = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)


@pytest.fixture(scope="module")
def model():
    return llama_init(CFG, jax.random.PRNGKey(0))


@pytest.fixture()
def engine(model):
    eng = ContinuousBatchingEngine(model, CFG, max_batch=4)
    yield eng
    eng.stop()


def _reference(model, prompt, n):
    return np.asarray(generate(model, CFG, jnp.asarray([prompt],
                                                       jnp.int32),
                               max_new_tokens=n))[0].tolist()


def test_single_request_matches_generate(model, engine):
    prompt = [1, 2, 3, 4, 5]
    got = engine.generate(prompt, 8)
    assert got == _reference(model, prompt, 8)


def test_concurrent_ragged_requests_match(model, engine):
    """Different prompt lengths and budgets, submitted together, all
    decode in the shared loop and match solo generation."""
    import concurrent.futures as cf

    prompts = [[7], [1, 2, 3], [9, 8, 7, 6, 5, 4], [2, 4, 6, 8]]
    budgets = [6, 9, 4, 7]
    with cf.ThreadPoolExecutor(4) as pool:
        futs = [pool.submit(engine.generate, p, n)
                for p, n in zip(prompts, budgets)]
        got = [f.result(timeout=120) for f in futs]
    for p, n, g in zip(prompts, budgets, got):
        assert g == _reference(model, p, n), (p, n)


def test_join_mid_decode_matches(model, engine):
    """A request arriving while another decodes must not perturb either
    sequence (slot isolation through per-slot positions/masking)."""
    import concurrent.futures as cf

    with cf.ThreadPoolExecutor(2) as pool:
        long_fut = pool.submit(engine.generate, [1, 2, 3], 20)
        time.sleep(0.2)  # the first request is mid-decode now
        short = engine.generate([5, 5, 5, 5], 5)
        long = long_fut.result(timeout=120)
    assert long == _reference(model, [1, 2, 3], 20)
    assert short == _reference(model, [5, 5, 5, 5], 5)


def test_more_requests_than_slots(model):
    eng = ContinuousBatchingEngine(model, CFG, max_batch=2)
    try:
        import concurrent.futures as cf

        prompts = [[i + 1] for i in range(5)]
        with cf.ThreadPoolExecutor(5) as pool:
            futs = [pool.submit(eng.generate, p, 4) for p in prompts]
            got = [f.result(timeout=120) for f in futs]
        for p, g in zip(prompts, got):
            assert g == _reference(model, p, 4), p
    finally:
        eng.stop()


def test_eos_frees_slot_early(model, engine):
    ref = _reference(model, [3, 1, 4], 10)
    eos = ref[1]
    got = engine.generate([3, 1, 4], 10, eos_token=eos)
    assert got == ref[:2]
    assert engine.active_slots == 0


def test_slot_reuse_is_clean(model, engine):
    """A slot's previous occupant must never leak into the next (stale
    cache beyond the new prompt is masked out)."""
    a = engine.generate([9, 9, 9, 9, 9, 9, 9, 9], 6)  # long occupant
    b = engine.generate([2], 6)                        # short successor
    assert a == _reference(model, [9] * 8, 6)
    assert b == _reference(model, [2], 6)


def test_gpt2_engine_matches_generate():
    from ray_tpu.models.gpt2 import GPT2Config, gpt2_init

    cfg = dataclasses.replace(GPT2Config.tiny(), dtype=jnp.float32)
    params = gpt2_init(cfg, jax.random.PRNGKey(3))
    eng = ContinuousBatchingEngine(params, cfg, max_batch=2)
    try:
        import concurrent.futures as cf

        prompts = [[1, 2, 3], [4, 5]]
        with cf.ThreadPoolExecutor(2) as pool:
            got = [f.result(timeout=120) for f in
                   [pool.submit(eng.generate, p, 5) for p in prompts]]
        for p, g in zip(prompts, got):
            want = np.asarray(generate(params, cfg,
                                       jnp.asarray([p], jnp.int32),
                                       max_new_tokens=5))[0].tolist()
            assert g == want, p
    finally:
        eng.stop()
