"""Conductor persistence + restart: the control plane snapshots its
durable tables (KV, actors, PGs, job metadata) to the session dir and a
restarted conductor recovers them; live workers re-register themselves.
Reference: GCS Redis-persisted tables + restart,
src/ray/gcs/gcs_server/gcs_server.h:103-110, gcs_table_storage.cc."""
from __future__ import annotations

import os
import time

import pytest

import ray_tpu
from ray_tpu._private.conductor import Conductor


def _crash_conductor():
    """Simulate a conductor crash: RPC server gone, monitor halted —
    WITHOUT the graceful stop() that would kill the worker processes."""
    c = ray_tpu._conductor
    c.handler._stopped = True
    c.server.stop()
    return c


def _wait_snapshot(session_dir, deadline_s=10.0):
    path = os.path.join(session_dir, "conductor_state.pkl")
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return
        time.sleep(0.1)
    raise AssertionError("no snapshot written")


@pytest.fixture
def restartable():
    info = ray_tpu.init(num_cpus=4)
    new_conductor = []
    yield info, new_conductor
    for c in new_conductor:
        c.stop()
    ray_tpu.shutdown()


def test_kv_and_named_actor_survive_restart(restartable):
    info, holder = restartable
    w = ray_tpu._private.worker.global_worker

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.options(name="survivor").remote()
    assert ray_tpu.get(c.inc.remote(), timeout=60.0) == 1
    w.conductor.call("kv_put", b"k", b"v-before-crash", True, "default",
                     timeout=5.0)
    time.sleep(0.6)  # let the monitor flush the dirty snapshot
    _wait_snapshot(info["session_dir"])

    old = _crash_conductor()
    host, port = old.address
    new = Conductor({"CPU": 4.0}, info["session_dir"],
                    host=host, port=port).start()
    holder.append(new)

    # driver's reconnecting client re-dials underneath
    assert w.conductor.call("kv_get", b"k", "default",
                            timeout=10.0) == b"v-before-crash"
    # named actor still resolvable and its in-memory state intact (the
    # worker process survived the control-plane crash)
    h = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(h.inc.remote(), timeout=60.0) == 2
    # the surviving worker re-announces itself within its 5s period
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        workers = new.handler.list_workers()
        if any(wk["pid"] is not None and wk["state"] == "ACTOR"
               for wk in workers):
            break
        time.sleep(0.5)
    else:
        raise AssertionError(f"actor worker never re-registered: {workers}")


def test_job_metadata_survives_restart(restartable):
    info, holder = restartable
    w = ray_tpu._private.worker.global_worker
    job_id = w.conductor.call(
        "submit_job", "echo done", None, None, None, {"who": "test"},
        timeout=30.0)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if w.conductor.call("get_job", job_id,
                            timeout=5.0)["status"] == "SUCCEEDED":
            break
        time.sleep(0.2)
    time.sleep(0.6)
    _wait_snapshot(info["session_dir"])

    old = _crash_conductor()
    host, port = old.address
    new = Conductor({"CPU": 4.0}, info["session_dir"],
                    host=host, port=port).start()
    holder.append(new)

    rec = w.conductor.call("get_job", job_id, timeout=10.0)
    assert rec["status"] == "SUCCEEDED"
    assert rec["metadata"] == {"who": "test"}


def test_placement_group_survives_restart(restartable):
    info, holder = restartable
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.wait(timeout_seconds=30.0)
    time.sleep(0.6)
    _wait_snapshot(info["session_dir"])

    old = _crash_conductor()
    host, port = old.address
    new = Conductor({"CPU": 4.0}, info["session_dir"],
                    host=host, port=port).start()
    holder.append(new)

    w = ray_tpu._private.worker.global_worker
    assert w.conductor.call("placement_group_ready", pg.id, timeout=10.0)

    # the restored PG's reserved bundle is actually leasable
    @ray_tpu.remote(num_cpus=2)
    def inside():
        return "ok"

    ref = inside.options(placement_group=pg).remote()
    assert ray_tpu.get(ref, timeout=60.0) == "ok"
