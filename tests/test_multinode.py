"""Multi-host layer tests: NodeAgent (per-host daemon) + jax.distributed
rendezvous — the in-process analog of the reference's
python/ray/cluster_utils.py:135 (Cluster.add_node) multi-node tests.

The NodeAgent is the raylet-equivalent (src/ray/raylet/node_manager.h:125);
the rendezvous replaces the reference's NCCL/MASTER_ADDR bootstrap
(python/ray/train/torch/config.py:64-117) with
jax.distributed.initialize over the conductor KV."""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu._private.node_agent import NodeAgent

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def small_head(monkeypatch):
    """A 1-CPU head: anything bigger must land on an agent node."""
    monkeypatch.setenv("RAY_TPU_NODE_TIMEOUT", "2.0")
    info = ray_tpu.init(num_cpus=1)
    yield info
    ray_tpu.shutdown()


def _head_address():
    return ray_tpu._private.worker.global_worker.conductor_address


def _conductor():
    return ray_tpu._private.worker.global_worker.conductor


def test_agent_registers_resources(small_head):
    agent = NodeAgent(_head_address(), {"CPU": 4.0, "widget": 2.0}).start()
    try:
        total = ray_tpu.cluster_resources()
        assert total["CPU"] == 5.0
        assert total["widget"] == 2.0
        nodes = _conductor().call("nodes", timeout=5.0)
        assert any(n["node_id"] == agent.node_id and n["alive"]
                   for n in nodes)
    finally:
        agent.stop()
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 1.0 and "widget" not in total


def test_task_placed_on_agent_node(small_head):
    """A task too big for the head must be spawned by the agent, on the
    agent's node, and report the agent's node id."""
    agent = NodeAgent(_head_address(), {"CPU": 4.0}).start()
    try:
        @ray_tpu.remote(num_cpus=2)
        def where():
            return os.environ.get("RAY_TPU_NODE_ID")

        assert ray_tpu.get(where.remote(), timeout=60.0) == agent.node_id
        # and the agent (not the head) owns that worker process
        assert agent.handler._procs, "agent spawned no worker"
    finally:
        agent.stop()


def test_actor_on_agent_node_death_detected(small_head):
    """Kill a remote-node actor's process: the agent's heartbeat reports
    the pid death and callers get ActorDiedError (the conductor cannot
    poll remote pids — node_heartbeat dead_worker_ids is the only path)."""
    agent = NodeAgent(_head_address(), {"CPU": 4.0}).start()
    try:
        @ray_tpu.remote(num_cpus=2, max_restarts=0)
        class Pinned:
            def pid(self):
                return os.getpid()

        a = Pinned.remote()
        pid = ray_tpu.get(a.pid.remote(), timeout=60.0)
        os.kill(pid, signal.SIGKILL)
        with pytest.raises(ray_tpu.exceptions.ActorDiedError):
            for _ in range(100):  # death arrives via next agent heartbeat
                ray_tpu.get(a.pid.remote(), timeout=30.0)
                time.sleep(0.1)
    finally:
        agent.stop()


def test_dead_agent_detected_by_heartbeat_expiry(small_head):
    """An agent that stops heartbeating (host crash) is marked dead and
    its resources leave the pool (gcs_health_check_manager.cc analog)."""
    agent = NodeAgent(_head_address(), {"CPU": 4.0}).start()
    assert ray_tpu.cluster_resources()["CPU"] == 5.0
    # simulate host crash: stop the heartbeat + RPC server, skip dereg
    agent._stopped.set()
    agent.server.stop()
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        if ray_tpu.cluster_resources().get("CPU") == 1.0:
            break
        time.sleep(0.2)
    assert ray_tpu.cluster_resources().get("CPU") == 1.0, \
        "dead agent's resources never reclaimed"


_CHILD = r"""
import os, sys
import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")  # sitecustomize may force a TPU

import ray_tpu
from ray_tpu.parallel.distributed import initialize_jax_distributed

rank = int(sys.argv[1])
ray_tpu.init(address=os.environ["RAY_TPU_TEST_HEAD"])
initialize_jax_distributed("test_gang", rank, 2)

import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.devices()
mesh = Mesh(np.array(jax.devices()).reshape(2), ("dp",))
arr = jax.make_array_from_callback(
    (2,), NamedSharding(mesh, P("dp")),
    lambda idx: np.array([float(rank) + 1.0], dtype=np.float32))
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(arr)
print("MULTIHOST_OK", float(total), flush=True)
"""


def test_two_process_jax_distributed(small_head):
    """Two driver processes rendezvous through the conductor KV into ONE
    jax.distributed job: each contributes its local CPU device to a
    global 2-device mesh and a jitted cross-process reduction agrees."""
    host, port = _head_address()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # children get 1 local device each
    env["JAX_PLATFORMS"] = "cpu"
    env["RAY_TPU_TEST_HEAD"] = f"{host}:{port}"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD, str(rank)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for rank in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert "MULTIHOST_OK 3.0" in out, f"rank {rank} output:\n{out}"


def test_spread_scheduling_strategy(small_head):
    """scheduling_strategy='SPREAD' prefers the emptiest node (reference
    spread_scheduling_policy.cc); DEFAULT packs head-first."""
    import time as _time

    agent = NodeAgent(_head_address(), {"CPU": 4.0}).start()
    try:
        @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
        def where():
            _time.sleep(0.8)  # keep leases overlapping
            return os.environ.get("RAY_TPU_NODE_ID")

        _time.sleep(1.0)  # let the agent register
        nodes = set(ray_tpu.get([where.remote() for _ in range(4)],
                                timeout=60.0))
        assert len(nodes) == 2, f"SPREAD used only {nodes}"

        @ray_tpu.remote(num_cpus=1)
        def where_default():
            return os.environ.get("RAY_TPU_NODE_ID")

        # sequential DEFAULT tasks pack onto the head
        head_nodes = {ray_tpu.get(where_default.remote(), timeout=60.0)
                      for _ in range(3)}
        assert agent.node_id not in head_nodes
    finally:
        agent.stop()


def test_locality_aware_leasing(small_head):
    """A task whose (large, locator-only) arg lives on the agent node must
    lease there even though the head also has room (reference
    core_worker/lease_policy.cc LocalityAwareLeasePolicy)."""
    agent = NodeAgent(_head_address(), {"CPU": 4.0}).start()
    try:
        import numpy as np

        @ray_tpu.remote(num_cpus=2)  # head has 1 CPU: runs on the agent
        def big():
            return np.zeros(16 << 20, np.uint8)  # >8MB: stays with holder

        ref = big.remote()
        ray_tpu.wait([ref], timeout=60.0)

        @ray_tpu.remote(num_cpus=1)  # fits the head too
        def consume(a):
            return (os.environ.get("RAY_TPU_NODE_ID"), a.nbytes)

        node, nbytes = ray_tpu.get(consume.remote(ref), timeout=60.0)
        assert nbytes == 16 << 20
        assert node == agent.node_id, \
            f"consumer ran on {node}, arg lives on {agent.node_id}"
    finally:
        agent.stop()


def test_node_affinity_strategies(small_head):
    """NodeAffinity: hard pins (or fails for unknown nodes), soft degrades
    (reference node_affinity_scheduling_policy.cc)."""
    from ray_tpu.exceptions import SchedulingError
    from ray_tpu.util.scheduling_strategies import \
        NodeAffinitySchedulingStrategy

    agent = NodeAgent(_head_address(), {"CPU": 4.0}).start()
    try:
        @ray_tpu.remote
        def where():
            return os.environ.get("RAY_TPU_NODE_ID")

        # hard pin to the agent: must run there though the head has room
        pinned = where.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                agent.node_id, soft=False))
        assert ray_tpu.get(pinned.remote(), timeout=60.0) == agent.node_id

        # hard pin to a dead node: typed failure, no infinite wait
        with pytest.raises(SchedulingError):
            ray_tpu.get(where.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    "no-such-node", soft=False)).remote(), timeout=30.0)

        # soft pin to a dead node: degrades to DEFAULT placement
        soft = where.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                "no-such-node", soft=True))
        assert ray_tpu.get(soft.remote(), timeout=60.0) is not None

        # actors honor the strategy too
        @ray_tpu.remote(num_cpus=1)
        class Where:
            def node(self):
                return os.environ.get("RAY_TPU_NODE_ID")

        a = Where.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                agent.node_id, soft=False)).remote()
        assert ray_tpu.get(a.node.remote(), timeout=60.0) == agent.node_id
        ray_tpu.kill(a)
    finally:
        agent.stop()


def test_serve_proxy_on_every_node(small_head):
    """Serve runs a proxy replica per cluster node, each serving the
    shared route table: a request through the NON-head node's proxy must
    succeed (reference serve/_private/proxy.py:1111 + proxy_state.py)."""
    import requests

    from ray_tpu import serve

    agent = NodeAgent(_head_address(), {"CPU": 4.0}).start()
    try:
        serve.start()

        @serve.deployment
        def hello(request):
            return {"from": os.environ.get("RAY_TPU_NODE_ID", "driver")}

        serve.run(hello.bind(), name="mn_app", route_prefix="/hello")

        proxies = {}
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            proxies = serve.status().get("proxies", {})
            if len(proxies) >= 2 and agent.node_id in proxies:
                break
            time.sleep(0.5)
        assert agent.node_id in proxies, \
            f"no proxy on agent node: {proxies}"

        host, port = proxies[agent.node_id]
        head_addr = serve.proxy_address()
        assert (host, port) != tuple(head_addr)
        r = requests.get(f"http://{host}:{port}/hello", timeout=30)
        assert r.status_code == 200 and "from" in r.json()
        # the same route serves through the head proxy too
        r2 = requests.get(
            f"http://{head_addr[0]}:{head_addr[1]}/hello", timeout=30)
        assert r2.status_code == 200
    finally:
        try:
            serve.shutdown()
        finally:
            agent.stop()
