"""State API + metrics tests — modeled on the reference's
python/ray/tests/test_state_api*.py and test_metrics_agent.py."""
from __future__ import annotations

import json
import time

import pytest

import ray_tpu
from ray_tpu.util import metrics, state


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_list_nodes_and_workers(cluster):
    nodes = state.list_nodes()
    assert len(nodes) >= 1
    assert all(n["alive"] and "total" in n for n in nodes)


def test_list_tasks_and_summary(cluster):
    @ray_tpu.remote
    def tracked_task(x):
        time.sleep(0.01)
        return x

    ray_tpu.get([tracked_task.remote(i) for i in range(5)])
    tasks = state.list_tasks(name="tracked_task")
    assert len(tasks) >= 5
    assert all(t["end"] >= t["start"] for t in tasks)
    summary = state.summarize_tasks()
    assert summary["tracked_task"]["count"] >= 5
    assert summary["tracked_task"]["mean_s"] >= 0.005


def test_failed_task_status(cluster):
    @ray_tpu.remote
    def exploding():
        raise ValueError("nope")

    with pytest.raises(Exception):
        ray_tpu.get(exploding.remote())
    tasks = state.list_tasks(name="exploding")
    assert any(t.get("status") == "FAILED" for t in tasks)


def test_list_actors(cluster):
    @ray_tpu.remote
    class Tracked:
        def ping(self):
            return 1

    a = Tracked.options(name="state-test-actor").remote()
    ray_tpu.get(a.ping.remote())
    actors = state.list_actors()
    assert any(rec.get("name") == "state-test-actor" for rec in actors)


def test_list_objects(cluster):
    import numpy as np

    ref = ray_tpu.put(np.ones(200_000))
    stats = state.list_objects()
    assert any(s.get("is_driver") for s in stats)
    assert sum(s["num_objects"] for s in stats) >= 1
    del ref


def test_timeline_chrome_trace(cluster, tmp_path):
    @ray_tpu.remote
    def traced():
        return 1

    ray_tpu.get([traced.remote() for _ in range(3)])
    out = tmp_path / "trace.json"
    trace = state.timeline(str(out))
    assert len(trace) >= 3
    loaded = json.loads(out.read_text())
    ev = next(e for e in loaded if e["name"] == "traced")
    assert ev["ph"] == "X" and ev["dur"] >= 0 and "ts" in ev


def test_metrics_counter_gauge(cluster):
    c = metrics.Counter("test_requests_total", "reqs", tag_keys=("route",))
    c.inc(1, tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    c.inc(5, tags={"route": "/b"})
    g = metrics.Gauge("test_queue_depth", "depth")
    g.set(7)
    metrics.flush()
    text = state.prometheus_metrics()
    assert 'test_requests_total{route="/a"' in text
    assert "# TYPE test_requests_total counter" in text
    assert "test_queue_depth" in text and " 7" in text


def test_metrics_histogram(cluster):
    h = metrics.Histogram("test_latency_s", "lat",
                          boundaries=[0.01, 0.1, 1.0])
    for v in [0.005, 0.05, 0.5, 5.0]:
        h.observe(v)
    metrics.flush()
    text = state.prometheus_metrics()
    assert 'test_latency_s_bucket' in text
    assert 'le="+Inf"} 4' in text
    assert "test_latency_s_count" in text


def test_metrics_in_worker(cluster):
    @ray_tpu.remote
    def emits_metrics():
        from ray_tpu.util import metrics as m

        c = m.Counter("test_worker_side_total", "from a task")
        c.inc(3)
        m.flush()
        return True

    assert ray_tpu.get(emits_metrics.remote())
    text = state.prometheus_metrics()
    assert "test_worker_side_total" in text


def test_cluster_summary(cluster):
    s = state.cluster_summary()
    assert s["resources_total"].get("CPU", 0) >= 4
    assert s["num_workers"] >= 0 and len(s["nodes"]) >= 1


def test_invalid_metric_usage(cluster):
    with pytest.raises(ValueError):
        metrics.Counter("bad name!")
    c = metrics.Counter("test_valid_total", tag_keys=("k",))
    with pytest.raises(ValueError):
        c.inc(1, tags={"unknown": "x"})
    with pytest.raises(ValueError):
        c.inc(-1)


def test_rpc_handler_stats(cluster):
    """The conductor's RPC server accounts per-method queue/handler
    latency (reference instrumented_io_context.h stats)."""
    from ray_tpu.util import state

    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get([f.remote() for _ in range(5)]) == [1] * 5
    stats = state.rpc_stats()
    assert "lease_worker" in stats, sorted(stats)
    s = stats["lease_worker"]
    # lease reuse pipelines same-shape tasks onto cached leases, so 5
    # tasks need >= 1 lease RPC, not 5 (worker.py _lease_recache)
    assert s["count"] >= 1
    assert s["mean_handler_ms"] >= 0.0
    assert s["max_handler_ms"] >= s["mean_handler_ms"] - 1e-9
    assert s["max_queue_ms"] >= 0.0
