"""shardlint (ray_tpu.analysis): one seeded violation per rule asserting
the exact rule id fires, clean-pass assertions on every built-in dryrun
layout, and the CLI surface. Everything here is deviceless except the
from_mesh exact-DCN test, which uses the virtual 8-device CPU mesh under
RAY_TPU_VIRTUAL_SLICES."""
from __future__ import annotations

import asyncio

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.analysis import (MeshLayout, abstract_mesh,
                              analyze_builtin_layouts, at_least,
                              check_collectives, check_specs, errors,
                              lint_source, scan_collectives)
from ray_tpu.parallel import MeshConfig, shard_map
from ray_tpu.parallel.multislice import (HybridMeshConfig,
                                         dcn_axis_factors,
                                         discover_slice_topology)


def _sds(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _rules(findings):
    return {f.rule for f in findings}


@pytest.fixture
def hybrid_layout():
    return MeshLayout.from_config(
        HybridMeshConfig(dp=-1, tp=2, dcn_dp=2), 8, num_slices=2)


# ------------------------------------------------- seeded shard violations


def test_unknown_axis_rule(hybrid_layout):
    fs = check_specs({"w": P("model")}, {"w": _sds((8, 8))},
                     hybrid_layout)
    assert _rules(fs) == {"unknown-axis"}
    assert fs[0].severity == "error"
    assert "MESH_AXES" in fs[0].fix_hint


def test_non_dividing_dim_rule(hybrid_layout):
    fs = check_specs({"w": P("tp")}, {"w": _sds((7, 4))}, hybrid_layout)
    assert _rules(fs) == {"non-dividing-dim"}


def test_rank_exceeds_ndim_rule(hybrid_layout):
    fs = check_specs({"w": P("dp", None, None)}, {"w": _sds((8, 8))},
                     hybrid_layout)
    assert _rules(fs) == {"rank-exceeds-ndim"}


def test_duplicate_axis_rule(hybrid_layout):
    fs = check_specs({"w": P("tp", "tp")}, {"w": _sds((8, 8))},
                     hybrid_layout)
    assert _rules(fs) == {"duplicate-axis"}


def test_replicated_large_param_rule(hybrid_layout):
    fs = check_specs({"w": P()}, {"w": _sds((8192, 8192))},
                     hybrid_layout)  # 256 MiB fp32, fully replicated
    assert _rules(fs) == {"replicated-large-param"}
    assert fs[0].severity == "warning"
    # axes of size 1 do not count as sharding: still a full copy each
    fs = check_specs({"w": P("sp")}, {"w": _sds((8192, 8192))},
                     hybrid_layout)
    assert "replicated-large-param" in _rules(fs)
    # genuinely sharded: clean
    fs = check_specs({"w": P("tp")}, {"w": _sds((8192, 8192))},
                     hybrid_layout)
    assert fs == []
    # typo'd axis: the unknown-axis error must NOT cascade into a
    # misdirecting "shard it" replication warning — the user tried
    fs = check_specs({"w": P("tpp")}, {"w": _sds((8192, 8192))},
                     hybrid_layout)
    assert _rules(fs) == {"unknown-axis"}


def test_clean_specs_pass(hybrid_layout):
    fs = check_specs({"w": P("fsdp", "tp"), "b": P()},
                     {"w": _sds((8, 8)), "b": _sds((8,))}, hybrid_layout)
    assert fs == []


# -------------------------------------------------------- DCN collectives


def test_tp_collective_over_dcn_warns_with_bytes():
    """A flat tp=8 mesh stretched over 2 slices routes the psum over DCN:
    the exact seeded violation the ISSUE names, with a nonzero
    bytes-over-DCN estimate."""
    layout = MeshLayout.from_config(MeshConfig(dp=1, tp=8), 8,
                                    num_slices=2, name="bad_tp")
    assert layout.dcn_factor("tp") == 2
    mesh = abstract_mesh(layout)
    if mesh is None:
        pytest.skip("this jax has no AbstractMesh")
    fn = shard_map(lambda x: jax.lax.psum(x, "tp"), mesh=mesh,
                   in_specs=P("tp"), out_specs=P(), check_vma=False)
    uses = scan_collectives(fn, _sds((1024,)))
    assert [u.primitive for u in uses] == ["psum"]
    assert uses[0].dcn_bytes(layout) > 0
    fs = check_collectives(layout, uses)
    assert _rules(fs) == {"collective-over-dcn"}
    assert fs[0].severity == "warning"
    assert "tp" in fs[0].message


def test_dcn_axis_collective_is_info_only():
    """psum over dp across slices is the hybrid design: info, not a
    warning."""
    layout = MeshLayout.from_config(HybridMeshConfig(dp=-1, dcn_dp=2), 8,
                                    num_slices=2)
    mesh = abstract_mesh(layout)
    if mesh is None:
        pytest.skip("this jax has no AbstractMesh")
    fn = shard_map(lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
                   in_specs=P("dp"), out_specs=P(), check_vma=False)
    fs = check_collectives(layout, scan_collectives(fn, _sds((64,))))
    assert fs and all(f.severity == "info" for f in fs)


def test_dcn_axis_factors_flat_vs_hybrid():
    # hybrid: declared dcn sizes
    f = dcn_axis_factors(HybridMeshConfig(dp=-1, tp=2, dcn_dp=2), 8, 2)
    assert f["dp"] == 2 and f["tp"] == 1
    # flat tp stretched across slices: stride analysis catches it
    f = dcn_axis_factors(MeshConfig(dp=1, tp=8), 8, 2)
    assert f["tp"] == 2
    # flat dp-outermost: dp crosses, tp stays inside
    f = dcn_axis_factors(MeshConfig(dp=2, tp=4), 8, 2)
    assert f["dp"] == 2 and f["tp"] == 1
    # single slice: nothing crosses
    f = dcn_axis_factors(MeshConfig(dp=2, tp=4), 8, 1)
    assert all(v == 1 for v in f.values())
    # non-aligned spans: a tp line straddling the slice boundary is
    # still caught (dp=3 x tp=2 over 2 slices of 3 devices)
    f = dcn_axis_factors(MeshConfig(dp=3, tp=2), 6, 2)
    assert f["tp"] == 2 and f["dp"] == 2


def test_from_mesh_exact_dcn_factors(cpu_mesh8, monkeypatch):
    """MeshLayout.from_mesh counts slice membership on the real device
    array — exact for hybrid block assembly."""
    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICES", "2")
    topo = discover_slice_topology(cpu_mesh8)
    mesh = HybridMeshConfig(dp=-1, tp=2, dcn_dp=2).build(cpu_mesh8)
    layout = MeshLayout.from_mesh(mesh, topo)
    assert layout.dcn_factor("dp") == 2
    assert layout.dcn_factor("tp") == 1
    assert layout.dcn_axes() == ["dp"]
    # flat tp=8 over the same topology: tp crosses both slices
    flat = MeshConfig(dp=1, tp=8).build(cpu_mesh8)
    layout = MeshLayout.from_mesh(flat, topo)
    assert layout.dcn_factor("tp") == 2


# ------------------------------------------------------ AST lint fixtures


def test_blocking_in_async_rule():
    src = ("import time\n"
           "async def handler(self):\n"
           "    time.sleep(0.1)\n")
    fs = lint_source(src, "x.py")
    assert _rules(fs) == {"blocking-in-async"}
    assert fs[0].severity == "error" and "x.py:3" in fs[0].location


def test_blocking_in_async_queue_and_get():
    src = ("import queue\nimport ray_tpu\n"
           "async def h(self, ref):\n"
           "    q = queue.Queue()\n"
           "    a = q.get()\n"
           "    return ray_tpu.get(ref)\n")
    fs = lint_source(src, "x.py")
    assert len(fs) == 2
    assert _rules(fs) == {"blocking-in-async"}


def test_blocking_in_nested_sync_def_not_flagged():
    src = ("import time\n"
           "async def h(self):\n"
           "    def worker():\n"
           "        time.sleep(1)\n"
           "    return worker\n")
    assert lint_source(src, "x.py") == []


def test_host_sync_in_jit_rule():
    src = ("import jax\n"
           "@jax.jit\n"
           "def step(x):\n"
           "    print('loss', x)\n"
           "    return x.item()\n")
    fs = lint_source(src, "x.py")
    assert _rules(fs) == {"host-sync-in-jit"}
    sev = {f.location: f.severity for f in fs}
    assert sev["x.py:4"] == "warning"  # print: trace-time only
    assert sev["x.py:5"] == "error"    # .item(): aborts tracing


def test_host_sync_in_jit_call_form():
    src = ("import jax\n"
           "def update(p):\n"
           "    return p.item()\n"
           "u = jax.jit(update)\n")
    assert _rules(lint_source(src, "x.py")) == {"host-sync-in-jit"}


def test_shardlint_suppression_comment():
    src = ("import time\n"
           "async def h(self):\n"
           "    time.sleep(0.1)  # shardlint: ok\n"
           "    time.sleep(0.2)  # shardlint: disable=blocking-in-async\n"
           "    time.sleep(0.3)  # shardlint: disable=unknown-axis\n")
    fs = lint_source(src, "x.py")
    assert len(fs) == 1 and "x.py:5" in fs[0].location


def test_undonated_pool_write_rule():
    """Seeded violations: copying writes into pool-named stacks — the
    .at[].set form and the bare dynamic_update_slice form — are
    flagged, while the same update inside a donate_argnums jit (the
    kvcache/lora write discipline) is exempt, donation-less jits
    included."""
    src = ("import functools\n"
           "import jax\n"
           "class Pool:\n"
           "    def write(self, bid, blk):\n"
           "        self._pool_k = self._pool_k.at[bid].set(blk)\n"
           "        self._pool_v = jax.lax.dynamic_update_slice(\n"
           "            self._pool_v, blk, (0, bid))\n"
           "@functools.partial(jax.jit, donate_argnums=(0,))\n"
           "def _ok(pool_k, bid, blk):\n"
           "    return jax.lax.dynamic_update_slice(pool_k, blk,\n"
           "                                        (0, bid))\n"
           "@functools.partial(jax.jit)\n"
           "def _undonated(pool_k, bid, blk):\n"
           "    return jax.lax.dynamic_update_slice(pool_k, blk,\n"
           "                                        (0, bid))\n")
    fs = [f for f in lint_source(src, "x.py")
          if f.rule == "undonated-pool-write"]
    assert {f.location for f in fs} == {"x.py:5", "x.py:6", "x.py:14"}
    assert all(f.severity == "warning" for f in fs)
    # non-pool receivers are not the rule's business
    clean = ("def f(cache, blk):\n"
             "    return cache.at[0].set(blk)\n")
    assert lint_source(clean, "y.py") == []


def test_undonated_pool_write_suppression():
    src = ("class P:\n"
           "    def w(self, b):\n"
           "        self._pool_k = self._pool_k.at[0].set(b)"
           "  # shardlint: disable=undonated-pool-write\n")
    assert lint_source(src, "x.py") == []


# ------------------------------------------- dryrun layouts analyze clean


def test_builtin_layouts_clean(monkeypatch):
    """Every dryrun layout (dcn_dp x tp, dcn_pp x fsdp, dp x pp, dp x sp,
    dp x ep) passes the analyzer with nothing above INFO — under the same
    RAY_TPU_VIRTUAL_SLICES the dryrun itself uses."""
    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICES", "2")
    results = analyze_builtin_layouts(8)
    assert set(results) == {"dcn_dp_tp", "dcn_pp_fsdp", "dp_pp", "dp_sp",
                            "dp_ep"}
    for name, findings in results.items():
        assert at_least(findings, "warning") == [], \
            f"layout {name} not clean: {[str(f) for f in findings]}"
    # the hybrid training layout reports its DCN traffic estimate
    assert any(f.rule == "collective-over-dcn"
               for f in results["dcn_dp_tp"])


def test_trainstep_rejects_bad_specs(cpu_mesh8):
    """TrainStep.init_state surfaces spec errors with the param named,
    before any compilation."""
    import optax

    from ray_tpu.parallel import make_mesh
    from ray_tpu.train.trainer import TrainStep

    mesh = make_mesh(MeshConfig(dp=4, tp=2), devices=cpu_mesh8)
    step = TrainStep(lambda p, b: jnp.sum(p["w"]), optax.sgd(0.1), mesh,
                     {"w": P("model")})
    with pytest.raises(ValueError, match="unknown-axis"):
        step.init_state({"w": jnp.ones((8, 8))})


# ----------------------------------------------------------------- CLI


def test_cli_analyze_reports_and_exit_code(tmp_path, capsys):
    from ray_tpu.scripts.cli import main

    bad = tmp_path / "bad.py"
    bad.write_text("import time\n"
                   "async def h():\n"
                   "    time.sleep(1)\n")
    with pytest.raises(SystemExit):
        main(["analyze", str(bad)])
    out = capsys.readouterr().out
    assert "blocking-in-async" in out and "1 error" in out

    clean = tmp_path / "clean.py"
    clean.write_text("import asyncio\n"
                     "async def h():\n"
                     "    await asyncio.sleep(1)\n")
    main(["analyze", str(clean)])  # exit 0 = no raise
    assert "0 error" in capsys.readouterr().out


def test_cli_analyze_json(tmp_path, capsys):
    from ray_tpu.scripts.cli import main

    bad = tmp_path / "bad.py"
    bad.write_text("import time\n"
                   "async def h():\n"
                   "    time.sleep(1)\n")
    with pytest.raises(SystemExit):
        main(["analyze", "--json", str(bad)])
    import json

    findings = json.loads(capsys.readouterr().out)
    assert findings[0]["rule"] == "blocking-in-async"
    assert findings[0]["severity"] == "error"


# ------------------------------------------- serve async-blocking fixes


def test_router_pick_refuses_to_block_event_loop(monkeypatch):
    """The no-replica wait must not poll-sleep on a running event loop
    (the old behavior froze every coroutine for up to 30s)."""
    from ray_tpu.serve.handle import Router

    router = Router("d", "a")
    monkeypatch.setattr(Router, "_refresh",
                        lambda self, force=False: None)

    async def call():
        router._pick()

    with pytest.raises(RuntimeError, match="remote_async"):
        asyncio.run(call())
    # off-loop the same call waits, then times out cleanly
    monkeypatch.setattr(Router, "_PICK_TIMEOUT_S", 0.2)
    with pytest.raises(TimeoutError, match="no running replicas"):
        router._pick()


def test_router_assign_async_yields_loop(monkeypatch):
    """assign_async picks and submits without blocking the loop; the
    response carries the replica's ref."""
    from ray_tpu.serve.handle import RequestMetadata, Router

    class FakeMethod:
        def remote(self, meta, args, kwargs):
            return ("ref", meta["call_method"], tuple(args))

    class FakeReplica:
        handle_request = FakeMethod()

    router = Router("d", "a")
    monkeypatch.setattr(Router, "_refresh",
                        lambda self, force=False: None)
    monkeypatch.setattr(Router, "_start_metrics_push",
                        lambda self: None)
    router._replicas = [("r1", FakeReplica())]
    router._inflight = {"r1": 0}

    async def call():
        return await router.assign_async(
            RequestMetadata(call_method="m"), (1, 2), {})

    resp = asyncio.run(call())
    assert resp._object_ref == ("ref", "m", (1, 2))
    assert router._inflight["r1"] == 1  # held while the response lives
    resp._mark_done()
    assert router._inflight["r1"] == 0  # released on completion


def test_deployment_response_is_awaitable(monkeypatch):
    """`await resp` resolves off-loop (result + its dead-replica retry
    run on the executor, never blocking the caller's event loop)."""
    from ray_tpu.serve.handle import DeploymentResponse, Router

    router = Router("d", "a")
    resp = DeploymentResponse("fake-ref", router, "r1")
    monkeypatch.setattr(
        DeploymentResponse, "result",
        lambda self, timeout_s=None: ("resolved", timeout_s))

    async def call():
        return await resp

    assert asyncio.run(call()) == ("resolved", None)


def test_replica_drain_is_async():
    """prepare_for_shutdown is a coroutine (await asyncio.sleep drain) —
    the shardlint blocking-in-async fix for serve/replica.py."""
    import inspect

    from ray_tpu.serve.replica import ReplicaActor

    assert inspect.iscoroutinefunction(ReplicaActor.prepare_for_shutdown)

    import threading

    replica = ReplicaActor.__new__(ReplicaActor)
    replica._lock = threading.Lock()
    replica._inflight = 1  # never drains: exercises the await-sleep path
    replica._callable = object()

    async def run():
        return await replica.prepare_for_shutdown(timeout_s=0.2)

    assert asyncio.run(run()) is True


# ------------------------------------- cross-module invariants (v2 rules)


def test_lock_discipline_rule_fires():
    """Seeded race: one attribute mutated under `with self._lock` in one
    method and bare in another — the finding cites BOTH sites."""
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._n = 0\n"
           "    def locked(self):\n"
           "        with self._lock:\n"
           "            self._n += 1\n"
           "    def racy(self):\n"
           "        self._n += 1\n")
    fs = [f for f in lint_source(src, "x.py")
          if f.rule == "lock-discipline"]
    assert len(fs) == 1 and fs[0].severity == "warning"
    assert fs[0].location == "x.py:10"   # the unlocked site
    assert "x.py:8" in fs[0].message     # ... citing the locked one


def test_lock_discipline_constructor_and_convention_exempt():
    """Clean-after-fix shapes: __init__ writes (no concurrent aliases
    yet), `_locked`-suffixed helpers, and "caller holds self._lock"
    docstrings all count as disciplined — zero findings."""
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._n = 0\n"
           "    def _bump_locked(self):\n"
           "        self._n += 1\n"
           "    def helper(self):\n"
           "        \"\"\"Caller holds self._lock.\"\"\"\n"
           "        self._n += 1\n"
           "    def locked(self):\n"
           "        with self._lock:\n"
           "            self._n += 1\n")
    assert [f for f in lint_source(src, "x.py")
            if f.rule == "lock-discipline"] == []


def test_lock_discipline_condition_alias_counts_as_locked():
    """`with self._cv:` (a Condition wrapping the lock) and a local
    Condition alias are both the lock for discipline purposes."""
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.RLock()\n"
           "        self._cv = threading.Condition(self._lock)\n"
           "        self._n = 0\n"
           "    def a(self):\n"
           "        with self._cv:\n"
           "            self._n += 1\n"
           "    def b(self):\n"
           "        with self._lock:\n"
           "            self._n += 1\n")
    assert [f for f in lint_source(src, "x.py")
            if f.rule == "lock-discipline"] == []


def test_lock_discipline_suppression():
    """A deliberate lock-free write silences with `ok=lock-free`."""
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._n = 0\n"
           "    def locked(self):\n"
           "        with self._lock:\n"
           "            self._n += 1\n"
           "    def racy(self):\n"
           "        self._n += 1  # shardlint: ok=lock-free\n")
    assert [f for f in lint_source(src, "x.py")
            if f.rule == "lock-discipline"] == []


def test_undonated_jit_pool_arg_rule():
    """Donation auditor: a jitted function updating a pool-shaped ARG
    without donate_argnums is an O(pool)-copy warning; the donated twin
    is clean."""
    src = ("import functools\n"
           "import jax\n"
           "@jax.jit\n"
           "def write(pool, bid, blk):\n"
           "    return pool.at[bid].set(blk)\n"
           "@functools.partial(jax.jit, donate_argnums=(0,))\n"
           "def write_ok(pool, bid, blk):\n"
           "    return pool.at[bid].set(blk)\n")
    fs = [f for f in lint_source(src, "x.py")
          if f.rule == "undonated-jit-pool-arg"]
    assert len(fs) == 1 and fs[0].severity == "warning"
    assert fs[0].location == "x.py:5" and "'pool'" in fs[0].message
    # non-poolish args are not the rule's business even in a bare jit
    clean = ("import jax\n"
             "@jax.jit\n"
             "def f(state, x):\n"
             "    return state.at[0].set(x)\n")
    assert [f for f in lint_source(clean, "y.py")
            if f.rule == "undonated-jit-pool-arg"] == []


def test_undonated_jit_pool_arg_suppression():
    src = ("import jax\n"
           "@jax.jit\n"
           "def write(pool, bid, blk):\n"
           "    return pool.at[bid].set(blk)"
           "  # shardlint: disable=undonated-jit-pool-arg\n")
    assert [f for f in lint_source(src, "x.py")
            if f.rule == "undonated-jit-pool-arg"] == []


def _rule_ids(findings):
    return {f.rule for f in findings}


def test_env_knob_registry_rules(tmp_path):
    """Seeded violations for all three env-knob rules: a hot-loop parse
    without caching, two sites with different literal defaults, and a
    knob missing from the README text."""
    from ray_tpu.analysis import analyze_invariants

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        "import os\n"
        "def tick(stop):\n"
        "    while not stop.wait(1):\n"
        "        t = float(os.environ.get('RAY_TPU_T_INTERVAL', '1.0'))\n")
    (pkg / "b.py").write_text(
        "import os\n"
        "T = os.environ.get('RAY_TPU_T_INTERVAL', '2.0')\n")
    fs = analyze_invariants(str(pkg), readme_text="no knobs here")
    assert _rule_ids(fs) == {"env-knob-hot-path",
                             "env-knob-inconsistent-default",
                             "env-knob-undocumented"}
    assert all(f.severity == "warning" for f in fs)
    # documented + consistent + cached accessor: all three rules clean
    (pkg / "a.py").write_text(
        "from ray_tpu.util import envknobs\n"
        "def tick(stop):\n"
        "    while not stop.wait(1):\n"
        "        t = envknobs.get_float('RAY_TPU_T_INTERVAL', 1.0)\n")
    (pkg / "b.py").write_text("")
    fs = analyze_invariants(str(pkg),
                            readme_text="| `RAY_TPU_T_INTERVAL` |")
    assert fs == []


def test_env_knob_lru_cached_reader_is_cold(tmp_path):
    """An lru_cache'd reader is the other accepted cached-env shape."""
    from ray_tpu.analysis import analyze_invariants

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        "import functools, os\n"
        "@functools.lru_cache\n"
        "def interval():\n"
        "    return float(os.environ.get('RAY_TPU_T_INTERVAL', '1.0'))\n"
        "def tick(stop):\n"
        "    while not stop.wait(1):\n"
        "        t = interval()\n")
    fs = analyze_invariants(str(pkg),
                            readme_text="| `RAY_TPU_T_INTERVAL` |")
    assert fs == []


def test_env_knob_suppression(tmp_path):
    """Per-line suppressions silence invariant findings at the cited
    site, exactly like the per-file rules."""
    from ray_tpu.analysis import analyze_invariants

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        "import os\n"
        "def tick(stop):\n"
        "    while not stop.wait(1):\n"
        "        t = float(os.environ.get('RAY_TPU_T_INTERVAL', '1.0'))"
        "  # shardlint: disable=env-knob-hot-path\n")
    fs = analyze_invariants(str(pkg),
                            readme_text="| `RAY_TPU_T_INTERVAL` |")
    assert fs == []


def _write_surface_tree(root, timeline_src):
    """A minimal ray_tpu-shaped tree with one conductor subsystem
    ('widget') and every surface except whatever timeline_src omits."""
    for rel, src in {
        "_private/conductor.py":
            "class Handler:\n"
            "    def report_widget_stats(self, s):\n"
            "        pass\n"
            "    def get_widget_stats(self):\n"
            "        return {}\n",
        "util/state.py": "def widget_status():\n    return {}\n",
        "scripts/cli.py":
            "def build(sub):\n"
            "    sp = sub.add_parser('widget')\n",
        "dashboard/__init__.py": "ROUTE = '/api/widget'\n",
        "observability/timeline.py": timeline_src,
        "util/metrics.py": "FAMILY = \"ray_tpu_widget_requests\"\n",
    }.items():
        dest = root / rel
        dest.parent.mkdir(parents=True, exist_ok=True)
        dest.write_text(src)


def test_surface_parity_fires_and_passes(tmp_path):
    """Seeded violation: a conductor subsystem with every surface BUT
    the timeline lane errors naming the missing surface; adding the
    lane clears it."""
    from ray_tpu.analysis import check_surface_parity

    pkg = tmp_path / "pkg"
    _write_surface_tree(pkg, "def unrelated():\n    return []\n")
    fs = check_surface_parity(str(pkg))
    assert len(fs) == 1 and fs[0].rule == "surface-parity"
    assert fs[0].severity == "error"
    assert "'widget'" in fs[0].message and "no timeline" in fs[0].message
    assert "conductor.py:2" in fs[0].location

    _write_surface_tree(
        pkg, "def widget_trace_events(evs):\n    return []\n")
    assert check_surface_parity(str(pkg)) == []


def test_surface_parity_suppression(tmp_path):
    """`# shardlint: disable=surface-parity` on the conductor method
    waives one subsystem (the documented alternative to a
    PARITY_WAIVERS entry)."""
    from ray_tpu.analysis import analyze_invariants

    pkg = tmp_path / "pkg"
    _write_surface_tree(pkg, "def unrelated():\n    return []\n")
    conductor = pkg / "_private" / "conductor.py"
    conductor.write_text(
        "class Handler:\n"
        "    def report_widget_stats(self, s):"
        "  # shardlint: disable=surface-parity\n"
        "        pass\n")
    assert analyze_invariants(str(pkg), readme_text="") == []


def test_envknobs_accessor_caches_and_retunes(monkeypatch):
    """util/envknobs: the parse is memoized on the raw string — same
    raw returns the cached value, a changed env re-parses (live
    retuning and monkeypatching tests both keep working), and a bad
    value falls back to the call-site default."""
    from ray_tpu.util import envknobs

    monkeypatch.setenv("RAY_TPU_TEST_KNOB", "3")
    assert envknobs.get_int("RAY_TPU_TEST_KNOB", 7) == 3
    monkeypatch.setenv("RAY_TPU_TEST_KNOB", "5")
    assert envknobs.get_int("RAY_TPU_TEST_KNOB", 7) == 5
    monkeypatch.setenv("RAY_TPU_TEST_KNOB", "not-an-int")
    assert envknobs.get_int("RAY_TPU_TEST_KNOB", 7) == 7
    monkeypatch.delenv("RAY_TPU_TEST_KNOB")
    assert envknobs.get_int("RAY_TPU_TEST_KNOB", 7) == 7
    monkeypatch.setenv("RAY_TPU_TEST_BOOL", "yes")
    assert envknobs.get_bool("RAY_TPU_TEST_BOOL") is True
    monkeypatch.setenv("RAY_TPU_TEST_BOOL", "off")
    assert envknobs.get_bool("RAY_TPU_TEST_BOOL", True) is False


def test_cli_analyze_invariants_and_knob_table(tmp_path, capsys):
    """`analyze --invariants` folds cross-module findings into the
    report and exit code; `--knob-table --json` rides the wrapper
    object as env_knobs."""
    import json

    from ray_tpu.scripts.cli import main

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        "import os\n"
        "A = os.environ.get('RAY_TPU_T_KNOB', '1')\n"
        "B = os.environ.get('RAY_TPU_T_KNOB', '2')\n")
    with pytest.raises(SystemExit):
        main(["analyze", "--invariants", "--fail-on", "warning",
              str(pkg)])
    out = capsys.readouterr().out
    assert "env-knob-inconsistent-default" in out

    main(["analyze", "--invariants", "--knob-table", "--json",
          "--fail-on", "error", str(pkg)])
    payload = json.loads(capsys.readouterr().out)
    assert [r["knob"] for r in payload["env_knobs"]] == ["RAY_TPU_T_KNOB"]
    assert any(f["rule"] == "env-knob-inconsistent-default"
               for f in payload["findings"])
